"""The capacity service: a long-lived server holding the snapshot on device.

One server serves one cluster snapshot (reloadable).  Query cost is a single
jitted kernel dispatch — the snapshot arrays stay device-resident between
requests, which is the whole point of the service boundary: the reference
re-walks the apiserver on every invocation (SURVEY.md §3.4); here a
front-end query is ~1 ms of kernel time.
"""

from __future__ import annotations

import socketserver
import threading
import weakref

import numpy as np

from kubernetesclustercapacity_tpu.oracle import reference_run
from kubernetesclustercapacity_tpu.ops.fit import fit_per_node
from kubernetesclustercapacity_tpu.report import (
    json_report,
    reference_report,
    table_report,
)
from kubernetesclustercapacity_tpu.scenario import (
    ScenarioError,
    ScenarioGrid,
    random_scenario_grid,
    scenario_from_flags,
)
from kubernetesclustercapacity_tpu.service import protocol
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    publish_group_metrics as _snapshot_publish_group_metrics,
)
from kubernetesclustercapacity_tpu.sources import resolve_source
from kubernetesclustercapacity_tpu.telemetry import (
    memledger as _memledger,
)

__all__ = ["CapacityServer"]


from kubernetesclustercapacity_tpu.masks import (
    implicit_taint_mask as _implicit_taint_mask,
)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many frames
        server: "CapacityServer" = self.server.capacity_server  # type: ignore[attr-defined]
        while True:
            try:
                msg = protocol.recv_msg(self.request)
            except (protocol.ProtocolError, OSError):
                # Mid-frame resets/aborts are routine client behavior, not
                # server errors — drop the connection quietly.
                return
            if msg is None:
                return
            try:
                reply = {"ok": True, "result": server.dispatch(msg)}
            except Exception as e:  # noqa: BLE001 - service boundary
                reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                # Machine-readable refusal class (overloaded/draining/
                # not_leader): multi-endpoint clients dispatch on this
                # token — never on error prose — to decide
                # retryable-elsewhere.
                code = getattr(e, "wire_code", None)
                if isinstance(code, str):
                    reply["code"] = code
            # The generation watermark: every reply says WHICH snapshot
            # generation answered (the plane's read-your-generation
            # monotonicity contract rides on it).  Same thread as the
            # dispatch, so the thread-local read is race-free.
            gen = server.last_dispatch_generation()
            if gen is not None:
                reply["generation"] = gen
            try:
                protocol.send_msg(self.request, reply)
            except OSError:
                return  # peer went away while we answered


class _ThreadingServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    # Track live per-connection sockets so shutdown can SEVER them: a
    # stopped server must look dead to connected clients (the failover
    # signal), not keep answering on old connections like a ghost.
    def __init__(self, *args, **kwargs) -> None:
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def get_request(self):
        sock, addr = super().get_request()
        with self._conns_lock:
            self._conns.add(sock)
        return sock, addr

    def shutdown_request(self, request) -> None:
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass


def _retire_fold_box(box: list) -> None:
    """Finalizer body for a dying :class:`_FoldedFetch` that never
    materialized: un-book its staged pair so the ledger stays honest.
    Swallows everything — it can run during interpreter shutdown."""
    try:
        staged = box[0]
        box[0] = None
        if staged is not None:
            _memledger.retire(staged)
    except Exception:
        pass


class _FoldedFetch:
    """Shared device->host materialization for one async folded dispatch.

    The whole batch rides ONE pair of ``jax.Array`` futures; the first
    member to build its response pays the single sync (and device->host
    transfer), everyone after slices the cached host arrays.  Slicing
    the *device* arrays per member instead would launch a fresh XLA
    slice program per (offset, length) — a compile on first sight that
    dwarfs the dispatch the fold exists to amortize.
    """

    def __init__(self, totals, sched) -> None:
        # The staged pair is registered with the device-memory ledger
        # under its own container identity and retired at the single
        # materialization below — an abandoned fold (a member that
        # never built its response) shows up as booked bytes the
        # reconciler can name, not silent HBM.
        self._staged: tuple | None = (totals, sched)
        self._totals, self._sched = totals, sched
        self._lock = threading.Lock()
        self._np: tuple | None = None
        _memledger.register(self._staged, "fold_fetch")
        # While the fetch object is alive an unmaterialized fold is
        # booked HBM the reconciler can name; once it dies the buffers
        # die with it, so the book entry must go too (the box — never
        # ``self`` — rides in the finalizer).
        self._staged_box: list = [self._staged]
        weakref.finalize(self, _retire_fold_box, self._staged_box)

    def arrays(self) -> tuple:
        with self._lock:
            if self._np is None:
                self._np = (
                    np.asarray(self._totals),
                    np.asarray(self._sched),
                )
                self._totals = self._sched = None
                if self._staged is not None:
                    _memledger.retire(self._staged)
                    self._staged = None
                    self._staged_box[0] = None
            return self._np


class _FoldedSlice:
    """One member's ``[offset:end]`` view of a :class:`_FoldedFetch`.

    Materializes through the numpy ``__array__`` protocol, so the
    response path's ``np.asarray`` is the (timed) sync point.
    """

    def __init__(self, fetch: _FoldedFetch, which: int, offset: int,
                 end: int) -> None:
        self._fetch = fetch
        self._which = which
        self._offset = offset
        self._end = end

    def __array__(self, dtype=None, copy=None):
        view = self._fetch.arrays()[self._which][self._offset:self._end]
        return np.asarray(view) if dtype is None else np.asarray(view, dtype)


class CapacityServer:
    """Serve capacity queries for one snapshot over the framed-JSON protocol.

    Guardrails (all opt-in, preserving the localhost-bench default):

    * ``auth_token`` — when set, every op except ``ping`` must carry a
      matching ``token`` field (compared constant-time); required before
      exposing the port beyond localhost, since ``reload``/``update``
      mutate served state.
    * ``max_inflight`` — cap on concurrently-executing compute ops
      (fit/sweep/sweep_multi/place/drain/topology_spread/plan); excess
      requests wait up to ``inflight_wait_s`` then fail with "server
      busy" instead of queuing unboundedly.
    * ``reload_roots`` — when non-empty, ``reload`` paths must resolve
      (symlinks followed) under one of these directories; otherwise any
      server-readable path can be probed through reload errors.
    """

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        fixture: dict | None = None,
        auth_token: str | None = None,
        max_inflight: int = 8,
        inflight_wait_s: float = 30.0,
        reload_roots: tuple[str, ...] = (),
        stats_source=None,
        registry=None,
        trace_log=None,
        trace_sample: str = "always",
        flight_records: int = 256,
        flight_dump_path: str | None = None,
        batch_window_ms: float = 1.0,
        batch_max: int = 32,
        timeline=None,
        request_log=None,
        audit_log=None,
        shadow=None,
        slo=None,
        admission=None,
        plane=None,
        drain_timeout_s: float = 10.0,
        tenants=None,
    ) -> None:
        """``stats_source`` is an optional zero-arg callable returning a
        JSON-able dict of upstream-feed health (e.g.
        :meth:`~..follower.ClusterFollower.stats`); it is surfaced under
        ``info.resilience.follower`` so clients can see retry/backoff/
        degradation counters without a side channel.

        ``registry`` is the :class:`~..telemetry.MetricsRegistry` this
        server instruments (default: a fresh private one, so co-hosted
        servers/tests never share counters; pass the process registry —
        as ``main`` does — to fold server metrics into one scrape).
        ``trace_log`` (a path or :class:`~..telemetry.TraceLog`) records
        one JSONL span tree per dispatched request, carrying the
        caller's ``trace_id`` when the request rode one.
        ``trace_sample`` picks which requests keep their span bodies
        (``always | p99-breach | errors | rate:N`` — see
        :func:`~..telemetry.tracectx.parse_sample_spec`); ids still
        propagate downstream for every request regardless, so an
        upstream hop that DID sample keeps a complete tree.

        ``flight_records`` sizes the flight recorder — the ring buffer
        of the last K dispatched requests served by the ``dump`` op.
        ``flight_dump_path``, when set, appends the whole ring as JSONL
        there every time a dispatch raises (the post-incident record of
        what led up to the failure).

        ``batch_window_ms`` arms server-side micro-batching: concurrent
        plain sweeps against the same snapshot generation collect for up
        to this window (``batch_max`` rows of requests at most) and
        dispatch as ONE kernel launch, each response scattered back with
        its own trace/deadline semantics.  ``0`` disables batching (every
        sweep dispatches solo, the pre-batching behavior).

        ``timeline`` (a :class:`~..timeline.CapacityTimeline`) turns the
        generation counter into a first-class capacity history: every
        snapshot swap — construction, ``replace_snapshot`` (the
        coalescer's publish thread under ``-follow``), ``reload``,
        ``update`` — is observed (watchlist re-evaluated, node-set diff
        recorded, alerts advanced) and served back through the
        ``timeline`` op.  Observation runs on the PUBLISHER'S thread,
        never a query dispatcher's.

        ``request_log`` (a path or :class:`~..telemetry.TraceLog`) emits
        one structured JSON line per dispatched request — op, trace_id,
        span_id, snapshot generation, latency, status — the log half of
        a logs↔traces join: the same ``span_id`` lands in the
        ``trace_log`` span record when both are wired.

        ``audit_log`` (an :class:`~..audit.AuditLog`) makes served
        state and answers durable: every snapshot swap is recorded as
        an invertible diff (periodic checkpoints bound replay cost) and
        every answering/mutating request with full args + a result
        digest, replayable offline via ``kccap -replay``.  Flight
        records gain an ``audit_ref`` pointing at the request's audit
        record.  ``shadow`` (a :class:`~..audit.ShadowSampler`)
        re-checks a sampled fraction of sweep responses against the
        pure-Python oracle off the request path.

        ``slo`` (a :class:`~..telemetry.slo.SLOMonitor`) evaluates
        latency/availability objectives as multi-window error-budget
        burn rates over this server's own request metrics, served by
        the ``slo`` op (and, in ``main``, wired into ``/healthz``).

        ``admission`` (a :class:`~.plane.AdmissionController`) gates
        every compute op BEFORE any work: deadline-slack shedding, an
        rps token bucket, and a bounded concurrency queue — refusals
        surface as the 503-style ``overloaded`` wire code that
        multi-endpoint clients treat as retryable-elsewhere.

        ``plane`` (a :class:`~.plane.PlanePublisher`) makes this server
        the LEADER of a replicated serving plane: every published
        generation (the same funnel the timeline and audit log observe)
        fans out to subscribed replica servers.  ``drain_timeout_s``
        bounds :meth:`begin_drain`'s wait for in-flight work.

        ``tenants`` (a :class:`~.tenancy.TenantMap`) makes the tenant a
        first-class identity on the dispatch path: every request is
        attributed (per-tenant token → explicit ``tenant`` field →
        ``"default"``), the identity rides admission (pass the SAME map
        to the :class:`~.plane.AdmissionController`), the flight
        recorder (``dump`` grows a ``tenant=`` filter), the request
        log, the audit trail, and the bounded-cardinality
        ``kccap_tenant_*`` metrics.  ``None`` (or ``KCCAP_TENANCY=0``
        upstream) is the exact pre-tenancy path — old tenantless
        clients keep working as ``"default"`` with unchanged reply
        envelopes."""
        import os

        from kubernetesclustercapacity_tpu.telemetry.flightrec import (
            FlightRecorder,
        )
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            MetricsRegistry,
        )
        from kubernetesclustercapacity_tpu.telemetry.tracing import TraceLog

        self.snapshot = snapshot
        self._stats_source = stats_source
        self.fixture = fixture
        self.registry = registry if registry is not None else MetricsRegistry()
        self._trace_log = (
            TraceLog(trace_log) if isinstance(trace_log, str) else trace_log
        )
        self._request_log = (
            TraceLog(request_log)
            if isinstance(request_log, str)
            else request_log
        )
        self._timeline = timeline
        self._audit = audit_log
        self._shadow = shadow
        self._slo = slo
        self._admission = admission
        self._plane = plane
        self._plane_role = "leader" if plane is not None else None
        self._plane_stats_source = (
            plane.stats if plane is not None else None
        )
        # Graceful-drain state: _draining flips once and never back;
        # _active_gated counts in-flight drain-gated ops (compute +
        # mutations) so begin_drain can wait for quiesce.
        self._drain_timeout_s = float(drain_timeout_s)
        self._drain_cv = threading.Condition()
        self._draining = False
        self._active_gated = 0
        self._drain_lock = threading.Lock()
        self._drain_result: dict | None = None
        self._drain_hooks: list = []
        #: Optional observer fired (with the drain record) after a
        #: completed drain — ``main`` uses it to stop the serve loop.
        self.on_drained = None
        m = self.registry
        self._m_requests = m.counter(
            "kccap_requests_total", "Requests dispatched, by op.", ("op",)
        )
        self._m_errors = m.counter(
            "kccap_request_errors_total",
            "Requests that raised, by op and exception type.",
            ("op", "error"),
        )
        self._m_latency = m.histogram(
            "kccap_request_latency_seconds",
            "End-to-end dispatch latency, by op.",
            ("op",),
        )
        self._m_inflight = m.gauge(
            "kccap_requests_in_flight",
            "Requests currently being dispatched.",
        )
        self._m_slot_wait = m.gauge(
            "kccap_compute_slot_waiting",
            "Compute requests currently waiting for an inflight slot.",
        )
        # The resilience counter's single source of truth is the
        # registry; info's resilience dict reads it back (one number,
        # two surfaces).
        self._m_shed = m.counter(
            "kccap_deadline_shed_total",
            "Requests shed because their deadline had already expired.",
        )
        self._m_draining = m.gauge(
            "kccap_server_draining",
            "1 while the server is draining (graceful shutdown), else 0.",
        )
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            SUB_MS_LATENCY_BUCKETS_S,
        )

        # Per-phase latency decomposition of every dispatched request
        # (telemetry/phases.py); sub-millisecond buckets — the default
        # ladder's 0.5 ms floor would flatten every phase of a ~0.7 ms
        # fused sweep into one bucket.
        self._m_phase = m.histogram(
            "kccap_phase_seconds",
            "Per-request phase latency decomposition, by op and phase.",
            ("op", "phase"),
            buckets=SUB_MS_LATENCY_BUCKETS_S,
        )
        # Tenancy: None means the exact pre-tenancy dispatch path (no
        # attribution, no per-tenant metrics, unchanged log/audit/flight
        # record shapes).  The metric families are created only when a
        # map is armed, and every label passes TenantMap.label() so the
        # cardinality is bounded by the map (unmapped names fold to
        # "other").
        self._tenants = tenants
        self._m_tenant_requests = None
        self._m_tenant_latency = None
        if tenants is not None:
            self._m_tenant_requests = m.counter(
                "kccap_tenant_requests_total",
                "Requests dispatched, by tenant (bounded: mapped names "
                "+ default + other).",
                ("tenant",),
            )
            self._m_tenant_latency = m.histogram(
                "kccap_tenant_request_latency_seconds",
                "End-to-end dispatch latency, by tenant (bounded "
                "cardinality; feeds per-tenant SLO specs).",
                ("tenant",),
            )
        self._flight = FlightRecorder(flight_records)
        self._flight_dump_path = flight_dump_path
        # Tail-based sampling: span ids are ALWAYS minted (cheap, keeps
        # cross-process propagation armed); span bodies route through
        # the sampler, which buffers them per trace and flushes or drops
        # the whole tree at end of request once the predicate has the
        # request's full latency/error picture.
        self._trace_sink = None
        if self._trace_log is not None:
            from kubernetesclustercapacity_tpu.telemetry.tracectx import (
                TailSampler,
            )

            self._trace_sink = TailSampler(
                self._trace_log,
                trace_sample,
                latency=self._m_latency,
                registry=m,
            )
        self._batcher = None
        if batch_window_ms and batch_window_ms > 0:
            from kubernetesclustercapacity_tpu.service.batching import (
                MicroBatcher,
            )

            fold_hook = None
            if self._tenants is not None:
                from kubernetesclustercapacity_tpu.service import (
                    tenancy as _tenancy,
                )

                if _tenancy.enabled():
                    # Cross-tenant fold attribution: the batcher reports
                    # each multi-request dispatch's member tenants so
                    # the per-tenant metrics can say whose work shared
                    # a launch.
                    fold_hook = _tenancy.FoldAccounting(self._tenants, m)
            self._batcher = MicroBatcher(
                self._dispatch_sweep_batch,
                window_s=float(batch_window_ms) / 1e3,
                max_batch=batch_max,
                registry=m,
                trace_sink=self._trace_sink,
                fold_hook=fold_hook,
            )
        # Per-dispatch-thread context: the snapshot generation captured
        # under the dispatch lock, so the flight record says which
        # generation ANSWERED (not whichever was current when the record
        # was written — a concurrent reload must not skew attribution).
        self._dispatch_tls = threading.local()
        # Served-state generation: bumped on every snapshot swap
        # (reload, update, replace_snapshot) so flight-recorder entries
        # and /healthz can say WHICH snapshot answered a request.
        self._generation = 1
        self._store = None  # lazy ClusterStore, built on first update op
        self._fixture_dirty = False  # fixture lags the store until needed
        self._fixture_source = None  # lazy fixture provider (follower feed)
        self._ptable_cache = None  # (fixture, snapshot, PriorityTable)
        self._implicit_mask = _implicit_taint_mask(snapshot)
        self._auth_token = auth_token
        self._max_inflight = max(1, int(max_inflight))
        self._inflight = threading.Semaphore(self._max_inflight)
        self._inflight_wait_s = float(inflight_wait_s)
        self._reload_roots = tuple(
            os.path.realpath(r) for r in reload_roots
        )
        self._lock = threading.Lock()
        self._tcp = _ThreadingServer((host, port), _Handler)
        self._tcp.capacity_server = self  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None
        # Generation 1 is a generation too: the timeline's baseline
        # record, so the very first publish already has something to
        # diff against (and the audit log's first checkpoint).
        self._observe_timeline(snapshot, self._generation)
        self._audit_generation(snapshot, self._generation)

    @property
    def address(self) -> tuple[str, int]:
        return self._tcp.server_address  # type: ignore[return-value]

    @property
    def generation(self) -> int:
        """Monotonic served-snapshot generation (1 at construction)."""
        with self._lock:
            return self._generation

    @property
    def flight_recorder(self):
        """The server's request flight recorder (read-mostly surface)."""
        return self._flight

    def tracing_stats(self) -> dict:
        """Distributed-tracing status (the ``info {tracing: true}``
        section and the doctor's tracing line): whether span recording
        is armed, the sampling policy, and the kept/dropped ledger."""
        out: dict = {
            "armed": self._trace_sink is not None,
            "request_log": self._request_log is not None,
        }
        if self._trace_sink is not None:
            out.update(self._trace_sink.stats())
        return out

    @property
    def timeline(self):
        """The capacity timeline this server feeds (``None`` unless
        configured)."""
        return self._timeline

    @property
    def draining(self) -> bool:
        """True once a graceful drain has begun (it never un-begins)."""
        with self._drain_cv:
            return self._draining

    def last_dispatch_generation(self) -> int | None:
        """The generation that answered the CURRENT thread's most recent
        dispatch (thread-local; the reply-envelope watermark)."""
        return getattr(self._dispatch_tls, "last_generation", None)

    def set_plane_role(self, role: str, stats_source=None) -> None:
        """Declare this server's plane membership (``"leader"`` /
        ``"replica"``).  A replica serves a read-only view — mutations
        are refused with the ``not_leader`` wire code.  ``stats_source``
        (zero-arg, JSON-able) feeds the ``info {plane: true}`` section."""
        if role not in ("leader", "replica"):
            raise ValueError(f"plane role must be leader/replica, got {role!r}")
        self._plane_role = role
        if stats_source is not None:
            self._plane_stats_source = stats_source

    def add_drain_hook(self, hook) -> None:
        """Register a zero-arg callable run at the START of a graceful
        drain (plane deregistration: the replica's subscriber stop, a
        follower stop).  Best-effort, run in registration order."""
        self._drain_hooks.append(hook)

    def begin_drain(self, *, timeout_s=None, reason: str = "") -> dict:
        """Gracefully drain this server: stop accepting compute/mutation
        ops (refused with the ``draining`` wire code — retryable
        elsewhere), deregister from the plane (drain hooks + leader
        drain announcement), wait up to ``timeout_s`` for in-flight
        gated ops to finish, then emit ONE durable drain record (audit
        log + request log) and fire :attr:`on_drained`.

        Idempotent and thread-safe: concurrent callers serialize; the
        second and later callers get the first drain's record back with
        ``"already": true``.  Diagnostics (ping/info/dump/...) keep
        answering throughout, so operators and load balancers can watch
        the drain happen.
        """
        import time as _time

        timeout_s = (
            self._drain_timeout_s if timeout_s is None else float(timeout_s)
        )
        with self._drain_cv:
            inflight0 = self._active_gated
            self._draining = True
        self._m_draining.set(1)
        with self._drain_lock:
            if self._drain_result is not None:
                return {**self._drain_result, "already": True}
            for hook in list(self._drain_hooks):
                try:
                    hook()
                except Exception:  # noqa: BLE001 - hooks never block a drain
                    pass
            if self._plane is not None:
                try:
                    self._plane.announce_drain()
                except Exception:  # noqa: BLE001 - fan-out never blocks a drain
                    pass
            t0 = _time.monotonic()
            with self._drain_cv:
                while self._active_gated > 0:
                    left = timeout_s - (_time.monotonic() - t0)
                    if left <= 0:
                        break
                    self._drain_cv.wait(min(left, 0.1))
                remaining = self._active_gated
            waited = _time.monotonic() - t0
            record = {
                "kind": "drain",
                "ts": _time.time(),
                "reason": reason,
                "generation": self.generation,
                "inflight_at_start": inflight0,
                "inflight_remaining": remaining,
                "waited_s": round(waited, 3),
                "drained": remaining == 0,
            }
            # The final drain record: durable in the audit log and the
            # structured request log — the forensic "this exit was
            # intentional and here is what it waited for".
            if self._audit is not None:
                try:
                    self._audit.append_raw(record)
                except Exception:  # noqa: BLE001 - best-effort by contract
                    pass
            if self._request_log is not None:
                try:
                    self._request_log.record(**record)
                except Exception:  # noqa: BLE001 - best-effort by contract
                    pass
            self._drain_result = record
        if self.on_drained is not None:
            try:
                self.on_drained(record)
            except Exception:  # noqa: BLE001 - observers never fail a drain
                pass
        return dict(record)

    def _op_drain_server(self, msg: dict) -> dict:
        """Graceful drain over the wire (auth-gated like every mutation).
        ``timeout_s`` overrides the server's ``drain_timeout_s``; the
        reply is the drain record, sent after in-flight work finished
        (or the timeout lapsed)."""
        timeout = msg.get("timeout_s")
        if timeout is not None and (
            isinstance(timeout, bool) or not isinstance(timeout, (int, float))
        ):
            raise ValueError(
                f"timeout_s must be a number, got {timeout!r}"
            )
        reason = msg.get("reason")
        if reason is not None and not isinstance(reason, str):
            raise ValueError(f"reason must be a string, got {reason!r}")
        return self.begin_drain(
            timeout_s=timeout, reason=reason or "drain_server op"
        )

    def _observe_timeline(self, snapshot, generation: int) -> None:
        """Record one published generation in the timeline.  Best-effort
        by the same rule as every observability hook: a failed watchlist
        evaluation must never fail the publish it observes (the
        coalescer would treat that as a fatal publish error and kill a
        supervised serve over a diagnostic)."""
        # Every publish path funnels here, so the node-shape-compression
        # gauges (kccap_group_count / kccap_compression_ratio) update on
        # the same publisher thread — itself best-effort and registry-
        # silent under KCCAP_TELEMETRY=0 or KCCAP_GROUPING=0.
        _snapshot_publish_group_metrics(snapshot)
        # Plane fan-out rides the same publisher thread, BEFORE the
        # timeline's O(N) watchlist evaluation: replicas should hear
        # about a generation as early as possible (bounded staleness),
        # and a failed fan-out must never fail the swap it observes.
        if self._plane is not None:
            try:
                self._plane.publish(snapshot, generation)
            except Exception:  # noqa: BLE001 - fan-out never fails a swap
                pass
        if self._timeline is None:
            return
        try:
            self._timeline.observe(snapshot, generation)
        except Exception:  # noqa: BLE001 - observability never fails a swap
            pass

    def _audit_generation(self, snapshot, generation: int) -> None:
        """Record one published generation in the audit log.  Same
        best-effort contract as the timeline hook: auditing must never
        fail the publish it records."""
        if self._audit is None:
            return
        try:
            self._audit.record_generation(snapshot, generation)
        except Exception:  # noqa: BLE001 - auditing never fails a swap
            pass

    # Ops worth a durable audit record: everything that answers from or
    # mutates served state.  Pure diagnostics (ping/info/dump/timeline)
    # would only bury the forensic record under its own readers.
    _AUDITED_OPS = frozenset(
        {
            "fit", "sweep", "sweep_multi", "place", "drain",
            "topology_spread", "plan", "explain", "car", "gang",
            "optimize", "forecast", "update", "reload",
        }
    )

    def _audit_request(
        self, msg, op_label, gen, error, result, tenant=None,
        trace_sampled=None,
    ):
        """One audit-log request record; returns its audit ref (or
        ``None``).  Best-effort: the audit trail observes dispatch, it
        never fails it.  When tenancy is armed the DERIVED tenant rides
        the stripped args (tokens never do), so audit replay can filter
        a single tenant's traffic.  ``trace_sampled`` is the tail
        sampler's verdict for this request (``None`` = no sampler),
        recorded so a replayed divergence knows whether a trace tree
        exists for it."""
        if self._audit is None or op_label not in self._AUDITED_OPS:
            return None
        from kubernetesclustercapacity_tpu.audit.log import strip_args

        try:
            args = strip_args(msg)
            if tenant is not None:
                args = dict(args, tenant=tenant)
            return self._audit.record_request(
                op=op_label,
                args=args,
                generation=gen,
                status="error" if error else "ok",
                result=result,
                error=error,
                trace_sampled=trace_sampled,
            )
        except Exception:  # noqa: BLE001 - auditing never fails an op
            return None

    def _tenant_of(self, msg: dict) -> str:
        """Attribute one request to a tenant (tenancy armed only).  The
        dedicated ``tenant_token`` field wins, then the ``token`` field
        doubling as a per-tenant token, then an explicit ``tenant``
        label (trusted only as a LABEL — quotas, not secrets), then the
        ``"default"`` identity every pre-tenancy client gets, so old
        clients keep working with unchanged reply envelopes.
        Attribution never authenticates; `_dispatch_routed` does."""
        t = self._tenants.tenant_of(msg.get("tenant_token"))
        if t is None:
            t = self._tenants.tenant_of(msg.get("token"))
        if t is None:
            explicit = msg.get("tenant")
            if isinstance(explicit, str) and explicit:
                t = explicit
        return t or "default"

    def start(self) -> None:
        self._serving = True
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._serving = True
        self._tcp.serve_forever()

    def shutdown(self) -> None:
        # socketserver.shutdown() handshakes with a running
        # serve_forever loop and would block forever without one — an
        # embedder that only ever called dispatch() directly (the audit
        # replayer does) still deserves a working shutdown.
        if getattr(self, "_serving", False):
            self._tcp.shutdown()
        self._tcp.server_close()
        # Sever live connections too: a shut-down server must be DEAD
        # to its connected clients (transport error → failover), not a
        # ghost that keeps answering on pre-shutdown sockets.  The
        # graceful path orders this after begin_drain's in-flight wait,
        # so drained replies are long flushed.
        self._tcp.close_all_connections()

    # -- dispatch ----------------------------------------------------------
    def _check_deadline(self, msg: dict, *, shed: bool = True):
        """Parse the optional absolute ``deadline`` riding the request;
        raise :class:`~..resilience.DeadlineExpired` (→ a normal error
        response) when the caller's budget is already spent — the whole
        point of threading deadlines is never burning a kernel dispatch
        on an answer nobody is waiting for."""
        from kubernetesclustercapacity_tpu.resilience import (
            Deadline,
            DeadlineExpired,
        )

        wire = msg.get("deadline")
        if wire is None:
            return None
        deadline = Deadline.from_wire(wire)  # ValueError on junk
        if shed and deadline.expired():
            self._m_shed.inc()
            raise DeadlineExpired(
                f"request deadline expired {-deadline.remaining():.3f}s "
                "ago; shedding without dispatch"
            )
        return deadline

    # Every op the dispatcher routes — the request-metrics label set.
    # Anything else is labeled "unknown" so a misbehaving client cannot
    # mint unbounded label cardinality through the op field.
    _KNOWN_OPS = frozenset(
        {
            "ping", "info", "fit", "sweep", "sweep_multi", "place",
            "drain", "topology_spread", "plan", "explain", "car",
            "gang", "optimize", "forecast", "dump", "timeline", "slo",
            "reload", "update", "drain_server",
        }
    )

    # The ops admission control governs: everything that dispatches
    # device/compute work.  Diagnostics (ping/info/dump/...) always pass
    # — an overloaded replica must still answer health probes, or the
    # failover that would RELIEVE the overload can never see it.
    _ADMISSION_OPS = frozenset(
        {
            "fit", "sweep", "sweep_multi", "place", "drain",
            "topology_spread", "plan", "explain", "car", "gang",
            "optimize", "forecast",
        }
    )

    # The ops a graceful drain refuses and waits out: compute work plus
    # mutations.  ping/info/dump/timeline/slo stay answerable so load
    # balancers and operators can watch the drain; drain_server itself
    # must pass or a second drain request could never be acknowledged.
    _DRAIN_GATED_OPS = _ADMISSION_OPS | {"update", "reload"}

    def dispatch(self, msg: dict) -> dict | str:
        """Instrumented entry: count/time every request (by op), record
        a trace span when a log is wired, then route.  The caller's
        ``trace_id`` (an optional string riding the envelope like
        ``deadline`` does) lands in the span record verbatim.

        Every dispatch also activates a per-request
        :class:`~..telemetry.phases.PhaseClock` (thread-local, so the
        deep layers — slot wait, micro-batcher, device cache, kernel
        wrappers — attribute their sub-intervals to THIS request); the
        decomposition lands in ``kccap_phase_seconds{op,phase}``, as
        child spans of the request's trace span, and as the flight
        record's ``phases`` field.  ``KCCAP_TELEMETRY=0`` makes the
        clock the no-op null singleton: zero allocations, zero phase
        registry calls."""
        import time as _time

        from kubernetesclustercapacity_tpu.telemetry import (
            phases as _phases,
        )

        op = msg.get("op")
        op_label = op if op in self._KNOWN_OPS else "unknown"
        trace_id = msg.get("trace_id")
        if trace_id is not None and not isinstance(trace_id, str):
            raise ValueError(
                f"trace_id must be a string, got {trace_id!r}"
            )
        # Full trace context (the additive ``tracectx.WIRE_FIELDS``
        # envelope), parsed up front so the request span id exists for
        # the WHOLE dispatch — the micro-batcher parents its
        # join/dispatch spans to it through the dispatch TLS.  An
        # untraced request (no caller ``trace_id``) still gets a span
        # id at record time (the request-log join needs one) but no
        # trace linkage.
        from kubernetesclustercapacity_tpu.telemetry import (
            tracectx as _tracectx,
        )

        trace_armed = (
            self._trace_sink is not None or self._request_log is not None
        )
        span_ctx = _tracectx.from_wire(msg) if trace_armed else None
        parent_span_id = msg.get("parent_span_id")
        if not isinstance(parent_span_id, str) or not parent_span_id:
            parent_span_id = None
        self._dispatch_tls.trace_ctx = (
            span_ctx if self._trace_sink is not None else None
        )
        wall0 = _time.time()
        # Tenant attribution happens ONCE, up front, and rides the
        # whole dispatch: admission quotas, the micro-batcher (via the
        # dispatch TLS), per-tenant metrics, the request log, the audit
        # trail, and the flight record.  None ⇔ tenancy off ⇔ the exact
        # pre-tenancy path (no new fields anywhere).
        tenant = self._tenant_of(msg) if self._tenants is not None else None
        self._dispatch_tls.tenant = tenant
        self._m_requests.labels(op=op_label).inc()
        if self._m_tenant_requests is not None:
            self._m_tenant_requests.labels(
                tenant=self._tenants.label(tenant)
            ).inc()
        self._m_inflight.inc()
        clk = _phases.new_clock()
        prev_clk = _phases.activate(clk)
        if clk:
            # Live (op, tenant) attribution for the sampling profiler:
            # a sample landing anywhere in this dispatch carries the op
            # and tenant; phase blocks add the third coordinate.
            _phases.live_set(op=op_label, tenant=tenant)
        t0 = _time.perf_counter()
        error: str | None = None
        result = None
        release = None
        gated = False
        try:
            if op_label in self._DRAIN_GATED_OPS:
                from kubernetesclustercapacity_tpu.resilience import (
                    DrainingError,
                )

                with self._drain_cv:
                    if self._draining:
                        draining = True
                    else:
                        draining = False
                        self._active_gated += 1
                        gated = True
                if draining:
                    # Refused BEFORE any work: safe to retry elsewhere
                    # (the wire code says so), mutations included.
                    if self._admission is not None:
                        self._admission.count_shed(op_label, "draining")
                    raise DrainingError(
                        "server is draining; retry another replica"
                    )
            if (
                self._admission is not None
                and op_label in self._ADMISSION_OPS
            ):
                # Admission gates BEFORE routing: a shed request never
                # parses a grid, never waits for a compute slot, never
                # touches the device.
                release = self._admission.admit(
                    op_label,
                    self._check_deadline(msg, shed=False),
                    # optimize refreshes the shadow-price signal, so it
                    # is never gated by it (see AdmissionController).
                    priced=op_label != "optimize",
                    tenant=tenant,
                )
            result = self._dispatch_routed(msg)
            return result
        except Exception as e:
            self._m_errors.labels(op=op_label, error=type(e).__name__).inc()
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            if release is not None:
                release()
            if gated:
                with self._drain_cv:
                    self._active_gated -= 1
                    self._drain_cv.notify_all()
            if clk:
                _phases.live_clear()
            _phases.restore(prev_clk)
            dur = _time.perf_counter() - t0
            self._m_inflight.dec()
            # Exemplar: the last trace id to land in each latency
            # bucket, exposed in OpenMetrics exemplar syntax — the
            # metrics→traces join ("what was a p99 request? here's one").
            self._m_latency.labels(op=op_label).observe(
                dur,
                exemplar=(
                    span_ctx.trace_id if span_ctx is not None else None
                ),
            )
            self._dispatch_tls.tenant = None
            if self._m_tenant_latency is not None:
                self._m_tenant_latency.labels(
                    tenant=self._tenants.label(tenant)
                ).observe(dur)
            phase_items = clk.items() if clk else ()
            for ph, secs in phase_items:
                self._m_phase.labels(op=op_label, phase=ph).observe(secs)
            # The generation that ANSWERED (captured under the dispatch
            # lock), shared by the flight record and the request log;
            # ops that never captured one (ping, shed requests) fall
            # back to the current generation.
            gen = getattr(self._dispatch_tls, "generation", None)
            self._dispatch_tls.generation = None
            gen = self.generation if gen is None else gen
            # Persisted (not cleared) for the reply envelope: the
            # handler thread reads it right after dispatch returns.
            self._dispatch_tls.last_generation = gen
            # Tail verdict BEFORE emission: the request span rides the
            # same keep/drop decision as its buffered children, and the
            # verdict lands in the flight/audit records as
            # ``trace_sampled``.  An upstream hop's sticky decision
            # (envelope ``trace_sampled: true``) forces keep.
            sampled = None
            if span_ctx is not None and self._trace_sink is not None:
                sampled = self._trace_sink.decide(
                    op_label, dur, error, forced=span_ctx.sampled
                )
            self._dispatch_tls.trace_ctx = None
            # One span ID correlates the trace-log span with the JSON
            # request-log line — minted only when something records it.
            span_id = None
            if trace_armed:
                span_id = (
                    span_ctx.span_id
                    if span_ctx is not None
                    else _tracectx.new_span_id()
                )
            if self._trace_sink is not None:
                _tracectx.span(
                    self._trace_sink,
                    ts=_time.time(),
                    start_ts=wall0,
                    trace_id=span_ctx.trace_id if span_ctx else "",
                    span_id=span_id,
                    **(
                        {"parent_span_id": parent_span_id}
                        if span_ctx is not None and parent_span_id
                        else {}
                    ),
                    op=op_label,
                    service="server",
                    **({"hops": span_ctx.hops} if span_ctx else {}),
                    duration_ms=round(dur * 1e3, 3),
                    status="error" if error else "ok",
                    **({"error": error} if error else {}),
                )
                # One child span per recorded phase, parented to the
                # request span — the decomposition in trace form, so
                # a trace viewer shows WHERE inside the dispatch the
                # time went (span_id still joins the request log).
                for ph, secs in phase_items:
                    _tracectx.span(
                        self._trace_sink,
                        ts=_time.time(),
                        trace_id=span_ctx.trace_id if span_ctx else "",
                        span_id=_tracectx.new_span_id(),
                        parent_span_id=span_id,
                        op=f"phase:{ph}",
                        phase=ph,
                        service="server",
                        duration_ms=round(secs * 1e3, 3),
                        status="ok",
                    )
                if span_ctx is not None:
                    self._trace_sink.finish(
                        span_ctx.trace_id, keep=bool(sampled)
                    )
            if self._request_log is not None:
                try:
                    self._request_log.record(
                        ts=_time.time(),
                        op=op_label,
                        trace_id=trace_id or "",
                        span_id=span_id,
                        generation=gen,
                        latency_ms=round(dur * 1e3, 3),
                        status="error" if error else "ok",
                        **({"tenant": tenant} if tenant is not None else {}),
                        **({"error": error} if error else {}),
                    )
                except Exception:  # noqa: BLE001 - logging must not fail ops
                    pass
            audit_ref = self._audit_request(
                msg, op_label, gen, error, result, tenant=tenant,
                trace_sampled=sampled,
            )
            self._flight_record(
                msg, op_label, trace_id, dur, error, result, gen, audit_ref,
                phases=(clk.to_ms() if clk else None), tenant=tenant,
                trace_sampled=sampled,
            )

    def _flight_record(
        self, msg, op_label, trace_id, dur, error, result, gen,
        audit_ref=None, phases=None, tenant=None, trace_sampled=None,
    ) -> None:
        """One flight-recorder entry per dispatch (the failing request
        included), then — on error, when configured — the whole ring
        dumped as JSONL.  Strictly best-effort: observability never
        fails the op it observes."""
        from kubernetesclustercapacity_tpu.telemetry import flightrec

        try:
            self._flight.record(
                op=op_label,
                args_digest=flightrec.args_digest(msg),
                generation=gen,
                trace_id=(trace_id or "") if isinstance(trace_id, str) else "",
                latency_ms=dur * 1e3,
                status="error" if error else "ok",
                result_digest=(
                    "" if result is None else flightrec.result_digest(result)
                ),
                error=error,
                audit_ref=audit_ref,
                phases=phases,
                tenant=tenant or "",
                trace_sampled=trace_sampled,
            )
            if error and self._flight_dump_path:
                self._flight.dump_jsonl(self._flight_dump_path)
        except Exception:  # noqa: BLE001 - recorder must not fail ops
            pass

    def _dispatch_routed(self, msg: dict) -> dict | str:
        op = msg.get("op")
        deadline = self._check_deadline(msg)
        if op == "ping":
            return "pong"
        if self._auth_token is not None:
            import hmac

            token = msg.get("token")
            # Compare as bytes: compare_digest on str raises TypeError for
            # non-ASCII, which would lock out a correct non-ASCII token.
            ok = isinstance(token, str) and hmac.compare_digest(
                token.encode(), self._auth_token.encode()
            )
            if not ok and self._tenants is not None:
                # A mapped per-tenant token authenticates too (lookup is
                # by SHA-256 digest — hash equality, no data-dependent
                # scan over secrets): identity and authorization ride
                # one field, so a single-token deployment upgrades to
                # per-tenant tokens without a wire change.  The
                # dedicated ``tenant_token`` field also authenticates,
                # for deployments that keep the shared token AND want
                # tenant identity.
                ok = (
                    self._tenants.tenant_of(token) is not None
                    or self._tenants.tenant_of(msg.get("tenant_token"))
                    is not None
                )
            if not ok:
                raise PermissionError("missing or invalid auth token")
        if op == "drain_server":
            return self._op_drain_server(msg)
        if op in (
            "fit", "sweep", "sweep_multi", "place", "drain",
            "topology_spread", "plan", "explain", "car", "gang",
        ):
            # Bounded concurrency for the compute ops: each holds device
            # dispatch + host packing; unbounded fan-in from one noisy
            # client must not starve the box.  A request carrying a
            # deadline never waits past it for a slot.
            wait_s = self._inflight_wait_s
            if deadline is not None:
                wait_s = max(0.0, min(wait_s, deadline.remaining()))
            self._m_slot_wait.inc()
            import time as _time

            from kubernetesclustercapacity_tpu.telemetry import (
                phases as _phases,
            )

            clk = _phases.current()
            t0 = _time.perf_counter() if clk else 0.0
            try:
                with clk.live("queue_wait"):
                    acquired = self._inflight.acquire(timeout=wait_s)
            finally:
                self._m_slot_wait.dec()
                if clk:
                    clk.record("queue_wait", _time.perf_counter() - t0)
            if not acquired:
                raise RuntimeError(
                    f"server busy: {self._max_inflight} compute requests "
                    "already in flight"
                )
            try:
                # The slot wait may have consumed the caller's budget:
                # shed now rather than dispatch a kernel nobody awaits.
                self._check_deadline(msg)
                return self._dispatch_inner(op, msg)
            finally:
                self._inflight.release()
        return self._dispatch_inner(op, msg)

    def _dispatch_inner(self, op: str, msg: dict) -> dict | str:
        # Snapshot the (snapshot, fixture) pair once under the lock so a
        # concurrent reload/update can never produce a torn read (fits
        # computed on the new snapshot, report rendered against the old
        # one).  The raw fixture is rebuilt from the store lazily — only
        # when an op actually consumes it (cpu-backend fit), not on every
        # watch-event batch.
        with self._lock:
            snap = self.snapshot
            generation = self._generation
            # Stashed per-thread so the flight record attributes this
            # request to the generation that actually answered it.
            self._dispatch_tls.generation = generation
            needs_fixture = (
                op == "drain"  # always reads per-pod requests
                # A sweep reads the fixture only on the priorities path
                # (strict-only; no point rematerializing for a request
                # the strict gate will reject anyway).
                or (
                    op == "sweep"
                    and "priorities" in msg
                    and snap.semantics == "strict"
                )
                or (
                    op in ("fit", "place", "topology_spread", "plan")
                    and self._fit_consumes_fixture(msg, snap.semantics)
                )
            )
            if needs_fixture and self._fixture_dirty and self._store is not None:
                # Store-fed staleness rematerializes under the same lock
                # hold that captured the snapshot: exact pairing (the
                # fixture rebuilds from the state the snapshot came from).
                self.fixture = self._store.fixture_view()
                self._fixture_dirty = False
            # A dirty fixture is NEVER served: consumers see None (and
            # fall back to packed-array walks) rather than stale objects.
            fixture = None if self._fixture_dirty else self.fixture
            # Follower-fed publishes swap snapshots without a fixture;
            # pull one lazily — but only for consumers that correlate
            # fixture to snapshot BY NODE NAME (drain, anti-affinity,
            # the priority table), which tolerate the follower moving a
            # little ahead of the published snapshot.  The reference
            # cpu cross-check pairs fits to rows POSITIONALLY, so it
            # keeps the self-consistent packed-array fallback instead.
            source = None
            if (
                needs_fixture
                and fixture is None
                and self._fixture_source is not None
                and (
                    op == "drain"
                    or "anti_affinity_labels" in msg
                    or "priority" in msg
                    or "priorities" in msg
                )
            ):
                source = self._fixture_source
            implicit_mask = self._implicit_mask
        if source is not None:
            # The O(N) deep copy runs OUTSIDE the dispatch lock (it also
            # takes the follower's lock — holding both would stall every
            # concurrent request AND watch-event application).
            fixture = source()
            with self._lock:
                if self.snapshot is snap and self.fixture is None:
                    self.fixture = fixture  # cache until the next publish
        if op == "info":
            out = {
                "nodes": snap.n_nodes,
                "semantics": snap.semantics,
                "healthy_nodes": int(np.sum(snap.healthy)),
                "extended_resources": sorted(snap.extended),
                "resilience": self._resilience_info(),
                # The protocol feature handshake: what THIS server
                # speaks, so new clients feature-gate plane-era ops
                # instead of erroring on unknown ops against old
                # servers (and old clients simply ignore the key).
                "capabilities": {
                    "protocol": 2,
                    "plane": self._plane_role is not None,
                    "admission": self._admission is not None,
                    "drain": True,
                    "tenancy": self._tenants is not None,
                },
                "draining": self.draining,
            }
            # Opt-in (``info {plane: true}``): the serving-plane section
            # — leader fan-out stats or replica sync/staleness state.
            # Opt-in for the pinned-default-shape reason the other
            # sections are.
            if msg.get("plane"):
                if self._plane_stats_source is None:
                    out["plane"] = None
                else:
                    try:
                        out["plane"] = self._plane_stats_source()
                    except Exception as e:  # noqa: BLE001 - info must not fail
                        out["plane"] = {
                            "error": f"{type(e).__name__}: {e}"
                        }
            # Opt-in (``info {metrics: true}``): the registry snapshot
            # rides the info op so clients see the server's counters
            # without scraping the (possibly un-exposed) metrics port.
            # Opt-in because live latency tallies make the response
            # non-deterministic, and info's default shape is pinned by
            # clients that diff it (the chaos suite among them).
            if msg.get("metrics"):
                out["metrics"] = self.registry.snapshot()
            # Opt-in (``info {hot_path: true}``): device-cache hit rates
            # and micro-batching stats.  Opt-in for the same reason
            # metrics is — live counters would churn the pinned default
            # shape clients diff.
            if msg.get("hot_path"):
                from kubernetesclustercapacity_tpu import devcache
                from kubernetesclustercapacity_tpu import (
                    snapshot as _snapshot_mod,
                )

                grouped = _snapshot_mod.grouped_for_dispatch(snap)
                out["hot_path"] = {
                    "devcache": devcache.CACHE.stats(),
                    "node_bucket_floor": devcache.node_bucket_floor(),
                    "batching": (
                        self._batcher.stats
                        if self._batcher is not None
                        else None
                    ),
                    "grouping": {
                        "enabled": _snapshot_mod.grouping_enabled(),
                        "engaged": grouped is not None,
                        "group_min_count": _snapshot_mod.group_min_count(),
                        **(
                            {
                                "groups": grouped.n_groups,
                                "compression_ratio": round(
                                    grouped.compression_ratio, 4
                                ),
                            }
                            if grouped is not None
                            else {}
                        ),
                    },
                }
            # Opt-in (``info {audit: true}``): audit-log and
            # shadow-oracle status — replay/audit visibility without a
            # side channel.  Opt-in for the pinned-default-shape reason
            # metrics/hot_path are.
            # Opt-in (``info {tenancy: true}``): the tenant map shape
            # (never tokens) plus per-tenant admission counters — the
            # doctor's tenancy line reads this.  Opt-in for the
            # pinned-default-shape reason the other sections are.
            if msg.get("tenancy"):
                if self._tenants is None:
                    out["tenancy"] = None
                else:
                    out["tenancy"] = {
                        "tenants": self._tenants.to_wire(),
                        "admission": (
                            self._admission.tenant_stats()
                            if self._admission is not None
                            else None
                        ),
                    }
            # Opt-in (``info {tracing: true}``): distributed-tracing
            # status — whether span propagation is armed, the sampling
            # policy, and the kept/dropped span ledger.  The doctor's
            # tracing line reads this; opt-in for the
            # pinned-default-shape reason the other sections are.
            if msg.get("tracing"):
                out["tracing"] = self.tracing_stats()
            if msg.get("audit"):
                out["audit"] = {
                    "enabled": (
                        self._audit is not None or self._shadow is not None
                    ),
                    "log": (
                        self._audit.stats()
                        if self._audit is not None
                        else None
                    ),
                    "shadow": (
                        self._shadow.stats()
                        if self._shadow is not None
                        else None
                    ),
                }
            return out
        if op == "fit":
            return self._op_fit(msg, snap, fixture, implicit_mask)
        if op == "sweep":
            return self._op_sweep(msg, snap, implicit_mask, fixture)
        if op == "sweep_multi":
            return self._op_sweep_multi(msg, snap, implicit_mask)
        if op == "place":
            return self._op_place(msg, snap, fixture)
        if op == "drain":
            return self._op_drain(msg, snap, fixture)
        if op == "topology_spread":
            return self._op_topology_spread(msg, snap, fixture)
        if op == "plan":
            return self._op_plan(msg, snap, fixture, implicit_mask)
        if op == "explain":
            return self._op_explain(msg, snap, implicit_mask)
        if op == "car":
            return self._op_car(msg, snap, implicit_mask)
        if op == "forecast":
            return self._op_forecast(msg, snap, implicit_mask)
        if op == "gang":
            return self._op_gang(msg, snap, implicit_mask)
        if op == "optimize":
            return self._op_optimize(msg, snap, implicit_mask)
        if op == "dump":
            return self._op_dump(msg)
        if op == "timeline":
            return self._op_timeline(msg)
        if op == "slo":
            return self._op_slo(msg)
        if op == "reload":
            return self._op_reload(msg, snap)
        if op == "update":
            return self._op_update(msg)
        raise ValueError(f"unknown op {op!r}")

    def _resilience_info(self) -> dict:
        """The service's degradation/health counters, folded into the
        ``info`` op (the breaker-state home the per-response
        ``fast_path_error`` reporting moved out of): fused-path breaker
        snapshot, deadline sheds, and — when a follower feeds this
        server — its retry/backoff counters."""
        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            fast_path_breaker_snapshot,
        )

        out = {
            # A view over the registry counter (single source of truth;
            # the wire shape predates the registry and is pinned by
            # tests/test_telemetry.py).
            "deadline_shed": int(self._m_shed.value),
            "fast_path_breaker": fast_path_breaker_snapshot(),
        }
        if self._stats_source is not None:
            try:
                out["follower"] = self._stats_source()
            except Exception as e:  # noqa: BLE001 - info must not fail
                out["follower"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # PodSpec extension fields a fit message may carry beyond the
    # reference's six flags (kube-scheduler constraint families).
    _SPEC_FIELDS = (
        "tolerations",
        "node_selector",
        "affinity_terms",
        "anti_affinity_labels",
        "spread",
        "extended_requests",
        "priority",
    )

    @staticmethod
    def _scenario_from_msg(msg: dict):
        """The six reference flags (shared defaults for every op)."""
        try:
            scenario = scenario_from_flags(
                cpuRequests=msg.get("cpuRequests", "100m"),
                cpuLimits=msg.get("cpuLimits", "200m"),
                memRequests=msg.get("memRequests", "100mb"),
                memLimits=msg.get("memLimits", "200mb"),
                replicas=msg.get("replicas", "1"),
            )
            scenario.validate()
        except ScenarioError as e:
            raise ValueError(str(e)) from e
        return scenario

    @staticmethod
    def _spec_from_msg(msg: dict, scenario):
        """msg → PodSpec: ONE copy of the spec-field wiring for fit and
        place.  ``spread`` follows the protocol's string-flag convention
        (``spread="2"`` and ``spread=2`` both work)."""
        from kubernetesclustercapacity_tpu.models import PodSpec

        spread = msg.get("spread")
        priority = msg.get("priority")
        try:
            return PodSpec(
                cpu_request_milli=scenario.cpu_request_milli,
                mem_request_bytes=scenario.mem_request_bytes,
                replicas=scenario.replicas,
                cpu_limit_milli=scenario.cpu_limit_milli,
                mem_limit_bytes=scenario.mem_limit_bytes,
                tolerations=tuple(msg.get("tolerations") or ()),
                node_selector=dict(msg.get("node_selector") or {}),
                affinity_terms=tuple(msg.get("affinity_terms") or ()),
                anti_affinity_labels=dict(
                    msg.get("anti_affinity_labels") or {}
                ),
                namespace=msg.get("namespace"),
                spread=int(spread) if spread is not None else None,
                priority=int(priority) if priority is not None else None,
                extended_requests={
                    k: int(v)
                    for k, v in (msg.get("extended_requests") or {}).items()
                },
            )
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError(f"bad pod spec: {e}") from e

    def _priority_table_for(self, fixture: dict, snap: ClusterSnapshot):
        """The preemption table, cached across dispatches.

        Self-validating by ``(fixture, snapshot)`` object identity: both
        are REPLACED, never mutated, on reload/update rematerialization,
        so a stale pair cannot match and no invalidation hooks are
        needed.  Concurrent misses may build twice; the atomic tuple
        swap keeps the cache coherent either way.
        """
        from kubernetesclustercapacity_tpu.ops.preemption import (
            build_priority_table,
        )

        cached = self._ptable_cache
        if (
            cached is not None
            and cached[0] is fixture
            and cached[1] is snap
        ):
            return cached[2]
        table = build_priority_table(
            fixture, snap, tuple(sorted(snap.extended))
        )
        self._ptable_cache = (fixture, snap, table)
        return table

    def _model_for(self, spec, snap: ClusterSnapshot, fixture: dict | None):
        """CapacityModel with the cached preemption table pre-seeded when
        the spec needs one (and the fixture exists to build it — a
        missing fixture keeps the model's own error path)."""
        from kubernetesclustercapacity_tpu.models import CapacityModel

        table = None
        if spec.priority is not None and fixture is not None:
            table = self._priority_table_for(fixture, snap)
        return CapacityModel(
            snap, mode=snap.semantics, fixture=fixture, priority_table=table
        )

    @staticmethod
    def _fit_consumes_fixture(msg: dict, semantics: str) -> bool:
        """The fit paths that read raw objects, not just packed arrays:
        the reference cpu cross-check walk, and anti-affinity masks (pod
        labels are not in the arrays).  dispatch() uses this to decide
        whether a store-dirty fixture must be rematerialized."""
        return (
            (msg.get("backend") == "cpu" and semantics == "reference")
            or "anti_affinity_labels" in msg
            # Preemption builds its priority table from raw pod objects
            # (priorities are not in the arrays); _priority_table_for
            # caches it across dispatches by fixture/snapshot identity.
            or "priority" in msg
        )

    def _op_fit(
        self,
        msg: dict,
        snap: ClusterSnapshot,
        fixture: dict | None,
        implicit_mask=None,
    ) -> dict:
        scenario = self._scenario_from_msg(msg)
        if any(k in msg for k in self._SPEC_FIELDS):
            return self._op_fit_spec(msg, snap, fixture, scenario)

        # The implicit strict-mode taint mask (precomputed per snapshot
        # swap) — the same mask CapacityModel applies, so the plain-flags
        # and PodSpec surfaces agree.
        node_mask = implicit_mask

        backend = msg.get("backend", "tpu")
        if backend == "cpu" and fixture is not None and snap.semantics == "reference":
            fits = np.array(
                reference_run(fixture, scenario).fits, dtype=np.int64
            )
        elif backend == "cpu":
            # No fixture (.npz source) or strict packing: sequential walk
            # over the packed arrays — same fallback the CLI uses, so the
            # cpu/tpu cross-check is never vacuous.
            from kubernetesclustercapacity_tpu.oracle import fit_arrays_python

            fits = np.array(
                fit_arrays_python(
                    snap.alloc_cpu_milli,
                    snap.alloc_mem_bytes,
                    snap.alloc_pods,
                    snap.used_cpu_req_milli,
                    snap.used_mem_req_bytes,
                    snap.pods_count,
                    scenario.cpu_request_milli,
                    scenario.mem_request_bytes,
                    mode=snap.semantics,
                    healthy=(
                        snap.healthy
                        if node_mask is None
                        else snap.healthy & node_mask
                    ),
                ),
                dtype=np.int64,
            )
        else:
            from kubernetesclustercapacity_tpu.utils.quantity import (
                int64_bits,
            )

            fits = np.asarray(
                fit_per_node(
                    snap.alloc_cpu_milli,
                    snap.alloc_mem_bytes,
                    snap.alloc_pods,
                    snap.used_cpu_req_milli,
                    snap.used_mem_req_bytes,
                    snap.pods_count,
                    snap.healthy,
                    # raw uint64 request -> the kernel's int64 bit pattern
                    int64_bits(scenario.cpu_request_milli),
                    scenario.mem_request_bytes,
                    mode=snap.semantics,
                    node_mask=node_mask,
                )
            )

        # Report rendering + list conversion is the fit op's serialize
        # phase (host string/JSON work, no device involvement).  The
        # phase() block (vs a bare record) also marks the live
        # attribution table so profiler samples landing here say
        # "serialize".
        from kubernetesclustercapacity_tpu.telemetry import phases as _phases

        clk = _phases.current()
        with clk.phase("serialize"):
            report = self._render_report(msg, snap, fits, scenario)
            total = int(fits.sum())
            out = {
                "total": total,
                "schedulable": total >= scenario.replicas,
                "fits": fits.tolist(),
                "report": report,
            }
        return out

    @staticmethod
    def _render_report(msg: dict, snap: ClusterSnapshot, fits, scenario):
        """One place maps the wire ``output`` flag to a report renderer —
        every fit path honors the same formats."""
        output = msg.get("output", "reference")
        if output == "json":
            return json_report(snap, fits, scenario)
        if output == "table":
            return table_report(snap, fits, scenario)
        return reference_report(snap, fits, scenario)

    def _op_fit_spec(
        self,
        msg: dict,
        snap: ClusterSnapshot,
        fixture: dict | None,
        scenario,
    ) -> dict:
        """Constrained/multi-resource fit through the CapacityModel facade.

        Exposes the full :class:`~..models.capacity.PodSpec` surface over
        the wire: taint tolerations, nodeSelector, node (anti-)affinity,
        spread, and extended resources — everything the reference's six
        flags could not express (SURVEY.md §5 "failure detection" masks,
        BASELINE configs 4-5).
        """
        spec = self._spec_from_msg(msg, scenario)
        try:
            model = self._model_for(spec, snap, fixture)
            result = model.evaluate(spec)
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError(f"bad pod spec: {e}") from e
        return {
            "total": result.total,
            "schedulable": result.schedulable,
            "fits": result.fits.tolist(),
            "report": self._render_report(msg, snap, result.fits, scenario),
        }

    def _op_place(
        self, msg: dict, snap: ClusterSnapshot, fixture: dict | None
    ) -> dict:
        """Placement simulation over the wire: which node gets replica k.

        Accepts the same spec fields as fit (one shared msg→PodSpec
        parser), so (anti-)affinity constraints bind placements too.
        """
        scenario = self._scenario_from_msg(msg)
        spec = self._spec_from_msg(msg, scenario)
        # Wire flag ``assignments``: false = counts-only (bulk engine,
        # O(N) instead of R scan steps).  Absent/true = the scan WITH the
        # per-replica order — the wire default stays the scan at every R
        # so pre-flag clients keep the response shape they were built
        # against; only an explicit opt-out changes it.
        want_order = msg.get("assignments", True)
        if not isinstance(want_order, bool):
            raise ValueError(
                f"assignments must be a JSON bool, got {want_order!r}"
            )
        try:
            model = self._model_for(spec, snap, fixture)
            result = model.place(
                spec,
                policy=msg.get("policy", "first-fit"),
                assignments=want_order,
            )
        except (TypeError, KeyError, ValueError) as e:
            # KeyError: an extended request naming a column the snapshot
            # does not carry (same shape _op_fit_spec wraps).
            raise ValueError(f"bad pod spec: {e}") from e
        return {
            "assignments": (
                None
                if result.assignments is None
                else [
                    snap.names[i] if i >= 0 else None
                    for i in result.assignments.tolist()
                ]
            ),
            "by_node": result.by_node(),
            "placed": result.placed,
            "all_placed": result.all_placed,
            "policy": result.policy,
            "engine": result.engine,
        }

    def _op_drain(
        self, msg: dict, snap: ClusterSnapshot, fixture: dict | None
    ) -> dict:
        """Drain simulation over the wire: a rehoming target per pod on
        the named node, and the evictable verdict."""
        from kubernetesclustercapacity_tpu.models import CapacityModel

        node = msg.get("node")
        if not isinstance(node, str) or not node:
            raise ValueError("drain wants a non-empty node name string")
        if fixture is None:
            raise ValueError(
                "drain needs a fixture-backed source (.json); an .npz "
                "checkpoint carries no per-pod requests"
            )
        try:
            model = CapacityModel(snap, mode=snap.semantics, fixture=fixture)
            result = model.drain(node, policy=msg.get("policy", "best-fit"))
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError(f"bad drain request: {e}") from e
        return {
            "node": result.node,
            "pods": result.pods,
            "assignments": result.assignments,
            "by_pod": result.by_pod(),
            "blocked": result.blocked,
            "evictable": result.evictable,
            "policy": result.policy,
        }

    def _op_topology_spread(
        self, msg: dict, snap: ClusterSnapshot, fixture: dict | None
    ) -> dict:
        """Capacity under a PodTopologySpread maxSkew constraint —
        :meth:`CapacityModel.topology_spread` over the wire; a message
        carrying scenario ARRAYS instead of the six flags rides the
        vectorized grid path (``topology_spread_grid``)."""
        key = msg.get("topology_key")
        if not isinstance(key, str) or not key:
            raise ValueError(
                "topology_spread wants a non-empty topology_key string"
            )
        if "cpu_request_milli" in msg:
            from kubernetesclustercapacity_tpu.models import CapacityModel

            try:
                grid = ScenarioGrid(
                    cpu_request_milli=np.asarray(msg["cpu_request_milli"]),
                    mem_request_bytes=np.asarray(msg["mem_request_bytes"]),
                    replicas=np.asarray(msg.get("replicas", [1])),
                )
                model = CapacityModel(
                    snap, mode=snap.semantics, fixture=fixture
                )
                totals, sched = model.topology_spread_grid(
                    grid,
                    topology_key=key,
                    max_skew=int(msg.get("max_skew", 1)),
                    node_taints_policy=msg.get(
                        "node_taints_policy", "ignore"
                    ),
                    # The shared constraints the scalar branch honors via
                    # the spec must not silently drop on the grid form.
                    tolerations=tuple(msg.get("tolerations") or ()),
                    node_selector=dict(msg.get("node_selector") or {}),
                )
            except (ScenarioError, KeyError, TypeError, ValueError) as e:
                raise ValueError(
                    f"bad topology_spread request: {e}"
                ) from e
            return {
                "topology_key": key,
                "max_skew": int(msg.get("max_skew", 1)),
                "totals": totals.tolist(),
                "schedulable": sched.tolist(),
                "scenarios": grid.size,
            }
        scenario = self._scenario_from_msg(msg)
        spec = self._spec_from_msg(msg, scenario)
        try:
            model = self._model_for(spec, snap, fixture)
            r = model.topology_spread(
                spec,
                topology_key=key,
                max_skew=int(msg.get("max_skew", 1)),
                node_taints_policy=msg.get("node_taints_policy", "ignore"),
            )
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError(f"bad topology_spread request: {e}") from e
        return {
            "topology_key": r.topology_key,
            "max_skew": r.max_skew,
            "zones": r.zones,
            "allowed": r.allowed,
            "total": r.total,
            "schedulable": r.schedulable,
            "unkeyed_nodes": r.unkeyed_nodes,
        }

    def _op_plan(
        self,
        msg: dict,
        snap: ClusterSnapshot,
        fixture: dict | None,
        implicit_mask=None,
    ) -> dict:
        """Scale-up planning over the wire, two forms:

        * **catalog** (``catalog`` present): the certified planner —
          :func:`~..forecast.planner.plan_capacity` over a declarative
          node-shape catalog, answering "cheapest node set restoring
          the quantile capacity to ``target``" with the LP lower
          bound, cannot-lie certification, shadow prices, and (with
          ``drain: true``) the scale-down dual;
        * **node_template** (legacy): homogeneous
          :meth:`CapacityModel.nodes_needed` (``nodes_needed`` is null
          when unsatisfiable).
        """
        if "catalog" in msg:
            return self._op_plan_catalog(msg, snap, implicit_mask)
        template = msg.get("node_template")
        if not isinstance(template, dict):
            raise ValueError(
                "plan wants a node_template object (or a 'catalog' "
                "for the certified shape planner)"
            )
        scenario = self._scenario_from_msg(msg)
        spec = self._spec_from_msg(msg, scenario)
        try:
            model = self._model_for(spec, snap, fixture)
            plan = model.nodes_needed(spec, template)
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError(f"bad plan request: {e}") from e
        return {
            "replicas_requested": plan.replicas_requested,
            "current_total": plan.current_total,
            "per_node_fit": plan.per_node_fit,
            "nodes_needed": plan.nodes_needed,
            "satisfiable": plan.satisfiable,
        }

    def _op_plan_catalog(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """The catalog form of the ``plan`` op: a stochastic usage spec
        plus a node-shape catalog → the certified cheapest purchase.
        The served semantics and implicit strict-mode taint mask apply
        exactly as they do to ``car``, so the plan restores the same
        capacity those ops report."""
        from kubernetesclustercapacity_tpu.forecast.planner import (
            PlannerError,
            parse_catalog,
            plan_capacity,
        )
        from kubernetesclustercapacity_tpu.stochastic.distributions import (
            DistributionError,
            parse_stochastic_spec,
        )

        if "usage" not in msg:
            raise ValueError(
                "plan with a catalog wants a 'usage' distribution "
                "block (the demand the purchase must hold)"
            )
        data = {"usage": msg["usage"]}
        for field in ("replicas", "samples", "seed", "confidence"):
            if field in msg:
                data[field] = msg[field]
        try:
            spec = parse_stochastic_spec(data)
            catalog = parse_catalog(msg["catalog"])
        except (DistributionError, PlannerError) as e:
            raise ValueError(str(e)) from e
        target = msg.get("target")
        if target is not None and (
            isinstance(target, bool) or not isinstance(target, int)
        ):
            raise ValueError("plan target must be an integer")
        quantile = msg.get("quantile", 0.95)
        if isinstance(quantile, bool) or not isinstance(
            quantile, (int, float)
        ):
            raise ValueError("plan quantile must be a number in (0, 1)")
        drain = msg.get("drain", False)
        if not isinstance(drain, bool):
            raise ValueError("plan drain must be a boolean")
        mask = implicit_mask
        try:
            result = plan_capacity(
                snap,
                spec,
                catalog,
                target=target,
                quantile=float(quantile),
                mode=snap.semantics,
                node_mask=mask,
                drain=drain,
            )
        except PlannerError as e:
            raise ValueError(str(e)) from e
        out = result.to_wire()
        output = msg.get("output")
        if output in ("table", "json"):
            from kubernetesclustercapacity_tpu.report import (
                plan_json_report,
                plan_table_report,
            )

            out["report"] = (
                plan_table_report(out)
                if output == "table"
                else plan_json_report(out)
            )
        return out

    def _op_explain(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """Bottleneck attribution over the wire: the same six flag fields
        as fit, answered with WHY — the binding constraint per node, the
        binding histogram, the saturation summary, and the marginal
        ("+1 replica") analysis.  Honors the served semantics and the
        same implicit strict-mode taint mask the fit/sweep ops apply, so
        the explanation explains the numbers those ops actually return.
        """
        from kubernetesclustercapacity_tpu.explain import explain_snapshot
        from kubernetesclustercapacity_tpu.report import (
            explain_json_report,
            explain_table_report,
        )

        scenario = self._scenario_from_msg(msg)
        grid = ScenarioGrid.from_scenarios([scenario])
        if self._batcher is not None:
            # Explain folds into the SAME queue as plain sweeps (key:
            # generation + semantics + the "auto" kernel family sweeps
            # default to).  A mixed batch rides the fused sweep+explain
            # super-kernel; this member takes its [S, N] row slice.
            generation = getattr(self._dispatch_tls, "generation", None)
            if generation is None:
                generation = ("snap-id", id(snap))
            grid.validate()
            result, _kernel = self._batcher.submit(
                (generation, snap.semantics, "auto"),
                ("explain", snap, implicit_mask, grid),
                deadline=self._check_deadline(msg),
                tenant=getattr(self._dispatch_tls, "tenant", None),
                trace=getattr(self._dispatch_tls, "trace_ctx", None),
                weight=grid.size,
            )
        else:
            result = explain_snapshot(
                snap, grid, mode=snap.semantics, node_mask=implicit_mask
            )
        total = int(result.totals[0])
        out = {
            "total": total,
            "schedulable": total >= scenario.replicas,
            "mode": result.mode,
            "binding": result.binding_names(0),
            "binding_counts": result.binding_counts(0),
            "marginal": result.marginal(0),
            "saturation": result.saturation(0),
        }
        output = msg.get("output")
        if output == "table":
            out["report"] = explain_table_report(result)
        elif output == "json":
            out["report"] = explain_json_report(result)
        return out

    def _op_car(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """Capacity-at-risk over the wire, two forms:

        * **evaluate** (``usage`` present): parse the stochastic spec
          (``usage``/``replicas``/``samples``/``seed``/``confidence``,
          optional ``quantiles`` list), draw the seed-deterministic
          Monte Carlo samples, sweep them through the production kernel
          path (same semantics and implicit taint mask as fit/sweep),
          and return capacity quantiles + mean + probability-of-fit +
          per-quantile binding attribution;
        * **watch status** (no ``usage``): the capacity-at-risk slice
          of the timeline — per quantile watch the last quantile
          capacity, probability-of-fit, and alert state (what
          ``kccap -car HOST:PORT`` renders and exits by).
        """
        from kubernetesclustercapacity_tpu.stochastic.car import (
            DEFAULT_QUANTILES,
            capacity_at_risk,
        )
        from kubernetesclustercapacity_tpu.stochastic.distributions import (
            DistributionError,
            parse_stochastic_spec,
        )

        if "usage" not in msg:
            tl = self._timeline
            watches = tl.car_status() if tl is not None else {}
            if not watches:
                return {"enabled": False, "watches": {}, "breached": []}
            return {
                "enabled": True,
                "generation": self.generation,
                "watches": watches,
                "breached": tl.car_breached(),
            }
        data = {"usage": msg["usage"]}
        for field in ("replicas", "samples", "seed", "confidence"):
            if field in msg:
                data[field] = msg[field]
        try:
            spec = parse_stochastic_spec(data)
        except DistributionError as e:
            raise ValueError(str(e)) from e
        quantiles = msg.get("quantiles")
        if quantiles is not None:
            if not isinstance(quantiles, list) or not quantiles:
                raise ValueError("quantiles must be a non-empty list")
            for q in quantiles:
                if (
                    isinstance(q, bool)
                    or not isinstance(q, (int, float))
                    or not 0.0 < float(q) < 1.0
                ):
                    raise ValueError(
                        f"quantiles must lie strictly inside (0, 1), "
                        f"got {q!r}"
                    )
            quantiles = tuple(float(q) for q in quantiles)
        result = capacity_at_risk(
            snap,
            spec,
            mode=snap.semantics,
            node_mask=implicit_mask,
            quantiles=quantiles or DEFAULT_QUANTILES,
        )

        from kubernetesclustercapacity_tpu.telemetry import phases as _phases

        clk = _phases.current()
        with clk.phase("serialize"):
            out = result.to_wire()
            output = msg.get("output")
            if output in ("table", "json"):
                from kubernetesclustercapacity_tpu.report import (
                    car_json_report,
                    car_table_report,
                )

                out["report"] = (
                    car_table_report(out)
                    if output == "table"
                    else car_json_report(out)
                )
        return out

    def _op_forecast(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """Capacity forecasting over the wire, two forms:

        * **evaluate** (``usage`` present): the capacity-at-risk spec
          plus a projection — ``steps``/``step_s`` and an EXPLICIT
          ``growth`` block (``{cpu_per_s, memory_per_s}`` relative
          rates) — answered with per-step capacity quantile ladders and
          ``time_to_breach_s``.  Growth is explicit by design: the op
          stays a pure function of the served snapshot, so an audited
          forecast re-answers identically on ``kccap -replay`` (trend
          FITTING from history lives client-side in
          :func:`~..forecast.trend.trend_from_audit`, where the audit
          log is);
        * **watch status** (no ``usage``): the forecast slice of the
          timeline — per horizon watch the projected minimum, time to
          breach, and alert state (what ``kccap -forecast HOST:PORT``
          renders and exits by).
        """
        from kubernetesclustercapacity_tpu.forecast.horizon import (
            DEFAULT_STEP_S,
            DEFAULT_STEPS,
            project_horizon,
        )
        from kubernetesclustercapacity_tpu.stochastic.distributions import (
            DistributionError,
            parse_stochastic_spec,
        )

        if "usage" not in msg:
            tl = self._timeline
            watches = tl.forecast_status() if tl is not None else {}
            if not watches:
                return {"enabled": False, "watches": {}, "breached": []}
            return {
                "enabled": True,
                "generation": self.generation,
                "watches": watches,
                "breached": tl.forecast_breached(),
            }
        data = {"usage": msg["usage"]}
        for field in ("replicas", "samples", "seed", "confidence"):
            if field in msg:
                data[field] = msg[field]
        try:
            spec = parse_stochastic_spec(data)
        except DistributionError as e:
            raise ValueError(str(e)) from e
        steps = msg.get("steps", DEFAULT_STEPS)
        if isinstance(steps, bool) or not isinstance(steps, int):
            raise ValueError("forecast steps must be an integer")
        step_s = msg.get("step_s", DEFAULT_STEP_S)
        if isinstance(step_s, bool) or not isinstance(step_s, (int, float)):
            raise ValueError("forecast step_s must be a number")
        growth = msg.get("growth", {})
        if not isinstance(growth, dict):
            raise ValueError(
                "forecast growth must be an object like "
                '{"cpu_per_s": 1e-6, "memory_per_s": 0}'
            )
        unknown = set(growth) - {"cpu_per_s", "memory_per_s"}
        if unknown:
            raise ValueError(
                f"unknown growth field(s) {sorted(unknown)} "
                "(want cpu_per_s/memory_per_s)"
            )
        rates = {}
        for key in ("cpu_per_s", "memory_per_s"):
            v = growth.get(key, 0.0)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"growth.{key} must be a number")
            rates[key] = float(v)
        threshold = msg.get("threshold")
        if threshold is not None and (
            isinstance(threshold, bool) or not isinstance(threshold, int)
        ):
            raise ValueError("forecast threshold must be an integer")
        quantiles = msg.get("quantiles")
        if quantiles is not None:
            if not isinstance(quantiles, list) or not quantiles:
                raise ValueError("quantiles must be a non-empty list")
            for q in quantiles:
                if (
                    isinstance(q, bool)
                    or not isinstance(q, (int, float))
                    or not 0.0 < float(q) < 1.0
                ):
                    raise ValueError(
                        f"quantiles must lie strictly inside (0, 1), "
                        f"got {q!r}"
                    )
            quantiles = tuple(float(q) for q in quantiles)
        try:
            result = project_horizon(
                snap,
                spec,
                steps=steps,
                step_s=float(step_s),
                growth_cpu_per_s=rates["cpu_per_s"],
                growth_mem_per_s=rates["memory_per_s"],
                mode=snap.semantics,
                node_mask=implicit_mask,
                **({"quantiles": quantiles} if quantiles else {}),
                threshold=threshold,
            )
        except ValueError as e:
            raise ValueError(f"bad forecast request: {e}") from e
        out = result.to_wire()
        output = msg.get("output")
        if output in ("table", "json"):
            from kubernetesclustercapacity_tpu.report import (
                forecast_json_report,
                forecast_table_report,
            )

            out["report"] = (
                forecast_table_report(out)
                if output == "table"
                else forecast_json_report(out)
            )
        return out

    def _op_gang(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """Gang capacity over the wire, two forms:

        * **evaluate** (``ranks`` present): the six per-rank flag fields
          (or the sweep op's scenario-array grammar) plus the gang
          constraint fields (``ranks``/``count``/``colocate``/
          ``spread_level``/``max_ranks_per_domain``/
          ``anti_affinity_host``), answered with whole-gang counts per
          scenario — same semantics and implicit taint mask as
          fit/sweep.  Single-scenario requests (and any request with
          ``explain: true``) also carry the binding-level explanation.
        * **watch status** (no ``ranks``): the gang slice of the
          timeline — per gang watch the last whole-gang count, binding
          level, and alert state (what ``kccap -gang HOST:PORT``
          renders and exits by).
        """
        from kubernetesclustercapacity_tpu.topology.gang import (
            GangSpecError,
            gang_capacity,
            gang_explain,
            gang_spec_from_msg,
        )

        if "ranks" not in msg:
            tl = self._timeline
            watches = tl.gang_status() if tl is not None else {}
            if not watches:
                return {"enabled": False, "watches": {}, "breached": []}
            return {
                "enabled": True,
                "generation": self.generation,
                "watches": watches,
                "breached": tl.gang_breached(),
            }
        if "cpu_request_milli" in msg:
            try:
                grid = ScenarioGrid(
                    cpu_request_milli=np.asarray(msg["cpu_request_milli"]),
                    mem_request_bytes=np.asarray(msg["mem_request_bytes"]),
                    replicas=np.asarray(msg.get("replicas", [1])),
                )
            except (ScenarioError, KeyError, TypeError, ValueError) as e:
                raise ValueError(f"bad gang request: {e}") from e
        else:
            grid = ScenarioGrid.from_scenarios([self._scenario_from_msg(msg)])
        try:
            spec = gang_spec_from_msg(msg)
            result = gang_capacity(
                snap, grid, spec,
                mode=snap.semantics, node_mask=implicit_mask,
            )
        except (GangSpecError, ScenarioError, ValueError) as e:
            raise ValueError(f"bad gang request: {e}") from e
        out = result.to_wire()
        if grid.size == 1 or msg.get("explain"):
            out["explain"] = gang_explain(
                snap, grid, spec,
                mode=snap.semantics, node_mask=implicit_mask,
            )
        return out

    def _op_optimize(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """Optimization-based packing over the wire: the sweep grammar
        (scenario arrays or the six flags), answered by the chosen
        ``backend``:

        * ``"lp"`` (default) — the certified LP solve
          (:func:`~..optimize.optimize_snapshot`): certified dual
          bound, integral rounded packing, FFD baseline, per-resource
          shadow prices, and the duality certificate.  A certified
          solve also refreshes the admission controller's
          shadow-price signal.
        * ``"ffd"`` — the bug-compatible first-fit reference alone
          (the production fit path's placed counts), for clients that
          want the baseline without paying the solve.

        Same semantics and implicit strict-mode taint mask as
        fit/sweep, so the optimizer prices the capacity those ops
        serve.
        """
        from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot
        from kubernetesclustercapacity_tpu.optimize import (
            OptimizeError,
            optimize_snapshot,
        )

        backend = msg.get("backend", "lp")
        if backend not in ("lp", "ffd"):
            raise ValueError(
                f"optimize backend must be 'lp' or 'ffd', got {backend!r}"
            )
        if "cpu_request_milli" in msg:
            try:
                grid = ScenarioGrid(
                    cpu_request_milli=np.asarray(msg["cpu_request_milli"]),
                    mem_request_bytes=np.asarray(msg["mem_request_bytes"]),
                    replicas=np.asarray(msg.get("replicas", [1])),
                )
            except (ScenarioError, KeyError, TypeError, ValueError) as e:
                raise ValueError(f"bad optimize request: {e}") from e
        else:
            grid = ScenarioGrid.from_scenarios([self._scenario_from_msg(msg)])

        if backend == "ffd":
            grid.validate()
            totals, sched = sweep_snapshot(
                snap, grid, mode=snap.semantics, node_mask=implicit_mask
            )[:2]
            totals = np.asarray(totals, dtype=np.int64)
            demand = np.asarray(grid.replicas, dtype=np.int64)
            out = {
                "backend": "ffd",
                "mode": snap.semantics,
                "scenarios": grid.size,
                "demand": demand.tolist(),
                "ffd": np.clip(totals, 0, demand).tolist(),
                "totals": totals.tolist(),
                "schedulable": (totals >= demand).tolist(),
            }
        else:
            kwargs = {}
            for key, cast in (("iters", int), ("tol", float)):
                if key in msg:
                    v = msg[key]
                    if isinstance(v, bool) or not isinstance(
                        v, (int, float)
                    ):
                        raise ValueError(
                            f"{key} must be a number, got {v!r}"
                        )
                    kwargs["max_iters" if key == "iters" else key] = cast(v)
            verify = msg.get("verify", True)
            if not isinstance(verify, bool):
                raise ValueError(f"verify must be a bool, got {verify!r}")
            try:
                result = optimize_snapshot(
                    snap,
                    grid,
                    mode=snap.semantics,
                    node_mask=implicit_mask,
                    verify=verify,
                    **kwargs,
                )
            except (OptimizeError, ScenarioError) as e:
                raise ValueError(f"bad optimize request: {e}") from e
            out = result.to_wire()
            if self._admission is not None and result.all_certified:
                # The dual prices the capacity this server is serving:
                # feed the worst (most scarce) scenario's capacity
                # share to the shed-by-shadow-price gate.
                share = max(
                    (s["capacity_share"] for s in result.shadow),
                    default=0.0,
                )
                self._admission.observe_shadow_price(
                    share, certified=True
                )
        output = msg.get("output")
        if output in ("table", "json"):
            from kubernetesclustercapacity_tpu.report import (
                optimize_json_report,
                optimize_table_report,
            )

            out["report"] = (
                optimize_table_report(out)
                if output == "table"
                else optimize_json_report(out)
            )
        return out

    def _op_dump(self, msg: dict) -> dict:
        """The flight recorder over the wire: the last K dispatched
        requests (this ``dump`` itself lands in the ring only after its
        own dispatch finishes, so the returned records end at the
        request before it).

        Server-side filters — ``op`` (exact op name), ``status``
        (``"ok"``/``"error"``), ``filter_tenant`` (exact derived tenant,
        only meaningful when tenancy is armed), ``limit`` (the N MOST
        RECENT matches) — so a triage client chasing "the last 5 errors" pulls
        5 records, not the whole ring.  ``count`` is the post-filter
        record count; ``matched`` the pre-``limit`` match count, so a
        reader knows how much history the filter found beyond what it
        was handed.
        """
        # ``op`` names THIS request's op on the envelope, so the filter
        # rides as ``filter_op`` (the client's ``dump(op=...)`` maps it).
        op_f = msg.get("filter_op")
        if op_f is not None and not isinstance(op_f, str):
            raise ValueError(f"filter_op must be a string, got {op_f!r}")
        status = msg.get("status")
        if status is not None and status not in ("ok", "error"):
            raise ValueError(
                f"status filter must be 'ok' or 'error', got {status!r}"
            )
        # ``tenant`` on the envelope is this request's own attribution
        # (tenant-configured clients stamp it on every call), so the
        # filter rides as ``filter_tenant`` — the ``filter_op`` move.
        tenant_f = msg.get("filter_tenant")
        if tenant_f is not None and not isinstance(tenant_f, str):
            raise ValueError(
                f"filter_tenant must be a string, got {tenant_f!r}"
            )
        # ``sampled`` filters on the tail sampler's recorded verdict:
        # True = records whose trace tree was retained (a ``-trace-tree``
        # will find them), False = records whose tree was dropped.
        # Records with no verdict (no sampler armed) match neither.
        sampled_f = msg.get("sampled")
        if sampled_f is not None and not isinstance(sampled_f, bool):
            raise ValueError(
                f"sampled filter must be a boolean, got {sampled_f!r}"
            )
        limit = msg.get("limit")
        if limit is not None:
            if isinstance(limit, bool) or not isinstance(limit, int):
                raise ValueError(f"limit must be an integer, got {limit!r}")
            if limit < 1:
                raise ValueError(f"limit must be >= 1, got {limit}")
        records = self._flight.records()
        if op_f is not None:
            records = [r for r in records if r.get("op") == op_f]
        if status is not None:
            records = [r for r in records if r.get("status") == status]
        if tenant_f is not None:
            records = [r for r in records if r.get("tenant") == tenant_f]
        if sampled_f is not None:
            records = [
                r for r in records if r.get("trace_sampled") is sampled_f
            ]
        matched = len(records)
        if limit is not None:
            records = records[-limit:]
        return {
            "records": records,
            "count": len(records),
            "matched": matched,
            "capacity": self._flight.capacity,
            "dropped": self._flight.dropped,
            "generation": self.generation,
        }

    def _op_slo(self, msg: dict) -> dict:
        """SLO burn-rate status over the wire: every objective's current
        short/long-window burn, alert state, and the fast-burning
        verdict.  Evaluated ON READ (one fresh counter sample per
        query), so a poller always sees current burn — the background
        evaluator only exists for scrape-only deployments."""
        if self._slo is None:
            return {"enabled": False}
        self._slo.evaluate()
        return self._slo.wire()

    def _op_timeline(self, msg: dict) -> dict:
        """The capacity timeline over the wire: per-generation records,
        attributed deltas, and alert states — filtered server-side by
        ``since_generation`` (strictly-after) and ``watch`` (one name),
        so a follower polling for news pulls only the transitions it has
        not seen."""
        if self._timeline is None:
            return {"enabled": False}
        since = msg.get("since_generation")
        if since is not None:
            if isinstance(since, bool) or not isinstance(since, int):
                raise ValueError(
                    f"since_generation must be an integer, got {since!r}"
                )
        watch = msg.get("watch")
        if watch is not None and not isinstance(watch, str):
            raise ValueError(f"watch must be a string, got {watch!r}")
        return self._timeline.wire(since_generation=since, watch=watch)

    def _op_sweep(
        self,
        msg: dict,
        snap: ClusterSnapshot,
        implicit_mask=None,
        fixture: dict | None = None,
    ) -> dict:
        # The generation _dispatch_inner captured WITH this snapshot
        # (stashed per thread): the micro-batch key, so only requests
        # answering from the same generation ever share a launch.  A
        # direct caller that bypassed dispatch keys by snapshot identity
        # instead — never by a mixable None.
        generation = getattr(self._dispatch_tls, "generation", None)
        if generation is None:
            generation = ("snap-id", id(snap))
        if "random" in msg:
            grid = random_scenario_grid(
                int(msg["random"]["n"]), seed=int(msg["random"].get("seed", 0))
            )
        else:
            grid = ScenarioGrid(
                cpu_request_milli=np.asarray(msg["cpu_request_milli"]),
                mem_request_bytes=np.asarray(msg["mem_request_bytes"]),
                replicas=np.asarray(msg.get("replicas", [1])),
            )
        if "priorities" in msg:
            return self._sweep_with_priorities(
                msg, snap, grid, implicit_mask, fixture
            )
        kernel_req = msg.get("kernel", "auto")
        if self._batcher is not None:
            # Validate BEFORE joining a batch: a bad grid must fail its
            # own request, never a batch it rode into.  Keyed by the
            # captured generation + served semantics + kernel family —
            # requests with DIFFERENT pod specs (even different tenants)
            # fold into one padded dispatch and split per request on
            # return (snap and implicit_mask are generation-determined,
            # so nothing else can diverge inside a key).
            grid.validate()
            totals, sched, kernel, attempted, attempt_error = (
                self._batcher.submit(
                    (generation, snap.semantics, kernel_req),
                    ("sweep", snap, implicit_mask, grid),
                    deadline=self._check_deadline(msg),
                    # Folding across tenants is the POINT (one padded
                    # dispatch, split per tenant on return, bit-exact
                    # vs solo) — the label only feeds accounting.
                    tenant=getattr(self._dispatch_tls, "tenant", None),
                    trace=getattr(self._dispatch_tls, "trace_ctx", None),
                    weight=grid.size,
                )
            )
        else:
            from kubernetesclustercapacity_tpu.ops.pallas_fit import (
                last_dispatch_fast_path,
                sweep_snapshot_auto,
            )

            # The same implicit taint mask the fit op applies: a strict
            # sweep over a tainted snapshot must not report higher totals
            # than fit does for the identical spec.
            totals, sched, kernel = sweep_snapshot_auto(
                snap,
                grid,
                mode=snap.semantics,
                kernel=kernel_req,
                node_mask=implicit_mask,
            )
            attempted, attempt_error = last_dispatch_fast_path()

        # Async pipelining: a folded batch answers with ``jax.Array``
        # futures (dispatch enqueued, not fetched) so the leader's
        # launch overlaps the NEXT batch's accumulation window.  Block
        # on device->host transfer at the last possible moment — here,
        # just before the response is built — and account the stall to
        # its own phase so the overlap is visible in evidence.
        from kubernetesclustercapacity_tpu.telemetry import phases as _phases

        if not isinstance(totals, np.ndarray):
            clk_f = _phases.current()
            if clk_f:
                import time as _time

                t0 = _time.perf_counter()
                with clk_f.live("fetch_overlap"):
                    totals = np.asarray(totals)
                    sched = np.asarray(sched)
                clk_f.record("fetch_overlap", _time.perf_counter() - t0)
            else:
                totals = np.asarray(totals)
                sched = np.asarray(sched)

        # Shadow-oracle sampling: decision + queue append only (the
        # oracle walk runs on the sampler's worker thread, never this
        # dispatcher's).  Best-effort by the observability contract.
        if self._shadow is not None:
            try:
                ctx = getattr(self._dispatch_tls, "trace_ctx", None)
                self._shadow.maybe_submit(
                    snap, generation, grid, totals, sched,
                    node_mask=implicit_mask,
                    trace_id=ctx.trace_id if ctx is not None else None,
                )
            except Exception:  # noqa: BLE001 - monitoring never fails ops
                pass

        # Attach the fused-path failure ONLY when THIS request's dispatch
        # attempted the fused kernel and it failed (captured on the
        # dispatching thread, so a concurrent request's failure can't be
        # misattributed; for a batch, the batch WAS this request's
        # dispatch).  A stale breaker error must never ride an
        # exact-kernel response — the breaker's standing state lives in
        # the info op instead.
        clk = _phases.current()
        with clk.phase("serialize"):
            return {
                "totals": totals.tolist(),
                "schedulable": sched.tolist(),
                "scenarios": grid.size,
                "kernel": kernel,
                **(
                    {"fast_path_error": attempt_error}
                    if attempted and attempt_error
                    else {}
                ),
            }

    def _dispatch_sweep_batch(self, key, items) -> list:
        """One kernel launch for a micro-batch of folded requests.

        ``items`` are ``(op, snap, implicit_mask, grid)`` tuples sharing
        one snapshot generation, served semantics, and kernel family —
        ``op`` is ``"sweep"`` or ``"explain"``.  Scenario rows from ALL
        members concatenate along the existing scenario axis (different
        pod specs, different tenants — the key already guarantees the
        dispatch is semantically identical), launch once, and scatter
        back per request.  A batch of one takes EXACTLY the solo path,
        so batching a single request is bit-identical (and observably
        identical) to no batching at all.

        * all-sweep batches dispatch **async** (``sync=False``): members
          receive ``jax.Array`` slices and block on the device->host
          fetch only at response-build time (``fetch_overlap`` phase),
          so the launch overlaps the next batch's window;
        * batches containing an explain ride the fused
          ``sweep+explain`` super-kernel — sweep members read the
          fused totals (pinned bit-identical to the sweep kernel's),
          explain members take ``[S, N]`` row slices of the one
          per-node result.
        """
        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            last_dispatch_fast_path,
            sweep_explain_snapshot_auto,
            sweep_snapshot_auto,
        )

        _generation, _semantics, kernel_req = key
        _op0, snap, mask, _grid0 = items[0]
        if len(items) == 1:
            op, _, _, grid = items[0]
            if op == "explain":
                from kubernetesclustercapacity_tpu.explain import (
                    explain_snapshot,
                )

                result = explain_snapshot(
                    snap, grid, mode=snap.semantics, node_mask=mask
                )
                return [(result, "explain")]
            totals, sched, kernel = sweep_snapshot_auto(
                snap, grid, mode=snap.semantics, kernel=kernel_req,
                node_mask=mask,
            )
            attempted, err = last_dispatch_fast_path()
            return [(totals, sched, kernel, attempted, err)]
        grids = [item[3] for item in items]
        combined = ScenarioGrid(
            cpu_request_milli=np.concatenate(
                [g.cpu_request_milli for g in grids]
            ),
            mem_request_bytes=np.concatenate(
                [g.mem_request_bytes for g in grids]
            ),
            replicas=np.concatenate([g.replicas for g in grids]),
        )
        if any(item[0] == "explain" for item in items):
            totals, sched, full, kernel = sweep_explain_snapshot_auto(
                snap, combined, mode=snap.semantics, node_mask=mask
            )
            attempted, err = False, None
        else:
            totals, sched, kernel = sweep_snapshot_auto(
                snap, combined, mode=snap.semantics, kernel=kernel_req,
                node_mask=mask, sync=False,
            )
            attempted, err = last_dispatch_fast_path()
            full = None
        # One shared sync for the whole batch when the dispatch really
        # went async (jax.Array futures): members scatter host-slicing
        # views, never per-member device slices (each of those would be
        # its own XLA slice program — a compile per fold composition).
        fetch = (
            _FoldedFetch(totals, sched)
            if not isinstance(totals, np.ndarray)
            else None
        )
        out, offset = [], 0
        for (op, _, _, g) in items:
            end = offset + g.size
            if op == "explain":
                from kubernetesclustercapacity_tpu.explain import (
                    ExplainResult,
                )

                out.append((
                    ExplainResult(
                        snapshot=snap,
                        mode=full.mode,
                        cpu_request_milli=full.cpu_request_milli[
                            offset:end
                        ],
                        mem_request_bytes=full.mem_request_bytes[
                            offset:end
                        ],
                        replicas=full.replicas[offset:end],
                        fits=full.fits[offset:end],
                        binding=full.binding[offset:end],
                        cpu_fit=full.cpu_fit[offset:end],
                        mem_fit=full.mem_fit[offset:end],
                        slots=full.slots[offset:end],
                        node_mask=full.node_mask,
                    ),
                    kernel,
                ))
            elif fetch is not None:
                out.append(
                    (
                        _FoldedSlice(fetch, 0, offset, end),
                        _FoldedSlice(fetch, 1, offset, end),
                        kernel,
                        attempted,
                        err,
                    )
                )
            else:
                out.append(
                    (
                        totals[offset:end],
                        sched[offset:end],
                        kernel,
                        attempted,
                        err,
                    )
                )
            offset = end
        return out

    def _sweep_with_priorities(
        self, msg, snap, grid, implicit_mask, fixture: dict | None
    ) -> dict:
        """The preemption axis over the wire: scenario ``s`` evicts pods
        below ``priorities[s]`` — delegated to
        :meth:`CapacityModel.sweep_preemption` with the server's cached
        table seeded, so the gate/shape/mask rules live in ONE place
        (the model's bare-spec taint mask equals the implicit mask the
        plain sweep applies)."""
        from kubernetesclustercapacity_tpu.models import CapacityModel

        if snap.semantics != "strict":
            raise ValueError(
                "priorities require strict semantics (the reference has "
                "no priority concept)"
            )
        if fixture is None:
            raise ValueError(
                "priorities need a fixture-backed source (pod priorities "
                "are not part of the dense snapshot)"
            )
        model = CapacityModel(
            snap, mode="strict", fixture=fixture,
            priority_table=self._priority_table_for(fixture, snap),
        )
        totals, sched = model.sweep_preemption(grid, msg["priorities"])
        return {
            "totals": totals.tolist(),
            "schedulable": sched.tolist(),
            "scenarios": grid.size,
            "kernel": "exact-preemption",
        }

    def _op_sweep_multi(
        self, msg: dict, snap: ClusterSnapshot, implicit_mask=None
    ) -> dict:
        """R-resource grid sweep (config 4): ``resources`` names the rows
        (cpu milli / memory bytes / extended columns), ``requests`` is the
        ``[S][R]`` request matrix, ``replicas`` the ``[S]`` targets.  Same
        implicit-taint-mask policy as the 2-resource sweep."""
        from kubernetesclustercapacity_tpu.ops.pallas_multi import (
            sweep_multi_auto,
        )
        from kubernetesclustercapacity_tpu.scenario import MultiResourceGrid

        try:
            grid = MultiResourceGrid(
                resources=tuple(msg["resources"]),
                requests=np.asarray(msg["requests"]),
                replicas=np.asarray(
                    msg.get("replicas", [1] * len(msg["requests"]))
                ),
            )
            grid.validate()
            alloc_rn, used_rn = snap.resource_matrix(grid.resources)
        except (ScenarioError, KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad multi-resource grid: {e}") from e
        totals, sched, kernel = sweep_multi_auto(
            alloc_rn,
            used_rn,
            snap.alloc_pods,
            snap.pods_count,
            snap.healthy,
            grid.requests,
            grid.replicas,
            mode=snap.semantics,
            node_masks=implicit_mask,
            force_exact=(msg.get("kernel", "auto") == "exact"),
        )
        return {
            "totals": totals.tolist(),
            "schedulable": sched.tolist(),
            "scenarios": grid.size,
            "resources": list(grid.resources),
            "kernel": kernel,
        }

    def replace_snapshot(
        self,
        snapshot: ClusterSnapshot,
        fixture: dict | None = None,
        *,
        fixture_source=None,
        warm: bool = False,
        generation: int | None = None,
    ) -> None:
        """Atomically swap the served snapshot (e.g. from a live follower).

        ``fixture_source`` is an optional zero-arg callable yielding the
        raw fixture for THIS snapshot on demand (the follower's
        ``fixture_view``).  Publishers that swap snapshots at watch-event
        rates pass the source instead of a materialized fixture, so the
        O(N) deep copy is paid only when a fixture-consuming request
        (drain, anti-affinity, priority, reference-cpu) actually
        arrives — without it, those requests would see ``fixture=None``
        forever after the first publish.

        Consistency: a lazily-pulled fixture reflects the follower's
        CURRENT state, which may lead the served snapshot by events that
        arrived since this publish — bounded by the coalescer window,
        since those same events schedule the next snapshot swap.  The
        store-fed ``update`` path keeps its exact pairing (fixture
        rebuilt from the same store state the snapshot came from).

        ``warm=True`` pre-stages the new snapshot's device arrays in the
        device cache AFTER the swap (the coalescer publish path passes
        it, so warming runs on the coalescer's worker thread — a relist
        never stalls a reader on a cold upload).  The retired snapshot's
        cache entries are invalidated either way, so swapped-out device
        buffers free promptly.

        ``generation`` (plane replicas only) ADOPTS the given generation
        number instead of incrementing the local counter, so a replica
        stamps its responses with the LEADER's generation — the number
        the client-side monotonicity watermark compares across
        endpoints.  A regressing generation is refused: the plane
        stream is ordered, so a lower number here means a confused
        publisher, and serving it would let watermarked clients observe
        time running backwards.
        """
        from kubernetesclustercapacity_tpu import devcache

        mask = _implicit_taint_mask(snapshot)
        with self._lock:
            if generation is not None:
                generation = int(generation)
                if generation < self._generation:
                    raise ValueError(
                        f"generation must not regress: {generation} < "
                        f"served {self._generation}"
                    )
            old = self.snapshot
            self.snapshot = snapshot
            self.fixture = fixture
            self._fixture_source = fixture_source
            self._store = None  # stale after a wholesale replace
            self._fixture_dirty = False
            self._implicit_mask = mask
            if generation is None:
                self._generation += 1
                generation = self._generation
            else:
                self._generation = generation
        if old is not snapshot and warm and devcache.enabled() \
                and devcache.donate_enabled():
            # Donated resident publish: the retired generation's staged
            # exact columns carry over where unchanged and re-upload
            # through the donate_argnums jit where not — a watch event
            # that touched a handful of nodes re-transfers only those
            # columns instead of the fleet.  KCCAP_DONATE=0 restores
            # the invalidate+warm publish below byte-for-byte.
            devcache.CACHE.stage_replace(old, snapshot)
            devcache.CACHE.warm(snapshot, forms=("pallas",))
        else:
            if old is not snapshot:
                devcache.CACHE.invalidate(old)
            if warm:
                devcache.CACHE.warm(snapshot)
        # Timeline observation rides the SAME publisher thread as the
        # warm pre-stage (the coalescer's worker under -follow), AFTER
        # warming — the watchlist evaluation hits a warm device cache,
        # and a query dispatcher never pays for either.  The audit
        # record follows for the same reason (the diff walk is O(N)
        # host work).
        self._observe_timeline(snapshot, generation)
        self._audit_generation(snapshot, generation)

    def _require_leader(self) -> None:
        """Mutations against a plane REPLICA are refused before any
        work: the replica's state is the leader's stream, and a local
        mutation would silently fork it (and be clobbered by the next
        frame).  The ``not_leader`` wire code tells multi-endpoint
        clients to re-route, not to fail."""
        if self._plane_role == "replica":
            from kubernetesclustercapacity_tpu.resilience import (
                NotLeaderError,
            )

            raise NotLeaderError(
                "this server is a plane replica (read-only view of the "
                "leader's snapshot stream); send mutations to the leader"
            )

    def _op_reload(self, msg: dict, snap: ClusterSnapshot) -> dict:
        """``snap`` is the dispatch's lock-captured snapshot — reading
        ``self.snapshot`` here could tear against a concurrent reload."""
        self._require_leader()
        with self._lock:
            if self._fixture_source is not None:
                # Same rule as update: the next coalesced publish would
                # silently clobber the reloaded state — and dropping
                # _fixture_source here would re-open the update guard.
                raise ValueError(
                    "this server follows a live cluster (-follow); "
                    "reload is only for file-backed servers"
                )
        path = msg["path"]
        # An unspecified semantics keeps the CURRENTLY-SERVED packing (a
        # plain reload must not flip a strict server to reference and
        # strand its extended/sweep_multi clients); the extended columns
        # default to the served set under the SAME resolved semantics —
        # an explicit switch to reference deliberately drops them, and an
        # explicit extended_resources list always wins.
        semantics = msg.get("semantics") or snap.semantics
        if msg.get("extended_resources") is not None:
            extended = tuple(msg["extended_resources"])
        elif semantics == "strict":
            extended = tuple(sorted(snap.extended))
        else:
            extended = ()
        if self._reload_roots:
            import os

            real = os.path.realpath(path)
            inside = False
            for root in self._reload_roots:
                try:
                    inside = os.path.commonpath([real, root]) == root
                except ValueError:  # mixed absolute/relative or drives
                    inside = False
                if inside:
                    break
            if not inside:
                raise PermissionError(
                    f"reload path {path!r} outside the allowed roots"
                )
            path = real
        new_fixture, new_snap, _ = resolve_source(
            path, semantics, extended_resources=extended
        )
        self.replace_snapshot(new_snap, new_fixture)
        return {"nodes": new_snap.n_nodes, "semantics": new_snap.semantics}

    def _op_update(self, msg: dict) -> dict:
        """Apply watch-style node/pod events to the served snapshot.

        Incremental (per-row recompute via :class:`ClusterStore`) — the
        informer analog of the reference's full re-walk.  Events apply in
        order; on a bad event the ops before it stay applied and the served
        snapshot/fixture are re-synced to the store before the error
        surfaces.
        """
        from kubernetesclustercapacity_tpu.store import ClusterStore

        self._require_leader()
        events = msg.get("events")
        if not isinstance(events, list):
            raise ValueError("update needs an 'events' list")
        with self._lock:
            if self._fixture_source is not None:
                # A follower feeds this server: an op-side store would be
                # clobbered by the next coalesced publish, silently
                # discarding the client's events.  The cluster itself is
                # the write surface here.
                raise ValueError(
                    "this server follows a live cluster (-follow); "
                    "update events must go to the cluster, not the server"
                )
            if self._store is None:
                if self.fixture is None:
                    raise ValueError(
                        "update needs a fixture-backed source (.json); "
                        ".npz checkpoints carry no raw objects to update"
                    )
                self._store = ClusterStore(
                    self.fixture,
                    semantics=self.snapshot.semantics,
                    extended_resources=tuple(sorted(self.snapshot.extended)),
                )
            old = self.snapshot
            try:
                self._store.apply(events)
            finally:
                snap = self.snapshot = self._store.snapshot()
                self._fixture_dirty = True  # rebuilt on demand (cpu fit)
                self._implicit_mask = _implicit_taint_mask(snap)
                self._generation += 1
                generation = self._generation
        if old is not snap:
            from kubernetesclustercapacity_tpu import devcache

            devcache.CACHE.invalidate(old)
        # update is a mutation op (never the query hot path): observing
        # on its dispatch thread keeps the record synchronous with the
        # event batch that produced the generation.
        self._observe_timeline(snap, generation)
        self._audit_generation(snap, generation)
        return {
            "nodes": snap.n_nodes,
            "healthy_nodes": int(np.sum(snap.healthy)),
            "applied": len(events),
        }


def main(argv=None) -> int:
    """``python -m kubernetesclustercapacity_tpu.service.server -snapshot ... -port N``"""
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="kccap-server")
    p.add_argument("-snapshot", default=None,
                   help="fixture .json / checkpoint .npz to serve")
    p.add_argument("-follow", action="store_true",
                   help="serve a live cluster and stay synced (list+watch)")
    p.add_argument("-kubeconfig", default=None,
                   help="kubeconfig for -follow (default: $KUBECONFIG or "
                        "$HOME/.kube/config)")
    p.add_argument("-port", type=int, default=7077)
    p.add_argument("-host", default="127.0.0.1")
    p.add_argument("-semantics", choices=("reference", "strict"),
                   default=None)
    p.add_argument("-extended-resources", default="",
                   dest="extended_resources", metavar="NAMES",
                   help="comma-separated extra resource columns to pack "
                        "(strict semantics; e.g. nvidia.com/gpu,"
                        "ephemeral-storage) — enables sweep_multi over them")
    p.add_argument("-coalesce-ms", type=int, default=100, dest="coalesce_ms",
                   help="min interval between snapshot repacks under "
                        "-follow churn (0 = repack on every event)")
    p.add_argument("-auth-token-file", default=None, dest="auth_token_file",
                   help="file holding the shared bearer token; when set (or "
                        "$KCCAP_AUTH_TOKEN is), every op except ping must "
                        "carry it")
    p.add_argument("-max-inflight", type=int, default=8, dest="max_inflight",
                   help="max concurrently-executing compute requests "
                        "(fit/sweep/place/drain/topology_spread/plan)")
    p.add_argument("-reload-root", action="append", default=[],
                   dest="reload_roots", metavar="DIR",
                   help="restrict reload paths to this directory "
                        "(repeatable; default: unrestricted)")
    p.add_argument("-metrics-port", type=int, default=0, dest="metrics_port",
                   metavar="PORT",
                   help="serve Prometheus /metrics and /healthz on this "
                        "port (0 = disabled); binds the -host address")
    p.add_argument("-profile-hz", type=float, default=0.0,
                   dest="profile_hz", metavar="HZ",
                   help="continuous-profiler sampling rate (0 = "
                        "KCCAP_PROFILE_HZ or the 29 Hz default); the "
                        "profiler itself arms with the server unless "
                        "KCCAP_PROFILER=0, and serves collapsed "
                        "flamegraphs at /debug/profile?seconds=N on "
                        "the metrics port")
    p.add_argument("-device-budget-bytes", type=int, default=0,
                   dest="device_budget_bytes", metavar="BYTES",
                   help="device-memory budget: when the ledger's live "
                        "staged bytes exceed this, healthz carries a "
                        "budget_breached signal and the doctor's "
                        "device-memory line FAILs (0 = no budget)")
    p.add_argument("-trace-log", default=None, dest="trace_log",
                   metavar="PATH",
                   help="append one JSONL span per dispatched request "
                        "(trace_id, op, duration, status) to PATH")
    p.add_argument("-trace-log-max-bytes", type=int, default=0,
                   dest="trace_log_max_bytes", metavar="N",
                   help="rotate the -trace-log file to PATH.1 once it "
                        "exceeds N bytes (0 = unbounded)")
    p.add_argument("-trace-sample", default="always", dest="trace_sample",
                   metavar="SPEC",
                   help="tail-based sampling policy for -trace-log span "
                        "bodies: always | p99-breach | errors | rate:N "
                        "(ids still propagate for every request; the "
                        "keep/drop decision happens at request END so "
                        "breaching requests keep their whole span tree)")
    p.add_argument("-flight-records", type=int, default=256,
                   dest="flight_records", metavar="K",
                   help="flight-recorder depth: remember the last K "
                        "dispatched requests (served by the dump op)")
    p.add_argument("-flight-dump", default=None, dest="flight_dump",
                   metavar="PATH",
                   help="append the flight recorder as JSONL to PATH "
                        "whenever a dispatch raises")
    p.add_argument("-batch-window-ms", type=float, default=1.0,
                   dest="batch_window_ms", metavar="MS",
                   help="micro-batch concurrent sweeps of one snapshot "
                        "generation for up to MS milliseconds into one "
                        "kernel launch (0 = dispatch every sweep solo)")
    p.add_argument("-batch-max", type=int, default=32, dest="batch_max",
                   metavar="N",
                   help="max requests per micro-batch (a full batch "
                        "dispatches before the window closes)")
    p.add_argument("-node-bucket-floor", type=int, default=0,
                   dest="node_bucket_floor", metavar="N",
                   help="floor of the node-axis shape-bucket ladder "
                        "(node counts pad to the next power of two >= "
                        "the floor, so ±1-node churn reuses compiled "
                        "kernels; 0 = keep the default/env setting)")
    p.add_argument("-group-min-count", type=int, default=0,
                   dest="group_min_count", metavar="K",
                   help="minimum mean nodes-per-group for the node-shape"
                        "-compressed (grouped) dispatch to engage "
                        "(KCCAP_GROUPING=0 disables grouping entirely; "
                        "0 = keep the default/KCCAP_GROUP_MIN_COUNT "
                        "setting)")
    p.add_argument("-watch", default=None, metavar="FILE",
                   help="watchlist (YAML/JSON) of named scenarios the "
                        "capacity timeline re-evaluates on every snapshot "
                        "publish; entries with min_replicas arm the "
                        "ok/breached/recovered alert machine (enables the "
                        "timeline op and kccap_watch_* gauges)")
    p.add_argument("-timeline-depth", type=int, default=0,
                   dest="timeline_depth", metavar="K",
                   help="keep a capacity timeline of the last K snapshot "
                        "generations (served by the timeline op; 0 = "
                        "disabled unless -watch is given, which implies 64)")
    p.add_argument("-timeline-log", default=None, dest="timeline_log",
                   metavar="PATH",
                   help="append one JSONL line per observed generation "
                        "and per watch alert transition to PATH (the "
                        "flight-recorder-style durable capacity history)")
    p.add_argument("-log-json", default=None, dest="log_json",
                   metavar="PATH",
                   help="structured request logging: append one JSON "
                        "line per dispatched request (op, trace_id, "
                        "span_id, generation, latency_ms, status) to "
                        "PATH; span_id joins these lines to -trace-log "
                        "spans")
    p.add_argument("-log-json-max-bytes", type=int, default=0,
                   dest="log_json_max_bytes", metavar="N",
                   help="rotate the -log-json file to PATH.1 once it "
                        "exceeds N bytes (0 = unbounded) — same "
                        "one-deep rotation as -trace-log-max-bytes")
    p.add_argument("-audit-dir", default=None, dest="audit_dir",
                   metavar="DIR",
                   help="durable audit log: append JSONL segments to "
                        "DIR recording every snapshot generation "
                        "(invertible diffs + periodic checkpoints, "
                        "digest-chained) and every answering/mutating "
                        "request (full args + result digest) — replay "
                        "offline with kccap -replay DIR")
    p.add_argument("-audit-max-bytes", type=int, default=8 << 20,
                   dest="audit_max_bytes", metavar="N",
                   help="rotate audit segments once they exceed N "
                        "bytes (default 8 MiB)")
    p.add_argument("-audit-checkpoint-every", type=int, default=16,
                   dest="audit_checkpoint_every", metavar="K",
                   help="write a full-snapshot checkpoint every K "
                        "generations (bounds replay cost; default 16)")
    p.add_argument("-shadow-sample-rate", type=float, default=0.0,
                   dest="shadow_sample_rate", metavar="FRACTION",
                   help="re-check this fraction of live sweep "
                        "responses against the pure-Python oracle, off "
                        "the request path (0 = off); a divergence "
                        "flips /healthz, trips the shadow alert, and "
                        "writes a repro bundle")
    p.add_argument("-shadow-bundle", default=None, dest="shadow_bundle",
                   metavar="PATH",
                   help="append shadow-divergence repro bundles as "
                        "JSONL to PATH (default: "
                        "<audit-dir>/shadow-divergence.jsonl when "
                        "-audit-dir is set)")
    p.add_argument("-slo", default=None, metavar="FILE",
                   help="SLO file (YAML/JSON): latency objectives "
                        "('p99 < 80ms', per op or all ops) and "
                        "availability objectives ('99.9%%') evaluated "
                        "as multi-window error-budget burn rates over "
                        "the server's own request metrics; a fast burn "
                        "flips /healthz to 503 and the kccap_slo_* "
                        "gauges (enables the slo op / kccap "
                        "-slo-status)")
    p.add_argument("-slo-log", default=None, dest="slo_log",
                   metavar="PATH",
                   help="append one JSONL line per SLO alert "
                        "transition (ok→breached→recovered) to PATH")
    p.add_argument("-slo-eval-s", type=float, default=5.0,
                   dest="slo_eval_s", metavar="SECONDS",
                   help="background SLO evaluation cadence (keeps the "
                        "burn-rate gauges fresh for scrapers that "
                        "never issue the slo op; the slo op and "
                        "/healthz also evaluate on read)")
    p.add_argument("-plane-port", type=int, default=0, dest="plane_port",
                   metavar="PORT",
                   help="serve the replication plane on this port "
                        "(LEADER mode): every published snapshot "
                        "generation fans out to subscribed replica "
                        "servers as digest-chained checkpoint/diff "
                        "frames (0 = no plane)")
    p.add_argument("-plane-leader", default=None, dest="plane_leader",
                   metavar="HOST:PORT",
                   help="follow another server's replication plane "
                        "(REPLICA mode): stage each digest-verified "
                        "generation from the leader's stream and serve "
                        "it read-only, stamped with the leader's "
                        "generation numbers")
    p.add_argument("-plane-stale-after-s", type=float, default=10.0,
                   dest="plane_stale_after_s", metavar="SECONDS",
                   help="replica staleness bound: with no plane frame "
                        "(heartbeats included) for this long, the "
                        "replica reports itself stale via info/healthz "
                        "so clients route around it")
    p.add_argument("-admission-max-concurrent", type=int, default=0,
                   dest="admission_max_concurrent", metavar="N",
                   help="admission control: at most N compute requests "
                        "admitted at once; excess queues briefly then "
                        "sheds with the retryable-elsewhere "
                        "'overloaded' error (0 = no concurrency gate)")
    p.add_argument("-admission-rps", type=float, default=0.0,
                   dest="admission_rps", metavar="RPS",
                   help="admission control: token-bucket cap on "
                        "admitted compute requests per second "
                        "(0 = no rps cap)")
    p.add_argument("-admission-burst", type=float, default=0.0,
                   dest="admission_burst", metavar="N",
                   help="token-bucket burst capacity for -admission-rps "
                        "(0 = max(rps, 1))")
    p.add_argument("-admission-price-budget", type=float, default=0.0,
                   dest="admission_price_budget", metavar="SHARE",
                   help="shed-by-shadow-price: while the last CERTIFIED "
                        "optimize solve prices more than this share of "
                        "capacity (its shadow-price capacity_share in "
                        "(0, 1]), compute requests shed with the "
                        "retryable-elsewhere 'overloaded' error "
                        "(0 = no price gate; the optimize op itself is "
                        "never price-gated)")
    p.add_argument("-tenants", default=None, metavar="FILE",
                   help="tenant map (YAML/JSON): named tenants with "
                        "per-tenant auth tokens, rps caps, concurrency "
                        "quotas, and weighted-fair admission weights; "
                        "requests are attributed by token (old "
                        "tenantless clients become 'default'), quota "
                        "overage sheds with the authoritative "
                        "'tenant_quota' error, and kccap_tenant_* "
                        "metrics follow the identity with bounded "
                        "cardinality (KCCAP_TENANCY=0 disables)")
    p.add_argument("-drain-timeout-s", type=float, default=10.0,
                   dest="drain_timeout_s", metavar="SECONDS",
                   help="graceful drain bound (SIGTERM/SIGINT or the "
                        "drain_server op): stop accepting compute/"
                        "mutation ops, wait up to this long for "
                        "in-flight work, emit the final drain record, "
                        "then exit")
    args = p.parse_args(argv)

    import os as _os

    # `or None`: an empty-but-set env var must not enable auth with an
    # empty token (which would lock out every client).
    auth_token = _os.environ.get("KCCAP_AUTH_TOKEN") or None
    if args.auth_token_file:
        try:
            with open(args.auth_token_file, encoding="utf-8") as fh:
                auth_token = fh.read().strip()
        except OSError as e:
            print(f"ERROR : cannot read auth token file: {e}",
                  file=sys.stderr)
            return 1
        if not auth_token:
            print("ERROR : auth token file is empty", file=sys.stderr)
            return 1

    extended = tuple(
        r.strip() for r in args.extended_resources.split(",") if r.strip()
    )
    # One process registry feeds every layer — follower sync counters,
    # server request metrics, the fused-path breaker (module-global on
    # the same default registry) — so the scrape is the whole story.
    from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY

    follower = None
    try:
        if args.follow:
            # The packers enforce the strict-only extended-columns rule as
            # the backstop; checking argv here too avoids paying a full
            # live-cluster LIST before a config error knowable up front.
            if extended and (args.semantics or "reference") != "strict":
                raise ValueError(
                    "-extended-resources requires -semantics strict "
                    "(reference semantics has no extended-column concept)"
                )
            from kubernetesclustercapacity_tpu.follower import ClusterFollower

            follower = ClusterFollower(
                args.kubeconfig,
                semantics=args.semantics or "reference",
                extended_resources=extended,
                registry=REGISTRY,
            ).start(watch=False)
            snap, fixture = follower.snapshot(), follower.fixture_view()
        elif args.snapshot:
            fixture, snap, _ = resolve_source(
                args.snapshot, args.semantics, extended_resources=extended
            )
        else:
            raise ValueError("one of -snapshot or -follow is required")
    except Exception as e:
        print(f"ERROR : {e}", file=sys.stderr)
        return 1
    from kubernetesclustercapacity_tpu.telemetry.tracing import TraceLog

    trace_log = None
    if args.trace_log:
        trace_log = TraceLog(
            args.trace_log, max_bytes=max(args.trace_log_max_bytes, 0)
        )
    try:
        from kubernetesclustercapacity_tpu.telemetry.tracectx import (
            parse_sample_spec,
        )

        parse_sample_spec(args.trace_sample)
    except ValueError as e:
        print(f"ERROR : {e}", file=sys.stderr)
        if follower is not None:
            follower.stop()
        return 1
    # Process self-telemetry (RSS/fds/threads/GC + build info) on the
    # same registry the scrape serves — no-op under KCCAP_TELEMETRY=0.
    from kubernetesclustercapacity_tpu.telemetry.process import (
        register_process_metrics,
    )

    register_process_metrics(REGISTRY)
    # The continuous profiler rides the whole serve (KCCAP_PROFILER=0
    # pins it to zero threads + zero registry calls), and the device
    # ledger's optional budget arms here.
    from kubernetesclustercapacity_tpu.telemetry.profiler import (
        start_profiler,
        stop_profiler,
    )

    profiler = start_profiler(
        args.profile_hz if args.profile_hz > 0 else None
    )
    if args.device_budget_bytes > 0:
        _memledger.LEDGER.set_budget(args.device_budget_bytes)
    if args.node_bucket_floor > 0:
        from kubernetesclustercapacity_tpu import devcache

        devcache.set_node_bucket_floor(args.node_bucket_floor)
    if args.group_min_count > 0:
        from kubernetesclustercapacity_tpu import snapshot as _snapshot_mod

        _snapshot_mod.set_group_min_count(args.group_min_count)
    timeline = None
    if args.watch or args.timeline_depth > 0 or args.timeline_log:
        from kubernetesclustercapacity_tpu.timeline import (
            CapacityTimeline,
            WatchError,
            load_watchlist,
        )

        watches = ()
        if args.watch:
            try:
                watches = load_watchlist(args.watch)
            except (OSError, WatchError) as e:
                print(f"ERROR : bad watchlist: {e}", file=sys.stderr)
                if follower is not None:
                    follower.stop()
                return 1
        timeline = CapacityTimeline(
            watches,
            depth=args.timeline_depth if args.timeline_depth > 0 else 64,
            registry=REGISTRY,
            log=args.timeline_log,
        )
    request_log = None
    if args.log_json:
        request_log = TraceLog(
            args.log_json, max_bytes=max(args.log_json_max_bytes, 0)
        )
    audit_log = None
    if args.audit_dir:
        from kubernetesclustercapacity_tpu.audit import AuditLog

        try:
            audit_log = AuditLog(
                args.audit_dir,
                segment_max_bytes=max(args.audit_max_bytes, 1),
                checkpoint_every=max(args.audit_checkpoint_every, 1),
                registry=REGISTRY,
            )
        except OSError as e:
            print(f"ERROR : cannot open audit dir: {e}", file=sys.stderr)
            if follower is not None:
                follower.stop()
            return 1
    shadow = None
    if args.shadow_sample_rate > 0:
        from kubernetesclustercapacity_tpu.audit import ShadowSampler

        bundle = args.shadow_bundle
        if bundle is None and args.audit_dir:
            bundle = _os.path.join(
                args.audit_dir, "shadow-divergence.jsonl"
            )
        try:
            shadow = ShadowSampler(
                args.shadow_sample_rate,
                registry=REGISTRY,
                bundle_path=bundle,
                audit_log=audit_log,
            )
        except ValueError as e:
            print(f"ERROR : {e}", file=sys.stderr)
            if follower is not None:
                follower.stop()
            return 1
    slo_monitor = None
    if args.slo:
        from kubernetesclustercapacity_tpu.telemetry.slo import (
            SLOError,
            SLOMonitor,
            load_slos,
        )

        try:
            slo_monitor = SLOMonitor(
                load_slos(args.slo),
                registry=REGISTRY,
                log=args.slo_log,
            ).start(max(args.slo_eval_s, 0.5))
        except (OSError, SLOError) as e:
            print(f"ERROR : bad SLO file: {e}", file=sys.stderr)
            if follower is not None:
                follower.stop()
            return 1
    tenants = None
    if args.tenants:
        from kubernetesclustercapacity_tpu.service import tenancy

        if not tenancy.enabled():
            # The escape hatch beats the flag: KCCAP_TENANCY=0 restores
            # the exact pre-tenancy single-queue admission path even
            # when a map is configured.
            print(
                "WARN  : -tenants ignored (KCCAP_TENANCY=0)",
                file=sys.stderr,
            )
        else:
            try:
                tenants = tenancy.load_tenants(args.tenants)
            except (OSError, tenancy.TenancyError) as e:
                print(
                    f"ERROR : bad tenant map: {e}", file=sys.stderr
                )
                if follower is not None:
                    follower.stop()
                return 1
    admission = None
    if (
        args.admission_max_concurrent > 0
        or args.admission_rps > 0
        or args.admission_price_budget > 0
        or tenants is not None
    ):
        from kubernetesclustercapacity_tpu.service.plane import (
            AdmissionController,
        )

        if not 0.0 <= args.admission_price_budget <= 1.0:
            print(
                "ERROR : -admission-price-budget must be in [0, 1]",
                file=sys.stderr,
            )
            if follower is not None:
                follower.stop()
            return 1
        admission = AdmissionController(
            max_concurrent=max(args.admission_max_concurrent, 0),
            rps=max(args.admission_rps, 0.0),
            burst=args.admission_burst if args.admission_burst > 0 else None,
            price_budget=args.admission_price_budget,
            registry=REGISTRY,
            tenants=tenants,
        )
    plane_pub = None
    if args.plane_port:
        if args.plane_leader:
            print(
                "ERROR : -plane-port (leader) and -plane-leader "
                "(replica) are mutually exclusive",
                file=sys.stderr,
            )
            if follower is not None:
                follower.stop()
            return 1
        from kubernetesclustercapacity_tpu.service.plane import (
            PlanePublisher,
        )

        try:
            plane_pub = PlanePublisher(
                host=args.host, port=args.plane_port,
                token=auth_token, registry=REGISTRY,
                trace_log=trace_log,
            )
        except OSError as e:
            print(f"ERROR : cannot bind plane port: {e}", file=sys.stderr)
            if follower is not None:
                follower.stop()
            return 1
    server = CapacityServer(
        snap, host=args.host, port=args.port, fixture=fixture,
        auth_token=auth_token, max_inflight=args.max_inflight,
        reload_roots=tuple(args.reload_roots),
        # -follow: the follower's retry/backoff/degradation counters ride
        # the info op, so a client can see a struggling sync loop.
        stats_source=follower.stats if follower is not None else None,
        registry=REGISTRY,
        trace_log=trace_log,
        trace_sample=args.trace_sample,
        flight_records=max(args.flight_records, 1),
        flight_dump_path=args.flight_dump,
        batch_window_ms=max(args.batch_window_ms, 0.0),
        batch_max=max(args.batch_max, 1),
        timeline=timeline,
        request_log=request_log,
        audit_log=audit_log,
        shadow=shadow,
        slo=slo_monitor,
        admission=admission,
        plane=plane_pub,
        drain_timeout_s=max(args.drain_timeout_s, 0.0),
        tenants=tenants,
    )
    subscriber = None
    if args.plane_leader:
        if args.follow:
            print(
                "ERROR : a plane replica (-plane-leader) cannot also "
                "-follow a cluster (its state IS the leader's stream)",
                file=sys.stderr,
            )
            server.shutdown()
            return 1
        from kubernetesclustercapacity_tpu.service.plane import (
            PlaneSubscriber,
        )

        host_s, _, port_s = args.plane_leader.rpartition(":")
        if not host_s or not port_s.isdigit():
            print(
                f"ERROR : bad -plane-leader {args.plane_leader!r} "
                "(want HOST:PORT)",
                file=sys.stderr,
            )
            server.shutdown()
            return 1
        subscriber = PlaneSubscriber(
            (host_s, int(port_s)),
            server,
            token=auth_token,
            stale_after_s=max(args.plane_stale_after_s, 0.1),
            registry=REGISTRY,
            trace_log=trace_log,
        )
    metrics_server = None
    coalescer_ref: list = []  # filled below; healthz closes over it
    if args.metrics_port:
        from kubernetesclustercapacity_tpu.telemetry.exposition import (
            start_metrics_server,
        )

        def _healthz_status() -> dict:
            # Snapshot freshness evidence for load balancers: the served
            # generation always, and — when a follower feeds this
            # server — how long ago the last full relist completed, so a
            # follower that still answers liveness but stopped syncing
            # is detectable from the scrape side alone.
            out = {"snapshot_generation": server.generation}
            if follower is not None:
                out["follower"] = {
                    "last_relist_age_s": follower.last_relist_age_s(),
                    "fatal": follower.fatal,
                }
            if coalescer_ref:
                out["coalescer"] = coalescer_ref[0].stats()
            if timeline is not None:
                # The capacity story behind the liveness answer: which
                # watches are breached RIGHT NOW, visible to the same
                # scraper that reads the gauges.
                out["timeline"] = timeline.stats()
            if audit_log is not None:
                out["audit"] = audit_log.stats()
            if shadow is not None:
                # The parity story: a diverged shadow oracle is a
                # correctness incident, and the scraper must see it.
                out["shadow"] = shadow.stats()
            if slo_monitor is not None:
                # The latency/availability story: which objectives are
                # burning budget right now — evaluated on read so the
                # probe never reports a stale verdict.
                slo_monitor.evaluate()
                out["slo"] = slo_monitor.stats()
            if plane_pub is not None:
                out["plane"] = plane_pub.stats()
            elif subscriber is not None:
                out["plane"] = subscriber.stats()
            if server.draining:
                out["draining"] = True
            if _memledger.enabled():
                # The device-byte book behind the liveness answer: a
                # reconcile runs on every probe so a sustained leak is
                # caught by the same scraper that reads the gauges.
                try:
                    _memledger.LEDGER.reconcile()
                except Exception:  # noqa: BLE001 - audit != liveness
                    pass
                out["device_memory"] = _memledger.LEDGER.stats()
            if profiler is not None:
                out["profiler"] = profiler.stats()
            return out

        def _overall_healthy() -> bool:
            # /healthz goes 503 the moment the feed is known-dead OR
            # the shadow oracle caught the kernels lying OR an SLO is
            # fast-burning OR a capacity-at-risk watch is breached OR
            # the plane replica went stale OR a drain began: a frozen
            # snapshot, a wrong answer, a missed latency objective, a
            # confidence statement that capacity no longer fits, a
            # bounded-staleness violation, and a deliberate departure
            # are all things a load balancer must route around, not
            # discover later.  (Plain watch breaches stay advisory —
            # they describe the CLUSTER; a CaR breach says the serving
            # tier's own promise "N replicas fit at P95" is broken.)
            if follower is not None and follower.fatal is not None:
                return False
            if shadow is not None and shadow.diverged:
                return False
            if slo_monitor is not None and slo_monitor.fast_burning:
                return False
            if timeline is not None and timeline.car_breached():
                return False
            if timeline is not None and timeline.gang_breached():
                # A breached gang watch is the all-or-nothing analog of
                # a CaR breach: "fewer than N whole gangs fit" is a
                # promise the serving tier can no longer make.
                return False
            if timeline is not None and timeline.forecast_breached():
                # A breached forecast watch says the projected quantile
                # capacity crosses the threshold INSIDE the horizon —
                # the whole value of forecasting is flipping health
                # BEFORE the outage, while a purchase can still land.
                return False
            if subscriber is not None and subscriber.stale:
                return False
            if server.draining:
                return False
            if _memledger.enabled() and (
                _memledger.LEDGER.leaking()
                or _memledger.LEDGER.budget_breached()
            ):
                # A sustained device-memory discrepancy (staged bytes
                # the backend no longer accounts for) or a breached HBM
                # budget: this replica's device footprint can no longer
                # be trusted, and the balancer must see it before the
                # allocator OOMs a kernel.
                return False
            return True

        debug_handlers = (
            {"/debug/profile": profiler.debug_handler}
            if profiler is not None
            else None
        )
        try:
            metrics_server = start_metrics_server(
                REGISTRY,
                host=args.host,
                port=args.metrics_port,
                healthy=_overall_healthy,
                status=_healthz_status,
                debug=debug_handlers,
            )
        except OSError as e:
            print(f"ERROR : cannot bind metrics port: {e}", file=sys.stderr)
            if follower is not None:
                follower.stop()
            server.shutdown()
            return 1
        print(
            f"metrics on http://{metrics_server.address[0]}:"
            f"{metrics_server.address[1]}/metrics",
            file=sys.stderr,
        )
    coalescer = None
    if follower is not None:
        # Watch events are applied to the store per-row (O(1)); snapshot
        # PUBLICATION (an O(N) repack+swap into the server) is coalesced:
        # first event flushes at once, bursts collapse to one trailing
        # repack per -coalesce-ms window.  Queries between pushes serve
        # the last published consistent state.  The raw fixture is left
        # unset — the cpu cross-check backend walks the packed arrays.
        from kubernetesclustercapacity_tpu.service.coalesce import (
            SnapshotCoalescer,
        )

        # A failing publish is fatal to the supervised serve — identical
        # policy to the pre-coalescing wiring, where the exception killed
        # the watch thread: answering queries from a silently frozen
        # snapshot is the one unacceptable outcome.
        publish_fatal: list[str] = []

        def _publish_failed(err: str) -> None:
            publish_fatal.append(err)
            follower.stop()

        coalescer = SnapshotCoalescer(
            lambda: server.replace_snapshot(
                follower.snapshot(),
                # Raw objects on demand only (drain/anti-affinity/
                # priority): the publish itself stays O(arrays).
                fixture_source=follower.fixture_view,
                # Pre-warm the new generation's device arrays on THIS
                # (coalescer-worker) thread: a relist never stalls a
                # reader on a cold host→device upload.
                warm=True,
            ),
            min_interval_s=max(args.coalesce_ms, 0) / 1e3,
            on_error=_publish_failed,
        )
        coalescer_ref.append(coalescer)
        follower.on_event = coalescer.notify
        follower.start_watches()  # after wiring: no event can be missed
    # Graceful shutdown: SIGTERM/SIGINT and the drain_server op all
    # route through begin_drain — stop accepting compute/mutation ops,
    # finish in-flight work, emit the drain record, then stop the serve
    # loop.  The stop runs on its own thread after a short grace so the
    # drain op's reply (and any in-flight replies) flush first.
    import signal as _signal
    import threading as _threading
    import time as _time

    def _stop_serving(record: dict) -> None:
        def _stop() -> None:
            try:
                _time.sleep(0.25)  # let replies flush before teardown
                if follower is not None:
                    follower.stop()
            except Exception as e:  # noqa: BLE001 - shutdown must follow
                print(f"drain teardown: {type(e).__name__}: {e}",
                      file=sys.stderr)
            try:
                server.shutdown()
            except Exception as e:  # noqa: BLE001 - last resort is loud
                print(f"drain shutdown: {type(e).__name__}: {e}",
                      file=sys.stderr)

        print(
            f"drain complete: inflight_at_start="
            f"{record.get('inflight_at_start')} "
            f"drained={record.get('drained')} "
            f"waited_s={record.get('waited_s')}",
            file=sys.stderr,
        )
        _threading.Thread(target=_stop, daemon=True).start()

    server.on_drained = _stop_serving

    def _graceful_exit(signum, frame) -> None:
        print(f"draining on signal {signum} ...", file=sys.stderr)
        _threading.Thread(
            target=server.begin_drain,
            kwargs={"reason": f"signal {signum}"},
            daemon=True,
        ).start()

    try:
        _signal.signal(_signal.SIGTERM, _graceful_exit)
        _signal.signal(_signal.SIGINT, _graceful_exit)
    except ValueError:
        pass  # not the main thread (embedded/test use): signals stay default
    print(
        f"serving {snap.n_nodes} nodes ({snap.semantics}) on "
        f"{server.address[0]}:{server.address[1]}",
        file=sys.stderr,
    )
    try:
        if follower is None:
            server.serve_forever()
        else:
            # Supervised serve: if the follower dies (fatal watch-thread
            # failure, e.g. ReferencePanic), the service must die WITH it —
            # silently answering every query from a snapshot frozen at the
            # failure instant is the one unacceptable outcome.
            server.start()
            while not follower.wait_stopped(1.0):
                pass
            if follower.fatal is not None:
                print(
                    f"ERROR : follower died: {follower.fatal}",
                    file=sys.stderr,
                )
                return 2
            if publish_fatal:
                print(
                    f"ERROR : snapshot publish failed: {publish_fatal[0]}",
                    file=sys.stderr,
                )
                return 2
    except KeyboardInterrupt:
        pass
    finally:
        if subscriber is not None:
            subscriber.stop()
        if plane_pub is not None:
            plane_pub.close()
        if follower is not None:
            follower.stop()
        if coalescer is not None:
            coalescer.stop()
        if metrics_server is not None:
            metrics_server.shutdown()
        if timeline is not None:
            timeline.close()  # flush the -timeline-log JSONL
        if slo_monitor is not None:
            slo_monitor.close()  # stop the evaluator, flush -slo-log
        if shadow is not None:
            shadow.close()
        if audit_log is not None:
            audit_log.close()
        stop_profiler()
        server.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
