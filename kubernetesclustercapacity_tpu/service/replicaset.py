"""Multi-endpoint capacity client: failover, hedging, monotonic reads.

A :class:`~.client.CapacityClient` talks to ONE server; this module
talks to the replicated serving plane (:mod:`.plane`): N endpoints —
typically one leader plus its replicas — behind one call surface.

* **Failover** — each endpoint has its own
  :class:`~..resilience.CircuitBreaker` and health state.  A transport
  failure, an open breaker, or a refuse-before-work error
  (:class:`~..resilience.RetryableElsewhere`: overloaded / draining /
  not-leader) moves the call to the next endpoint.  Refusals are safe
  to retry ANYWHERE — the server provably did no work — so even
  mutations fail over across refusals; a mutation whose transport died
  *mid-call* is never resent (at-most-once, same rule as the
  single-endpoint client).
* **Read-your-generation monotonicity** — every server reply envelope
  carries the generation that answered.  The set keeps a high-water
  mark per client session; an answer stamped OLDER than the watermark
  is discarded (the endpoint is marked stale and the call fails over)
  — a client that has seen generation G never regresses to a replica
  still serving G-1, no matter how routing lands.
* **Hedged reads** — optional, idempotent ops only (mutations are
  NEVER hedged).  If the primary attempt has not answered within the
  hedge delay — adaptive: the p95 of recent call latencies, clamped to
  ``[hedge_min_delay_s, hedge_max_delay_s]`` — a second attempt fires
  on the next healthy endpoint and the first verified answer wins.
  Tail latency becomes min(two samples) at the cost of bounded extra
  load.
* **Capability handshake** — :meth:`probe` reads each endpoint's
  ``info.capabilities``; plane-era features degrade cleanly against
  pre-plane servers (no generation watermark → monotonicity not
  enforced there; :meth:`drain_server` refuses locally instead of
  sending an op the server would not recognize).
"""

from __future__ import annotations

import queue as _queue
import threading
import time

from kubernetesclustercapacity_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExpired,
    RetryableElsewhere,
    RetryPolicy,
)
from kubernetesclustercapacity_tpu.service.client import (
    IDEMPOTENT_OPS,
    CapacityClient,
)

__all__ = ["ReplicaSet", "ReplicaSetError", "StaleReadError", "parse_endpoints"]


class ReplicaSetError(ConnectionError):
    """Every endpoint was tried and none produced a valid answer."""


class StaleReadError(RuntimeError):
    """Every reachable endpoint answered with a generation older than
    the session watermark — the set as a whole has regressed (e.g. the
    only fresh replica died).  Retrying later is reasonable; returning
    the stale answer would violate read-your-generation monotonicity,
    so it is never done."""


def parse_endpoints(spec) -> list[tuple[str, int]]:
    """``"h1:p1,h2:p2"`` / iterable of ``"h:p"`` / ``(h, p)`` pairs →
    endpoint list (the ``kccap -server`` flag grammar)."""
    if isinstance(spec, str):
        spec = [s for s in spec.split(",") if s.strip()]
    out: list[tuple[str, int]] = []
    for item in spec:
        if isinstance(item, str):
            host, _, port_s = item.strip().rpartition(":")
            if not host or not port_s.isdigit():
                raise ValueError(
                    f"bad endpoint {item!r} (want HOST:PORT)"
                )
            out.append((host, int(port_s)))
        else:
            host, port = item
            out.append((str(host), int(port)))
    if not out:
        raise ValueError("ReplicaSet needs at least one endpoint")
    return out


class _Endpoint:
    """One replica: its lazy client, breaker, and health bookkeeping.
    ``lock`` serializes use of the underlying single-connection client
    (concurrent ReplicaSet calls hedge across DIFFERENT endpoints, never
    share one socket)."""

    def __init__(self, addr: tuple[str, int], breaker: CircuitBreaker) -> None:
        self.addr = addr
        self.breaker = breaker
        self.lock = threading.Lock()
        self.client: CapacityClient | None = None
        self.stale = False
        self.draining = False
        # A federation endpoint reporting the set's queried cluster as
        # ``lost`` — demoted like a draining endpoint (it holds no
        # servable view of that cluster, not even a stale one) but still
        # tried last, since it may have resynced.
        self.lost = False
        self.role: str | None = None
        self.capabilities: dict = {}
        self.last_generation: int | None = None

    @property
    def name(self) -> str:
        return f"{self.addr[0]}:{self.addr[1]}"


class ReplicaSet:
    """Call the replicated serving plane as if it were one server.

    ``endpoints`` accepts the :func:`parse_endpoints` grammar.  Each
    call walks the healthy endpoints (sticky: the last endpoint that
    answered goes first) under an overall ``deadline_s`` budget;
    ``rounds`` bounds how many full passes over the set a call may make
    before giving up.  ``hedge=True`` arms hedged reads for idempotent
    ops.  Thread-safe: concurrent calls are serialized per endpoint,
    not per set.
    """

    def __init__(
        self,
        endpoints,
        *,
        token: str | None = None,
        tenant: str | None = None,
        tenant_token: str | None = None,
        deadline_s: float | None = None,
        connect_timeout_s: float = 5.0,
        timeout_s: float | None = 120.0,
        rounds: int = 3,
        retry_backoff: RetryPolicy | None = None,
        breaker_factory=None,
        hedge: bool = False,
        hedge_min_delay_s: float = 0.01,
        hedge_max_delay_s: float = 1.0,
        registry=None,
        trace: bool = False,
        trace_log=None,
        cluster: str | None = None,
    ) -> None:
        """``cluster`` names the federation cluster this set's queries
        concern (endpoints being ``kccap-fed`` servers): :meth:`probe`
        then demotes any endpoint whose federation status reports that
        cluster ``lost`` — the way it demotes a draining endpoint —
        and a typed ``cluster_lost`` refusal mid-call marks it the same
        way while the call retries elsewhere.

        ``tenant``/``tenant_token`` ride every per-endpoint client (see
        :class:`~.client.CapacityClient`).  A ``tenant_quota`` refusal
        is AUTHORITATIVE — every replica enforces the same map — so the
        set surfaces it immediately instead of failing over.

        ``trace_log`` (a path or :class:`~..telemetry.TraceLog`) records
        the set's own spans: one ``rs:{op}`` span per call, with one
        ``rs:attempt`` child per endpoint try carrying the endpoint,
        the hedge/winner flags, and the failover reason — the trace
        form of the failover story the metrics only count."""
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            MetricsRegistry,
        )

        addrs = parse_endpoints(endpoints)
        if breaker_factory is None:
            def breaker_factory(addr):
                return CircuitBreaker(
                    failure_threshold=3,
                    recovery_timeout_s=1.0,
                    name=f"{addr[0]}:{addr[1]}",
                )
        self._endpoints = [_Endpoint(a, breaker_factory(a)) for a in addrs]
        self._token = token
        self._tenant = tenant
        self._tenant_token = tenant_token
        self._deadline_s = deadline_s
        self._connect_timeout = connect_timeout_s
        self._timeout = timeout_s
        self._rounds = max(1, int(rounds))
        self._backoff = (
            retry_backoff
            if retry_backoff is not None
            else RetryPolicy(max_attempts=1, base_delay_s=0.01,
                             max_delay_s=0.25)
        )
        self._hedge = bool(hedge)
        self._hedge_min = float(hedge_min_delay_s)
        self._hedge_max = float(hedge_max_delay_s)
        self._trace = bool(trace)
        if isinstance(trace_log, str):
            from kubernetesclustercapacity_tpu.telemetry.tracing import (
                TraceLog,
            )

            trace_log = TraceLog(trace_log)
        self._trace_log = trace_log
        self._cluster = cluster
        self._lock = threading.Lock()
        self._watermark = 0
        #: Generation stamped on the last successful answer (None until
        #: one arrives) — the chaos suite joins answers to their oracle
        #: snapshot through it.
        self.last_generation: int | None = None
        self._preferred = 0
        self._latencies: list[float] = []  # bounded sample window
        self._closed = False
        self.registry = registry if registry is not None else MetricsRegistry()
        m = self.registry
        self._m_calls = m.counter(
            "kccap_replicaset_calls_total",
            "ReplicaSet calls issued, by op.",
            ("op",),
        )
        self._m_failover = m.counter(
            "kccap_replicaset_failovers_total",
            "Endpoint-to-endpoint failovers, by cause.",
            ("cause",),
        )
        self._m_hedges = m.counter(
            "kccap_replicaset_hedges_total",
            "Hedged (secondary) attempts launched.",
        )
        self._m_hedge_wins = m.counter(
            "kccap_replicaset_hedge_wins_total",
            "Calls won by the hedged attempt.",
        )
        self._m_stale = m.counter(
            "kccap_replicaset_stale_rejected_total",
            "Answers discarded for regressing the generation watermark.",
        )

    # -- introspection -----------------------------------------------------
    @property
    def watermark(self) -> int:
        """The highest generation this session has observed."""
        with self._lock:
            return self._watermark

    @property
    def endpoints(self) -> list[str]:
        return [ep.name for ep in self._endpoints]

    def stats(self) -> dict:
        with self._lock:
            watermark = self._watermark
        return {
            "watermark": watermark,
            "endpoints": [
                {
                    "endpoint": ep.name,
                    "breaker": ep.breaker.state,
                    "stale": ep.stale,
                    "draining": ep.draining,
                    "lost": ep.lost,
                    "role": ep.role,
                    "last_generation": ep.last_generation,
                }
                for ep in self._endpoints
            ],
            "hedge_delay_s": round(self._hedge_delay(), 6),
        }

    def probe(self, *, deadline_s: float = 2.0) -> list[dict]:
        """One ``info`` round over every endpoint: refresh role,
        draining, capability, and plane-staleness state (used by the
        rotation order and by feature gating).  Never raises — an
        unreachable endpoint is reported, not fatal."""
        out = []
        for ep in self._endpoints:
            entry: dict = {"endpoint": ep.name}
            try:
                info = self._call_endpoint(
                    ep, "info", {"plane": True},
                    Deadline.after(deadline_s),
                )
            except Exception as e:  # noqa: BLE001 - probe summarizes, never raises
                entry["error"] = f"{type(e).__name__}: {e}"
                out.append(entry)
                continue
            caps = info.get("capabilities") or {}
            plane = info.get("plane") or {}
            ep.capabilities = caps if isinstance(caps, dict) else {}
            ep.role = plane.get("role") if isinstance(plane, dict) else None
            ep.draining = bool(info.get("draining"))
            if isinstance(plane, dict) and plane.get("stale"):
                ep.stale = True
            # Federation endpoints: one reporting the set's queried
            # cluster as ``lost`` holds NO servable view of it — demote
            # it exactly like a draining endpoint (tried last, never
            # first) until a later probe sees the cluster resynced.
            fed = info.get("federation")
            cluster_state = None
            if self._cluster is not None and isinstance(fed, dict):
                cl = (fed.get("clusters") or {}).get(self._cluster)
                if isinstance(cl, dict):
                    cluster_state = cl.get("state")
                ep.lost = cluster_state == "lost"
            entry.update(
                capabilities=ep.capabilities,
                role=ep.role,
                draining=ep.draining,
                generation=ep.last_generation,
                **(
                    {"cluster_state": cluster_state}
                    if cluster_state is not None
                    else {}
                ),
            )
            out.append(entry)
        return out

    def capability(self, name: str) -> bool:
        """True when ANY probed endpoint advertises the capability
        (``probe()`` refreshes; unknown until then)."""
        return any(
            bool(ep.capabilities.get(name)) for ep in self._endpoints
        )

    # -- the call loop -----------------------------------------------------
    def call(self, op: str, deadline_s: float | None = None, **params):
        """Issue one op against the healthiest endpoint, failing over /
        hedging as configured.  Raises :class:`ReplicaSetError` when
        every endpoint fails, :class:`StaleReadError` when only
        watermark-regressing answers exist."""
        with self._lock:
            if self._closed:
                raise ReplicaSetError("ReplicaSet is closed")
        budget = self._deadline_s if deadline_s is None else deadline_s
        deadline = Deadline.after(budget) if budget is not None else None
        self._m_calls.labels(op=op).inc()
        hedgeable = self._hedge and op in IDEMPOTENT_OPS
        # Trace context: adopt the caller's (params carried a
        # trace_id) or originate one.  Every endpoint try below gets an
        # "rs:attempt" child span; the wire envelope each try sends
        # names THAT attempt as the server's parent, so failovers and
        # hedges become sibling subtrees under this call's span.
        rs_ctx = None
        caller_parent = params.get("parent_span_id")
        if not isinstance(caller_parent, str) or not caller_parent:
            caller_parent = None
        if self._trace_log is not None:
            from kubernetesclustercapacity_tpu.telemetry import (
                tracectx as _tracectx,
            )

            rs_ctx = _tracectx.from_wire(params) or _tracectx.TraceContext()
            params = dict(params, trace_id=rs_ctx.trace_id)
        wall_call0 = time.time()
        t_call0 = time.perf_counter()
        call_error: str | None = None
        try:
            return self._call_loop(
                op, params, deadline, hedgeable, rs_ctx
            )
        except Exception as e:
            call_error = f"{type(e).__name__}: {e}"
            raise
        finally:
            if rs_ctx is not None:
                from kubernetesclustercapacity_tpu.telemetry import (
                    tracectx as _tracectx,
                )

                _tracectx.span(
                    self._trace_log,
                    ts=time.time(),
                    start_ts=wall_call0,
                    trace_id=rs_ctx.trace_id,
                    span_id=rs_ctx.span_id,
                    **(
                        {"parent_span_id": caller_parent}
                        if caller_parent
                        else {}
                    ),
                    op=f"rs:{op}",
                    service="replicaset",
                    duration_ms=round(
                        (time.perf_counter() - t_call0) * 1e3, 3
                    ),
                    status="error" if call_error else "ok",
                    **({"error": call_error} if call_error else {}),
                )

    def _call_loop(self, op, params, deadline, hedgeable, rs_ctx):
        """The failover/hedging loop behind :meth:`call` (split out so
        the call span wraps every exit path exactly once)."""
        errors: list[str] = []
        stale_seen = 0
        prev_delay: float | None = None
        for round_i in range(self._rounds):
            for ep in self._rotation():
                if deadline is not None and deadline.expired():
                    raise DeadlineExpired(
                        f"deadline expired after {len(errors)} endpoint "
                        f"attempt(s) of {op!r}"
                        + (f"; last: {errors[-1]}" if errors else "")
                    )
                if not ep.breaker.allow():
                    errors.append(f"{ep.name}: breaker open")
                    self._m_failover.labels(cause="breaker_open").inc()
                    self._attempt_span(
                        rs_ctx, None, ep, time.time(), 0.0,
                        reason="breaker_open", error="breaker open",
                    )
                    continue
                att_id, att_params = self._attempt_params(rs_ctx, params)
                wall_att0 = time.time()
                t0 = time.perf_counter()
                try:
                    if hedgeable:
                        result, gen, won_by_hedge = self._attempt_hedged(
                            ep, op, params, deadline, rs_ctx
                        )
                        if won_by_hedge:
                            self._m_hedge_wins.inc()
                    else:
                        result = self._call_endpoint(
                            ep, op, att_params, deadline
                        )
                        self._note_latency(time.perf_counter() - t0)
                        gen = ep.last_generation
                        self._attempt_span(
                            rs_ctx, att_id, ep, wall_att0,
                            time.perf_counter() - t0, winner=True,
                        )
                except DeadlineExpired:
                    raise
                except RetryableElsewhere as e:
                    if e.wire_code == "tenant_quota":
                        # AUTHORITATIVE refusal: every replica enforces
                        # the same tenant map, so failing over would
                        # just spend the other replicas' admission
                        # budget re-refusing.  The quota error IS the
                        # answer — surface it.
                        raise
                    # The server refused before doing work: safe to try
                    # the next replica, mutations included.
                    errors.append(f"{ep.name}: {e}")
                    ep.draining = e.wire_code == "draining"
                    if e.wire_code == "cluster_lost":
                        # A federation endpoint with no view of the
                        # queried cluster: demote like draining.
                        ep.lost = True
                    self._m_failover.labels(cause=e.wire_code).inc()
                    if not hedgeable:  # hedged legs record their own
                        self._attempt_span(
                            rs_ctx, att_id, ep, wall_att0,
                            time.perf_counter() - t0,
                            reason=e.wire_code, error=str(e),
                        )
                    continue
                except CircuitOpenError as e:
                    errors.append(f"{ep.name}: {e}")
                    self._m_failover.labels(cause="breaker_open").inc()
                    if not hedgeable:
                        self._attempt_span(
                            rs_ctx, att_id, ep, wall_att0,
                            time.perf_counter() - t0,
                            reason="breaker_open", error=str(e),
                        )
                    continue
                except Exception as e:
                    transport = RetryPolicy.is_transport_error(e)
                    if not transport:
                        raise  # deterministic app error: the answer
                    ep.breaker.record_failure(f"{type(e).__name__}: {e}")
                    errors.append(f"{ep.name}: {type(e).__name__}: {e}")
                    self._m_failover.labels(cause="transport").inc()
                    if not hedgeable:
                        self._attempt_span(
                            rs_ctx, att_id, ep, wall_att0,
                            time.perf_counter() - t0,
                            reason="transport",
                            error=f"{type(e).__name__}: {e}",
                        )
                    if op not in IDEMPOTENT_OPS:
                        # The mutation may have executed before the
                        # transport died: at-most-once forbids resending
                        # it anywhere.
                        raise
                    continue
                ep.breaker.record_success()
                ok, verdict = self._advance_watermark(ep, gen)
                if ok:
                    with self._lock:
                        self._preferred = self._endpoints.index(ep)
                        if gen is not None:
                            self.last_generation = int(gen)
                    return result
                # Stale answer: discard, mark, move on.
                stale_seen += 1
                errors.append(f"{ep.name}: {verdict}")
                self._m_stale.inc()
                self._m_failover.labels(cause="stale").inc()
                self._attempt_span(
                    rs_ctx, None, ep, wall_att0, 0.0,
                    reason="stale", error=verdict,
                )
            if round_i + 1 < self._rounds:
                prev_delay = self._backoff.next_delay(prev_delay)
                if deadline is not None:
                    prev_delay = min(
                        prev_delay, max(deadline.remaining(), 0.0)
                    )
                time.sleep(prev_delay)
        if stale_seen:
            # Data WAS available — but only below the session watermark.
            # Refusing it is the monotonicity contract; say so instead
            # of a generic all-endpoints-failed error.
            raise StaleReadError(
                f"every reachable endpoint answered below watermark "
                f"{self.watermark} for {op!r}: {'; '.join(errors)}"
            )
        raise ReplicaSetError(
            f"all {len(self._endpoints)} endpoint(s) failed for {op!r} "
            f"after {len(errors)} attempt(s): {'; '.join(errors[-4:])}"
        )

    # -- attempt tracing ---------------------------------------------------
    def _attempt_params(self, rs_ctx, params):
        """``(attempt_span_id, params_for_the_wire)`` for one endpoint
        try: the envelope announces the ATTEMPT span as the server's
        parent (hops advanced), so each failover/hedge leg owns its own
        server-side subtree.  ``(None, params)`` untraced."""
        if rs_ctx is None:
            return None, params
        from kubernetesclustercapacity_tpu.telemetry.tracing import (
            new_span_id,
        )

        att_id = new_span_id()
        wire = rs_ctx.to_wire()
        wire["parent_span_id"] = att_id
        return att_id, dict(params, **wire)

    def _attempt_span(
        self, rs_ctx, span_id, ep, start_ts, duration_s, *,
        hedge=False, winner=False, reason=None, error=None,
    ) -> None:
        """One "rs:attempt" child span under the call span: which
        endpoint, whether it was the hedged leg, whether it won the
        race, and — on failure — the failover cause (the same
        vocabulary as ``kccap_replicaset_failovers_total``)."""
        if rs_ctx is None or self._trace_log is None:
            return
        from kubernetesclustercapacity_tpu.telemetry import (
            tracectx as _tracectx,
        )
        from kubernetesclustercapacity_tpu.telemetry.tracing import (
            new_span_id,
        )

        _tracectx.span(
            self._trace_log,
            ts=time.time(),
            start_ts=start_ts,
            trace_id=rs_ctx.trace_id,
            span_id=span_id or new_span_id(),
            parent_span_id=rs_ctx.span_id,
            op="rs:attempt",
            service="replicaset",
            endpoint=ep.name,
            hedge=bool(hedge),
            winner=bool(winner),
            **({"failover_reason": reason} if reason else {}),
            duration_ms=round(duration_s * 1e3, 3),
            status="error" if (error or reason) else "ok",
            **({"error": error} if error else {}),
        )

    def _rotation(self) -> list[_Endpoint]:
        """Endpoints in try order: sticky-preferred first, then the
        rest; known-stale/draining/cluster-lost endpoints demoted to the
        back (still tried — they may have recovered, and a lone endpoint
        is better than none)."""
        with self._lock:
            start = self._preferred
        eps = self._endpoints
        ordered = [eps[(start + i) % len(eps)] for i in range(len(eps))]
        healthy = [
            ep for ep in ordered if not (ep.stale or ep.draining or ep.lost)
        ]
        demoted = [
            ep for ep in ordered if ep.stale or ep.draining or ep.lost
        ]
        return healthy + demoted

    def _client_for(self, ep: _Endpoint) -> CapacityClient:
        """The endpoint's lazy client (caller holds ``ep.lock``)."""
        if ep.client is None:
            ep.client = CapacityClient(
                ep.addr[0],
                ep.addr[1],
                token=self._token,
                tenant=self._tenant,
                tenant_token=self._tenant_token,
                connect_timeout_s=self._connect_timeout,
                timeout_s=self._timeout,
                # The set owns cross-endpoint retry; the per-endpoint
                # client must surface the FIRST transport failure so
                # failover is immediate, not after a local retry storm.
                retry=RetryPolicy(max_attempts=1),
                trace=self._trace,
            )
        return ep.client

    def _call_endpoint(self, ep: _Endpoint, op, params, deadline):
        """One op on one endpoint (its lock serializes the socket).
        Records the endpoint's reply generation on success."""
        with ep.lock:
            client = self._client_for(ep)
            result = client.call(
                op,
                deadline_s=(
                    max(deadline.remaining(), 0.001)
                    if deadline is not None
                    else None
                ),
                **params,
            )
            gen = client.last_generation
        if gen is not None:
            ep.last_generation = gen
        return result

    # -- hedging -----------------------------------------------------------
    def _hedge_delay(self) -> float:
        """p95 of the recent successful-call latencies, clamped — the
        'this attempt is taking suspiciously long' threshold."""
        with self._lock:
            samples = sorted(self._latencies)
        if len(samples) < 8:
            return self._hedge_max / 4
        idx = min(len(samples) - 1, int(0.95 * len(samples)))
        return min(self._hedge_max, max(self._hedge_min, samples[idx]))

    def _note_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)
            if len(self._latencies) > 64:
                del self._latencies[0]

    def _attempt_hedged(
        self, primary: _Endpoint, op, params, deadline, rs_ctx=None
    ):
        """Primary attempt plus (after the hedge delay) one secondary on
        the next healthy endpoint; first answer wins.  Returns
        ``(result, generation, won_by_hedge)``; raises the primary's
        error when both fail.

        Each leg records its own "rs:attempt" span (``hedge`` flags the
        secondary); the race's winner — the first leg to SUCCEED, which
        is the leg whose answer the caller gets — carries ``winner:
        true``, so a hedged read always shows exactly two sibling
        attempt spans with one winner."""
        results: _queue.Queue = _queue.Queue()
        race_lock = threading.Lock()
        race = {"won": False}

        def attempt(ep: _Endpoint, tag: str) -> None:
            att_id = None
            wall0 = None
            t0 = None
            try:
                att_id, att_params = self._attempt_params(rs_ctx, params)
                wall0 = time.time()
                t0 = time.perf_counter()
                r = self._call_endpoint(ep, op, att_params, deadline)
                self._note_latency(time.perf_counter() - t0)
                with race_lock:
                    won = not race["won"]
                    race["won"] = True
                self._attempt_span(
                    rs_ctx, att_id, ep, wall0,
                    time.perf_counter() - t0,
                    hedge=tag == "hedge", winner=won,
                )
                results.put((tag, ep, r, None))
            except Exception as e:  # noqa: BLE001 - reported via the queue
                self._attempt_span(
                    rs_ctx, att_id, ep, wall0 or 0.0,
                    (time.perf_counter() - t0) if t0 is not None else 0.0,
                    hedge=tag == "hedge",
                    reason=(
                        "transport"
                        if RetryPolicy.is_transport_error(e)
                        else getattr(e, "wire_code", None)
                    ),
                    error=f"{type(e).__name__}: {e}",
                )
                # EVERY exit posts to the queue: a silently-dead attempt
                # would leave the hedged read blocked on results.get().
                results.put((tag, ep, None, e))

        t_primary = threading.Thread(
            target=attempt, args=(primary, "primary"), daemon=True
        )
        t_primary.start()
        delay = self._hedge_delay()
        if deadline is not None:
            delay = min(delay, max(deadline.remaining(), 0.0))
        try:
            tag, ep, result, err = results.get(timeout=delay)
        except _queue.Empty:
            secondary = self._hedge_candidate(primary)
            if secondary is None:
                tag, ep, result, err = results.get()
            else:
                self._m_hedges.inc()
                threading.Thread(
                    target=attempt, args=(secondary, "hedge"), daemon=True
                ).start()
                tag, ep, result, err = results.get()
                if err is not None:
                    # First finisher failed; give the other leg its
                    # chance before surfacing anything.
                    tag, ep, result, err = results.get()
        if err is not None:
            if isinstance(err, Exception):
                raise err
            raise ReplicaSetError(str(err))
        if ep is not primary:
            ep.breaker.record_success()
        return result, ep.last_generation, tag == "hedge"

    def _hedge_candidate(self, primary: _Endpoint) -> _Endpoint | None:
        for ep in self._rotation():
            if ep is primary:
                continue
            if ep.breaker.allow():
                return ep
        return None

    # -- monotonicity ------------------------------------------------------
    def _advance_watermark(self, ep: _Endpoint, gen) -> tuple[bool, str]:
        """Enforce read-your-generation: an answer older than the
        watermark is rejected (never returned).  Servers that stamp no
        generation (pre-plane) cannot be checked — degrade to
        best-effort, documented in the handshake contract."""
        if gen is None:
            return True, ""
        gen = int(gen)
        with self._lock:
            if gen < self._watermark:
                ep.stale = True
                return False, (
                    f"stale answer: generation {gen} < session "
                    f"watermark {self._watermark}"
                )
            self._watermark = gen
        ep.stale = False
        return True, ""

    # -- convenience wrappers (the single-client surface) ------------------
    def ping(self, **kw) -> str:
        return self.call("ping", **kw)

    def info(self, **kw) -> dict:
        return self.call("info", **kw)

    def fit(self, **flags) -> dict:
        return self.call("fit", **flags)

    def sweep(self, **params) -> dict:
        for key in ("cpu_request_milli", "mem_request_bytes", "replicas"):
            v = params.get(key)
            if v is not None and hasattr(v, "tolist"):
                params[key] = v.tolist()
        return self.call("sweep", **params)

    def explain(self, **flags) -> dict:
        return self.call("explain", **flags)

    def dump(self, **kw) -> dict:
        return self.call("dump", **kw)

    def update(self, events: list[dict], **kw) -> dict:
        """Mutation: routed with failover ONLY across refuse-before-work
        errors (draining / not-leader / overloaded); never hedged,
        never resent after a mid-call transport failure."""
        return self.call("update", events=events, **kw)

    def reload(self, path: str, **kw) -> dict:
        return self.call("reload", path=path, **kw)

    def drain_server(self, endpoint: str | None = None, **kw) -> dict:
        """Gracefully drain ONE endpoint (default: the first).  Checks
        the capability handshake first so a pre-plane server gets a
        clean local refusal instead of an unknown-op error."""
        targets = (
            [ep for ep in self._endpoints if ep.name == endpoint]
            if endpoint is not None
            else self._endpoints[:1]
        )
        if not targets:
            raise ValueError(f"unknown endpoint {endpoint!r}")
        ep = targets[0]
        if not ep.capabilities:
            # Capabilities unknown (never probed, or a pre-plane server
            # that advertises none): one info round settles it before we
            # risk an op the server may not recognize.
            try:
                info = self._call_endpoint(
                    ep, "info", {}, Deadline.after(5.0)
                )
                caps = info.get("capabilities")
                ep.capabilities = caps if isinstance(caps, dict) else {}
            except Exception:  # noqa: BLE001 - unreachable = not capable
                ep.capabilities = {}
        if not ep.capabilities.get("drain"):
            raise ReplicaSetError(
                f"{ep.name} does not advertise the drain capability "
                "(pre-plane server?)"
            )
        deadline = Deadline.after(
            kw.pop("deadline_s", None) or 30.0
        )
        result = self._call_endpoint(ep, "drain_server", kw, deadline)
        ep.draining = True
        return result

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Idempotent, thread-safe (same contract as the single
        client's close — pinned by test)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for ep in self._endpoints:
            with ep.lock:
                client, ep.client = ep.client, None
            if client is not None:
                client.close()

    def __enter__(self) -> "ReplicaSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
