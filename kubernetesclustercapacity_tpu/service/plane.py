"""The replicated serving plane: snapshot pub-sub fan-out + admission.

One :class:`~.server.CapacityServer` is a single point of failure — a
killed process, a stalled socket, or an overload burst takes the whole
capacity plane down with it.  This module multiplies it:

* :class:`PlanePublisher` — the **leader** side.  Every published
  snapshot generation (the same ``replace_snapshot`` funnel the
  timeline and audit log observe) fans out over a dedicated TCP stream
  to N subscribed replicas as the invertible checkpoint/diff record
  vocabulary the audit log pioneered: a fresh subscriber gets one
  full **checkpoint** of the current generation, every generation after
  rides as a **diff** against the previous one, and every frame carries
  the generation's :func:`~..timeline.diff.snapshot_digest` plus its
  parent's — a digest chain, so a replica can prove each reconstruction
  before serving it.  A subscriber that cannot keep up (bounded send
  queue overflows) is **ejected** — visibly behind, never silently
  wrong.
* :class:`PlaneSubscriber` — the **replica** side.  Follows the
  leader's stream, reconstructs each generation
  (:func:`~..audit.log.snapshot_from_summary`), verifies its digest,
  and stages it into the local server via
  ``replace_snapshot(generation=...)`` so the replica serves the
  LEADER's generation numbering — the watermark clients use for
  read-your-generation monotonicity.  A garbled or broken stream is
  dropped and resynced from a fresh checkpoint; an unverifiable frame
  is never applied.  A stream silent past ``stale_after_s`` marks the
  replica stale (surfaced via ``info``/``/healthz``) so load balancers
  route around bounded-staleness violations instead of discovering
  them.
* :class:`AdmissionController` — per-replica overload protection in
  the dispatch path: a bounded concurrency limiter (excess waits in a
  gauged queue, never unboundedly), a token-bucket rps cap
  (:class:`~..resilience.TokenBucket`), and deadline-slack shedding —
  a request whose budget is already spent (or below ``min_slack_s``)
  is refused before any work.  Refusals raise the 503-style
  :class:`~..resilience.OverloadedError`, which multi-endpoint clients
  treat as retryable-elsewhere.

The coordination-under-failure discipline mirrors gang-scheduled MPI
workers (PAPERS.md, "Rank-Aware Resource Scheduling for Tightly-Coupled
MPI Workloads"): every member serves a consistent view or is visibly
ejected — never silently wrong.
"""

from __future__ import annotations

import threading
import time

from kubernetesclustercapacity_tpu.resilience import (
    DeadlineExpired,
    OverloadedError,
    TenantQuotaError,
    TokenBucket,
    decorrelated_jitter,
)
from kubernetesclustercapacity_tpu.service import protocol
from kubernetesclustercapacity_tpu.utils.threads import supervised
from kubernetesclustercapacity_tpu.timeline.diff import (
    SnapshotDiff,
    diff_summaries,
    node_summary,
    snapshot_digest,
)

__all__ = [
    "PLANE_PROTOCOL_VERSION",
    "AdmissionController",
    "PlaneError",
    "PlanePublisher",
    "PlaneSubscriber",
]

#: Version stamped into the subscriber hello and checked by the
#: publisher: a frame-vocabulary change bumps it, and a mismatched pair
#: refuses cleanly at attach instead of mis-applying frames.
PLANE_PROTOCOL_VERSION = 1


class PlaneError(RuntimeError):
    """Plane stream violation: bad hello, digest mismatch, unsupported
    version."""


def _disambiguate(names: list[str]) -> list[str]:
    """Row keys for a names list — the same rule
    :func:`~..timeline.diff.node_summary` applies (repeated names get
    ``#<occurrence>`` from their second occurrence on)."""
    seen: dict[str, int] = {}
    keys = []
    for name in names:
        n = seen.get(name, 0)
        seen[name] = n + 1
        keys.append(name if n == 0 else f"{name}#{n}")
    return keys


# ---------------------------------------------------------------------------
# Leader side
# ---------------------------------------------------------------------------
class _Subscriber:
    """One attached replica: its socket, bounded frame queue, and writer
    thread (sends must never run on the publisher thread — one slow
    replica must not stall the leader's publish funnel)."""

    def __init__(self, sock, peer: str, max_queue: int) -> None:
        self.sock = sock
        self.peer = peer
        self.max_queue = max_queue
        self.cv = threading.Condition()
        self.queue: list[dict] = []
        self.dead = False
        self.sent = 0
        self.thread: threading.Thread | None = None

    def offer(self, frame: dict) -> bool:
        """Enqueue one frame; False = queue full (caller ejects us)."""
        with self.cv:
            if self.dead:
                return False
            if len(self.queue) >= self.max_queue:
                return False
            self.queue.append(frame)
            self.cv.notify()
        return True

    def kill(self) -> None:
        with self.cv:
            self.dead = True
            self.cv.notify()
        try:
            self.sock.close()
        except OSError:
            pass

    def run(self) -> None:
        """Writer loop: drain the queue onto the socket until killed or
        the peer vanishes."""
        while True:
            with self.cv:
                while not self.dead and not self.queue:
                    self.cv.wait()
                if self.dead and not self.queue:
                    return
                frame = self.queue.pop(0)
            try:
                protocol.send_msg(self.sock, frame)
                self.sent += 1
            except (OSError, protocol.ProtocolError):
                self.kill()
                return


class PlanePublisher:
    """Leader-side snapshot fan-out over a dedicated plane port.

    Wire shape: a replica connects, sends one hello frame
    ``{"plane": PLANE_PROTOCOL_VERSION, "generation": G, "digest": d,
    "token": ...}`` (``generation``/``digest`` describe what it already
    holds; 0/"" for a cold start), and the publisher answers with either
    a ``resume`` ack (the replica's digest matches the current
    generation — no state transfer needed) or a full ``checkpoint``
    frame.  From then on every published generation arrives as a
    ``diff`` frame (same record vocabulary as the audit log), and a
    ``heartbeat`` rides every ``heartbeat_s`` of publish silence so
    subscribers can bound staleness.  A draining leader sends a
    ``drain`` frame before closing, so replicas distinguish "leader
    going away on purpose" from a cut link.

    ``publish`` is called on the server's publisher thread (the
    ``replace_snapshot`` funnel); it takes one lock shared with
    subscriber attach, so no generation is ever skipped or double-sent
    around an attach.
    """

    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        token: str | None = None,
        max_queue: int = 128,
        heartbeat_s: float = 2.0,
        registry=None,
        trace_log=None,
    ) -> None:
        import socket as _socket

        if isinstance(trace_log, str):
            from kubernetesclustercapacity_tpu.telemetry.tracing import (
                TraceLog,
            )

            trace_log = TraceLog(trace_log)
        # ``plane:publish`` spans: each published generation mints a
        # fresh trace, and the frame carries (trace_id, span_id) as
        # additive fields so every subscriber's ``plane:stage`` span
        # joins the SAME tree.  The digest covers the snapshot, not the
        # frame, so the trace fields never perturb verification.
        self._trace_log = trace_log
        self._token = token
        self._max_queue = int(max_queue)
        self._heartbeat_s = float(heartbeat_s)
        self._lock = threading.Lock()
        self._subs: list[_Subscriber] = []
        # Retained state of the last published generation: what a fresh
        # subscriber's checkpoint is built from, and what the next
        # publish diffs against.
        self._summary: dict[str, tuple[int, ...]] | None = None
        self._names: list[str] = []
        self._taints: list = []
        self._labels: list = []
        self._semantics = ""
        self._generation = 0
        self._digest = ""
        self._published = 0
        self._ejected = 0
        self._draining = False
        self._m_frames = None
        self._m_subs = None
        self._m_ejected = None
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m_frames = registry.counter(
                    "kccap_plane_frames_total",
                    "Plane frames fanned out to subscribers, by kind.",
                    ("kind",),
                )
                self._m_subs = registry.gauge(
                    "kccap_plane_subscribers",
                    "Replicas currently subscribed to the plane stream.",
                )
                self._m_ejected = registry.counter(
                    "kccap_plane_ejected_total",
                    "Subscribers ejected for falling behind the stream.",
                )
        self._listener = _socket.create_server((host, port))
        self._listener.settimeout(0.2)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=supervised(self._accept_loop, name="kccap-plane-accept"),
            daemon=True,
        )
        self._accept_thread.start()
        self._hb_thread = threading.Thread(
            target=supervised(
                self._heartbeat_loop, name="kccap-plane-heartbeat"
            ),
            daemon=True,
        )
        self._hb_thread.start()

    @property
    def address(self) -> tuple[str, int]:
        return self._listener.getsockname()

    # -- publish (leader's replace_snapshot funnel) ------------------------
    def publish(self, snapshot, generation: int) -> None:
        """Fan one published generation out to every subscriber.  Called
        in publish order on the publisher thread; best-effort per
        subscriber (a full queue ejects that subscriber, never fails the
        publish)."""
        t0 = time.perf_counter()
        wall0 = time.time()
        summary = node_summary(snapshot)
        digest = snapshot_digest(snapshot)
        with self._lock:
            if self._summary is None or snapshot.semantics != self._semantics:
                frame = self._checkpoint_frame_locked(
                    summary, snapshot, generation, digest
                )
            else:
                frame = self._diff_frame_locked(
                    summary, snapshot, generation, digest
                )
            if self._trace_log is not None:
                from kubernetesclustercapacity_tpu.telemetry.tracing import (
                    new_span_id,
                    new_trace_id,
                )

                frame["trace_id"] = new_trace_id()
                frame["span_id"] = new_span_id()
            self._summary = summary
            self._names = list(snapshot.names)
            self._taints = list(snapshot.taints or [])
            self._labels = list(getattr(snapshot, "labels", None) or [])
            self._semantics = snapshot.semantics
            self._generation = int(generation)
            self._digest = digest
            self._published += 1
            self._offer_all_locked(frame)
        if self._trace_log is not None:
            from kubernetesclustercapacity_tpu.telemetry import (
                tracectx as _tracectx,
            )

            _tracectx.span(
                self._trace_log,
                ts=time.time(),
                start_ts=wall0,
                trace_id=frame["trace_id"],
                span_id=frame["span_id"],
                op="plane:publish",
                service="plane",
                kind=frame["kind"],
                generation=int(generation),
                duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
                status="ok",
            )

    def _checkpoint_frame_locked(
        self, summary, snapshot, generation, digest
    ) -> dict:
        frame = {
            "kind": "checkpoint",
            "generation": int(generation),
            "digest": digest,
            "parent": "",
            "semantics": snapshot.semantics,
            "nodes": snapshot.n_nodes,
            "names": list(snapshot.names),
            "rows": [list(v) for v in summary.values()],
            "ts": time.time(),
        }
        if any(snapshot.taints or []):
            frame["taints"] = list(snapshot.taints)
        labels = getattr(snapshot, "labels", None) or []
        if any(labels):
            # Labels ride checkpoints (like taints) so replicas answer
            # topology/gang ops against the leader's hierarchy.
            frame["labels"] = list(labels)
        return frame

    def _diff_frame_locked(self, summary, snapshot, generation, digest) -> dict:
        diff = diff_summaries(self._summary, summary)
        names_by_key = dict(zip(summary.keys(), snapshot.names))
        frame = {
            "kind": "diff",
            "generation": int(generation),
            "digest": digest,
            "parent": self._digest,
            "semantics": snapshot.semantics,
            "nodes": snapshot.n_nodes,
            "added": {k: list(v) for k, v in diff.added.items()},
            "removed": {k: list(v) for k, v in diff.removed.items()},
            "changed": {k: dict(d) for k, d in diff.changed.items()},
            "ts": time.time(),
        }
        added_names = {
            k: names_by_key[k] for k in diff.added if names_by_key[k] != k
        }
        if added_names:
            frame["added_names"] = added_names
        labels = getattr(snapshot, "labels", None) or []
        if diff.added and any(labels):
            labels_by_key = dict(zip(summary.keys(), labels))
            added_labels = {
                k: labels_by_key[k]
                for k in diff.added
                if labels_by_key.get(k)
            }
            if added_labels:
                frame["added_labels"] = added_labels
        # apply() yields old-order-minus-removed then added; when the
        # true row order differs (a mid-list insert), the frame must say
        # so — the digest covers row order, so the replica must too.
        expected = list(diff.apply(self._summary))
        if expected != list(summary):
            frame["order"] = list(summary)
        return frame

    def _offer_all_locked(self, frame: dict) -> None:
        kind = frame.get("kind", "?")
        dead = []
        for sub in self._subs:
            if not sub.offer(frame):
                dead.append(sub)
            elif self._m_frames is not None:
                self._m_frames.labels(kind=kind).inc()
        for sub in dead:
            self._eject_locked(sub)

    def _eject_locked(self, sub: _Subscriber) -> None:
        sub.kill()
        if sub in self._subs:
            self._subs.remove(sub)
            self._ejected += 1
            if self._m_ejected is not None:
                self._m_ejected.inc()
            if self._m_subs is not None:
                self._m_subs.set(len(self._subs))

    # -- attach ------------------------------------------------------------
    def _accept_loop(self) -> None:
        import socket as _socket

        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                return  # listener closed
            threading.Thread(
                target=supervised(self._attach, name="kccap-plane-attach"),
                args=(conn, addr),
                daemon=True,
            ).start()

    def _attach(self, conn, addr) -> None:
        peer = f"{addr[0]}:{addr[1]}"
        try:
            conn.settimeout(10.0)
            hello = protocol.recv_msg(conn)
        except (OSError, protocol.ProtocolError):
            self._close_quietly(conn)
            return
        try:
            self._validate_hello(hello)
        except PlaneError as e:
            try:
                protocol.send_msg(
                    conn, {"kind": "reject", "error": str(e)}
                )
            except (OSError, protocol.ProtocolError):
                pass
            self._close_quietly(conn)
            return
        conn.settimeout(None)
        sub = _Subscriber(conn, peer, self._max_queue)
        with self._lock:
            if self._draining or self._stop.is_set():
                self._close_quietly(conn)
                return
            if (
                self._summary is not None
                and hello.get("digest") == self._digest
                and hello.get("generation") == self._generation
            ):
                # The replica already holds the current generation
                # bit-for-bit (digest-proven): resume with diffs only.
                first = {
                    "kind": "resume",
                    "generation": self._generation,
                    "digest": self._digest,
                    "ts": time.time(),
                }
            elif self._summary is not None:
                first = self._checkpoint_frame_locked(
                    self._summary,
                    _RetainedView(
                        self._names, self._taints, self._semantics,
                        self._summary, self._labels,
                    ),
                    self._generation,
                    self._digest,
                )
            else:
                first = {"kind": "resume", "generation": 0, "digest": "",
                         "ts": time.time()}
            sub.offer(first)
            if self._m_frames is not None:
                self._m_frames.labels(kind=first["kind"]).inc()
            self._subs.append(sub)
            if self._m_subs is not None:
                self._m_subs.set(len(self._subs))
        sub.thread = threading.Thread(target=sub.run, daemon=True)
        sub.thread.start()
        # Reader side of the subscriber socket: the only thing a replica
        # ever sends after hello is EOF (disconnect) — watch for it so a
        # vanished replica deregisters promptly instead of at next send.
        try:
            while protocol.recv_msg(conn) is not None:
                pass
        except (OSError, protocol.ProtocolError):
            pass
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
                if self._m_subs is not None:
                    self._m_subs.set(len(self._subs))
        sub.kill()

    def _validate_hello(self, hello) -> None:
        if not isinstance(hello, dict) or "plane" not in hello:
            raise PlaneError("expected a plane hello frame")
        if hello.get("plane") != PLANE_PROTOCOL_VERSION:
            raise PlaneError(
                f"unsupported plane protocol {hello.get('plane')!r} "
                f"(speaking {PLANE_PROTOCOL_VERSION})"
            )
        if self._token is not None:
            import hmac

            token = hello.get("token")
            if not isinstance(token, str) or not hmac.compare_digest(
                token.encode(), self._token.encode()
            ):
                raise PlaneError("missing or invalid plane token")

    @staticmethod
    def _close_quietly(conn) -> None:
        try:
            conn.close()
        except OSError:
            pass

    # -- heartbeats / drain / teardown -------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._heartbeat_s):
            with self._lock:
                if self._draining:
                    return
                self._offer_all_locked(
                    {
                        "kind": "heartbeat",
                        "generation": self._generation,
                        "ts": time.time(),
                    }
                )

    def announce_drain(self) -> None:
        """Tell every subscriber the leader is draining (they keep
        serving their current generation and poll for a successor),
        then stop accepting new subscribers."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._offer_all_locked(
                {
                    "kind": "drain",
                    "generation": self._generation,
                    "ts": time.time(),
                }
            )

    def stats(self) -> dict:
        """JSON-able leader-plane health (info op / healthz / doctor)."""
        with self._lock:
            return {
                "role": "leader",
                "address": list(self.address),
                "subscribers": len(self._subs),
                "generation": self._generation,
                "digest": self._digest,
                "published": self._published,
                "ejected": self._ejected,
                "draining": self._draining,
            }

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            subs = list(self._subs)
            self._subs.clear()
            if self._m_subs is not None:
                self._m_subs.set(0)
        for sub in subs:
            sub.kill()
        self._accept_thread.join(timeout=5)


class _RetainedView:
    """Duck-typed snapshot stand-in for checkpoint frames built from the
    publisher's retained state (a fresh subscriber attaching between
    publishes must get the CURRENT generation without the publisher
    holding a reference to the full snapshot object)."""

    def __init__(self, names, taints, semantics, summary, labels=()) -> None:
        self.names = names
        self.taints = taints
        self.labels = list(labels)
        self.semantics = semantics
        self.n_nodes = len(names)


# ---------------------------------------------------------------------------
# Replica side
# ---------------------------------------------------------------------------
class PlaneSubscriber:
    """Replica-side stream follower: stage each verified generation into
    the local server.

    Every frame is digest-verified before it is served: a checkpoint
    reconstructs a snapshot and must hash to the frame's digest; a diff
    must chain from the replica's current digest (``parent``) and its
    application must hash to the frame's digest.  Any violation — a
    garbled frame, a broken chain, invalid JSON — drops the connection
    and resyncs from a fresh checkpoint.  **An unverified generation is
    never staged**; under arbitrary link corruption the replica serves
    a stale-but-correct generation, not a wrong one.

    ``clock`` is injectable (monotonic seconds) so staleness tests are
    deterministic.  ``on_apply(generation)`` is an optional observer
    fired after each staged generation (tests synchronize on it).
    """

    def __init__(
        self,
        leader: tuple[str, int],
        server,
        *,
        token: str | None = None,
        stale_after_s: float = 10.0,
        reconnect_base_s: float = 0.05,
        reconnect_max_s: float = 2.0,
        seed: int | None = None,
        registry=None,
        clock=time.monotonic,
        on_apply=None,
        trace_log=None,
    ) -> None:
        import random as _random

        if isinstance(trace_log, str):
            from kubernetesclustercapacity_tpu.telemetry.tracing import (
                TraceLog,
            )

            trace_log = TraceLog(trace_log)
        # ``plane:stage`` spans, parented to the publisher's
        # ``plane:publish`` span via the (trace_id, span_id) the frame
        # carries — the cross-process replication link of the trace
        # tree.
        self._trace_log = trace_log
        self._leader = tuple(leader)
        self._server = server
        self._token = token
        self._stale_after = float(stale_after_s)
        self._base = float(reconnect_base_s)
        self._cap = float(reconnect_max_s)
        self._rng = _random.Random(seed)
        self._clock = clock
        self._on_apply = on_apply
        self._lock = threading.Lock()
        self._sock = None
        self._stop = threading.Event()
        # Held replica state: the summary vocabulary of the staged
        # generation (what diffs apply against).
        self._summary: dict[str, tuple[int, ...]] | None = None
        self._name_of: dict[str, str] = {}
        self._taints_of: dict[str, list] = {}
        self._labels_of: dict[str, dict] = {}
        self._generation = 0
        self._digest = ""
        self._last_frame_at: float | None = None
        # The VERIFIED clock: last instant the held generation was
        # digest-proven current — a staged frame, an idempotent
        # re-delivery of the held generation, a digest-match resume, or
        # a heartbeat stamped with the held generation.  Garbled frames
        # and heartbeats announcing a NEWER generation (frames were
        # missed) do not advance it, so federation staleness math reads
        # ONE clock instead of re-deriving wall-clock in two places.
        self._last_verified_at: float | None = None
        self._applied = 0
        self._skipped = 0
        self._resyncs = 0
        self._errors = 0
        self._leader_draining = False
        self._last_error: str | None = None
        self._m_generation = None
        self._m_applied = None
        self._m_age = None
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m_generation = registry.gauge(
                    "kccap_plane_generation",
                    "Last plane generation applied by this replica.",
                )
                self._m_applied = registry.counter(
                    "kccap_plane_applied_total",
                    "Plane generations staged into the local server, "
                    "by result.",
                    ("result",),
                )
                self._m_age = registry.gauge(
                    "kccap_plane_sync_age_seconds",
                    "Seconds since the last frame arrived from the "
                    "leader.",
                )
                self._m_age.labels().set_function(
                    lambda: -1.0 if self._last_frame_at is None
                    else round(self._clock() - self._last_frame_at, 3)
                )
        # The replica is read-only: mutations must go to the leader.
        # Its plane stats feed the server's ``info {plane: true}``
        # section, and a server drain stops the stream first
        # (deregistration from the plane).
        server.set_plane_role("replica", stats_source=self.stats)
        server.add_drain_hook(self.stop)
        self._thread = threading.Thread(
            target=supervised(self._run, name="kccap-plane-subscriber"),
            daemon=True,
        )
        self._thread.start()

    # -- observability -----------------------------------------------------
    @property
    def applied_generation(self) -> int:
        with self._lock:
            return self._generation

    def sync_age_s(self) -> float | None:
        with self._lock:
            if self._last_frame_at is None:
                return None
            return self._clock() - self._last_frame_at

    def last_verified_age_s(self) -> float | None:
        """Seconds (on the injectable monotonic ``clock``) since the held
        generation was last digest-proven current; ``None`` before the
        first verification.  Stricter than :meth:`sync_age_s`: a frame
        that arrives but does not verify (garbage, a heartbeat stamped
        with a generation this replica missed) resets nothing — the
        federation tier's fresh/stale/lost state machine reads exactly
        this accessor, so staleness is never computed from two clocks."""
        with self._lock:
            if self._last_verified_at is None:
                return None
            return self._clock() - self._last_verified_at

    @property
    def stale(self) -> bool:
        """True once the stream has been silent past ``stale_after_s``
        (heartbeats reset it) — the bounded-staleness detector."""
        age = self.sync_age_s()
        return age is None or age > self._stale_after

    def stats(self) -> dict:
        age = self.sync_age_s()
        with self._lock:
            return {
                "role": "replica",
                "leader": list(self._leader),
                "generation": self._generation,
                "digest": self._digest,
                "applied": self._applied,
                "skipped": self._skipped,
                "resyncs": self._resyncs,
                "errors": self._errors,
                "leader_draining": self._leader_draining,
                "sync_age_s": None if age is None else round(age, 3),
                "stale": age is None or age > self._stale_after,
                "stale_after_s": self._stale_after,
                "last_error": self._last_error,
            }

    # -- stream loop -------------------------------------------------------
    def _run(self) -> None:
        import socket as _socket

        delay = None
        while not self._stop.is_set():
            try:
                sock = _socket.create_connection(self._leader, timeout=5.0)
            except OSError as e:
                self._note_error(f"connect: {type(e).__name__}: {e}")
                delay = decorrelated_jitter(
                    self._rng, self._base, delay, self._cap
                )
                self._stop.wait(delay)
                continue
            delay = None
            with self._lock:
                self._sock = sock
            try:
                self._follow(sock)
            except (OSError, protocol.ProtocolError, PlaneError) as e:
                self._note_error(f"{type(e).__name__}: {e}")
                with self._lock:
                    self._resyncs += 1
            finally:
                with self._lock:
                    if self._sock is sock:
                        self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass
            # Brief jittered pause before resync so a flapping link
            # cannot spin this thread hot.
            delay = decorrelated_jitter(self._rng, self._base, delay, self._cap)
            self._stop.wait(delay)

    def _follow(self, sock) -> None:
        with self._lock:
            hello = {
                "plane": PLANE_PROTOCOL_VERSION,
                "generation": self._generation,
                "digest": self._digest,
            }
        if self._token is not None:
            hello["token"] = self._token
        sock.settimeout(10.0)
        protocol.send_msg(sock, hello)
        # Frame read timeout: generous vs the heartbeat cadence, so a
        # live-but-quiet leader never times the replica out, while a
        # dead TCP peer is noticed without an OS-default multi-minute
        # wait.  Staleness itself is judged by stale_after_s.
        sock.settimeout(max(self._stale_after, 1.0))
        while not self._stop.is_set():
            frame = protocol.recv_msg(sock)
            if frame is None:
                raise PlaneError("leader closed the plane stream")
            if not isinstance(frame, dict):
                raise PlaneError(f"non-object plane frame: {frame!r}")
            self._handle_frame(frame)

    def _handle_frame(self, frame: dict) -> None:
        kind = frame.get("kind")
        now = self._clock()
        with self._lock:
            self._last_frame_at = now
        if kind == "reject":
            raise PlaneError(f"leader rejected us: {frame.get('error')}")
        if kind in ("heartbeat", "resume"):
            # A heartbeat/resume stamped with the generation we HOLD is
            # proof the held snapshot is still the leader's current one.
            with self._lock:
                held = self._generation
                if self._summary is not None and (
                    frame.get("generation") == held
                ):
                    self._last_verified_at = now
            if kind == "heartbeat":
                gen = frame.get("generation")
                if isinstance(gen, int) and gen > held:
                    # The leader is ahead of us but the connection is
                    # "live": frames were dropped on this link (e.g. a
                    # partition that healed before our read timed out).
                    # Waiting for the next diff to break the digest
                    # chain could wait forever on a quiet leader — the
                    # heartbeat itself is the gap evidence, so resync
                    # NOW through a fresh checkpoint.
                    raise PlaneError(
                        f"heartbeat announces generation {gen} ahead of "
                        f"held {held}: frames were missed on this "
                        "stream; resyncing"
                    )
            return
        if kind == "drain":
            with self._lock:
                self._leader_draining = True
            return
        if kind == "checkpoint":
            self._apply_checkpoint(frame)
            return
        if kind == "diff":
            self._apply_diff(frame)
            return
        raise PlaneError(f"unknown plane frame kind {kind!r}")

    def _apply_checkpoint(self, frame: dict) -> None:
        names = [str(n) for n in frame["names"]]
        keys = _disambiguate(names)
        rows = {
            k: tuple(int(x) for x in row)
            for k, row in zip(keys, frame["rows"])
        }
        name_of = dict(zip(keys, names))
        taints_of = {k: t for k, t in zip(keys, frame.get("taints") or [])}
        labels_of = {
            k: lb for k, lb in zip(keys, frame.get("labels") or [])
        }
        self._stage(
            rows, name_of, taints_of, labels_of, frame, chain_parent=False
        )

    def _apply_diff(self, frame: dict) -> None:
        with self._lock:
            if self._summary is None:
                raise PlaneError("diff frame before any checkpoint")
            if frame.get("parent") != self._digest:
                raise PlaneError(
                    f"digest chain broken: frame parent "
                    f"{frame.get('parent')!r} != held {self._digest!r}"
                )
            held = dict(self._summary)
            name_of = dict(self._name_of)
            taints_of = dict(self._taints_of)
            labels_of = dict(self._labels_of)
        diff = SnapshotDiff(
            added={
                k: tuple(int(x) for x in v)
                for k, v in frame.get("added", {}).items()
            },
            removed={
                k: tuple(int(x) for x in v)
                for k, v in frame.get("removed", {}).items()
            },
            changed={
                k: {f: int(d) for f, d in ch.items()}
                for k, ch in frame.get("changed", {}).items()
            },
        )
        rows = diff.apply(held)
        order = frame.get("order")
        if order is not None:
            try:
                rows = {k: rows[k] for k in order}
            except KeyError as e:
                raise PlaneError(f"order references unknown row {e}")
        added_names = frame.get("added_names", {})
        added_labels = frame.get("added_labels", {})
        for k in diff.removed:
            name_of.pop(k, None)
            taints_of.pop(k, None)
            labels_of.pop(k, None)
        for k in diff.added:
            name_of[k] = added_names.get(k, k)
            if k in added_labels:
                labels_of[k] = added_labels[k]
        self._stage(
            rows, name_of, taints_of, labels_of, frame, chain_parent=True
        )

    def _stage(
        self, rows, name_of, taints_of, labels_of, frame, *, chain_parent
    ) -> None:
        """Reconstruct, digest-verify, and stage one generation.  The
        digest check is the whole safety story: a frame that does not
        reconstruct bit-identically is a :class:`PlaneError` (→ resync),
        never a served snapshot."""
        from kubernetesclustercapacity_tpu.audit.log import (
            snapshot_from_summary,
        )

        t_stage0 = time.perf_counter()
        wall_stage0 = time.time()
        generation = int(frame["generation"])
        with self._lock:
            current = self._generation
            current_digest = self._digest
        if generation < current:
            with self._lock:
                self._skipped += 1
            if self._m_applied is not None:
                self._m_applied.labels(result="skipped").inc()
            return
        snap = snapshot_from_summary(
            rows, name_of, taints_of, frame["semantics"],
            labels_of=labels_of,
        )
        actual = snapshot_digest(snap)
        if actual != frame["digest"]:
            if self._m_applied is not None:
                self._m_applied.labels(result="digest_mismatch").inc()
            raise PlaneError(
                f"generation {generation} reconstruction digest "
                f"{actual!r} != frame digest {frame['digest']!r}"
            )
        if generation == current and actual == current_digest:
            # Idempotent re-delivery (reconnect checkpoint of the held
            # generation): nothing to stage, but the held generation was
            # just digest-proven current again.
            with self._lock:
                self._skipped += 1
                self._last_verified_at = self._clock()
            return
        self._server.replace_snapshot(snap, generation=generation)
        with self._lock:
            self._summary = rows
            self._name_of = name_of
            self._taints_of = taints_of
            self._labels_of = labels_of
            self._generation = generation
            self._digest = actual
            self._applied += 1
            self._last_verified_at = self._clock()
            self._leader_draining = False
        if self._m_generation is not None:
            self._m_generation.set(generation)
        if self._m_applied is not None:
            self._m_applied.labels(result="applied").inc()
        if self._trace_log is not None:
            tid = frame.get("trace_id")
            pid = frame.get("span_id")
            if isinstance(tid, str) and tid:
                from kubernetesclustercapacity_tpu.telemetry import (
                    tracectx as _tracectx,
                )
                from kubernetesclustercapacity_tpu.telemetry.tracing import (
                    new_span_id,
                )

                _tracectx.span(
                    self._trace_log,
                    ts=time.time(),
                    start_ts=wall_stage0,
                    trace_id=tid,
                    span_id=new_span_id(),
                    **(
                        {"parent_span_id": pid}
                        if isinstance(pid, str) and pid
                        else {}
                    ),
                    op="plane:stage",
                    service="plane",
                    kind=str(frame.get("kind", "")),
                    generation=generation,
                    duration_ms=round(
                        (time.perf_counter() - t_stage0) * 1e3, 3
                    ),
                    status="ok",
                )
        if self._on_apply is not None:
            try:
                self._on_apply(generation)
            except Exception:  # noqa: BLE001 - observers never break the stream
                pass

    def _note_error(self, err: str) -> None:
        with self._lock:
            self._errors += 1
            self._last_error = err

    def stop(self) -> None:
        """Stop following (idempotent; also the server's drain hook)."""
        self._stop.set()
        with self._lock:
            sock = self._sock
            self._sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=5)

    def __enter__(self) -> "PlaneSubscriber":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------
class AdmissionController:
    """Refuse-before-work overload protection for the dispatch path.

    Three gates, cheapest first, each with its own shed reason:

    1. **deadline slack** — a request whose budget is already spent (or
       below ``min_slack_s``) sheds with
       :class:`~..resilience.DeadlineExpired` *before* any queueing or
       token accounting: no kernel, no device touch, no bucket debit
       for an answer nobody is waiting for.
    2. **rps token bucket** — sustained arrival rate above ``rps``
       sheds with :class:`~..resilience.OverloadedError` (burst up to
       ``burst`` rides the bucket capacity).
    3. **concurrency** — at most ``max_concurrent`` admitted requests
       at once; excess waits in a bounded, gauged queue
       (``kccap_admission_queue_depth``) up to
       ``min(max_queue_wait_s, deadline slack)``, recording the wait as
       the ``admission`` phase, then sheds with
       :class:`~..resilience.OverloadedError`.

    An optional **shadow-price budget** (``price_budget`` in ``(0, 1]``)
    adds a scarcity gate between 1 and 2: the optimizer's certified
    dual solution prices served capacity (the ``capacity_share`` of its
    shadow prices — 0 when demand-bound, 1 when every requested replica
    is priced by a scarce resource), and while the last *certified*
    observation exceeds the budget, governed compute requests shed with
    :class:`~..resilience.OverloadedError` — "this request is worth
    shedding: its shadow price exceeds budget".  Only certified solves
    move the signal (an uncertified dual is a loose bound, not a
    price), so the gate can never act on a lie.

    A :class:`~.tenancy.TenantMap` (``tenants=...``) arms **per-tenant
    quotas** between 2 and 3: each mapped tenant's own
    :class:`~..resilience.TokenBucket` rps cap and concurrency quota,
    shed with the AUTHORITATIVE
    :class:`~..resilience.TenantQuotaError` (reason ``tenant_quota`` —
    every replica enforces the same map, so clients must not fail
    over), and the concurrency gate becomes a
    :class:`~.tenancy.FairSlotQueue` — deficit round-robin across
    per-tenant sub-queues instead of the global FIFO semaphore, so a
    hot tenant's backlog cannot starve an idle tenant's first request.
    Without a map the controller is byte-identical to the pre-tenancy
    single-queue path (``tenant=`` is accepted and ignored).

    Counters are exact under concurrency (pinned by a 16-thread hammer
    in ``tests/test_plane.py``): every governed request is counted
    exactly once as admitted or shed.
    """

    def __init__(
        self,
        *,
        max_concurrent: int = 0,
        rps: float = 0.0,
        burst: float | None = None,
        max_queue_wait_s: float = 0.5,
        min_slack_s: float = 0.0,
        price_budget: float = 0.0,
        registry=None,
        clock=time.monotonic,
        tenants=None,
    ) -> None:
        if max_concurrent < 0:
            raise ValueError(
                f"max_concurrent must be >= 0, got {max_concurrent}"
            )
        if rps < 0:
            raise ValueError(f"rps must be >= 0, got {rps}")
        if not 0.0 <= price_budget <= 1.0:
            raise ValueError(
                f"price_budget must be in [0, 1], got {price_budget}"
            )
        self.price_budget = float(price_budget)
        self._shadow_price: float | None = None
        self.max_concurrent = int(max_concurrent)
        self.rps = float(rps)
        self.max_queue_wait_s = float(max_queue_wait_s)
        self.min_slack_s = float(min_slack_s)
        self._tenants = tenants
        self._fair = None
        if tenants is not None and self.max_concurrent > 0:
            from kubernetesclustercapacity_tpu.service.tenancy import (
                FairSlotQueue,
            )

            self._fair = FairSlotQueue(
                self.max_concurrent, weight_of=tenants.weight
            )
        self._sem = (
            threading.Semaphore(self.max_concurrent)
            if self.max_concurrent > 0 and self._fair is None
            else None
        )
        self._bucket = (
            TokenBucket(self.rps, burst, clock=clock) if self.rps > 0 else None
        )
        self._tenant_buckets: dict = {}
        self._tenant_quota: dict = {}
        if tenants is not None:
            for spec in tenants.specs:
                if spec.rps > 0:
                    self._tenant_buckets[spec.name] = TokenBucket(
                        spec.rps, spec.burst, clock=clock
                    )
                if spec.max_concurrent > 0:
                    self._tenant_quota[spec.name] = int(spec.max_concurrent)
        self._lock = threading.Lock()
        self._queue_depth = 0
        self._admitted = 0
        self._shed: dict[str, int] = {}
        self._tenant_active: dict[str, int] = {}
        self._tenant_queued: dict[str, int] = {}
        self._m_admitted = None
        self._m_shed = None
        self._m_queue = None
        self._m_tenant_admitted = None
        self._m_tenant_shed = None
        self._m_tenant_queue = None
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m_admitted = registry.counter(
                    "kccap_admission_admitted_total",
                    "Requests admitted past admission control, by op.",
                    ("op",),
                )
                self._m_shed = registry.counter(
                    "kccap_admission_shed_total",
                    "Requests shed at admission, by op and reason.",
                    ("op", "reason"),
                )
                self._m_queue = registry.gauge(
                    "kccap_admission_queue_depth",
                    "Requests currently queued at the admission "
                    "concurrency gate.",
                )
                if tenants is not None:
                    # Bounded cardinality: labels come from
                    # TenantMap.label (map-named tenants + "default" +
                    # the "other" fold), never raw request identity.
                    self._m_tenant_admitted = registry.counter(
                        "kccap_tenant_admitted_total",
                        "Requests admitted, by tenant (map-named "
                        "tenants only; everything else folds to "
                        "'other').",
                        ("tenant",),
                    )
                    self._m_tenant_shed = registry.counter(
                        "kccap_tenant_shed_total",
                        "Requests shed at admission, by tenant and "
                        "reason.",
                        ("tenant", "reason"),
                    )
                    self._m_tenant_queue = registry.gauge(
                        "kccap_tenant_queue_depth",
                        "Requests queued at the weighted-fair "
                        "admission gate, by tenant.",
                        ("tenant",),
                    )

    def observe_shadow_price(
        self, capacity_share: float, *, certified: bool
    ) -> None:
        """Record one optimize solve's capacity-price signal.

        Uncertified observations are DISCARDED — the budget gate only
        ever acts on a certified dual solution.  Called by the server
        after each ``optimize`` dispatch; harmless without a budget.
        """
        if not certified:
            return
        with self._lock:
            self._shadow_price = float(capacity_share)

    def shadow_price(self) -> float | None:
        """The last certified capacity-price observation (None before
        any certified solve)."""
        with self._lock:
            return self._shadow_price

    def count_shed(self, op: str, reason: str) -> None:
        """Record one shed decided OUTSIDE this controller's gates (the
        server's draining refusal uses it, so every refusal lands in the
        same ``kccap_admission_shed_total`` story)."""
        with self._lock:
            self._shed[reason] = self._shed.get(reason, 0) + 1
        if self._m_shed is not None:
            self._m_shed.labels(op=op, reason=reason).inc()

    def admit(self, op: str, deadline=None, *, priced: bool = True,
              tenant: str | None = None):
        """Gate one governed request: returns a zero-arg ``release``
        callable on admission, raises on shed.  Callers MUST invoke the
        release in a ``finally`` (the server's dispatch does).
        ``priced=False`` skips the shadow-price gate — the server
        exempts the ``optimize`` op itself, since that is the dispatch
        that refreshes the price (a price-gated refresher could latch
        the gate shut forever).  ``tenant`` names the calling tenant
        for the per-tenant quota gates and the weighted-fair queue
        (``None`` folds to ``"default"``); without a tenant map it is
        accepted and ignored — the pre-tenancy path, byte-identical."""
        # Gate 1: deadline slack — cheapest, and shedding here must not
        # debit the token bucket (the request consumed no capacity).
        if deadline is not None:
            remaining = deadline.remaining()
            if remaining <= self.min_slack_s:
                self.count_shed(op, "deadline")
                raise DeadlineExpired(
                    f"deadline slack {remaining:.3f}s <= "
                    f"{self.min_slack_s:.3f}s at admission; shedding "
                    "without dispatch"
                )
        # Gate 1.5: shadow-price budget — a pure read, before the token
        # bucket (a priced-out request consumed no capacity).
        if priced and self.price_budget > 0.0:
            with self._lock:
                price = self._shadow_price
            if price is not None and price > self.price_budget:
                self.count_shed(op, "shadow_price")
                raise OverloadedError(
                    f"capacity shadow price {price:.3f} exceeds budget "
                    f"{self.price_budget:.3f}; shedding — retry another "
                    "replica"
                )
        # Gate 2: rps.
        if self._bucket is not None and not self._bucket.try_acquire():
            self.count_shed(op, "rps")
            raise OverloadedError(
                f"admission rps cap {self.rps:g}/s exceeded; "
                "retry another replica"
            )
        # Gate 2.5: per-tenant quotas (rps cap + concurrency share).
        # These refusals are AUTHORITATIVE — every replica enforces the
        # same map — so the typed tenant_quota code tells multi-endpoint
        # clients not to fail over.
        reserved = False
        if self._tenants is not None:
            tenant = tenant or "default"
            bucket = self._tenant_buckets.get(tenant)
            if bucket is not None and not bucket.try_acquire():
                self._shed_tenant(op, tenant, "tenant_quota")
                spec = self._tenants.spec(tenant)
                raise TenantQuotaError(
                    f"tenant {tenant!r} rps cap {spec.rps:g}/s "
                    "exceeded; back off (authoritative refusal — do "
                    "not fail over)"
                )
            quota = self._tenant_quota.get(tenant, 0)
            if quota > 0:
                with self._lock:
                    active = self._tenant_active.get(tenant, 0)
                    if active < quota:
                        self._tenant_active[tenant] = active + 1
                        reserved = True
                if not reserved:
                    self._shed_tenant(op, tenant, "tenant_quota")
                    raise TenantQuotaError(
                        f"tenant {tenant!r} concurrency quota {quota} "
                        "saturated; back off (authoritative refusal — "
                        "do not fail over)"
                    )
        # Gate 3: concurrency (bounded queue; deficit round-robin
        # across tenant sub-queues when a tenant map armed it).
        if self._fair is not None:
            try:
                self._admit_fair(op, tenant, deadline)
            except BaseException:
                if reserved:
                    self._unreserve(tenant)
                raise
        elif self._sem is not None:
            acquired = self._sem.acquire(blocking=False)
            if not acquired:
                wait_s = self.max_queue_wait_s
                if deadline is not None:
                    wait_s = max(
                        0.0, min(wait_s, deadline.remaining())
                    )
                with self._lock:
                    self._queue_depth += 1
                    if self._m_queue is not None:
                        self._m_queue.set(self._queue_depth)
                from kubernetesclustercapacity_tpu.telemetry import (
                    phases as _phases,
                )

                clk = _phases.current()
                t0 = time.perf_counter() if clk else 0.0
                try:
                    acquired = self._sem.acquire(timeout=wait_s)
                finally:
                    with self._lock:
                        self._queue_depth -= 1
                        if self._m_queue is not None:
                            self._m_queue.set(self._queue_depth)
                    if clk:
                        clk.record(
                            "admission", time.perf_counter() - t0
                        )
                if not acquired:
                    self.count_shed(op, "concurrency")
                    raise OverloadedError(
                        f"admission concurrency cap "
                        f"{self.max_concurrent} saturated after "
                        f"{wait_s:.3f}s queue wait; retry another "
                        "replica"
                    )
        with self._lock:
            self._admitted += 1
        if self._m_admitted is not None:
            self._m_admitted.labels(op=op).inc()
        if self._tenants is not None:
            if self._m_tenant_admitted is not None:
                self._m_tenant_admitted.labels(
                    tenant=self._tenants.label(tenant)
                ).inc()
            return self._release_tenant(tenant, reserved)
        if self._sem is not None:
            return self._sem.release
        return _noop

    def _admit_fair(self, op: str, tenant: str, deadline) -> None:
        """Tenancy's Gate 3: the deficit-round-robin concurrency gate,
        with the exact bounded-wait / ``admission``-phase contract of
        the semaphore path it replaces."""
        if self._fair.try_acquire(tenant):
            return
        wait_s = self.max_queue_wait_s
        if deadline is not None:
            wait_s = max(0.0, min(wait_s, deadline.remaining()))
        label = self._tenants.label(tenant)
        with self._lock:
            self._queue_depth += 1
            if self._m_queue is not None:
                self._m_queue.set(self._queue_depth)
            depth = self._tenant_queued.get(label, 0) + 1
            self._tenant_queued[label] = depth
            if self._m_tenant_queue is not None:
                self._m_tenant_queue.labels(tenant=label).set(depth)
        from kubernetesclustercapacity_tpu.telemetry import (
            phases as _phases,
        )

        clk = _phases.current()
        t0 = time.perf_counter() if clk else 0.0
        try:
            acquired = self._fair.acquire(tenant, timeout=wait_s)
        finally:
            with self._lock:
                self._queue_depth -= 1
                if self._m_queue is not None:
                    self._m_queue.set(self._queue_depth)
                depth = max(0, self._tenant_queued.get(label, 0) - 1)
                if depth:
                    self._tenant_queued[label] = depth
                else:
                    self._tenant_queued.pop(label, None)
                if self._m_tenant_queue is not None:
                    self._m_tenant_queue.labels(tenant=label).set(depth)
            if clk:
                clk.record(
                    "admission", time.perf_counter() - t0
                )
        if not acquired:
            self._shed_tenant(op, tenant, "concurrency")
            raise OverloadedError(
                f"admission concurrency cap {self.max_concurrent} "
                f"saturated after {wait_s:.3f}s weighted-fair queue "
                f"wait (tenant {tenant!r}); retry another replica"
            )

    def _shed_tenant(self, op: str, tenant: str, reason: str) -> None:
        """One tenant-attributed shed: the shared op/reason counter
        plus the bounded-cardinality per-tenant family."""
        self.count_shed(op, reason)
        if self._m_tenant_shed is not None:
            self._m_tenant_shed.labels(
                tenant=self._tenants.label(tenant), reason=reason
            ).inc()

    def _unreserve(self, tenant: str) -> None:
        with self._lock:
            n = self._tenant_active.get(tenant, 0)
            if n <= 1:
                self._tenant_active.pop(tenant, None)
            else:
                self._tenant_active[tenant] = n - 1

    def _release_tenant(self, tenant: str, reserved: bool):
        """The release callable for a tenancy-armed admission: frees
        the DRR slot (when one was held) and the tenant's quota
        reservation, exactly once (dispatch calls it in a finally)."""
        fair = self._fair

        def release() -> None:
            if fair is not None:
                fair.release(tenant)
            if reserved:
                self._unreserve(tenant)

        return release

    def tenant_stats(self) -> dict | None:
        """The ``info``/doctor tenancy section: per-tenant in-flight
        quota reservations, shed counts by reason, and the fair
        queue's live occupancy.  ``None`` without a tenant map."""
        if self._tenants is None:
            return None
        with self._lock:
            active = dict(self._tenant_active)
            shed = dict(self._shed)
        return {
            "tenants": len(self._tenants),
            "active": active,
            "shed": shed,
            "fair_queue": (
                self._fair.stats() if self._fair is not None else None
            ),
        }


def _noop() -> None:
    pass
