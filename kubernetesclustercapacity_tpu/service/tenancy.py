"""First-class multi-tenancy: the tenant map and the weighted-fair queue.

Two pieces, both consumed by :class:`~.plane.AdmissionController` and
:class:`~.server.CapacityServer`:

* :class:`TenantMap` — the ``-tenants FILE`` grammar: named tenants,
  each with an optional bearer token (requests presenting it are
  attributed to that tenant — the handshake stays byte-compatible, the
  token rides the existing ``token``/``tenant_token`` fields), an
  optional per-tenant rps cap + burst, an optional per-tenant
  concurrency quota, and a fair-share ``weight``.  Token lookup goes
  through a SHA-256 index so attribution is hash-equality, never a
  data-dependent scan over secrets.
* :class:`FairSlotQueue` — a deficit-round-robin (DRR) concurrency
  gate: N slots shared across per-tenant sub-queues.  Each released
  slot is granted to the tenant sub-queue whose deficit counter has
  banked enough credit; every queued tenant gains ``quantum * weight``
  credit per rotation, so no tenant can starve another — a hot tenant
  with a thousand queued requests advances exactly as fast as its
  weight entitles it, and an idle tenant's first request waits at most
  one rotation.  The starvation bound is pinned by tests and the
  sanitize hammer drives the class under adversarial schedules.

Tenancy as a whole is gated by ``KCCAP_TENANCY`` (unset/``1`` = armed
when a map is given; ``0`` = the exact pre-tenancy single-queue
admission path, map or not).
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
from dataclasses import dataclass

__all__ = [
    "TenancyError",
    "TenantSpec",
    "TenantMap",
    "FairSlotQueue",
    "FoldAccounting",
    "parse_tenants",
    "load_tenants",
    "enabled",
]

#: Metric-label-safe tenant names (also keeps the map greppable).
_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)

_TENANT_KEYS = frozenset(
    {"name", "token", "rps", "burst", "max_concurrent", "weight"}
)


def enabled() -> bool:
    """The ``KCCAP_TENANCY`` gate: ``0`` disables tenancy everywhere
    (the exact pre-tenancy admission path), anything else arms it when
    a tenant map is configured."""
    return os.environ.get("KCCAP_TENANCY", "1") != "0"


class TenancyError(ValueError):
    """Malformed tenant map (bad grammar, bad numbers, dupes)."""


@dataclass(frozen=True)
class TenantSpec:
    """One tenant: identity plus its quota envelope."""

    name: str
    token: str | None = None  # bearer token attributing requests to us
    rps: float = 0.0  # per-tenant token-bucket cap (0 = uncapped)
    burst: float | None = None  # bucket capacity (None = max(rps, 1))
    max_concurrent: int = 0  # per-tenant in-flight quota (0 = uncapped)
    weight: float = 1.0  # DRR fair-share weight

    def to_wire(self) -> dict:
        """The info/doctor rendering — the token NEVER rides it."""
        return {
            "name": self.name,
            "rps": self.rps,
            "max_concurrent": self.max_concurrent,
            "weight": self.weight,
        }


def _token_key(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def _parse_tenant(i: int, entry) -> TenantSpec:
    if not isinstance(entry, dict):
        raise TenancyError(f"tenant #{i}: expected a mapping, got {entry!r}")
    name = entry.get("name")
    if not isinstance(name, str) or not name:
        raise TenancyError(f"tenant #{i}: 'name' must be a non-empty string")
    if not set(name) <= _NAME_OK:
        raise TenancyError(
            f"tenant {name!r}: names are metric labels — stick to "
            "[A-Za-z0-9._-]"
        )
    unknown = set(entry) - _TENANT_KEYS
    if unknown:
        raise TenancyError(
            f"tenant {name!r}: unknown field(s) {sorted(unknown)} "
            f"(want a subset of {sorted(_TENANT_KEYS)})"
        )
    token = entry.get("token")
    if token is not None and (not isinstance(token, str) or not token):
        raise TenancyError(
            f"tenant {name!r}: 'token' must be a non-empty string"
        )
    rps = entry.get("rps", 0.0)
    if isinstance(rps, bool) or not isinstance(rps, (int, float)) or rps < 0:
        raise TenancyError(f"tenant {name!r}: rps must be a number >= 0")
    burst = entry.get("burst")
    if burst is not None and (
        isinstance(burst, bool)
        or not isinstance(burst, (int, float))
        or burst < 1
    ):
        raise TenancyError(f"tenant {name!r}: burst must be a number >= 1")
    max_concurrent = entry.get("max_concurrent", 0)
    if (
        isinstance(max_concurrent, bool)
        or not isinstance(max_concurrent, int)
        or max_concurrent < 0
    ):
        raise TenancyError(
            f"tenant {name!r}: max_concurrent must be an int >= 0"
        )
    weight = entry.get("weight", 1.0)
    if (
        isinstance(weight, bool)
        or not isinstance(weight, (int, float))
        or weight <= 0
    ):
        raise TenancyError(f"tenant {name!r}: weight must be a number > 0")
    return TenantSpec(
        name=name,
        token=token,
        rps=float(rps),
        burst=None if burst is None else float(burst),
        max_concurrent=int(max_concurrent),
        weight=float(weight),
    )


class TenantMap:
    """The parsed ``-tenants FILE``: immutable after construction, so
    every reader (admission gates, the server's attribution seam,
    metric-label folding) shares it lock-free."""

    def __init__(self, specs) -> None:
        self.specs = tuple(specs)
        self._by_name = {s.name: s for s in self.specs}
        if len(self._by_name) != len(self.specs):
            names = [s.name for s in self.specs]
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise TenancyError(f"duplicate tenant name(s): {dupes}")
        self._token_index: dict[str, str] = {}
        for s in self.specs:
            if s.token is None:
                continue
            key = _token_key(s.token)
            if key in self._token_index:
                raise TenancyError(
                    f"tenant {s.name!r} reuses another tenant's token"
                )
            self._token_index[key] = s.name

    def __len__(self) -> int:
        return len(self.specs)

    def __contains__(self, name) -> bool:
        return name in self._by_name

    @property
    def names(self) -> tuple:
        return tuple(s.name for s in self.specs)

    def spec(self, name: str) -> TenantSpec | None:
        return self._by_name.get(name)

    def tenant_of(self, token) -> str | None:
        """Token → tenant name (``None`` when the token names nobody).
        Comparison happens on SHA-256 digests, so attribution is a hash
        lookup — never a data-dependent walk over stored secrets."""
        if not isinstance(token, str) or not token:
            return None
        return self._token_index.get(_token_key(token))

    def weight(self, name: str) -> float:
        """DRR weight for the tenant (unmapped tenants weigh 1.0)."""
        spec = self._by_name.get(name)
        return spec.weight if spec is not None else 1.0

    def label(self, tenant: str) -> str:
        """The bounded-cardinality metric label: map-named tenants (and
        the ``default`` fallback identity) keep their name; everything
        else folds to ``other`` so a tenant-id flood can never explode
        a label set."""
        if tenant == "default" or tenant in self._by_name:
            return tenant
        return "other"

    def to_wire(self) -> dict:
        return {
            "tenants": [s.to_wire() for s in self.specs],
        }


class FoldAccounting:
    """Cross-tenant fold attribution: who shared whose kernel launch.

    The micro-batcher's fold queue coalesces concurrent requests across
    tenants into one padded dispatch (bit-exact vs solo — the combined
    dispatch is index-scattered and never reads the label), which makes
    "whose work rode that launch" invisible to the per-tenant admission
    metrics.  This is the batcher's ``fold_hook``: called once per
    MULTI-request dispatch with the members' tenant identities, it
    counts each member on ``kccap_tenant_folded_requests_total`` under
    its bounded :meth:`TenantMap.label` (so a tenant-id flood cannot
    explode the label set) and bumps ``kccap_fold_cross_tenant_total``
    when the fold actually crossed a tenant boundary — the number the
    multi-tenant amortization claim rests on.  Pure attribution: it
    influences nothing and must never fail a dispatch (the batcher
    swallows exceptions, and this class raises none by construction).
    """

    def __init__(self, tenant_map: TenantMap | None, registry) -> None:
        self._map = tenant_map
        self._folded = registry.counter(
            "kccap_tenant_folded_requests_total",
            "Requests served as members of a multi-request folded "
            "dispatch, by (bounded) tenant label.",
            ("tenant",),
        )
        self._cross = registry.counter(
            "kccap_fold_cross_tenant_total",
            "Folded dispatches whose members spanned more than one "
            "tenant (one padded launch shared across tenant "
            "boundaries).",
        )

    def _label(self, tenant) -> str:
        if not isinstance(tenant, str) or not tenant:
            return "other"  # anonymous member (tenancy off for it)
        if self._map is None:
            return "other"
        return self._map.label(tenant)

    def __call__(self, tenants) -> None:
        labels = [self._label(t) for t in tenants]
        for lab in labels:
            self._folded.labels(tenant=lab).inc()
        if len(set(labels)) > 1:
            self._cross.inc()


def parse_tenants(data) -> TenantMap:
    """Parsed document (``{"tenants": [...]}`` or a bare list) → map."""
    if isinstance(data, dict):
        entries = data.get("tenants")
        extra = set(data) - {"tenants"}
        if extra:
            raise TenancyError(
                f"unknown top-level field(s) {sorted(extra)}"
            )
    else:
        entries = data
    if not isinstance(entries, list) or not entries:
        raise TenancyError(
            "tenant file wants a non-empty 'tenants' list (or a bare list)"
        )
    return TenantMap(_parse_tenant(i, e) for i, e in enumerate(entries))


def load_tenants(path: str) -> TenantMap:
    """Load ``path`` — YAML when PyYAML is present, else strict JSON
    (the watchlist/SLO loaders' exact gating)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = json.loads(text)
        except ValueError as e:
            raise TenancyError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise TenancyError(f"{path}: cannot parse: {e}") from e
    return parse_tenants(data)


class _Waiter:
    """One queued acquire: its wakeup event and the granted flag (both
    owned by the queue's lock; the event is the only cross-thread
    signal)."""

    __slots__ = ("event", "granted")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.granted = False


class FairSlotQueue:
    """Deficit-round-robin concurrency gate: ``slots`` shared slots,
    one sub-queue per tenant, weighted-fair grants.

    The DRR invariant: every rotation of the backlog credits each
    queued tenant ``quantum * weight(tenant)``; a grant costs 1.0.  A
    tenant's service rate under full backlog is therefore proportional
    to its weight, and — the starvation-proof property — ANY queued
    tenant is granted within a bounded number of grants to everyone
    else (its credit grows every rotation and is never confiscated
    while it waits).  Credit does not bank across idle periods: a
    tenant whose sub-queue empties is dropped from the rotation and
    re-enters at zero, so bursting after a quiet hour earns no stored
    advantage.

    ``acquire``/``release`` pair like a semaphore (``release`` hands
    the freed slot straight to the next DRR pick, so the slot count is
    exact under concurrency — pinned by the sanitize hammer).
    """

    def __init__(self, slots: int, *, weight_of=None, quantum: float = 1.0):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if quantum <= 0:
            raise ValueError(f"quantum must be > 0, got {quantum}")
        self._slots = int(slots)
        self._weight_of = weight_of
        self._quantum = float(quantum)
        self._lock = threading.Lock()
        self._free = self._slots
        self._queues: dict[str, collections.deque] = {}
        self._order: collections.deque = collections.deque()
        self._deficits: dict[str, float] = {}
        self._active: dict[str, int] = {}
        self._waiting = 0

    def _weight(self, tenant: str) -> float:
        if self._weight_of is None:
            return 1.0
        w = float(self._weight_of(tenant))
        return w if w > 0 else 1.0

    def _enqueue_locked(self, tenant: str) -> "_Waiter":
        w = _Waiter()
        q = self._queues.get(tenant)
        if q is None:
            q = collections.deque()
            self._queues[tenant] = q
            self._deficits[tenant] = 0.0
            self._order.append(tenant)
        q.append(w)
        self._waiting += 1
        return w

    def _drop_tenant_locked(self, tenant: str) -> None:
        self._queues.pop(tenant, None)
        self._deficits.pop(tenant, None)
        try:
            self._order.remove(tenant)
        except ValueError:
            pass

    def _grant_locked(self):
        """The DRR pick: ``(waiter, tenant)`` or ``(None, None)`` when
        nobody waits.  Terminates: every full rotation credits each
        queued tenant ``quantum * weight > 0``, and empty sub-queues
        are pruned as visited, so while the rotation is non-empty some
        tenant crosses the unit cost within finitely many rotations."""
        while self._order:
            tenant = self._order[0]
            q = self._queues.get(tenant)
            if not q:
                self._order.popleft()
                self._queues.pop(tenant, None)
                self._deficits.pop(tenant, None)
                continue
            if self._deficits[tenant] >= 1.0:
                self._deficits[tenant] -= 1.0
                w = q.popleft()
                self._waiting -= 1
                if not q:
                    # Idle tenants bank no credit (classic DRR).
                    self._order.popleft()
                    self._queues.pop(tenant, None)
                    self._deficits.pop(tenant, None)
                return w, tenant
            self._deficits[tenant] += self._quantum * self._weight(tenant)
            self._order.rotate(-1)
        return None, None

    def try_acquire(self, tenant: str) -> bool:
        """Non-blocking: take a slot only when one is free AND nobody
        is queued (a free slot with a backlog belongs to the DRR pick,
        not to whoever races in)."""
        with self._lock:
            if self._free > 0 and self._waiting == 0:
                self._free -= 1
                self._active[tenant] = self._active.get(tenant, 0) + 1
                return True
            return False

    def acquire(self, tenant: str, timeout: float | None = None) -> bool:
        """Take a slot, queueing up to ``timeout`` seconds behind this
        tenant's sub-queue.  Returns False on timeout (the waiter is
        withdrawn); a grant that races the timeout is honored — the
        slot is already ours, so the caller proceeds."""
        with self._lock:
            if self._free > 0 and self._waiting == 0:
                self._free -= 1
                self._active[tenant] = self._active.get(tenant, 0) + 1
                return True
            w = self._enqueue_locked(tenant)
        if w.event.wait(timeout):
            return True
        with self._lock:
            if w.granted:
                return True
            try:
                self._queues[tenant].remove(w)
            except (KeyError, ValueError):
                return w.granted  # pruned by a racing grant
            self._waiting -= 1
            if not self._queues[tenant]:
                self._drop_tenant_locked(tenant)
            return False

    def release(self, tenant: str) -> None:
        """Return the tenant's slot; the freed slot goes straight to
        the next DRR pick (never back to the free pool while anyone
        waits)."""
        with self._lock:
            n = self._active.get(tenant, 0)
            if n <= 0:
                raise ValueError(
                    f"release without acquire for tenant {tenant!r}"
                )
            if n == 1:
                self._active.pop(tenant, None)
            else:
                self._active[tenant] = n - 1
            w, grantee = self._grant_locked()
            if w is None:
                self._free += 1
            else:
                self._active[grantee] = self._active.get(grantee, 0) + 1
                w.granted = True
                w.event.set()

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self._slots,
                "free": self._free,
                "waiting": self._waiting,
                "active": dict(self._active),
                "queued": {t: len(q) for t, q in self._queues.items() if q},
            }
