"""Wire protocol: 4-byte big-endian length prefix + UTF-8 JSON body.

Requests are JSON objects with an ``"op"`` field:

=========  ==========================================================
op         params
=========  ==========================================================
ping       —
info       optional ``metrics`` (bool, default false) — include the
           server's telemetry-registry snapshot under ``metrics``;
           optional ``audit`` (bool, default false) — include the
           audit-log and shadow-oracle status under ``audit``
           (``{enabled, log: {segments, records, by_kind,
           last_generation, ...}, shadow: {sample_rate, checked,
           divergences, alert, ...}}``) — the replay/audit visibility
           surface ``kccap -doctor -doctor-service`` reads
fit        ``cpuRequests``/``cpuLimits``/``memRequests``/``memLimits``/
           ``replicas`` (flag STRINGS, parsed server-side with exact
           reference semantics), optional ``output`` (``reference`` |
           ``json`` | ``table``), optional ``backend`` (``tpu`` |
           ``cpu``), optional PodSpec constraint fields
           (``tolerations``/``node_selector``/``affinity_terms``/
           ``anti_affinity_labels``/``spread``/``extended_requests``)
sweep      ``cpu_request_milli``/``mem_request_bytes``/``replicas``
           (numeric arrays) OR ``random: {n, seed}``; optional
           ``kernel`` (``auto`` — Pallas fast path when provably
           bit-exact — | ``exact``); result carries the kernel used
sweep_multi  R-resource grid sweep: ``resources`` (``[R]`` names —
           ``cpu`` in millicores, ``memory`` in bytes, anything else an
           extended column of the served snapshot), ``requests``
           (``[S][R]`` numeric), ``replicas`` (``[S]``); optional
           ``kernel`` as for sweep; result carries totals/schedulable
           and the kernel used
place      the fit flag/spec fields plus optional ``policy``
           (``first-fit`` | ``best-fit`` | ``spread``) and optional
           ``assignments`` (bool, default true) — placement
           simulation.  Default: the scan, result maps each replica
           to a node.  ``assignments: false`` opts into the
           closed-form bulk engine (O(N) instead of R scan steps):
           result ``assignments`` is null, ``by_node``/``placed``
           identical to the scan's; result ``engine`` says which ran
explain    the fit flag fields — per-node bottleneck attribution for
           the served snapshot: binding constraint (``cpu`` | ``memory``
           | ``pods`` | ``unhealthy`` | ``masked``) per node, binding
           histogram, saturation summary, and the marginal analysis
           (smallest single-node capacity increment yielding +1
           replica); optional ``output`` (``table`` | ``json``) adds a
           rendered ``report``
dump       the server's flight recorder (ring buffer of the last K
           dispatched requests: op, args digest, snapshot generation,
           trace_id, latency, status, result digest) as
           ``{records, count, matched, capacity, dropped, generation}``;
           optional server-side filters: ``filter_op`` (exact op name —
           the envelope's own ``op`` field is taken), ``status``
           (``ok`` | ``error``), ``limit`` (the N most recent matches)
timeline   the server's capacity timeline: per-generation watchlist
           capacities + binding histograms, attributed
           generation-to-generation deltas (nodes added/removed/mutated
           with per-resource deltas, per-watch capacity movement,
           binding-constraint shift, per-node fit contributions), and
           per-watch alert state (ok | breached | recovered) as
           ``{enabled, depth, count, generation, watchlist, records,
           deltas, alerts}``; optional ``since_generation`` (strictly
           after) and ``watch`` (one name) filters; ``{enabled: false}``
           when the server runs without ``-watch``/``-timeline-depth``
reload     ``path`` — swap the served snapshot (fixture .json or .npz);
           optional ``semantics``; refused with code ``not_leader`` on
           a plane replica
update     ``events`` — watch-style node/pod event list applied
           incrementally to the served snapshot (fixture-backed only);
           refused with code ``not_leader`` on a plane replica
drain_server  graceful drain: stop accepting compute/mutation ops
           (refused with code ``draining``), finish in-flight work
           (optional ``timeout_s`` bounds the wait, optional ``reason``
           is recorded), emit the final drain record, deregister from
           the plane; the reply IS the drain record; idempotent (a
           repeat returns the first record with ``already: true``)
=========  ==========================================================

``info`` additionally reports the protocol feature handshake under
``capabilities`` (``{protocol, plane, admission, drain}``) and a
top-level ``draining`` flag, and accepts optional ``plane`` (bool) to
include the serving-plane section (leader fan-out stats or replica
sync/staleness state) — clients built for the replicated plane
feature-gate on ``capabilities`` so old↔new pairings degrade cleanly.

Any request may additionally carry:

``token``
    shared bearer token (required for every op except ``ping`` when the
    server was started with auth enabled).
``deadline``
    absolute unix timestamp (``time.time()`` epoch seconds) after which
    the caller no longer wants the answer.  The server sheds the request
    with a ``DeadlineExpired`` error instead of dispatching — before
    parsing, and again after any wait for a compute slot — so a queue of
    abandoned requests cannot occupy the device.  Same-host deployments
    share a clock exactly; cross-host callers should keep budgets above
    their NTP skew (the client's own budget check is authoritative).
``trace_id``
    opaque request-correlation string (conventionally 32 hex chars, see
    :mod:`..telemetry.tracing`).  The server stamps it into its span
    record when started with ``-trace-log``, so one client-side ID finds
    the request in the server's trace log; it never changes the reply.
``parent_span_id``
    the caller's span for THIS hop (see :mod:`..telemetry.tracectx`) —
    the receiver's request span parents to it, which is what lets the
    offline analyzer (``kccap -trace-tree``) stitch per-process span
    logs into one tree without comparing wall clocks.
``trace_sampled``
    the caller's sticky tail-sampling decision (bool).  ``true`` forces
    every downstream hop to keep its span bodies for this trace even if
    its own ``-trace-sample`` predicate would drop them, so a kept trace
    is whole rather than a ragged subset.
``trace_hops``
    propagation depth (int), incremented per hop and capped at
    ``tracectx.MAX_HOPS`` — a forwarding loop degrades to untraced
    requests instead of unbounded envelope growth.

All three ride only alongside ``trace_id`` and, like it, never change
the reply — a server without tracing armed ignores them.

Responses: ``{"ok": true, "result": ...}`` or ``{"ok": false, "error": "..."}``.
Every response envelope also carries ``generation`` — the snapshot
generation that answered (a plane replica stamps the LEADER's numbering),
the watermark clients use for read-your-generation monotonicity — and a
refusal additionally carries ``code`` (``overloaded`` | ``draining`` |
``not_leader``): the server provably did no work, so the request is
safe to retry on another replica, mutations included.
Maximum frame size 64 MiB (a 10k-node JSON report is ~3 MB).
"""

from __future__ import annotations

import json
import socket
import struct

__all__ = ["send_msg", "recv_msg", "MAX_FRAME", "ProtocolError"]

MAX_FRAME = 64 * 1024 * 1024


class ProtocolError(RuntimeError):
    pass


def send_msg(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj).encode()
    if len(body) > MAX_FRAME:
        raise ProtocolError(f"frame too large: {len(body)}")
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ProtocolError("connection closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> dict | None:
    """Read one frame; None on clean EOF (or reset) at a frame boundary.

    The error taxonomy is total: every OS-level socket failure surfaces
    as :class:`ProtocolError` (reset before any frame byte is a clean
    None), so callers handle exactly two shapes — None = no more frames,
    ProtocolError = broken peer/transport.
    """
    try:
        header = sock.recv(4)
    except ConnectionResetError:
        return None
    except OSError as e:
        raise ProtocolError(f"socket error awaiting frame: {e}") from e
    if not header:
        return None
    try:
        while len(header) < 4:
            more = sock.recv(4 - len(header))
            if not more:
                raise ProtocolError("connection closed mid-header")
            header += more
        (length,) = struct.unpack(">I", header)
        if length > MAX_FRAME:
            raise ProtocolError(f"frame too large: {length}")
        body = _recv_exact(sock, length)
    except OSError as e:  # reset/abort/timeout mid-frame
        raise ProtocolError(f"socket error mid-frame: {e}") from e
    try:
        return json.loads(body)
    except ValueError as e:  # malformed/empty body is a protocol error
        raise ProtocolError(f"invalid JSON frame: {e}") from e
