"""Python client for the capacity service (same protocol as the C++ CLI)."""

from __future__ import annotations

import socket

from kubernetesclustercapacity_tpu.service import protocol

__all__ = ["CapacityClient"]


class CapacityClient:
    """Connect once, issue many requests (context-manager friendly)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 7077,
        *,
        token: str | None = None,
    ) -> None:
        self._sock = socket.create_connection((host, port))
        self._token = token

    def __enter__(self) -> "CapacityClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        self._sock.close()

    def call(self, op: str, **params):
        if self._token is not None:
            params.setdefault("token", self._token)
        protocol.send_msg(self._sock, {"op": op, **params})
        resp = protocol.recv_msg(self._sock)
        if resp is None:
            raise protocol.ProtocolError("server closed connection")
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "unknown server error"))
        return resp["result"]

    # Convenience wrappers -------------------------------------------------
    def ping(self) -> str:
        return self.call("ping")

    def info(self) -> dict:
        return self.call("info")

    def fit(self, **flags) -> dict:
        return self.call("fit", **flags)

    def sweep(self, **params) -> dict:
        return self.call("sweep", **params)

    def sweep_multi(self, resources, requests, **params) -> dict:
        """R-resource grid sweep: ``resources`` row names, ``requests``
        an ``[S][R]`` matrix in each resource's native unit."""
        return self.call(
            "sweep_multi",
            resources=list(resources),
            requests=[list(map(int, row)) for row in requests],
            **params,
        )

    def reload(self, path: str, **params) -> dict:
        return self.call("reload", path=path, **params)

    def update(self, events: list[dict]) -> dict:
        """Apply watch-style node/pod events to the served snapshot."""
        return self.call("update", events=events)

    def place(self, **flags) -> dict:
        """Simulate where each replica lands (greedy scheduler)."""
        return self.call("place", **flags)

    def drain(self, node: str, **flags) -> dict:
        """Simulate draining a node: a rehoming target per evicted pod."""
        return self.call("drain", node=node, **flags)

    def topology_spread(self, topology_key: str, **flags) -> dict:
        """Capacity under a maxSkew topology spread constraint."""
        return self.call("topology_spread", topology_key=topology_key, **flags)

    def plan(self, node_template: dict, **flags) -> dict:
        """Scale-up plan: nodes of this shape needed to fit the spec."""
        return self.call("plan", node_template=node_template, **flags)
