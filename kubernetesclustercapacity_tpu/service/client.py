"""Python client for the capacity service (same protocol as the C++ CLI).

Hardened transport: connect/read timeouts, automatic reconnect, bounded
jittered retry of *idempotent* ops, optional per-call deadlines threaded
to the server, and an optional circuit breaker.  The retry boundary is
the op table below — ``update`` and ``reload`` mutate served state and
are NEVER auto-retried (a lost reply does not prove the op was lost:
the server may have executed it before the transport died).

==============  =======================================================
op              auto-retry on transport failure?
==============  =======================================================
ping, info      yes (read-only)
fit, sweep,     yes (pure queries against an immutable snapshot — a
sweep_multi,    duplicate execution returns the identical result;
place, drain,   ``car`` included: its Monte Carlo draw is seeded, so a
topology_spread, retry re-draws the identical samples)
plan, explain,
car
dump,           yes (read-only views of the flight recorder / capacity
timeline, slo   timeline / SLO burn rates; a retry re-reads the ring,
                which may have advanced — acceptable for a diagnostic
                surface)
drain_server    yes (graceful drain is idempotent BY CONTRACT: the
                second call returns the first drain's record)
update, reload  NO (state mutations; at-most-once from this client)
==============  =======================================================

Reply envelopes additionally carry ``generation`` (the snapshot
generation that answered — kept on :attr:`CapacityClient.last_generation`
for the plane's read-your-generation monotonicity) and, on refusals, a
``code`` (``overloaded`` / ``draining`` / ``not_leader``) that maps to
the typed :class:`~..resilience.RetryableElsewhere` exceptions — the
server provably did no work, so a multi-endpoint client retries
elsewhere; this client surfaces them unchanged.
"""

from __future__ import annotations

import socket
import threading
import time

from kubernetesclustercapacity_tpu.resilience import (
    WIRE_CODES,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExpired,
    RetryPolicy,
)
from kubernetesclustercapacity_tpu.service import protocol

__all__ = ["CapacityClient", "IDEMPOTENT_OPS"]

#: Ops safe to re-send after a transport failure: they never mutate
#: served state (or, for drain_server, are idempotent by contract), so
#: duplicate execution is invisible.  Anything not in this set
#: (update/reload, future unknown ops) is at-most-once.
IDEMPOTENT_OPS = frozenset(
    {
        "ping", "info", "fit", "sweep", "sweep_multi", "place", "drain",
        "topology_spread", "plan", "explain", "car", "gang", "optimize",
        "forecast", "dump", "timeline", "slo", "drain_server",
        # Federation ops are pure reads over the federation tier's held
        # snapshots — a retry re-reads the fleet view, which may have
        # advanced; acceptable for the same reason dump/timeline are.
        "fed_status", "fed_sweep", "fed_rank", "spillover",
    }
)


class CapacityClient:
    """Connect once, issue many requests (context-manager friendly).

    ``retry`` (a :class:`~..resilience.RetryPolicy`) governs idempotent
    ops only; ``None`` disables auto-retry entirely.  ``deadline_s``
    sets a default per-call time budget, overridable per call
    (``client.fit(deadline_s=0.5)``); the absolute deadline rides the
    request so the server sheds it once expired.  ``breaker`` (a
    :class:`~..resilience.CircuitBreaker`) fail-fasts every call while
    open.  ``stats`` counts retries/reconnects/deadline hits for the
    ``info``-op style of observability — a dict view over the client's
    ``registry`` counters (default: a fresh private
    :class:`~..telemetry.MetricsRegistry`; pass a shared one to fold
    client transport health into a process scrape).  ``trace`` adds a
    fresh ``trace_id`` to every call (kept on :attr:`last_trace_id`) so
    client attempts correlate with server-side trace-log spans; an
    explicit ``trace_id=...`` per call always wins.

    ``trace_log`` (a path or :class:`~..telemetry.TraceLog`) records
    the client's side of every call as JSONL spans: one span per CALL
    plus one child span per transport ATTEMPT (attempt index, the
    backoff delay slept before it, status) — a retry storm is visible
    as a fan of attempt spans under one call, where a single call-level
    span would hide it entirely.
    """

    #: stats() keys → (metric name, help) — one table so the dict view
    #: and the registry can never drift.
    _STAT_METRICS = (
        ("calls", "kccap_client_calls_total", "Ops issued."),
        ("retries", "kccap_client_retries_total",
         "Transport-failure retries of idempotent ops."),
        ("reconnects", "kccap_client_reconnects_total",
         "Socket reconnects after teardown."),
        ("deadline_expired", "kccap_client_deadline_expired_total",
         "Calls abandoned because their budget ran out."),
        ("breaker_rejected", "kccap_client_breaker_rejected_total",
         "Calls refused fail-fast by an open circuit breaker."),
    )

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 7077,
        *,
        token: str | None = None,
        tenant: str | None = None,
        tenant_token: str | None = None,
        connect_timeout_s: float = 10.0,
        timeout_s: float | None = 120.0,
        retry: RetryPolicy | None = None,
        deadline_s: float | None = None,
        breaker: CircuitBreaker | None = None,
        registry=None,
        trace: bool = False,
        trace_log=None,
    ) -> None:
        """``tenant`` / ``tenant_token`` ride every call's envelope for
        multi-tenant servers (``kccap-server -tenants``): a per-tenant
        ``tenant_token`` both authenticates and attributes; a bare
        ``tenant`` is a label only (quota attribution without secrets).
        A per-tenant token may equally be passed as ``token=`` — the
        server derives identity from either field.  Both are ignored by
        tenantless servers, so a tenant-configured client stays
        compatible with old deployments.  Tenant-quota refusals raise
        :class:`~...resilience.TenantQuotaError` — authoritative (every
        replica enforces the same map): back off, don't fail over."""
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            MetricsRegistry,
        )
        from kubernetesclustercapacity_tpu.telemetry.tracing import TraceLog

        self._addr = (host, port)
        self._token = token
        self._tenant = tenant
        self._tenant_token = tenant_token
        self._connect_timeout = connect_timeout_s
        self._timeout = timeout_s
        self._retry = retry if retry is not None else RetryPolicy()
        self._deadline_s = deadline_s
        self._breaker = breaker
        # Guards the socket FIELD (swap in/out), not socket I/O: close()
        # must be idempotent and safe against a concurrent in-flight
        # call, which owns whatever socket object it already read.
        self._sock_lock = threading.Lock()
        self._sock: socket.socket | None = None
        #: Generation watermark from the last reply envelope (None until
        #: a reply carries one — pre-plane servers never stamp it).
        self.last_generation: int | None = None
        self.registry = registry if registry is not None else MetricsRegistry()
        self._m = {
            key: self.registry.counter(name, help_)
            for key, name, help_ in self._STAT_METRICS
        }
        if breaker is not None:
            # Callback gauge: reads the breaker's CURRENT state at
            # collection time (0 closed / 1 half-open / 2 open), so the
            # scrape can never show a stale transition.
            self.registry.gauge(
                "kccap_client_breaker_state",
                "Circuit breaker state (0=closed, 1=half_open, 2=open).",
            ).labels().set_function(
                lambda: {"closed": 0, "half_open": 1, "open": 2}.get(
                    breaker.state, -1
                )
            )
        self._trace = bool(trace)
        self._trace_log = (
            TraceLog(trace_log) if isinstance(trace_log, str) else trace_log
        )
        self.last_trace_id: str | None = None
        self._connect()  # fail fast, like the original one-shot client

    @property
    def stats(self) -> dict:
        """Transport-health counters (the historical dict shape), read
        straight from the registry — one source of truth."""
        return {key: int(c.value) for key, c in self._m.items()}

    def __enter__(self) -> "CapacityClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Idempotent and thread-safe: the socket is swapped out under
        the lock exactly once, so concurrent closers (or a close racing
        an in-flight call's teardown) each see a consistent field and
        ``socket.close`` is never double-invoked on a replaced socket."""
        with self._sock_lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:  # already torn down by the peer: closed is closed
                pass

    # -- transport ---------------------------------------------------------
    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout
        )
        sock.settimeout(self._timeout)
        with self._sock_lock:
            self._sock = sock
        return sock

    def _ensure_connected(self) -> socket.socket:
        with self._sock_lock:
            sock = self._sock
        if sock is None:
            self._m["reconnects"].inc()
            return self._connect()
        return sock

    def _attempt(self, msg: dict, deadline: Deadline | None):
        """One send/recv round trip.  Transport failures tear the socket
        down (the stream may be desynced mid-frame) so the next attempt
        reconnects cleanly."""
        if deadline is not None and deadline.expired():
            self._m["deadline_expired"].inc()
            raise DeadlineExpired(
                f"deadline expired before sending {msg.get('op')!r}"
            )
        sock = self._ensure_connected()
        if deadline is not None:
            # The read must give up when the budget does, even if the
            # configured read timeout is longer (or unset).
            remaining = max(deadline.remaining(), 0.001)
            sock.settimeout(
                remaining
                if self._timeout is None
                else min(self._timeout, remaining)
            )
        try:
            protocol.send_msg(sock, msg)
            resp = protocol.recv_msg(sock)
        except (protocol.ProtocolError, OSError):
            self.close()
            raise
        finally:
            if deadline is not None:
                try:
                    sock.settimeout(self._timeout)
                except OSError:
                    pass  # socket already torn down by close()
        if resp is None:
            self.close()
            raise protocol.ProtocolError("server closed connection")
        gen = resp.get("generation")
        if isinstance(gen, int) and not isinstance(gen, bool):
            # The reply's generation watermark (success or refusal) —
            # the plane client compares it across endpoints to enforce
            # read-your-generation monotonicity.
            self.last_generation = gen
        if not resp.get("ok"):
            err = resp.get("error", "unknown server error")
            cls = WIRE_CODES.get(resp.get("code"))
            if cls is not None:
                # Typed refusal (overloaded/draining/not_leader): the
                # server provably did no work — retryable elsewhere.
                raise cls(err)
            raise RuntimeError(err)
        return resp["result"]

    # -- the call loop -----------------------------------------------------
    def call(self, op: str, deadline_s: float | None = None, **params):
        """Issue one op.  ``deadline_s`` overrides the client default
        for this call only.  Idempotent ops retry transport failures
        under the retry policy (within the deadline); ``update`` /
        ``reload`` surface the first transport failure unchanged.  A
        ``trace_id=...`` param rides the envelope to the server's trace
        log; with ``trace=True`` one is generated per call (every retry
        attempt reuses it — the retries ARE the story a trace tells)."""
        if self._token is not None:
            params.setdefault("token", self._token)
        if self._tenant_token is not None:
            params.setdefault("tenant_token", self._tenant_token)
        if self._tenant is not None:
            params.setdefault("tenant", self._tenant)
        if self._trace and "trace_id" not in params:
            from kubernetesclustercapacity_tpu.telemetry.tracing import (
                new_trace_id,
            )

            params["trace_id"] = new_trace_id()
        self.last_trace_id = params.get("trace_id", self.last_trace_id)
        budget = self._deadline_s if deadline_s is None else deadline_s
        deadline = Deadline.after(budget) if budget is not None else None
        msg = {"op": op, **params}
        if deadline is not None:
            msg["deadline"] = deadline.to_wire()
        retryable_op = op in IDEMPOTENT_OPS
        self._m["calls"].inc()
        call_span_id = None
        _new_span = None
        # A caller-supplied ``parent_span_id`` (the ReplicaSet's attempt
        # span, the fed's member span) becomes the CALL span's parent;
        # the envelope's own parent is rewritten per attempt below so
        # the server's request span hangs under the attempt that
        # actually reached it.
        caller_parent = params.get("parent_span_id")
        if not isinstance(caller_parent, str) or not caller_parent:
            caller_parent = None
        if self._trace_log is not None:
            from kubernetesclustercapacity_tpu.telemetry.tracing import (
                new_span_id as _new_span,
            )

            call_span_id = _new_span()
        trace_id = params.get("trace_id") or ""
        t_call0 = time.perf_counter()
        wall_call0 = time.time()
        call_error: str | None = None
        prev_delay: float | None = None
        attempt = 0
        backoff_before = 0.0  # seconds slept before the CURRENT attempt
        try:
            while True:
                attempt += 1
                if self._breaker is not None and not self._breaker.allow():
                    self._m["breaker_rejected"].inc()
                    raise CircuitOpenError(
                        f"circuit breaker open for {self._addr[0]}:"
                        f"{self._addr[1]}"
                        + (
                            f" (last error: {self._breaker.last_error})"
                            if self._breaker.last_error
                            else ""
                        )
                    )
                attempt_span_id = None
                if _new_span is not None and trace_id:
                    # The server's request span parents to THIS attempt:
                    # retries (and the ReplicaSet's hedges) become
                    # sibling subtrees, each owning the server-side
                    # children of the wire call it actually made.
                    attempt_span_id = _new_span()
                    msg["parent_span_id"] = attempt_span_id
                    msg.setdefault("trace_hops", 1)
                t_attempt0 = time.perf_counter()
                wall_attempt0 = time.time()
                try:
                    result = self._attempt(msg, deadline)
                except Exception as e:
                    self._record_attempt_span(
                        op, trace_id, call_span_id, attempt,
                        backoff_before,
                        time.perf_counter() - t_attempt0,
                        error=f"{type(e).__name__}: {e}",
                        span_id=attempt_span_id,
                        start_ts=wall_attempt0,
                    )
                    transport = RetryPolicy.is_transport_error(e)
                    if transport and self._breaker is not None:
                        self._breaker.record_failure(
                            f"{type(e).__name__}: {e}"
                        )
                    if (
                        deadline is not None
                        and deadline.expired()
                        and transport
                    ):
                        # The budget, not the transport, is what gave
                        # out: surface that (retrying cannot un-spend
                        # it).
                        self._m["deadline_expired"].inc()
                        raise DeadlineExpired(
                            f"deadline expired after {attempt} attempt(s) "
                            f"of {op!r}; last transport error: "
                            f"{type(e).__name__}: {e}"
                        ) from e
                    if (
                        not transport  # app error/deadline: deterministic
                        or not retryable_op  # update/reload: at-most-once
                        or attempt >= self._retry.max_attempts
                    ):
                        raise
                    prev_delay = self._retry.next_delay(prev_delay)
                    if deadline is not None:
                        prev_delay = min(
                            prev_delay, max(deadline.remaining(), 0.0)
                        )
                    time.sleep(prev_delay)
                    backoff_before = prev_delay
                    self._m["retries"].inc()
                    continue
                self._record_attempt_span(
                    op, trace_id, call_span_id, attempt, backoff_before,
                    time.perf_counter() - t_attempt0, error=None,
                    span_id=attempt_span_id, start_ts=wall_attempt0,
                )
                if self._breaker is not None:
                    self._breaker.record_success()
                return result
        except Exception as e:
            call_error = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._record_call_span(
                op, trace_id, call_span_id, attempt,
                time.perf_counter() - t_call0, call_error,
                parent_span_id=caller_parent, start_ts=wall_call0,
            )

    def _record_attempt_span(
        self, op, trace_id, call_span_id, attempt, backoff_s, duration_s,
        *, error, span_id=None, start_ts=None,
    ) -> None:
        """One child span per transport attempt (parent: the call span)
        — the satellite that makes retry storms visible: attempt index,
        the backoff slept before this attempt, and what failed.
        ``span_id`` is the id the attempt's wire envelope already
        announced as the server's parent (minted up front), so the
        server's request span hangs under this one.  Spans are
        observability: they never fail the call they observe."""
        if self._trace_log is None:
            return
        from kubernetesclustercapacity_tpu.telemetry.tracing import (
            new_span_id,
        )

        try:
            self._trace_log.record(
                ts=time.time(),
                **({"start_ts": start_ts} if start_ts is not None else {}),
                trace_id=trace_id,
                span_id=span_id or new_span_id(),
                parent_span_id=call_span_id,
                op=f"{op}:attempt",
                service="client",
                attempt=attempt,
                backoff_ms=round(backoff_s * 1e3, 3),
                duration_ms=round(duration_s * 1e3, 3),
                status="error" if error else "ok",
                **({"error": error} if error else {}),
            )
        except Exception:  # noqa: BLE001 - tracing must not fail calls
            pass

    def _record_call_span(
        self, op, trace_id, call_span_id, attempts, duration_s, error,
        parent_span_id=None, start_ts=None,
    ) -> None:
        """The call-level span the attempt spans parent to (its
        ``attempts`` field is the retry count at a glance).
        ``parent_span_id`` links it under the caller's own span when
        one rode in on the params (ReplicaSet attempt, fed member)."""
        if self._trace_log is None:
            return
        try:
            self._trace_log.record(
                ts=time.time(),
                **({"start_ts": start_ts} if start_ts is not None else {}),
                trace_id=trace_id,
                span_id=call_span_id,
                **(
                    {"parent_span_id": parent_span_id}
                    if parent_span_id
                    else {}
                ),
                op=f"client:{op}",
                service="client",
                attempts=attempts,
                duration_ms=round(duration_s * 1e3, 3),
                status="error" if error else "ok",
                **({"error": error} if error else {}),
            )
        except Exception:  # noqa: BLE001 - tracing must not fail calls
            pass

    # Convenience wrappers -------------------------------------------------
    # (each forwards **kwargs through ``call``, so every wrapper accepts
    # a per-call ``deadline_s=...`` override for free)
    def ping(self, **kw) -> str:
        return self.call("ping", **kw)

    def info(self, **kw) -> dict:
        return self.call("info", **kw)

    def fit(self, **flags) -> dict:
        return self.call("fit", **flags)

    def sweep(self, **params) -> dict:
        """Grid sweep.  Scenario arrays may be numpy (coerced to JSON
        lists here, so ScenarioGrid columns pass straight through)."""
        for key in ("cpu_request_milli", "mem_request_bytes", "replicas"):
            v = params.get(key)
            if v is not None and hasattr(v, "tolist"):
                params[key] = v.tolist()
        return self.call("sweep", **params)

    def sweep_multi(self, resources, requests, **params) -> dict:
        """R-resource grid sweep: ``resources`` row names, ``requests``
        an ``[S][R]`` matrix in each resource's native unit."""
        return self.call(
            "sweep_multi",
            resources=list(resources),
            requests=[list(map(int, row)) for row in requests],
            **params,
        )

    def reload(self, path: str, **params) -> dict:
        return self.call("reload", path=path, **params)

    def update(self, events: list[dict], **kw) -> dict:
        """Apply watch-style node/pod events to the served snapshot."""
        return self.call("update", events=events, **kw)

    def place(self, **flags) -> dict:
        """Simulate where each replica lands (greedy scheduler)."""
        return self.call("place", **flags)

    def drain(self, node: str, **flags) -> dict:
        """Simulate draining a node: a rehoming target per evicted pod."""
        return self.call("drain", node=node, **flags)

    def topology_spread(self, topology_key: str, **flags) -> dict:
        """Capacity under a maxSkew topology spread constraint."""
        return self.call("topology_spread", topology_key=topology_key, **flags)

    def plan(
        self,
        node_template: dict | None = None,
        *,
        catalog=None,
        **flags,
    ) -> dict:
        """Scale-up plan.  With ``catalog`` (a node-shape list/mapping
        plus ``usage`` and optional ``target``/``quantile``/``drain``),
        runs the certified shape planner — cheapest catalog purchase
        restoring the quantile capacity, with LP bound and cannot-lie
        certification.  With ``node_template``, the legacy homogeneous
        ``nodes_needed`` count.  Exactly one of the two is required."""
        if (node_template is None) == (catalog is None):
            raise TypeError(
                "plan() wants exactly one of node_template= or catalog="
            )
        if catalog is not None:
            flags["catalog"] = catalog
        else:
            flags["node_template"] = node_template
        return self.call("plan", **flags)

    def explain(self, **flags) -> dict:
        """Why the fit stops where it does: binding constraint per node,
        binding histogram, saturation summary, marginal (+1) analysis."""
        return self.call("explain", **flags)

    def car(self, usage: dict | None = None, **params) -> dict:
        """Capacity-at-risk.  With ``usage`` (per-pod distribution
        block ``{"cpu": {...}, "memory": {...}}`` plus optional
        ``replicas``/``samples``/``seed``/``quantiles``), evaluates the
        stochastic spec against the served snapshot and returns the
        capacity quantiles, mean, probability-of-fit, and per-quantile
        binding attribution — seed-deterministic, so a transport retry
        re-draws the identical samples.  Without ``usage``, returns the
        server's quantile-watch status (last quantile capacities and
        alert states)."""
        if usage is not None:
            params["usage"] = usage
        return self.call("car", **params)

    def forecast(self, usage: dict | None = None, **params) -> dict:
        """Capacity forecast.  With ``usage`` (the capacity-at-risk
        distribution block) plus ``steps``/``step_s`` and an explicit
        ``growth={"cpu_per_s": ..., "memory_per_s": ...}`` relative-
        rate block, projects the capacity quantiles over the horizon
        and returns per-step ladders plus ``time_to_breach_s`` —
        seed-deterministic and a pure function of the served snapshot,
        so transport retries (and audit replays) re-answer
        identically.  Without ``usage``, returns the server's forecast-
        watch status (projected minima, time to breach, alert
        states)."""
        if usage is not None:
            params["usage"] = usage
        return self.call("forecast", **params)

    def gang(self, ranks: int | None = None, **params) -> dict:
        """Gang capacity.  With ``ranks`` (plus the six per-rank flag
        fields or scenario arrays, and optional ``count``/``colocate``/
        ``spread_level``/``max_ranks_per_domain``/
        ``anti_affinity_host``), evaluates whole-gang capacity against
        the served snapshot — all-or-nothing groups of co-scheduled
        ranks under the topology hierarchy, with the binding-level
        explanation on single-scenario requests.  Without ``ranks``,
        returns the server's gang-watch status (last whole-gang counts
        and alert states)."""
        if ranks is not None:
            # Passed verbatim: the server owns validation (its typed
            # errors are the contract the tests pin).
            params["ranks"] = ranks
        for key in ("cpu_request_milli", "mem_request_bytes", "replicas"):
            v = params.get(key)
            if v is not None and hasattr(v, "tolist"):
                params[key] = v.tolist()
        return self.call("gang", **params)

    def optimize(self, backend: str | None = None, **params) -> dict:
        """Optimization-based packing.  Takes the sweep grammar
        (scenario arrays or the six flag fields) plus optional
        ``backend`` (``"lp"`` — the certified LP solve with duality
        certificate, shadow prices, rounded integral packing and FFD
        baseline — or ``"ffd"`` for the bug-compatible first-fit
        reference alone), ``iters``/``tol`` solver knobs, and
        ``verify`` (re-check the rounded packing against the
        sequential oracle; default True).  Deterministic given the
        snapshot, so transport retries are safe; every answer is
        either certified or explicitly marked ``uncertified``."""
        if backend is not None:
            params["backend"] = backend
        for key in ("cpu_request_milli", "mem_request_bytes", "replicas"):
            v = params.get(key)
            if v is not None and hasattr(v, "tolist"):
                params[key] = v.tolist()
        return self.call("optimize", **params)

    def dump(self, op: str | None = None, status: str | None = None,
             limit: int | None = None, tenant: str | None = None,
             sampled: bool | None = None, **kw) -> dict:
        """The server's flight recorder: its last K dispatched requests.

        Filters apply SERVER-side: ``op`` keeps records of one op (sent
        as ``filter_op`` — the envelope's own ``op`` field names this
        request), ``status`` keeps ``"ok"``/``"error"`` records,
        ``tenant`` keeps one tenant's records (sent as
        ``filter_tenant`` — the envelope's own ``tenant`` field is this
        request's attribution), ``sampled`` keeps records by the tail
        sampler's verdict (``True`` = a retained trace tree backs the
        record, so ``kccap -trace-tree`` will find it), and ``limit``
        returns only the N most recent matches.
        """
        if op is not None:
            kw["filter_op"] = op
        if status is not None:
            kw["status"] = status
        if limit is not None:
            kw["limit"] = limit
        if tenant is not None:
            kw["filter_tenant"] = tenant
        if sampled is not None:
            kw["sampled"] = sampled
        return self.call("dump", **kw)

    def audit_status(self, **kw) -> dict:
        """The server's audit-log and shadow-oracle status (the
        ``info {audit: true}`` section): segment/record counts, last
        recorded generation, shadow checked/divergence counters and
        alert state.  ``{"enabled": false, ...}``-shaped when the
        server runs without ``-audit-dir``/``-shadow-sample-rate``."""
        return self.call("info", audit=True, **kw).get(
            "audit", {"enabled": False, "log": None, "shadow": None}
        )

    def drain_server(self, timeout_s: float | None = None, **kw) -> dict:
        """Gracefully drain the server: it stops accepting compute and
        mutation ops (refusing them with the retryable-elsewhere
        ``draining`` code), finishes in-flight work (bounded by
        ``timeout_s``), emits its final drain record, and deregisters
        from the plane.  Returns the drain record; idempotent — a
        repeat call returns the first record with ``already: true``."""
        if timeout_s is not None:
            kw["timeout_s"] = timeout_s
        return self.call("drain_server", **kw)

    # Federation surface (a kccap-fed endpoint; see federation/) -----------
    def fed_status(self, **kw) -> dict:
        """The federation tier's per-cluster degradation vector: every
        cluster's ``{generation, age_s, state: fresh|stale|lost}``,
        state counts, the stale/evict horizons, and the named exclusion
        list.  ``{"enabled": false, ...}``-shaped when the endpoint
        federates no clusters."""
        return self.call("fed_status", **kw)

    def fed_sweep(self, **params) -> dict:
        """Fleet-global sweep: grand totals over every non-lost cluster
        plus the per-cluster split, each reply annotated with the
        degradation vector (lost clusters are EXCLUDED from totals and
        named in ``excluded`` — never silently summed).  Accepts the
        sweep op's array grammar or the six reference flags."""
        for key in ("cpu_request_milli", "mem_request_bytes", "replicas"):
            v = params.get(key)
            if v is not None and hasattr(v, "tolist"):
                params[key] = v.tolist()
        return self.call("fed_sweep", **params)

    def fed_rank(self, **flags) -> dict:
        """Placement ranking per cluster for one scenario: fitting
        clusters first (cheapest first when a ``costs`` map is given,
        most headroom otherwise), lost clusters never ranked."""
        return self.call("fed_rank", **flags)

    def spillover(self, cluster: str, **flags) -> dict:
        """Drain-cluster what-if: where does cluster X's load land?
        Demand defaults to X's current pod count (override with
        ``demand=``); the rest of the fleet absorbs greedily, most
        headroom first.  A LOST X refuses with the typed
        ``cluster_lost`` code — its load is unknowable."""
        return self.call("spillover", cluster=cluster, **flags)

    def plane_status(self, **kw) -> dict | None:
        """The server's serving-plane section (``info {plane: true}``):
        leader fan-out stats or replica sync/staleness state; ``None``
        when the server is not part of a plane."""
        return self.call("info", plane=True, **kw).get("plane")

    def capabilities(self, **kw) -> dict:
        """The server's protocol feature handshake (``info`` →
        ``capabilities``).  Pre-plane servers advertise nothing — an
        empty dict, which feature gates treat as "assume not supported"
        (degrade, don't error)."""
        caps = self.call("info", **kw).get("capabilities")
        return caps if isinstance(caps, dict) else {}

    def slo_status(self, **kw) -> dict:
        """The server's SLO burn-rate status: every objective's
        short/long-window burn rate, alert state
        (ok/breached/recovered), and the fast-burning verdict.
        ``{"enabled": false}``-shaped when the server runs without
        ``-slo``."""
        return self.call("slo", **kw)

    def timeline(self, since_generation: int | None = None,
                 watch: str | None = None, **kw) -> dict:
        """The server's capacity timeline: per-generation watchlist
        capacities, attributed deltas (node-set diff + binding-constraint
        shift), and alert states.  ``since_generation`` returns only
        records/deltas strictly after that generation; ``watch`` narrows
        the per-watch sections to one name.  ``{"enabled": false}`` when
        the server runs without a timeline."""
        if since_generation is not None:
            kw["since_generation"] = since_generation
        if watch is not None:
            kw["watch"] = watch
        return self.call("timeline", **kw)
