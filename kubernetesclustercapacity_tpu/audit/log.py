"""Durable audit log: append-only JSONL segments of state + requests.

The log records two families of events:

* **generation records** — one per published snapshot generation.  The
  first record (and every ``checkpoint_every``-th after it, and any
  semantics flip) is a **checkpoint**: the full fit-relevant state
  (names, the seven :data:`~..timeline.diff.NODE_FIELDS` columns,
  semantics, taints).  Every other generation is a **diff**: the PR-5
  invertible :class:`~..timeline.diff.SnapshotDiff` against the
  previous generation, so replay cost is bounded by the checkpoint
  cadence while the on-disk cost of steady churn stays O(changed
  nodes).  Each record carries the generation's
  :func:`~..timeline.diff.snapshot_digest` and its parent's, chaining
  the history: a reconstruction that does not hash to the recorded
  digest is a corruption, detected, never silently served.
* **request records** — one per answering/mutating dispatch: op, the
  full arguments (secret-bearing envelope fields stripped), the
  generation that answered, status, and a *canonical* result digest
  (volatile fields like the kernel choice stripped, so a replay on a
  different backend still verifies the semantics).

Segments rotate at ``segment_max_bytes`` (``audit-000001.jsonl``,
``audit-000002.jsonl`` …); a reopened log always starts a fresh
segment, never appends to a possibly-torn one.  Loading is
crash-tolerant: a record torn by a mid-write crash (the final line of
the final segment) is dropped and counted, not fatal — everything
before it replays.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time

import numpy as np

from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
from kubernetesclustercapacity_tpu.timeline.diff import (
    NODE_FIELDS,
    SnapshotDiff,
    diff_summaries,
    node_summary,
    snapshot_digest,
)

__all__ = [
    "AuditError",
    "AuditLog",
    "AuditReader",
    "canonical_result_digest",
    "snapshot_from_summary",
    "strip_args",
]

_SEGMENT_RE = re.compile(r"^audit-(\d{6})\.jsonl$")

#: Envelope fields never recorded in ``args``: secrets (the shared
#: ``token`` AND the per-tenant ``tenant_token`` — the server records
#: the DERIVED tenant name instead, never the credential), per-attempt
#: noise that does not change what the request MEANS (the flight
#: recorder strips the same set from its digests), and ``op`` — a
#: request record carries the op as its own top-level field.
_ARGS_EXCLUDED = (
    "op", "token", "tenant_token", "trace_id", "deadline",
    "parent_span_id", "trace_sampled", "trace_hops",
)

#: Result fields that legitimately vary between record time and replay
#: time without a semantics change: which kernel answered (fused on a
#: TPU, exact on the replay host), its failure note, and rendered
#: report text (reference transcripts carry fixture provenance a
#: reconstructed snapshot cannot).  Stripped before digesting so the
#: digest pins WHAT was answered, not HOW.
#: ``engine`` joins for the gang op: which reduction served (grouped
#: count-matrix vs per-node) is a dispatch choice like ``kernel``, and
#: the gang counts are parity-pinned identical across both.
_VOLATILE_RESULT_FIELDS = frozenset(
    {"kernel", "fast_path_error", "report", "engine"}
)

#: Per-op additions to the volatile set.  The optimize op's float
#: solver artifacts (bounds, residuals, shadow prices, timings, the
#: certificate verdict itself, and the LP-guided per-group split) are
#: legitimately host/device-dependent — f64 iteration on a TPU replays
#: on a CPU — while the INTEGER packing answer (rounded totals, FFD
#: totals, schedulability, demand) is closed-form deterministic and
#: stays in the digest.
_VOLATILE_RESULT_FIELDS_BY_OP = {
    "optimize": frozenset(
        {
            "lp_bound", "gap_pct", "status", "certified", "duality_gap",
            "primal_residual", "dual_residual", "iterations", "tol",
            "solve_seconds", "shadow_prices", "ffd_exceeds_bound",
            "verified", "groups", "grouping_engaged",
        }
    ),
    # The forecast's integer ladders and time-to-breach are exact
    # order statistics over exact integer sweeps — they stay in the
    # digest; only the wall-time measurement is volatile.
    "forecast": frozenset({"eval_ms"}),
    # The catalog plan keeps its INTEGER answer (buy counts, projected
    # capacity, satisfiability) in the digest; float solver artifacts
    # (bounds, prices, costs, the certificate verdict) replay host-
    # dependent exactly like the optimize op's.
    "plan": frozenset(
        {
            "lp_bound", "gap_pct", "shadow_prices", "demand_price",
            "total_cost", "status", "certified", "uncertified_reason",
            "eval_ms", "drain",
        }
    ),
}

_DIGEST_HEX = 16  # matches flightrec/timeline truncation


class AuditError(RuntimeError):
    """Unloadable or integrity-violating audit log content."""


def _jsonable(obj):
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return repr(obj)


def strip_args(msg: dict) -> dict:
    """Request args safe to persist: the message minus envelope secrets
    and per-attempt noise (same exclusion set as the flight recorder's
    digests, so an audit record and a flight record describe the same
    request)."""
    return {k: v for k, v in msg.items() if k not in _ARGS_EXCLUDED}


def canonical_result(op: str, result):
    """The replay-comparable view of an op result (volatile fields
    stripped, globally and per op; non-dict results pass through)."""
    if not isinstance(result, dict):
        return result
    volatile = _VOLATILE_RESULT_FIELDS | _VOLATILE_RESULT_FIELDS_BY_OP.get(
        op, frozenset()
    )
    return {k: v for k, v in result.items() if k not in volatile}


def canonical_result_digest(op: str, result) -> str:
    """Truncated SHA-256 over the canonical result — the bit-exactness
    pin replay asserts against."""
    try:
        blob = json.dumps(
            canonical_result(op, result), sort_keys=True, default=_jsonable
        )
    except (TypeError, ValueError):
        blob = repr(result)
    return hashlib.sha256(blob.encode()).hexdigest()[:_DIGEST_HEX]


def _disambiguate(names: list[str]) -> list[str]:
    """Node keys for a names list — the exact rule
    :func:`~..timeline.diff.node_summary` applies (repeated names get
    ``#<occurrence>`` from their second occurrence on)."""
    seen: dict[str, int] = {}
    keys = []
    for name in names:
        n = seen.get(name, 0)
        seen[name] = n + 1
        keys.append(name if n == 0 else f"{name}#{n}")
    return keys


class AuditLog:
    """Append-only writer; one instance per server, safe for concurrent
    dispatch threads (one lock serializes appends).

    ``registry`` wires a ``kccap_audit_records_total`` counter (by
    record kind); ``None`` — or ``KCCAP_TELEMETRY=0`` — keeps the log
    registry-silent.
    """

    def __init__(
        self,
        directory: str,
        *,
        segment_max_bytes: int = 8 << 20,
        checkpoint_every: int = 16,
        registry=None,
    ) -> None:
        if segment_max_bytes < 1:
            raise ValueError(
                f"segment_max_bytes must be >= 1, got {segment_max_bytes}"
            )
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}"
            )
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.segment_max_bytes = int(segment_max_bytes)
        self.checkpoint_every = int(checkpoint_every)
        self._lock = threading.Lock()
        self._fh = None
        self._closed = False
        # Never append to an existing (possibly torn) segment: resume
        # numbering after whatever is already on disk.
        existing = [
            int(m.group(1))
            for f in os.listdir(directory)
            if (m := _SEGMENT_RE.match(f))
        ]
        self._segment_index = max(existing, default=0)
        self._segment_name = None
        self._records = 0
        self._by_kind: dict[str, int] = {}
        # Replay/diff state: the previous generation's summary vocabulary.
        self._last_summary: dict[str, tuple[int, ...]] | None = None
        self._last_semantics: str | None = None
        self._last_digest = ""
        self._last_generation = 0
        self._since_checkpoint = 0
        self._generation_refs: dict[int, str] = {}
        self._m_records = None
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m_records = registry.counter(
                    "kccap_audit_records_total",
                    "Audit-log records appended, by kind.",
                    ("kind",),
                )

    # -- appends -----------------------------------------------------------
    def _open_segment_locked(self) -> None:
        self._segment_index += 1
        self._segment_name = f"audit-{self._segment_index:06d}.jsonl"
        self._fh = open(
            os.path.join(self.directory, self._segment_name),
            "a",
            encoding="utf-8",
        )
        header = {
            "kind": "segment_header",
            "version": 1,
            "ts": time.time(),
            "segment": self._segment_name,
        }
        self._fh.write(json.dumps(header, sort_keys=True) + "\n")
        self._fh.flush()
        self._records += 1
        self._by_kind["segment_header"] = (
            self._by_kind.get("segment_header", 0) + 1
        )
        if self._m_records is not None:
            self._m_records.labels(kind="segment_header").inc()

    def _append_locked(self, rec: dict) -> str:
        """Write one record; returns its ``segment:offset`` audit ref.
        The record that crosses the size cap stays in its segment (a
        record is never torn across a rotation boundary)."""
        if self._closed:
            raise AuditError("audit log is closed")
        if self._fh is None:
            self._open_segment_locked()
        offset = self._fh.tell()
        segment = self._segment_name
        self._fh.write(json.dumps(rec, sort_keys=True, default=_jsonable) + "\n")
        self._fh.flush()
        self._records += 1
        kind = rec.get("kind", "?")
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
        if self._m_records is not None:
            self._m_records.labels(kind=kind).inc()
        if self._fh.tell() > self.segment_max_bytes:
            self._fh.close()
            self._fh = None
        return f"{segment}:{offset}"

    def record_generation(
        self, snapshot: ClusterSnapshot, generation: int, *, ts=None
    ) -> str:
        """One generation record (checkpoint or diff); returns its
        audit ref.  Must be called in publish order — the diff is taken
        against the previously recorded generation."""
        summary = node_summary(snapshot)
        digest = snapshot_digest(snapshot)
        names_by_key = dict(zip(summary.keys(), snapshot.names))
        with self._lock:
            checkpoint = (
                self._last_summary is None
                or snapshot.semantics != self._last_semantics
                or self._since_checkpoint >= self.checkpoint_every
            )
            rec: dict = {
                "generation": int(generation),
                "ts": time.time() if ts is None else float(ts),
                "nodes": snapshot.n_nodes,
                "semantics": snapshot.semantics,
                "digest": digest,
                "parent": self._last_digest,
            }
            if checkpoint:
                rec["kind"] = "checkpoint"
                rec["names"] = list(snapshot.names)
                rec["rows"] = [list(v) for v in summary.values()]
                if any(snapshot.taints or []):
                    rec["taints"] = list(snapshot.taints)
                if any(snapshot.labels or []):
                    # Labels ride checkpoints so gang/topology requests
                    # replay against the hierarchy that answered them.
                    # Like taints, labels sit OUTSIDE the digest-chained
                    # fit vocabulary: an in-place label edit between
                    # checkpoints is carried forward (bounded by the
                    # checkpoint cadence), never detected as a diff.
                    rec["labels"] = list(snapshot.labels)
                self._since_checkpoint = 0
            else:
                diff = diff_summaries(self._last_summary, summary)
                rec["kind"] = "diff"
                rec["added"] = {k: list(v) for k, v in diff.added.items()}
                rec["removed"] = {
                    k: list(v) for k, v in diff.removed.items()
                }
                rec["changed"] = {
                    k: dict(d) for k, d in diff.changed.items()
                }
                added_names = {
                    k: names_by_key[k]
                    for k in diff.added
                    if names_by_key[k] != k
                }
                if added_names:
                    rec["added_names"] = added_names
                if diff.added and any(snapshot.labels or []):
                    labels_by_key = dict(
                        zip(summary.keys(), snapshot.labels)
                    )
                    added_labels = {
                        k: labels_by_key[k]
                        for k in diff.added
                        if labels_by_key.get(k)
                    }
                    if added_labels:
                        rec["added_labels"] = added_labels
                # apply() yields old-order-minus-removed then added; when
                # the true row order differs (a mid-list insert), record
                # it — the digest covers row order, so replay must too.
                expected = list(diff.apply(self._last_summary))
                if expected != list(summary):
                    rec["order"] = list(summary)
                self._since_checkpoint += 1
            ref = self._append_locked(rec)
            self._last_summary = summary
            self._last_semantics = snapshot.semantics
            self._last_digest = digest
            self._last_generation = int(generation)
            self._generation_refs[int(generation)] = ref
            if len(self._generation_refs) > 1024:
                oldest = min(self._generation_refs)
                self._generation_refs.pop(oldest, None)
            return ref

    def record_request(
        self,
        *,
        op: str,
        args: dict,
        generation,
        status: str,
        result=None,
        error: str | None = None,
        ts=None,
        trace_sampled: bool | None = None,
    ) -> str:
        """One request record; returns its ``segment:offset`` audit ref
        (the flight recorder attaches it, so ``dump`` output points
        straight back into this log).  ``trace_sampled`` — the tail
        sampler's verdict for this request (``None``, no sampler armed,
        keeps the record shape unchanged): a replayed divergence can
        say up front whether a retained trace tree backs it."""
        rec = {
            "kind": "request",
            "ts": time.time() if ts is None else float(ts),
            "op": op,
            "args": args,
            "generation": generation,
            "status": status,
            "result_digest": (
                "" if result is None else canonical_result_digest(op, result)
            ),
        }
        if trace_sampled is not None:
            rec["trace_sampled"] = bool(trace_sampled)
        if error:
            rec["error"] = error
        with self._lock:
            return self._append_locked(rec)

    def append_raw(self, rec: dict) -> str:
        """Append an arbitrary record (the shadow sampler's divergence
        bundles ride the same log when no separate bundle path is
        configured)."""
        with self._lock:
            return self._append_locked(dict(rec))

    def generation_ref(self, generation: int) -> str | None:
        """Audit ref of a recorded generation (recent generations only —
        the map is bounded)."""
        with self._lock:
            return self._generation_refs.get(int(generation))

    def stats(self) -> dict:
        """Compact health view (``info {audit: true}``, doctor,
        ``/healthz``)."""
        with self._lock:
            return {
                "dir": self.directory,
                "segment": self._segment_name,
                "segments": self._segment_index,
                "records": self._records,
                "by_kind": dict(self._by_kind),
                "last_generation": self._last_generation,
                "checkpoint_every": self.checkpoint_every,
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self) -> "AuditLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AuditReader:
    """Loaded audit history: records across all segments, in order.

    ``recovered_tail`` counts torn final records dropped during the
    load (0 on a clean shutdown); a torn record anywhere else is an
    :class:`AuditError` — only the tail can legitimately be mid-write
    when a process dies.
    """

    def __init__(
        self, directory: str, records: list[dict], recovered_tail: int
    ) -> None:
        self.directory = directory
        self.records = records
        self.recovered_tail = recovered_tail
        self._snapshots: dict[int, ClusterSnapshot] = {}

    @classmethod
    def load(cls, directory: str) -> "AuditReader":
        try:
            segments = sorted(
                f for f in os.listdir(directory) if _SEGMENT_RE.match(f)
            )
        except OSError as e:
            raise AuditError(f"cannot read audit dir {directory!r}: {e}")
        if not segments:
            raise AuditError(f"no audit segments in {directory!r}")
        records: list[dict] = []
        recovered = 0
        for si, seg in enumerate(segments):
            last_segment = si == len(segments) - 1
            with open(os.path.join(directory, seg), "rb") as fh:
                data = fh.read()
            offset = 0
            while offset < len(data):
                nl = data.find(b"\n", offset)
                if nl == -1:
                    # A committed record is newline-terminated (the
                    # writer appends record + "\n" in one flushed
                    # write): an unterminated tail is a torn write even
                    # when the bytes happen to parse.
                    if last_segment:
                        recovered += 1
                        break
                    raise AuditError(
                        f"unterminated audit record in {seg} at byte "
                        f"{offset}"
                    )
                chunk = data[offset:nl]
                final_chunk = nl >= len(data) - 1
                try:
                    rec = json.loads(chunk.decode("utf-8"))
                    if not isinstance(rec, dict):
                        raise ValueError("record is not an object")
                except (ValueError, UnicodeDecodeError) as e:
                    if last_segment and final_chunk:
                        recovered += 1
                        break
                    raise AuditError(
                        f"corrupt audit record in {seg} at byte {offset}: {e}"
                    )
                rec["_ref"] = f"{seg}:{offset}"
                records.append(rec)
                offset = nl + 1
        return cls(directory, records, recovered)

    # -- views -------------------------------------------------------------
    def generations(self) -> list[dict]:
        """Generation records (checkpoints + diffs), log order."""
        return [
            r for r in self.records if r.get("kind") in ("checkpoint", "diff")
        ]

    def requests(self) -> list[dict]:
        return [r for r in self.records if r.get("kind") == "request"]

    def record_at(self, ref: str) -> dict:
        """The record at one ``segment:offset`` audit ref."""
        segment, _, offset_s = ref.rpartition(":")
        try:
            offset = int(offset_s)
        except ValueError:
            raise AuditError(f"bad audit ref {ref!r} (want SEGMENT:OFFSET)")
        if not _SEGMENT_RE.match(segment):
            raise AuditError(f"bad audit ref segment {segment!r}")
        path = os.path.join(self.directory, segment)
        try:
            with open(path, "rb") as fh:
                fh.seek(offset)
                line = fh.readline()
        except OSError as e:
            raise AuditError(f"cannot read {ref!r}: {e}")
        try:
            rec = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise AuditError(f"no complete record at {ref!r}: {e}")
        rec["_ref"] = ref
        return rec

    # -- reconstruction ----------------------------------------------------
    def verify_chain(self) -> list[int]:
        """Walk every generation record: parent digests must chain, and
        every reconstruction must hash to its recorded digest.  Returns
        the verified generation numbers (raises on the first break)."""
        verified = []
        prev_digest = None
        for rec in self.generations():
            # A checkpoint with an empty parent restarts the chain: a
            # reopened writer has no prior summary, so its first record
            # is a self-contained (digest-verified) checkpoint.
            if rec["kind"] == "checkpoint" and not rec["parent"]:
                prev_digest = None
            if prev_digest is not None and rec["parent"] != prev_digest:
                raise AuditError(
                    f"digest chain broken at generation "
                    f"{rec['generation']}: parent {rec['parent']!r} != "
                    f"recorded {prev_digest!r}"
                )
            self.snapshot_at(rec["generation"])  # digest-verifying
            prev_digest = rec["digest"]
            verified.append(int(rec["generation"]))
        return verified

    def snapshot_at(self, generation: int) -> ClusterSnapshot:
        """Reconstruct one recorded generation: nearest checkpoint at or
        before it, then ``apply(old, diff)`` forward.  The result is
        digest-verified against the record — a reconstruction that does
        not hash identically raises, never silently replays."""
        generation = int(generation)
        cached = self._snapshots.get(generation)
        if cached is not None:
            return cached
        gens = self.generations()
        target_i = None
        for i, rec in enumerate(gens):
            if rec["generation"] == generation:
                target_i = i
                break
        if target_i is None:
            raise AuditError(f"generation {generation} not in the audit log")
        start_i = None
        for i in range(target_i, -1, -1):
            if gens[i]["kind"] == "checkpoint":
                start_i = i
                break
        if start_i is None:
            raise AuditError(
                f"no checkpoint at or before generation {generation}"
            )
        ck = gens[start_i]
        names = list(ck["names"])
        keys = _disambiguate(names)
        rows = {k: tuple(int(x) for x in row) for k, row in zip(keys, ck["rows"])}
        name_of = dict(zip(keys, names))
        taints_of = {
            k: t for k, t in zip(keys, ck.get("taints") or [])
        }
        labels_of = {
            k: lb for k, lb in zip(keys, ck.get("labels") or [])
        }
        semantics = ck["semantics"]
        for rec in gens[start_i + 1 : target_i + 1]:
            diff = SnapshotDiff(
                added={
                    k: tuple(int(x) for x in v)
                    for k, v in rec.get("added", {}).items()
                },
                removed={
                    k: tuple(int(x) for x in v)
                    for k, v in rec.get("removed", {}).items()
                },
                changed={
                    k: {f: int(d) for f, d in ch.items()}
                    for k, ch in rec.get("changed", {}).items()
                },
            )
            rows = diff.apply(rows)
            order = rec.get("order")
            if order is not None:
                rows = {k: rows[k] for k in order}
            added_names = rec.get("added_names", {})
            added_labels = rec.get("added_labels", {})
            for k in diff.removed:
                name_of.pop(k, None)
                taints_of.pop(k, None)
                labels_of.pop(k, None)
            for k in diff.added:
                name_of[k] = added_names.get(k, k)
                if k in added_labels:
                    labels_of[k] = added_labels[k]
            semantics = rec["semantics"]
        snap = self._snapshot_from_state(
            rows, name_of, taints_of, semantics, labels_of
        )
        recorded = gens[target_i]["digest"]
        actual = snapshot_digest(snap)
        if actual != recorded:
            raise AuditError(
                f"generation {generation} reconstruction digest {actual!r} "
                f"!= recorded {recorded!r} (audit log corrupt or "
                "out-of-vocabulary mutation)"
            )
        self._snapshots[generation] = snap
        return snap

    @staticmethod
    def _snapshot_from_state(
        rows: dict[str, tuple[int, ...]],
        name_of: dict[str, str],
        taints_of: dict[str, list],
        semantics: str,
        labels_of: dict[str, dict] | None = None,
    ) -> ClusterSnapshot:
        return snapshot_from_summary(
            rows, name_of, taints_of, semantics, labels_of=labels_of
        )


def snapshot_from_summary(
    rows: dict[str, tuple[int, ...]],
    name_of: dict[str, str],
    taints_of: dict[str, list],
    semantics: str,
    *,
    labels_of: dict[str, dict] | None = None,
) -> ClusterSnapshot:
    """Summary vocabulary → a servable snapshot.  Columns outside the
    fit vocabulary (usage limits, extended resources) reconstruct
    empty — no replayable op consumes them.  Labels ride checkpoint
    records (``labels_of``) so topology/gang requests replay against
    the hierarchy that answered them; absent, they reconstruct empty
    and gang co-location falls to the explicit missing-label policy.
    Shared by the audit replayer and the serving plane's replica
    subscriber (:mod:`..service.plane`), which reconstruct snapshots
    from exactly the same checkpoint+diff record shapes."""
    keys = list(rows)
    n = len(keys)
    cols = {
        f: np.array([rows[k][i] for k in keys], dtype=np.int64)
        for i, f in enumerate(NODE_FIELDS[:-1])
    }
    healthy = np.array(
        [bool(rows[k][len(NODE_FIELDS) - 1]) for k in keys],
        dtype=np.bool_,
    )
    taints = [list(taints_of.get(k) or []) for k in keys]
    labels = [dict((labels_of or {}).get(k) or {}) for k in keys]
    return ClusterSnapshot(
        names=[name_of.get(k, k) for k in keys],
        alloc_cpu_milli=cols["alloc_cpu_milli"],
        alloc_mem_bytes=cols["alloc_mem_bytes"],
        alloc_pods=cols["alloc_pods"],
        used_cpu_req_milli=cols["used_cpu_req_milli"],
        used_cpu_lim_milli=np.zeros(n, dtype=np.int64),
        used_mem_req_bytes=cols["used_mem_req_bytes"],
        used_mem_lim_bytes=np.zeros(n, dtype=np.int64),
        pods_count=cols["pods_count"],
        healthy=healthy,
        semantics=semantics,
        taints=taints if any(taints) else [],
        labels=labels if any(labels) else [],
    )
