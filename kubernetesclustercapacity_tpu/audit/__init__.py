"""Audit subsystem: durable request/state history, deterministic replay,
and shadow-oracle parity monitoring.

Three layers, one goal — make bit-exactness a continuously *observed*
production invariant rather than a test-time claim:

* :mod:`.log` — an append-only JSONL audit log (rotating segments)
  recording every snapshot mutation as the invertible
  :class:`~..timeline.diff.SnapshotDiff` (with periodic full-snapshot
  checkpoints and a ``snapshot_digest`` chain pinning integrity) and
  every answering/mutating request with full arguments plus a result
  digest;
* :mod:`.replay` — offline reconstruction of any recorded generation
  from the nearest checkpoint (``apply(old, diff)``) and bit-exact
  re-answering of recorded requests (``kccap -replay``);
* :mod:`.shadow` — an off-request-path sampler re-checking a fraction
  of live sweep responses against the pure-Python oracle
  (:func:`~..oracle.fit_arrays_python`), alarming on divergence with a
  self-contained repro bundle.
"""

from kubernetesclustercapacity_tpu.audit.log import (
    AuditError,
    AuditLog,
    AuditReader,
)
from kubernetesclustercapacity_tpu.audit.replay import (
    Replayer,
    replay_shadow_bundle,
)
from kubernetesclustercapacity_tpu.audit.shadow import ShadowSampler

__all__ = [
    "AuditError",
    "AuditLog",
    "AuditReader",
    "Replayer",
    "ShadowSampler",
    "replay_shadow_bundle",
]
