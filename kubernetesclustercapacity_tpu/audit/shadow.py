"""Shadow-oracle sampler: live parity monitoring off the request path.

A configurable fraction of live sweep responses is re-evaluated against
the pure-Python sequential oracle (:func:`~..oracle.fit_arrays_python`
— the same ground truth every kernel is pinned bit-exact against at
test time) on a background worker thread.  The request path pays only
the sampling decision and a queue append: no device work, no oracle
walk, and — like every other observability hook — zero registry calls
under ``KCCAP_TELEMETRY=0``.  Nothing here runs inside jitted code.

A divergence is treated as what it is — evidence of kernel/cache/batch
corruption in production:

* ``kccap_shadow_divergence_total`` increments and the
  ``kccap_shadow_divergence`` gauge flips to 1;
* the :class:`~..timeline.alerts.WatchAlert` machine (the SAME machine
  watchlist breaches drive) transitions ``ok → breached`` — sticky
  through ``recovered``, so "it diverged overnight" stays visible;
* a self-contained repro bundle (generation, snapshot digest, the full
  scenario grid, served vs oracle totals, the generation's audit ref)
  is appended as JSONL — :func:`~.replay.replay_shadow_bundle` turns it
  into an offline confirmed mismatch;
* ``/healthz`` reports it (the server's health callable consults
  :attr:`ShadowSampler.diverged`) and ``kccap -doctor -doctor-service``
  prints it as a hard FAILED line.

Sampling is deterministic (an error-diffusion accumulator, not an
RNG): at rate ``r`` exactly every ``1/r``-th eligible sweep is
checked, so a fault is detected within one sample window.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

import numpy as np

from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.timeline.alerts import WatchAlert
from kubernetesclustercapacity_tpu.timeline.diff import snapshot_digest

__all__ = ["ShadowSampler", "oracle_totals"]

#: Sentinel: derive the node mask from the snapshot (the implicit
#: strict-mode taint mask every serving surface applies).
_IMPLICIT = "implicit"


def oracle_totals(snapshot, grid, node_mask=_IMPLICIT) -> list[int]:
    """Sequential-oracle sweep totals for one snapshot × grid — the
    reference answer a served sweep must equal.  ``node_mask`` defaults
    to the snapshot's own implicit taint mask (what the service
    applies); pass an explicit mask (or ``None``) to override."""
    if node_mask is _IMPLICIT:
        node_mask = implicit_taint_mask(snapshot)
    healthy = np.asarray(snapshot.healthy, dtype=bool)
    if node_mask is not None:
        healthy = healthy & np.asarray(node_mask, dtype=bool)
    totals = []
    for s in range(grid.size):
        fits = fit_arrays_python(
            snapshot.alloc_cpu_milli,
            snapshot.alloc_mem_bytes,
            snapshot.alloc_pods,
            snapshot.used_cpu_req_milli,
            snapshot.used_mem_req_bytes,
            snapshot.pods_count,
            int(grid.cpu_request_milli[s]),
            int(grid.mem_request_bytes[s]),
            mode=snapshot.semantics,
            healthy=healthy,
        )
        totals.append(int(sum(fits)))
    return totals


class ShadowSampler:
    """Sample live sweeps, re-check against the oracle, alarm on drift.

    ``sample_rate`` is the checked fraction of eligible sweeps (0 — the
    default posture — disables sampling entirely; 1 checks every
    sweep).  ``bundle_path`` receives one JSONL repro bundle per
    divergent check; with ``audit_log`` set the bundle also lands in
    the audit log itself and carries the divergent generation's audit
    ref.  ``max_queue`` bounds the worker backlog — a slow oracle must
    shed samples, never requests (drops are counted).
    """

    def __init__(
        self,
        sample_rate: float,
        *,
        registry=None,
        oracle=None,
        bundle_path: str | None = None,
        audit_log=None,
        max_queue: int = 128,
        on_divergence=None,
    ) -> None:
        rate = float(sample_rate)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in [0, 1], got {sample_rate}"
            )
        self.sample_rate = rate
        self._oracle = oracle
        self._bundle_path = bundle_path
        self._audit_log = audit_log
        self._max_queue = max(1, int(max_queue))
        self._on_divergence = on_divergence
        self._alert = WatchAlert("shadow-oracle", min_replicas=1)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._inflight = 0
        self._worker: threading.Thread | None = None
        self._closed = False
        self._acc = 0.0
        self._sampled = 0
        self._checked = 0
        self._divergences = 0
        self._dropped = 0
        self._oracle_errors = 0
        self._last_divergence: dict | None = None
        self._m = None
        if registry is not None:
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                enabled as _telemetry_enabled,
            )

            if _telemetry_enabled():
                self._m = {
                    "checked": registry.counter(
                        "kccap_shadow_checked_total",
                        "Live sweep responses re-checked against the "
                        "pure-Python oracle.",
                    ),
                    "divergence": registry.counter(
                        "kccap_shadow_divergence_total",
                        "Shadow checks whose served totals diverged "
                        "from the oracle.",
                    ),
                    "diverged": registry.gauge(
                        "kccap_shadow_divergence",
                        "1 while the shadow-oracle alert is breached "
                        "(a divergence was seen and no clean check "
                        "followed), else 0.",
                    ),
                    "dropped": registry.counter(
                        "kccap_shadow_dropped_total",
                        "Sampled sweeps shed because the shadow queue "
                        "was full.",
                    ),
                }

    # -- request-path side -------------------------------------------------
    def maybe_submit(
        self,
        snapshot,
        generation,
        grid,
        totals,
        schedulable,
        *,
        node_mask=None,
        ts=None,
        trace_id=None,
    ) -> bool:
        """Sampling decision + queue append; the ENTIRE request-path
        cost.  Returns whether this sweep was sampled.  ``totals`` /
        ``schedulable`` are the served answers (host arrays/lists);
        ``node_mask`` is the mask the serving dispatch applied.
        ``trace_id`` is the originating request's trace — a divergence
        bundle that names it can be joined straight to the retained
        span tree of the request that produced the bad answer."""
        if self.sample_rate <= 0.0:
            return False
        with self._cond:
            # Checked under the lock: a lock-free read raced close() —
            # a sample admitted after _closed flips would sit in the
            # queue forever (the worker exits on close).
            if self._closed:
                return False
            self._acc += self.sample_rate
            if self._acc < 1.0:
                return False
            self._acc -= 1.0
            self._sampled += 1
            if len(self._queue) >= self._max_queue:
                self._dropped += 1
                if self._m is not None:
                    self._m["dropped"].inc()
                return True
            self._queue.append(
                (
                    snapshot,
                    generation,
                    grid,
                    np.asarray(totals, dtype=np.int64).copy(),
                    np.asarray(schedulable, dtype=bool).copy(),
                    None if node_mask is None else np.asarray(
                        node_mask, dtype=bool
                    ).copy(),
                    time.time() if ts is None else float(ts),
                    trace_id if isinstance(trace_id, str) else None,
                )
            )
            if self._worker is None:
                from kubernetesclustercapacity_tpu.utils.threads import (
                    supervised,
                )

                self._worker = threading.Thread(
                    target=supervised(self._run, name="kccap-shadow"),
                    daemon=True,
                    name="kccap-shadow",
                )
                self._worker.start()
            self._cond.notify()
        return True

    # -- worker side -------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.25)
                if self._closed and not self._queue:
                    return
                job = self._queue.popleft()
                self._inflight += 1
            try:
                self._check(*job)
            except Exception:  # noqa: BLE001 - monitoring never crashes
                with self._cond:
                    self._oracle_errors += 1
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _check(
        self, snapshot, generation, grid, totals, schedulable, node_mask,
        ts, trace_id=None,
    ) -> None:
        if self._oracle is not None:
            oracle = [
                int(t) for t in self._oracle(snapshot, grid, node_mask)
            ]
        else:
            oracle = oracle_totals(
                snapshot, grid, node_mask=node_mask
            )
        replicas = np.asarray(grid.replicas, dtype=np.int64)
        rows = []
        for s in range(grid.size):
            want_sched = oracle[s] >= int(replicas[s])
            if int(totals[s]) != oracle[s] or bool(
                schedulable[s]
            ) != want_sched:
                rows.append(
                    {
                        "scenario": s,
                        "served_total": int(totals[s]),
                        "oracle_total": oracle[s],
                        "served_schedulable": bool(schedulable[s]),
                        "oracle_schedulable": want_sched,
                    }
                )
        gen_for_alert = generation if isinstance(generation, int) else -1
        with self._cond:
            self._checked += 1
            if rows:
                self._divergences += 1
        if self._m is not None:
            self._m["checked"].inc()
        if not rows:
            self._alert.update(1, gen_for_alert)
            if self._m is not None:
                self._m["diverged"].set(
                    1 if self._alert.state == "breached" else 0
                )
            return
        bundle = {
            "kind": "shadow_divergence",
            "ts": ts,
            **({"trace_id": trace_id} if trace_id else {}),
            "generation": generation,
            "digest": snapshot_digest(snapshot),
            "semantics": snapshot.semantics,
            "nodes": snapshot.n_nodes,
            "scenarios": grid.size,
            "cpu_request_milli": np.asarray(
                grid.cpu_request_milli
            ).tolist(),
            "mem_request_bytes": np.asarray(
                grid.mem_request_bytes
            ).tolist(),
            "replicas": replicas.tolist(),
            "served_totals": np.asarray(totals).tolist(),
            "oracle_totals": oracle,
            "divergent_scenarios": len(rows),
            "rows": rows[:16],
        }
        if self._audit_log is not None:
            try:
                ref = self._audit_log.generation_ref(generation)
                if ref is not None:
                    bundle["audit_ref"] = ref
                bundle["audit_dir"] = self._audit_log.directory
            except Exception:  # noqa: BLE001 - bundling is best-effort
                pass
        self._alert.update(0, gen_for_alert)
        with self._cond:
            self._last_divergence = {
                k: bundle[k]
                for k in (
                    "ts", "generation", "digest", "semantics",
                    "divergent_scenarios",
                )
            }
        if self._m is not None:
            self._m["divergence"].inc()
            self._m["diverged"].set(1)
        self._write_bundle(bundle)
        if self._on_divergence is not None:
            try:
                self._on_divergence(bundle)
            except Exception:  # noqa: BLE001 - observer, not dispatcher
                pass

    def _write_bundle(self, bundle: dict) -> None:
        if self._bundle_path:
            try:
                with open(self._bundle_path, "a", encoding="utf-8") as fh:
                    fh.write(json.dumps(bundle, sort_keys=True) + "\n")
            except OSError:
                pass
        if self._audit_log is not None:
            try:
                self._audit_log.append_raw(bundle)
            except Exception:  # noqa: BLE001 - best-effort
                pass

    # -- read surfaces -----------------------------------------------------
    @property
    def diverged(self) -> bool:
        """True while the shadow alert is breached — the ``/healthz``
        verdict (a clean check after a divergence moves to
        ``recovered``, restoring health but keeping the history)."""
        return self._alert.state == "breached"

    def stats(self) -> dict:
        """Compact health view (``info {audit: true}``, ``/healthz``,
        doctor)."""
        with self._cond:
            return {
                "sample_rate": self.sample_rate,
                "sampled": self._sampled,
                "checked": self._checked,
                "divergences": self._divergences,
                "dropped": self._dropped,
                "oracle_errors": self._oracle_errors,
                "queue": len(self._queue),
                "alert": self._alert.to_wire(),
                "last_divergence": self._last_divergence,
            }

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every queued sample is checked (tests/bench)."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.25))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            # Snapshot under the lock: _worker is lazily spawned under
            # _cond, so a lock-free read could miss a thread started by
            # a concurrent submit and skip the join below.
            worker = self._worker
        if worker is not None:
            worker.join(timeout=5.0)
