"""Deterministic replay: any recorded anomaly becomes an offline repro.

:class:`Replayer` re-answers recorded requests against audit-log
reconstructions of the generations that originally answered them,
through the REAL dispatch path — a private :class:`~..service.server.
CapacityServer` (micro-batching off: a replay is sequential, and a
batch of one is pinned identical to solo anyway) — and asserts the
canonical result digest matches the recorded one.  Volatile fields
(kernel choice, fused-path notes, rendered report text) are stripped by
the canonicalization on BOTH sides, so a divergence is a semantics
divergence, never a backend cosmetic.

Replayable ops are the pure snapshot queries: ``sweep``, ``explain``,
and plain-flag ``fit``.  Requests that consumed raw fixture objects the
audit vocabulary does not carry (drain, priorities, spec-field
constraints, multi-resource sweeps over extended columns) are recorded
for the forensic trail but reported ``skipped`` with the reason.

Surfaced as ``kccap -replay DIR`` (all requests + the digest chain),
``-replay-ref SEGMENT:OFFSET`` (one record — the ``audit_ref`` a
flight-recorder ``dump`` prints, copy-paste round trip), and
``-replay-generation G`` (state reconstruction only).
"""

from __future__ import annotations

import numpy as np

from kubernetesclustercapacity_tpu.audit.log import (
    AuditReader,
    canonical_result_digest,
)

__all__ = ["Replayer", "replay_shadow_bundle"]

#: Ops whose full answer is a function of the packed snapshot alone.
#: ``gang`` qualifies because node labels ride audit checkpoints (the
#: topology hierarchy reconstructs with the fit columns), and the gang
#: result's ``engine`` field is canonical-stripped like ``kernel``.
#: ``optimize`` qualifies because its canonical digest keeps only the
#: closed-form integer packing answer (rounded/FFD totals, demand,
#: schedulability) — every float solver artifact is per-op
#: canonical-stripped, so a TPU-recorded solve verifies on a CPU.
#: ``forecast`` qualifies because growth rates ride the request args
#: explicitly (the server refuses to fit trends; that happens client-
#: side from the audit log itself) — the projection is a pure, seeded
#: function of the reconstructed snapshot.  ``plan`` (the catalog
#: form) likewise: the per-op canonical digest keeps only the integer
#: purchase answer, stripping the float bounds/prices/certificates.
_REPLAYABLE = frozenset(
    {"sweep", "explain", "fit", "gang", "optimize", "forecast", "plan"}
)

#: fit/sweep args that pull in raw fixture objects or columns outside
#: the audit vocabulary — present means "recorded, not replayable".
_FIXTURE_ARGS = frozenset(
    {
        "tolerations", "node_selector", "affinity_terms",
        "anti_affinity_labels", "spread", "extended_requests",
        "priority", "priorities", "namespace",
    }
)


class Replayer:
    """Re-answer recorded requests from audit-log reconstructions.

    Owns one private dispatch server, lazily built and re-pointed at
    each generation as the replay walks the log; ``close()`` tears it
    down.  Context-manager friendly.
    """

    def __init__(self, reader: AuditReader) -> None:
        self._reader = reader
        self._server = None
        self._server_generation = None

    def __enter__(self) -> "Replayer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._server is not None:
            try:
                self._server.shutdown()
            finally:
                self._server = None
                self._server_generation = None

    def _dispatch(self, generation: int, msg: dict):
        from kubernetesclustercapacity_tpu.service.server import (
            CapacityServer,
        )

        snap = self._reader.snapshot_at(generation)
        if self._server is None:
            self._server = CapacityServer(
                snap, port=0, batch_window_ms=0.0, flight_records=1
            )
            self._server_generation = generation
        elif self._server_generation != generation:
            self._server.replace_snapshot(snap)
            self._server_generation = generation
        return self._server.dispatch(msg)

    @staticmethod
    def _skip_reason(rec: dict) -> str | None:
        op = rec.get("op")
        args = rec.get("args") or {}
        if op not in _REPLAYABLE:
            return f"op {op!r} is recorded but not replayable"
        if op == "gang" and "ranks" not in args:
            # The watch-status form answers from the LIVE timeline's
            # alert state, not the snapshot — recorded for the
            # forensic trail, unreplayable by construction.
            return (
                "gang watch-status form reads the live timeline, "
                "not the snapshot"
            )
        if op == "forecast" and "usage" not in args:
            # Same split as gang: the status form is timeline state.
            return (
                "forecast watch-status form reads the live timeline, "
                "not the snapshot"
            )
        if op == "plan" and "catalog" not in args:
            # The legacy node_template form consumes the capacity
            # model's fixture view, which the audit vocabulary does
            # not carry; only the catalog form is a pure snapshot
            # function.
            return (
                "plan node_template form reads the capacity model, "
                "not the snapshot alone"
            )
        blocked = sorted(_FIXTURE_ARGS & set(args))
        if blocked:
            return (
                "args need raw fixture objects the audit vocabulary "
                f"does not carry: {', '.join(blocked)}"
            )
        return None

    def replay_record(self, rec: dict) -> dict:
        """Replay one request record → outcome dict (``status`` one of
        ``ok`` / ``mismatch`` / ``skipped`` / ``error``)."""
        out = {
            "ref": rec.get("_ref", ""),
            "op": rec.get("op"),
            "generation": rec.get("generation"),
            "recorded_digest": rec.get("result_digest", ""),
        }
        if rec.get("kind") != "request":
            out.update(
                status="error",
                reason=f"not a request record (kind={rec.get('kind')!r})",
            )
            return out
        reason = self._skip_reason(rec)
        if reason is not None:
            out.update(status="skipped", reason=reason)
            return out
        msg = {"op": rec["op"], **(rec.get("args") or {})}
        msg.pop("op", None)
        msg["op"] = rec["op"]
        try:
            result = self._dispatch(int(rec["generation"]), msg)
        except Exception as e:  # noqa: BLE001 - the error IS the answer
            replay_error = f"{type(e).__name__}: {e}"
            if rec.get("status") == "error":
                recorded = rec.get("error", "")
                out["replayed_error"] = replay_error
                out["status"] = (
                    "ok" if replay_error == recorded else "mismatch"
                )
                if out["status"] == "mismatch":
                    out["recorded_error"] = recorded
                return out
            out.update(status="error", reason=replay_error)
            return out
        if rec.get("status") == "error":
            out.update(
                status="mismatch",
                reason="recorded dispatch raised; replay answered",
            )
            return out
        digest = canonical_result_digest(rec["op"], result)
        out["replayed_digest"] = digest
        out["status"] = (
            "ok" if digest == rec.get("result_digest", "") else "mismatch"
        )
        return out

    def replay_all(
        self,
        *,
        ops: tuple[str, ...] | None = None,
        tenant: str | None = None,
    ) -> dict:
        """Verify the generation digest chain, then replay every
        recorded request (optionally only ``ops``, optionally only one
        ``tenant`` — the server stamps the DERIVED tenant into each
        audited request's args when tenancy is armed, so one tenant's
        traffic replays in isolation).  The summary dict is the
        ``kccap -replay`` report body; ``clean`` is the exit verdict
        (no mismatches, no replay errors, chain intact)."""
        chain_error = None
        try:
            verified = self._reader.verify_chain()
        except Exception as e:  # noqa: BLE001 - report, don't traceback
            chain_error = f"{type(e).__name__}: {e}"
            verified = []
        outcomes = []
        for rec in self._reader.requests():
            if ops is not None and rec.get("op") not in ops:
                continue
            if (
                tenant is not None
                and (rec.get("args") or {}).get("tenant") != tenant
            ):
                continue
            outcomes.append(self.replay_record(rec))
        counts = {"ok": 0, "mismatch": 0, "skipped": 0, "error": 0}
        for o in outcomes:
            counts[o["status"]] = counts.get(o["status"], 0) + 1
        return {
            "directory": self._reader.directory,
            "generations_verified": verified,
            "chain_error": chain_error,
            "recovered_tail_records": self._reader.recovered_tail,
            "requests": len(outcomes),
            "counts": counts,
            "outcomes": outcomes,
            "clean": (
                chain_error is None
                and counts["mismatch"] == 0
                and counts["error"] == 0
            ),
        }


def replay_shadow_bundle(reader: AuditReader, bundle: dict) -> dict:
    """Re-run a shadow-divergence repro bundle offline: reconstruct the
    recorded generation, dispatch the recorded sweep through the live
    kernel path, and re-check against the pure-Python oracle.  Confirms
    (or refutes) the divergence the sampler alarmed on — with the same
    fault present, the mismatch reproduces; on a healthy build it does
    not."""
    from kubernetesclustercapacity_tpu.audit.shadow import oracle_totals
    from kubernetesclustercapacity_tpu.scenario import ScenarioGrid

    snap = reader.snapshot_at(int(bundle["generation"]))
    grid = ScenarioGrid(
        cpu_request_milli=np.asarray(bundle["cpu_request_milli"]),
        mem_request_bytes=np.asarray(bundle["mem_request_bytes"]),
        replicas=np.asarray(bundle["replicas"]),
    )
    with Replayer(reader) as rp:
        result = rp._dispatch(
            int(bundle["generation"]),
            {
                "op": "sweep",
                "cpu_request_milli": grid.cpu_request_milli.tolist(),
                "mem_request_bytes": grid.mem_request_bytes.tolist(),
                "replicas": grid.replicas.tolist(),
            },
        )
    served = [int(t) for t in result["totals"]]
    oracle = oracle_totals(snap, grid)
    rows = [
        {
            "scenario": s,
            "served_total": served[s],
            "oracle_total": oracle[s],
        }
        for s in range(grid.size)
        if served[s] != oracle[s]
    ]
    return {
        "generation": int(bundle["generation"]),
        "digest": bundle.get("digest"),
        "scenarios": grid.size,
        "diverged": bool(rows),
        "rows": rows,
        "served_matches_bundle": served
        == [int(t) for t in bundle.get("served_totals", [])],
    }
