"""CLI (L4): the reference's six flags, plus the TPU-era surface.

Flag parity with the reference (``ClusterCapacity.go:50-62``): same names,
same single-dash style (``-cpuRequests=200m``), same defaults, same fatal
error text for invalid memory/replicas values.  Added flags gate the new
capabilities:

* ``-backend {tpu,cpu}`` — the jitted JAX kernel (default) or the sequential
  CPU walk (the reference's algorithm, for cross-checking);
* ``-snapshot PATH`` — offline operation from a fixture (``.json``) or a
  checkpointed snapshot (``.npz``) instead of a live apiserver;
* ``-semantics {reference,strict}`` — bug-compatible vs corrected semantics
  (SURVEY.md §2.4);
* ``-output {reference,json,table}`` — the reference's transcript
  (byte-parity), structured JSON, or a compact table;
* ``-grid N`` / ``-seed`` — evaluate a random N-scenario what-if sweep
  instead of a single spec.

Examples::

    kccap -snapshot tests/fixtures/kind-3node.json \
          -cpuRequests=200m -memRequests=250mb -replicas=10
    kccap -snapshot cluster.npz -grid 1000 -output json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

__all__ = ["main", "build_parser"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kccap",
        description="TPU-native Kubernetes cluster-capacity simulator",
    )
    home = os.environ.get("HOME", "") or os.environ.get("USERPROFILE", "")
    default_kubeconfig = os.path.join(home, ".kube", "config") if home else ""
    # The reference's six flags (same defaults, ClusterCapacity.go:50-62).
    p.add_argument("-kubeconfig", default=default_kubeconfig,
                   help="(optional) absolute path to the kubeconfig file")
    p.add_argument("-cpuRequests", default="100m",
                   help="CPU Requests either in cores(1) or milicores(250m)")
    p.add_argument("-cpuLimits", default="200m",
                   help="CPU Limits either in cores(2) or milicores(500m)")
    p.add_argument("-memRequests", default="100mb",
                   help="Memory requests either in GB(1) or megabytes(250mb)")
    p.add_argument("-memLimits", default="200mb",
                   help="Memory limits either in GB(2) or megabytes(500mb)")
    p.add_argument("-replicas", default="1", help="No of pod replicas")
    # New surface.
    p.add_argument("-backend", choices=("tpu", "cpu", "native"), default="tpu",
                   help="vectorized JAX kernel (tpu), pure-Python sequential "
                        "walk (cpu), or the compiled C++ loop (native)")
    p.add_argument("-snapshot", default="",
                   help="offline source: fixture .json or checkpoint .npz")
    p.add_argument("-semantics", choices=("reference", "strict"),
                   default=None,
                   help="bug-compatible reference semantics or corrected mode "
                        "(default: reference; for .npz snapshots, the "
                        "semantics they were packed with)")
    p.add_argument("-output", choices=("reference", "json", "table"),
                   default="reference", help="report format")
    p.add_argument("-grid", type=int, default=0, metavar="N",
                   help="evaluate a random N-scenario sweep instead of one spec")
    p.add_argument("-seed", type=int, default=0, help="sweep RNG seed")
    p.add_argument("-kernel", choices=("auto", "exact"), default="auto",
                   help="sweep kernel: auto (Pallas fast path when provably "
                        "bit-exact) or exact (force the int64 XLA kernel)")
    p.add_argument("-save-snapshot", default="", metavar="PATH",
                   help="checkpoint the packed snapshot to PATH (.npz)")
    p.add_argument("-extended-resources", default="",
                   dest="extended_resources", metavar="NAMES",
                   help="comma-separated extra resource columns to pack "
                        "(requires -semantics strict; e.g. nvidia.com/gpu)")
    p.add_argument("-extended-request", action="append", default=[],
                   dest="extended_requests", metavar="NAME=QTY",
                   help="per-replica request for an extended resource "
                        "(repeatable; strict quantity grammar, e.g. "
                        "nvidia.com/gpu=2, ephemeral-storage=10Gi)")
    p.add_argument("-drain", default="", metavar="NODE",
                   help="simulate kubectl drain: rehome NODE's pods (each "
                        "with its own requests) onto the remaining nodes "
                        "and print the plan; exit 1 if any pod cannot be "
                        "rehomed (strict semantics, fixture/live sources)")
    p.add_argument("-drain-policy", dest="drain_policy", default="best-fit",
                   choices=("first-fit", "best-fit", "spread"),
                   help="bin-packing policy for -drain rehoming")
    p.add_argument("-doctor", action="store_true",
                   help="diagnose the environment (backend probe with a "
                        "hang-proof timeout, native toolchain, fast-path "
                        "state) and exit; exit code 1 on any hard failure")
    p.add_argument("-doctor-timeout", type=float, default=30.0,
                   metavar="SECONDS",
                   help="how long -doctor waits for backend init before "
                        "declaring it wedged")
    p.add_argument("-doctor-service", dest="doctor_service", default=None,
                   metavar="HOST:PORT",
                   help="with -doctor: also probe a running capacity "
                        "service's resilience counters (deadline sheds, "
                        "fused-path breaker, follower backoff) over its "
                        "info op")
    p.add_argument("-metrics-port", type=int, default=0, dest="metrics_port",
                   metavar="PORT",
                   help="serve Prometheus /metrics (the process telemetry "
                        "registry: fused-path health, kernel latency) on "
                        "localhost:PORT for the run's duration")
    p.add_argument("-trace-log", default=None, dest="trace_log",
                   metavar="PATH",
                   help="append a JSONL span for this invocation (op, "
                        "duration, status) to PATH")
    p.add_argument("-trace-log-max-bytes", type=int, default=0,
                   dest="trace_log_max_bytes", metavar="N",
                   help="rotate the -trace-log file to PATH.1 once it "
                        "exceeds N bytes (0 = unbounded)")
    p.add_argument("-explain", action="store_true",
                   help="print per-node bottleneck attribution (binding "
                        "constraint, per-resource fits, marginal '+1 "
                        "replica' analysis) for the spec instead of the "
                        "fit report; -output json selects the structured "
                        "form (-backend tpu only)")
    p.add_argument("-jax-profile", default="", dest="jax_profile",
                   metavar="DIR",
                   help="capture a jax.profiler trace of the run into "
                        "DIR (view with TensorBoard/Perfetto) — opt-in "
                        "compile/runtime visibility for kernel work")
    p.add_argument("-node-bucket-floor", type=int, default=0,
                   dest="node_bucket_floor", metavar="N",
                   help="floor of the node-axis shape-bucket ladder for "
                        "the exact sweep kernels (node counts pad to the "
                        "next power of two >= the floor; 0 = keep the "
                        "default/KCCAP_NODE_BUCKET_FLOOR setting)")
    p.add_argument("-group-min-count", type=int, default=0,
                   dest="group_min_count", metavar="K",
                   help="minimum mean nodes-per-group for the node-shape"
                        "-compressed (grouped) sweep dispatch to engage "
                        "(KCCAP_GROUPING=0 disables grouping; 0 = keep "
                        "the default/KCCAP_GROUP_MIN_COUNT setting)")
    p.add_argument("-timeline", default=None, metavar="HOST:PORT",
                   help="render a running capacity service's timeline "
                        "(per-generation watchlist capacities, attributed "
                        "deltas, alert states) and exit; -output json "
                        "selects the structured form")
    p.add_argument("-timeline-since", type=int, default=None,
                   dest="timeline_since", metavar="GEN",
                   help="with -timeline: only records/deltas strictly "
                        "after generation GEN")
    p.add_argument("-timeline-watch", default=None, dest="timeline_watch",
                   metavar="NAME",
                   help="with -timeline: narrow records/deltas/alerts to "
                        "one watch")
    p.add_argument("-car", default=None, metavar="HOST:PORT",
                   help="render a running capacity service's "
                        "capacity-at-risk status (per quantile watch: "
                        "capacity at its confidence, probability-of-fit, "
                        "alert state) and exit; -output json selects the "
                        "structured form; exit 1 while any quantile "
                        "watch is breached (or none are configured)")
    p.add_argument("-car-spec", default="", dest="car_spec", metavar="FILE",
                   help="offline capacity-at-risk: load a stochastic "
                        "usage spec (YAML/JSON: per-pod cpu/memory "
                        "distributions, replicas, samples, seed) and "
                        "report capacity quantiles for the -snapshot "
                        "source; deterministic in the seed; exit 1 when "
                        "the spec's replicas miss its confidence bar")
    p.add_argument("-car-samples", type=int, default=0, dest="car_samples",
                   metavar="S",
                   help="with -car-spec: override the spec's Monte "
                        "Carlo sample count (0 = keep the spec's / the "
                        "KCCAP_CAR_SAMPLES default)")
    p.add_argument("-car-seed", type=int, default=None, dest="car_seed",
                   metavar="N",
                   help="with -car-spec: override the spec's sampling "
                        "seed (explicit seeds make every run replayable)")
    p.add_argument("-forecast", default=None, metavar="HOST:PORT",
                   help="render a running capacity service's forecast "
                        "status (per horizon watch: current capacity at "
                        "its quantile, projected horizon minimum, "
                        "time-to-breach, alert state) and exit; -output "
                        "json selects the structured form; exit 1 while "
                        "any horizon watch is breached (or none are "
                        "configured)")
    p.add_argument("-forecast-spec", default="", dest="forecast_spec",
                   metavar="FILE",
                   help="offline capacity forecast: load a stochastic "
                        "usage spec extended with a horizon block "
                        "(steps, step_s) and either explicit growth "
                        "rates (growth: cpu_per_s/memory_per_s) or an "
                        "audit_dir to fit them from verified history, "
                        "then project the quantile ladder over the "
                        "horizon against the -snapshot source; exit 1 "
                        "when any projected quantile crosses the "
                        "threshold within the horizon")
    p.add_argument("-plan", default="", dest="plan_spec", metavar="FILE",
                   help="offline certified capacity plan: load a "
                        "stochastic usage spec (plus optional target, "
                        "quantile, drain fields) and answer 'cheapest "
                        "node set from -catalog that restores the "
                        "quantile to the target' for the -snapshot "
                        "source, with an LP lower bound and host-side "
                        "certification; exit 1 unless the plan is "
                        "certified")
    p.add_argument("-catalog", default="", metavar="FILE",
                   help="with -plan: the node-shape catalog (YAML/JSON: "
                        "shapes with name, cpu, memory, pods, "
                        "unit_cost, max_count)")
    p.add_argument("-gang", default=None, metavar="HOST:PORT",
                   help="render a running capacity service's gang-watch "
                        "status (per gang watch: last whole-gang count, "
                        "binding topology level, alert state) and exit; "
                        "-output json selects the structured form; exit "
                        "1 while any gang watch is breached (or none "
                        "are configured)")
    p.add_argument("-gang-spec", default="", dest="gang_spec",
                   metavar="FILE",
                   help="offline gang capacity: load a gang spec "
                        "(YAML/JSON: the watchlist pod-block grammar "
                        "plus a gang block — ranks, count, colocate, "
                        "spread_level, max_ranks_per_domain, "
                        "anti_affinity_host) and count whole gangs "
                        "against the -snapshot source's zone/rack/host "
                        "hierarchy; exit code by schedulability (1 when "
                        "fewer than 'count' gangs fit)")
    p.add_argument("-optimize", action="store_true",
                   help="answer the spec (or -grid sweep) with the "
                        "optimization backend instead of the fit "
                        "report: certified LP upper bound, rounded "
                        "integral packing, first-fit baseline, "
                        "optimality gap, and per-resource shadow "
                        "prices; every answer carries a duality "
                        "certificate or is marked uncertified; exit 1 "
                        "when unschedulable or any solve is "
                        "uncertified (-backend tpu only)")
    p.add_argument("-opt-backend", dest="opt_backend",
                   choices=("ffd", "lp"), default="lp",
                   help="with -optimize: the certified LP/PDHG solver "
                        "(lp, default) or the bug-compatible first-fit "
                        "reference walk alone (ffd)")
    p.add_argument("-replay", default="", metavar="DIR",
                   help="replay a kccap-server audit log: verify the "
                        "generation digest chain, reconstruct every "
                        "recorded generation from the nearest "
                        "checkpoint, and re-answer every recorded "
                        "sweep/explain/fit bit-for-bit against its "
                        "recorded result digest; -output json selects "
                        "the structured form; exit 1 on any mismatch")
    p.add_argument("-replay-ref", default=None, dest="replay_ref",
                   metavar="SEGMENT:OFFSET",
                   help="with -replay: replay only the request at this "
                        "audit ref (the audit_ref field flight-recorder "
                        "dump records carry)")
    p.add_argument("-replay-generation", type=int, default=None,
                   dest="replay_generation", metavar="GEN",
                   help="with -replay: reconstruct generation GEN and "
                        "verify its digest instead of replaying "
                        "requests")
    p.add_argument("-replay-tenant", default=None, dest="replay_tenant",
                   metavar="TENANT",
                   help="with -replay: replay only requests the server "
                        "attributed to TENANT (servers started with "
                        "-tenants stamp the derived tenant into each "
                        "audited request)")
    p.add_argument("-slo-status", default=None, dest="slo_status",
                   metavar="HOST:PORT",
                   help="render a running capacity service's SLO "
                        "burn-rate status (objectives, short/long-"
                        "window burn rates, alert states) and exit; "
                        "-output json selects the structured form; "
                        "exit 1 while any SLO is breached (or the "
                        "server runs without -slo)")
    p.add_argument("-dump", default=None, metavar="HOST:PORT",
                   help="render a running capacity service's flight "
                        "recorder (its last K dispatched requests, "
                        "each with the per-phase latency breakdown) "
                        "and exit; -output json selects the "
                        "structured form")
    p.add_argument("-dump-limit", type=int, default=None,
                   dest="dump_limit", metavar="N",
                   help="with -dump: only the N most recent records")
    p.add_argument("-dump-tenant", default=None, dest="dump_tenant",
                   metavar="TENANT",
                   help="with -dump: only records the server attributed "
                        "to TENANT (requires a server started with "
                        "-tenants)")
    p.add_argument("-drain-server", default=None, dest="drain_server",
                   metavar="HOST:PORT",
                   help="gracefully drain a running capacity server: it "
                        "stops accepting compute/mutation ops, finishes "
                        "in-flight work, emits its final drain record, "
                        "and deregisters from the replication plane; "
                        "prints the drain record and exits 1 if in-"
                        "flight work outlived the timeout")
    p.add_argument("-drain-timeout-s", type=float, default=None,
                   dest="drain_timeout_s", metavar="SECONDS",
                   help="with -drain-server: how long the server may "
                        "wait for in-flight work (default: the "
                        "server's own -drain-timeout-s)")
    p.add_argument("-plane-status", default=None, dest="plane_status",
                   metavar="HOST:PORT",
                   help="print a running server's serving-plane status "
                        "(leader fan-out stats or replica sync/"
                        "staleness state, plus capabilities) and exit; "
                        "exit 1 when the replica is stale or the "
                        "server is draining")
    p.add_argument("-fed-status", default=None, dest="fed_status",
                   metavar="HOST:PORT",
                   help="print a federation endpoint's per-cluster "
                        "degradation vector (generation, verified age, "
                        "fresh/stale/lost) and exit; exit 1 when any "
                        "cluster is lost (excluded from fleet totals)")
    p.add_argument("-fed-sweep", default=None, dest="fed_sweep",
                   metavar="HOST:PORT",
                   help="fleet-global capacity for the six scenario "
                        "flags against a federation endpoint: grand "
                        "totals over non-lost clusters plus the "
                        "per-cluster split, every reply annotated with "
                        "the staleness vector; exit 1 when the scenario "
                        "does not fit or any cluster is lost")
    p.add_argument("-doctor-federation", dest="doctor_federation",
                   default=None, metavar="HOST:PORT",
                   help="with -doctor: also probe a federation "
                        "endpoint (cluster states, generations) — a "
                        "lost cluster is a hard FAILED line")
    p.add_argument("-trace-tree", default=None, dest="trace_tree",
                   metavar="TRACE_ID",
                   help="stitch one distributed trace back together "
                        "from per-process span logs (-trace-logs) and "
                        "print the tree, critical path, and dominating "
                        "phase; -output json selects the structured "
                        "form; exit 1 when the trace is not found or "
                        "the critical path is refused (clock skew)")
    p.add_argument("-trace-logs", default="", dest="trace_logs",
                   metavar="DIR[,DIR...]",
                   help="with -trace-tree: comma-separated trace-log "
                        "files or directories (directories contribute "
                        "every *.jsonl plus .1 rotations) — one per "
                        "process in the topology")
    p.add_argument("-profile", default=None, metavar="HOST:PORT",
                   help="collect a collapsed flamegraph window from a "
                        "running capacity service's sampling profiler "
                        "(/debug/profile on its metrics port), print "
                        "the phase-attribution summary, and exit; "
                        "-output json selects the structured form; "
                        "exit 1 when the server's profiler is off")
    p.add_argument("-profile-seconds", type=float, default=5.0,
                   dest="profile_seconds", metavar="SECONDS",
                   help="with -profile: how long the server samples "
                        "before replying (server caps at 300)")
    p.add_argument("-profile-out", default="", dest="profile_out",
                   metavar="FILE",
                   help="with -profile: write the collapsed profile "
                        "to FILE (flamegraph.pl/speedscope food) "
                        "instead of stdout")
    p.add_argument("-bench-diff", nargs="+", default=None,
                   dest="bench_diff", metavar="OLD_NEW_OR_DIR",
                   help="compare two bench artifacts (OLD.json "
                        "NEW.json) under the committed per-row noise "
                        "thresholds and exit 1 on any regression; a "
                        "single directory argument walks every "
                        "BENCH_r*.json round in order (trajectory "
                        "mode); degraded rounds and missing rows are "
                        "named, never failed; -output json selects "
                        "the structured artifact")
    p.add_argument("-bench-thresholds", default="",
                   dest="bench_thresholds", metavar="FILE",
                   help="with -bench-diff: the per-row noise model "
                        "(default: BENCH_THRESHOLDS.json next to the "
                        "inputs, else built-in defaults)")
    return p


def _split_single_dash_eq(argv: list[str]) -> list[str]:
    """Support Go-style ``-flag=value`` (argparse only splits ``--flag=``)."""
    out = []
    for a in argv:
        if a.startswith("-") and not a.startswith("--") and "=" in a:
            flag, _, val = a.partition("=")
            out += [flag, val]
        else:
            out.append(a)
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(
        _split_single_dash_eq(sys.argv[1:] if argv is None else list(argv))
    )

    if args.doctor:
        from kubernetesclustercapacity_tpu.utils.doctor import run_doctor

        service_addr = None
        if args.doctor_service:
            host, _, port = args.doctor_service.rpartition(":")
            try:
                service_addr = (host or "127.0.0.1", int(port))
            except ValueError:
                print(f"ERROR : bad -doctor-service {args.doctor_service!r} "
                      "(want HOST:PORT)", file=sys.stderr)
                return 1
        federation_addr = None
        if args.doctor_federation:
            host, _, port = args.doctor_federation.rpartition(":")
            try:
                federation_addr = (host or "127.0.0.1", int(port))
            except ValueError:
                print(f"ERROR : bad -doctor-federation "
                      f"{args.doctor_federation!r} (want HOST:PORT)",
                      file=sys.stderr)
                return 1
        report, code = run_doctor(
            backend_timeout_s=args.doctor_timeout, service_addr=service_addr,
            federation_addr=federation_addr,
        )
        print(report)
        return code

    if args.timeline:
        return _run_timeline(args)

    if args.car:
        return _run_car_status(args)

    if args.forecast:
        return _run_forecast_status(args)

    if args.gang:
        return _run_gang_status(args)

    if args.slo_status:
        return _run_slo_status(args)

    if args.dump:
        return _run_dump(args)

    if args.drain_server:
        return _run_drain_server(args)

    if args.plane_status:
        return _run_plane_status(args)

    if args.fed_status:
        return _run_fed_status(args)

    if args.fed_sweep:
        return _run_fed_sweep(args)

    if args.replay:
        return _run_replay(args)

    if args.trace_tree:
        return _run_trace_tree(args)

    if args.profile:
        return _run_profile(args)

    if args.bench_diff:
        return _run_bench_diff(args)

    # Telemetry surfaces (both opt-in, zero cost otherwise): a scrape
    # endpoint over the process registry — the fused-path counters and
    # kernel-latency histograms the sweep below feeds — and a JSONL
    # span for the whole invocation.
    metrics_server = None
    trace_log = None
    if args.metrics_port:
        from kubernetesclustercapacity_tpu.telemetry.exposition import (
            start_metrics_server,
        )
        from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY
        from kubernetesclustercapacity_tpu.telemetry.process import (
            register_process_metrics,
        )

        # The offline CLI serves the same first-questions gauges (RSS,
        # fds, threads, build) a long-running server does — a -grid
        # sweep scraped mid-run was previously blind to them.
        register_process_metrics(REGISTRY)
        try:
            metrics_server = start_metrics_server(
                REGISTRY, port=args.metrics_port
            )
        except OSError as e:
            print(f"ERROR : cannot bind metrics port: {e}", file=sys.stderr)
            return 1
        print(
            f"metrics on http://{metrics_server.address[0]}:"
            f"{metrics_server.address[1]}/metrics",
            file=sys.stderr,
        )
    if args.trace_log:
        from kubernetesclustercapacity_tpu.telemetry.tracing import (
            Span,
            TraceLog,
        )

        trace_log = TraceLog(
            args.trace_log, max_bytes=max(args.trace_log_max_bytes, 0)
        )

    def run() -> int:
        if args.jax_profile:
            # Opt-in jax.profiler capture of the whole run (compile +
            # device work); the trace directory is TensorBoard/Perfetto
            # food.  Wrapping here (after source/flag validation would
            # be nicer, but the compile happens inside _run_command)
            # keeps profiling a pure observation.
            import jax

            with jax.profiler.trace(args.jax_profile):
                return _run_command(args)
        return _run_command(args)

    try:
        if trace_log is not None:
            mode = (
                "drain" if args.drain else
                "car" if args.car_spec else
                "forecast" if args.forecast_spec else
                "plan" if args.plan_spec else
                "gang" if args.gang_spec else
                "optimize" if args.optimize else
                "explain" if args.explain else
                "grid" if args.grid > 0 else "fit"
            )
            with Span(f"kccap:{mode}", trace_log=trace_log) as span:
                rc = run()
                span._extra["exit_code"] = rc
                return rc
        return run()
    finally:
        if trace_log is not None:
            trace_log.close()
        if metrics_server is not None:
            metrics_server.shutdown()


def _run_command(args) -> int:
    """Everything after flag parsing/telemetry setup: source resolution
    and the fit/grid/drain dispatch (the pre-telemetry ``main`` body)."""
    from kubernetesclustercapacity_tpu.scenario import (
        ScenarioError,
        scenario_from_flags,
    )

    if args.node_bucket_floor > 0:
        from kubernetesclustercapacity_tpu import devcache

        devcache.set_node_bucket_floor(args.node_bucket_floor)
    if args.group_min_count > 0:
        from kubernetesclustercapacity_tpu import snapshot as _snapshot_mod

        _snapshot_mod.set_group_min_count(args.group_min_count)

    try:
        scenario = scenario_from_flags(
            cpuRequests=args.cpuRequests,
            cpuLimits=args.cpuLimits,
            memRequests=args.memRequests,
            memLimits=args.memLimits,
            replicas=args.replicas,
        )
    except ScenarioError as e:
        # The reference prints an ERROR line and exits 1 (:68-83) —
        # reproduced byte-for-byte when the error maps to one of its
        # fatal flag paths.
        print(e.reference_line or f"ERROR : {e} ...exiting")
        return 1

    if args.grid <= 0:
        try:
            scenario.validate()
        except ScenarioError as e:
            # No reference line exists here: the reference would NOT exit —
            # it would panic later at the division (Q8 divergence).
            print(f"ERROR : {e} ...exiting")
            return 1

    fixture, snapshot = _load_source(args)
    if snapshot is None:
        return 1

    if args.save_snapshot:
        snapshot.save(args.save_snapshot)
        print(f"snapshot checkpointed to {args.save_snapshot}", file=sys.stderr)

    if args.car_spec:
        return _run_car_spec(args, snapshot)
    if args.forecast_spec:
        return _run_forecast_spec(args, snapshot)
    if args.plan_spec:
        return _run_plan(args, snapshot)
    if args.gang_spec:
        return _run_gang_spec(args, snapshot)
    if args.optimize:
        return _run_optimize(args, snapshot, scenario)
    if args.drain:
        return _run_drain(args, fixture, snapshot)
    if args.explain:
        return _run_explain(args, snapshot, scenario)
    if args.grid > 0:
        return _run_grid(args, snapshot)
    return _run_single(args, fixture, snapshot, scenario)


def _run_timeline(args) -> int:
    """-timeline HOST:PORT: fetch and render a service's capacity
    timeline (the drift view no offline snapshot can answer — it lives
    with the server that watched the generations go by)."""
    from kubernetesclustercapacity_tpu.report import (
        timeline_json_report,
        timeline_table_report,
    )

    addr = _parse_addr("-timeline", args.timeline)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.timeline(
                since_generation=args.timeline_since,
                watch=args.timeline_watch,
            )
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch timeline from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(timeline_json_report(result))
    else:
        print(timeline_table_report(result))
    if not result.get("enabled", False):
        return 1
    breached = [
        name
        for name, a in result.get("alerts", {}).items()
        if a.get("state") == "breached"
    ]
    # Exit by the verdict, like -drain does: a breached watchlist is a
    # scriptable signal, not just prose.
    return 1 if breached else 0


def _parse_addr(flag_name: str, value: str):
    """``HOST:PORT`` → ``(host, port)`` or ``None`` (error printed)."""
    host, _, port = value.rpartition(":")
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        print(f"ERROR : bad {flag_name} {value!r} (want HOST:PORT)",
              file=sys.stderr)
        return None


def _diag_client(addr):
    """The short-budget client every one-shot diagnostic flag uses."""
    from kubernetesclustercapacity_tpu.resilience import RetryPolicy
    from kubernetesclustercapacity_tpu.service.client import CapacityClient

    return CapacityClient(
        *addr,
        connect_timeout_s=5.0,
        timeout_s=10.0,
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.1),
        deadline_s=10.0,
    )


def _run_car_status(args) -> int:
    """-car HOST:PORT: fetch and render a service's capacity-at-risk
    watch status (the quantile-watch slice of the timeline).  Exits by
    the verdict, like -timeline: a breached quantile watch — "with 95%
    confidence fewer than N replicas fit" — is a scriptable failure,
    and so is a server with no quantile watches at all."""
    from kubernetesclustercapacity_tpu.report import (
        car_status_json_report,
        car_status_table_report,
    )

    addr = _parse_addr("-car", args.car)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.car()
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch capacity-at-risk status from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(car_status_json_report(result))
    else:
        print(car_status_table_report(result))
    if not result.get("enabled", False):
        return 1
    return 1 if result.get("breached") else 0


def _run_car_spec(args, snapshot) -> int:
    """-car-spec FILE: offline capacity-at-risk against the -snapshot
    source.  Applies the same implicit strict-mode taint mask as every
    other surface, prints the quantile ladder (table or JSON), and
    exits by the spec's own confidence bar: 1 when
    ``P(fit replicas) < confidence``."""
    import dataclasses as _dc

    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.report import (
        car_json_report,
        car_table_report,
    )
    from kubernetesclustercapacity_tpu.stochastic import (
        DistributionError,
        capacity_at_risk,
        load_stochastic_spec,
    )

    if args.backend != "tpu":
        print("ERROR : -car-spec runs on the JAX kernels (-backend tpu); "
              "cpu/native backends are fit-only cross-checks ...exiting")
        return 1
    try:
        spec = load_stochastic_spec(args.car_spec)
    except (OSError, DistributionError) as e:
        print(f"ERROR : bad -car-spec: {e}")
        return 1
    if args.car_samples:
        if args.car_samples < 2:
            print("ERROR : -car-samples must be >= 2 ...exiting")
            return 1
        spec = _dc.replace(spec, samples=args.car_samples)
    if args.car_seed is not None:
        spec = _dc.replace(spec, seed=args.car_seed)
    try:
        result = capacity_at_risk(
            snapshot, spec, mode=args.semantics,
            node_mask=implicit_taint_mask(snapshot),
        )
    except (DistributionError, ValueError) as e:
        print(f"ERROR : {e}")
        return 1
    if args.output == "json":
        print(car_json_report(result.to_wire()))
    else:
        print(car_table_report(result.to_wire()))
    return 0 if result.schedulable else 1


def _run_forecast_status(args) -> int:
    """-forecast HOST:PORT: fetch and render a service's forecast
    (horizon) watch status.  Exits by the verdict, like -car: a
    breached horizon watch — "the p95 capacity crosses the threshold
    within the horizon" — is a scriptable failure, and so is a server
    with no horizon watches at all."""
    from kubernetesclustercapacity_tpu.report import (
        forecast_status_json_report,
        forecast_status_table_report,
    )

    addr = _parse_addr("-forecast", args.forecast)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.forecast()
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch forecast status from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(forecast_status_json_report(result))
    else:
        print(forecast_status_table_report(result))
    if not result.get("enabled", False):
        return 1
    return 1 if result.get("breached") else 0


def _load_operator_doc(path: str):
    """YAML-when-PyYAML-else-strict-JSON — the same loader split every
    operator file (watchlist, stochastic spec, catalog) uses."""
    import json as _json

    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = _json.loads(text)
        except ValueError as e:
            raise ValueError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise ValueError(f"{path}: cannot parse: {e}") from e
    return data


def _run_forecast_spec(args, snapshot) -> int:
    """-forecast-spec FILE: offline horizon projection against the
    -snapshot source.

    The file extends the stochastic usage-spec grammar with a
    ``horizon:`` block (steps, step_s), an optional ``threshold``, and
    growth provenance: either explicit ``growth: {cpu_per_s,
    memory_per_s}`` relative rates or ``audit_dir:`` pointing at a
    kccap-server audit log, in which case the trend is Theil–Sen
    fitted from the digest-verified generations.  Exits 1 when any
    projected quantile crosses the threshold within the horizon."""
    from kubernetesclustercapacity_tpu.forecast import (
        DEFAULT_STEP_S,
        DEFAULT_STEPS,
        max_steps,
        project_horizon,
        trend_from_audit,
    )
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.report import (
        forecast_json_report,
        forecast_table_report,
    )
    from kubernetesclustercapacity_tpu.stochastic import (
        DistributionError,
        InsufficientHistoryError,
        parse_stochastic_spec,
    )

    if args.backend != "tpu":
        print("ERROR : -forecast-spec runs on the JAX kernels (-backend "
              "tpu); cpu/native backends are fit-only cross-checks "
              "...exiting")
        return 1
    try:
        doc = _load_operator_doc(args.forecast_spec)
    except (OSError, ValueError) as e:
        print(f"ERROR : bad -forecast-spec: {e}")
        return 1
    if not isinstance(doc, dict):
        print("ERROR : bad -forecast-spec: expected a mapping")
        return 1
    doc = dict(doc)
    horizon = doc.pop("horizon", None) or {}
    growth = doc.pop("growth", None)
    audit_dir = doc.pop("audit_dir", None)
    threshold = doc.pop("threshold", None)
    quantiles = doc.pop("quantiles", None)
    try:
        spec = parse_stochastic_spec(doc)
        if not isinstance(horizon, dict) or not set(horizon) <= {
            "steps", "step_s"
        }:
            raise ValueError(
                "horizon: wants a mapping with steps and/or step_s"
            )
        steps = horizon.get("steps", DEFAULT_STEPS)
        step_s = horizon.get("step_s", DEFAULT_STEP_S)
        if (growth is None) == (audit_dir is None):
            raise ValueError(
                "exactly one of growth: {cpu_per_s, memory_per_s} or "
                "audit_dir: is required"
            )
        if threshold is not None and (
            isinstance(threshold, bool) or not isinstance(threshold, int)
        ):
            raise ValueError(f"threshold: expected an int, got {threshold!r}")
        if quantiles is not None:
            if not isinstance(quantiles, list) or not quantiles:
                raise ValueError("quantiles: expected a non-empty list")
            quantiles = tuple(float(q) for q in quantiles)
    except (DistributionError, ValueError, TypeError) as e:
        print(f"ERROR : bad -forecast-spec: {e}")
        return 1

    trend_wire = {}
    degraded = False
    if audit_dir is not None:
        try:
            fit_cpu, series = trend_from_audit(audit_dir, "cpu", "usage")
            fit_mem, _ = trend_from_audit(audit_dir, "memory", "usage")
        except (OSError, InsufficientHistoryError, ValueError) as e:
            print(f"ERROR : cannot fit trend from {audit_dir}: {e}")
            return 1
        growth_cpu = max(fit_cpu.relative_slope_per_s, 0.0)
        growth_mem = max(fit_mem.relative_slope_per_s, 0.0)
        degraded = series.degraded_time_axis
        trend_wire = {
            "source": str(audit_dir),
            "cpu": fit_cpu.to_wire(),
            "memory": fit_mem.to_wire(),
        }
    else:
        if not isinstance(growth, dict) or not set(growth) <= {
            "cpu_per_s", "memory_per_s"
        }:
            print("ERROR : bad -forecast-spec: growth wants cpu_per_s "
                  "and/or memory_per_s")
            return 1
        try:
            growth_cpu = float(growth.get("cpu_per_s", 0.0))
            growth_mem = float(growth.get("memory_per_s", 0.0))
        except (TypeError, ValueError):
            print("ERROR : bad -forecast-spec: growth rates must be numbers")
            return 1
    try:
        result = project_horizon(
            snapshot, spec,
            steps=int(steps), step_s=float(step_s),
            growth_cpu_per_s=growth_cpu, growth_mem_per_s=growth_mem,
            mode=args.semantics or snapshot.semantics,
            node_mask=implicit_taint_mask(snapshot),
            **({"quantiles": quantiles} if quantiles else {}),
            threshold=threshold,
            degraded_time_axis=degraded,
        )
    except (DistributionError, ValueError, TypeError) as e:
        print(f"ERROR : {e} (steps must stay within "
              f"KCCAP_FORECAST_MAX_STEPS={max_steps()})")
        return 1
    result.trend = trend_wire
    wire = result.to_wire()
    if args.output == "json":
        print(forecast_json_report(wire))
    else:
        print(forecast_table_report(wire))
    return 1 if wire["breached_within_horizon"] else 0


def _run_plan(args, snapshot) -> int:
    """-plan FILE -catalog FILE: offline certified capacity planning
    against the -snapshot source.

    The plan file is the stochastic usage-spec grammar plus optional
    ``target`` (replicas to restore, default the spec's), ``quantile``
    (default 0.95) and ``drain: true`` (also compute the scale-down
    dual).  Exits 0 only when the plan is certified — an uncertified
    answer is a scriptable failure, exactly like -optimize."""
    from kubernetesclustercapacity_tpu.forecast import (
        PlannerError,
        load_catalog,
        plan_capacity,
    )
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.report import (
        plan_json_report,
        plan_table_report,
    )
    from kubernetesclustercapacity_tpu.stochastic import (
        DistributionError,
        parse_stochastic_spec,
    )

    if args.backend != "tpu":
        print("ERROR : -plan runs on the JAX kernels (-backend tpu); "
              "cpu/native backends are fit-only cross-checks ...exiting")
        return 1
    if not args.catalog:
        print("ERROR : -plan needs -catalog FILE (the node-shape "
              "catalog to buy from) ...exiting")
        return 1
    try:
        catalog = load_catalog(args.catalog)
    except (OSError, PlannerError) as e:
        print(f"ERROR : bad -catalog: {e}")
        return 1
    try:
        doc = _load_operator_doc(args.plan_spec)
    except (OSError, ValueError) as e:
        print(f"ERROR : bad -plan: {e}")
        return 1
    if not isinstance(doc, dict):
        print("ERROR : bad -plan: expected a mapping")
        return 1
    doc = dict(doc)
    target = doc.pop("target", None)
    quantile = doc.pop("quantile", 0.95)
    drain = doc.pop("drain", False)
    try:
        spec = parse_stochastic_spec(doc)
        if target is not None and (
            isinstance(target, bool) or not isinstance(target, int)
        ):
            raise ValueError(f"target: expected an int, got {target!r}")
        if not isinstance(drain, bool):
            raise ValueError(f"drain: expected a bool, got {drain!r}")
        result = plan_capacity(
            snapshot, spec, catalog,
            target=target, quantile=float(quantile),
            mode=args.semantics or snapshot.semantics,
            node_mask=implicit_taint_mask(snapshot),
            drain=drain,
        )
    except (DistributionError, PlannerError, ValueError, TypeError) as e:
        print(f"ERROR : bad -plan: {e}")
        return 1
    wire = result.to_wire()
    if args.output == "json":
        print(plan_json_report(wire))
    else:
        print(plan_table_report(wire))
    return 0 if result.certified else 1


def _run_gang_status(args) -> int:
    """-gang HOST:PORT: fetch and render a service's gang-watch status
    (the gang slice of the timeline).  Exits by the verdict, like -car:
    a breached gang watch — fewer than N whole gangs fit — is a
    scriptable failure, and so is a server with no gang watches."""
    from kubernetesclustercapacity_tpu.report import (
        gang_status_json_report,
        gang_status_table_report,
    )

    addr = _parse_addr("-gang", args.gang)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.gang()
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch gang status from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(gang_status_json_report(result))
    else:
        print(gang_status_table_report(result))
    if not result.get("enabled", False):
        return 1
    return 1 if result.get("breached") else 0


def _run_gang_spec(args, snapshot) -> int:
    """-gang-spec FILE: offline whole-gang capacity against the
    -snapshot source's topology hierarchy.  Applies the same implicit
    strict-mode taint mask as every other surface, prints the gang
    verdict with its binding-level explanation, and exits by
    schedulability: 1 when fewer than the spec's ``count`` gangs fit."""
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.report import (
        gang_json_report,
        gang_table_report,
    )
    from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
    from kubernetesclustercapacity_tpu.topology import (
        GangSpecError,
        gang_capacity,
        gang_explain,
        load_gang_spec,
    )

    if args.backend != "tpu":
        print("ERROR : -gang-spec runs on the JAX kernels (-backend tpu); "
              "cpu/native backends are fit-only cross-checks ...exiting")
        return 1
    try:
        scenario, spec = load_gang_spec(args.gang_spec)
    except (OSError, GangSpecError) as e:
        print(f"ERROR : bad -gang-spec: {e}")
        return 1
    grid = ScenarioGrid.from_scenarios([scenario])
    mask = implicit_taint_mask(snapshot)
    try:
        result = gang_capacity(
            snapshot, grid, spec, mode=args.semantics, node_mask=mask
        )
        wire = result.to_wire()
        wire["explain"] = gang_explain(
            snapshot, grid, spec, mode=args.semantics, node_mask=mask
        )
    except (GangSpecError, ValueError) as e:
        print(f"ERROR : {e}")
        return 1
    if args.output == "json":
        print(gang_json_report(wire))
    else:
        print(gang_table_report(wire))
    return 0 if bool(result.schedulable[0]) else 1


def _run_optimize(args, snapshot, scenario) -> int:
    """-optimize: the optimization-based packing backend, offline.

    Answers the six-flag spec (or a ``-grid N`` random sweep) with the
    chosen ``-opt-backend`` against the -snapshot source, under the
    same implicit strict-mode taint mask as every other surface.
    Exits 1 when the spec is unschedulable by the integral packing, or
    when any LP solve failed to certify — an uncertified bound is a
    scriptable failure, not a silent one.
    """
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot
    from kubernetesclustercapacity_tpu.optimize import (
        OptimizeError,
        optimize_snapshot,
    )
    from kubernetesclustercapacity_tpu.report import (
        optimize_json_report,
        optimize_table_report,
    )
    from kubernetesclustercapacity_tpu.scenario import (
        ScenarioGrid,
        random_scenario_grid,
    )

    if args.backend != "tpu":
        print("ERROR : -optimize runs on the JAX kernels (-backend tpu); "
              "cpu/native backends are fit-only cross-checks ...exiting")
        return 1
    if args.grid > 0:
        grid = random_scenario_grid(args.grid, seed=args.seed)
    else:
        grid = ScenarioGrid.from_scenarios([scenario])
    mask = implicit_taint_mask(snapshot)
    mode = args.semantics or snapshot.semantics
    if args.opt_backend == "ffd":
        totals, _ = sweep_snapshot(snapshot, grid, mode=mode,
                                   node_mask=mask)[:2]
        totals = np.asarray(totals, dtype=np.int64)
        demand = np.asarray(grid.replicas, dtype=np.int64)
        wire = {
            "backend": "ffd",
            "mode": mode,
            "scenarios": grid.size,
            "demand": demand.tolist(),
            "ffd": np.clip(totals, 0, demand).tolist(),
            "totals": totals.tolist(),
            "schedulable": (totals >= demand).tolist(),
        }
        if args.output == "json":
            print(optimize_json_report(wire))
        else:
            print(optimize_table_report(wire))
        return 0 if all(wire["schedulable"]) else 1
    try:
        result = optimize_snapshot(snapshot, grid, mode=mode,
                                   node_mask=mask)
    except OptimizeError as e:
        print(f"ERROR : {e}")
        return 1
    wire = result.to_wire()
    if args.output == "json":
        print(optimize_json_report(wire))
    else:
        print(optimize_table_report(wire))
    ok = result.all_certified and bool(result.schedulable.all())
    return 0 if ok else 1


def _run_slo_status(args) -> int:
    """-slo-status HOST:PORT: fetch and render a service's SLO burn-rate
    status.  Exits by the verdict, like -timeline: a breached objective
    (or a server with no -slo at all) is a scriptable failure."""
    from kubernetesclustercapacity_tpu.report import (
        slo_json_report,
        slo_table_report,
    )

    addr = _parse_addr("-slo-status", args.slo_status)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.slo_status()
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch SLO status from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(slo_json_report(result))
    else:
        print(slo_table_report(result))
    if not result.get("enabled", False):
        return 1
    breached = [
        name
        for name, s in result.get("status", {}).items()
        if s.get("state") == "breached"
    ]
    return 1 if breached else 0


def _run_dump(args) -> int:
    """-dump HOST:PORT: fetch and render a service's flight recorder —
    the last K dispatched requests, each carrying its per-phase latency
    breakdown, so a slow request is self-explaining from the paste."""
    from kubernetesclustercapacity_tpu.report import (
        dump_json_report,
        dump_table_report,
    )

    addr = _parse_addr("-dump", args.dump)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.dump(limit=args.dump_limit, tenant=args.dump_tenant)
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch flight records from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(dump_json_report(result))
    else:
        print(dump_table_report(result))
    return 0


def _run_drain_server(args) -> int:
    """-drain-server HOST:PORT: trigger a graceful drain over the wire
    and print the server's drain record.  Exits by the verdict: 0 only
    when every in-flight request finished inside the timeout."""
    import json as _json

    addr = _parse_addr("-drain-server", args.drain_server)
    if addr is None:
        return 1
    # The drain op waits for in-flight work server-side: the client
    # budget must comfortably outlive the server's wait.
    wait = args.drain_timeout_s if args.drain_timeout_s is not None else 30.0
    try:
        with _diag_client(addr) as c:
            record = c.drain_server(
                timeout_s=args.drain_timeout_s,
                deadline_s=wait + 10.0,
            )
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot drain {addr[0]}:{addr[1]}: {e}",
              file=sys.stderr)
        return 1
    if args.output == "json":
        print(_json.dumps(record, sort_keys=True))
    else:
        print(
            f"drain {'complete' if record.get('drained') else 'TIMED OUT'}"
            f" : inflight_at_start={record.get('inflight_at_start')}"
            f" remaining={record.get('inflight_remaining')}"
            f" waited_s={record.get('waited_s')}"
            + (" (already draining)" if record.get("already") else "")
        )
    return 0 if record.get("drained") else 1


def _run_plane_status(args) -> int:
    """-plane-status HOST:PORT: one look at an endpoint's place in the
    replicated serving plane — role, generation, fan-out or sync
    health, capabilities.  Exit 1 when the endpoint should be routed
    around (stale replica / draining server)."""
    import json as _json

    addr = _parse_addr("-plane-status", args.plane_status)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            info = c.info(plane=True)
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot reach {addr[0]}:{addr[1]}: {e}",
              file=sys.stderr)
        return 1
    plane = info.get("plane")
    caps = info.get("capabilities") or {}
    draining = bool(info.get("draining"))
    if args.output == "json":
        print(_json.dumps(
            {"plane": plane, "capabilities": caps, "draining": draining},
            sort_keys=True,
        ))
    else:
        if plane is None:
            print("plane     : not a plane member")
        else:
            print(f"plane     : role={plane.get('role')} "
                  f"generation={plane.get('generation')}")
            if plane.get("role") == "replica":
                print(f"sync      : age_s={plane.get('sync_age_s')} "
                      f"stale={plane.get('stale')} "
                      f"applied={plane.get('applied')} "
                      f"resyncs={plane.get('resyncs')}")
            else:
                print(f"fan-out   : subscribers={plane.get('subscribers')} "
                      f"published={plane.get('published')} "
                      f"ejected={plane.get('ejected')}")
        print(f"caps      : {caps or '(pre-plane server)'}")
        print(f"draining  : {draining}")
    stale = bool(plane and plane.get("role") == "replica" and plane.get("stale"))
    return 1 if (stale or draining) else 0


def _run_fed_status(args) -> int:
    """-fed-status HOST:PORT: the federation tier's degradation vector.
    Exit by the verdict: 1 when any cluster is LOST — a fleet answer is
    provably incomplete then, and scripts must see that, not parse
    prose.  Stale clusters render explicitly but stay exit 0 (they are
    the contract working, not a failure of it)."""
    from kubernetesclustercapacity_tpu.report import (
        fed_status_json_report,
        fed_status_table_report,
    )

    addr = _parse_addr("-fed-status", args.fed_status)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.fed_status()
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch federation status from "
              f"{addr[0]}:{addr[1]}: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(fed_status_json_report(result))
    else:
        print(fed_status_table_report(result))
    if not result.get("enabled", False):
        return 1
    return 1 if result.get("excluded") else 0


def _run_fed_sweep(args) -> int:
    """-fed-sweep HOST:PORT: fleet capacity for the six scenario flags.
    Exit 0 only when the scenario fits across the fleet AND no cluster
    is lost (a lost cluster makes every total an explicit lower bound)."""
    from kubernetesclustercapacity_tpu.report import (
        fed_sweep_json_report,
        fed_sweep_table_report,
    )

    addr = _parse_addr("-fed-sweep", args.fed_sweep)
    if addr is None:
        return 1
    try:
        with _diag_client(addr) as c:
            result = c.fed_sweep(
                cpuRequests=args.cpuRequests,
                cpuLimits=args.cpuLimits,
                memRequests=args.memRequests,
                memLimits=args.memLimits,
                replicas=args.replicas,
            )
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fed-sweep {addr[0]}:{addr[1]}: {e}",
              file=sys.stderr)
        return 1
    if args.output == "json":
        print(fed_sweep_json_report(result))
    else:
        print(fed_sweep_table_report(result))
    schedulable = all(result.get("schedulable", []) or [False])
    return 0 if schedulable and not result.get("excluded") else 1


def _run_replay(args) -> int:
    """-replay DIR: the offline half of the audit subsystem — turn a
    recorded production history into a verified repro.  Exits by the
    verdict: 0 only when the digest chain holds and every replayed
    request re-answered identically."""
    from kubernetesclustercapacity_tpu.audit import (
        AuditError,
        AuditReader,
        Replayer,
    )
    from kubernetesclustercapacity_tpu.report import (
        replay_json_report,
        replay_table_report,
    )
    from kubernetesclustercapacity_tpu.timeline.diff import snapshot_digest

    try:
        reader = AuditReader.load(args.replay)
    except AuditError as e:
        print(f"ERROR : cannot load audit log: {e}", file=sys.stderr)
        return 1
    if args.replay_generation is not None:
        try:
            snap = reader.snapshot_at(args.replay_generation)
        except AuditError as e:
            print(f"ERROR : {e}", file=sys.stderr)
            return 1
        out = {
            "generation": args.replay_generation,
            "nodes": snap.n_nodes,
            "semantics": snap.semantics,
            "digest": snapshot_digest(snap),
            "verified": True,
        }
        if args.output == "json":
            print(json.dumps(out, sort_keys=True))
        else:
            print(
                f"generation {out['generation']}: {out['nodes']} node(s) "
                f"({out['semantics']}), digest {out['digest']} — "
                "reconstruction verified"
            )
        return 0
    with Replayer(reader) as replayer:
        if args.replay_ref:
            try:
                rec = reader.record_at(args.replay_ref)
            except AuditError as e:
                print(f"ERROR : {e}", file=sys.stderr)
                return 1
            outcome = replayer.replay_record(rec)
            counts = {outcome["status"]: 1}
            result = {
                "directory": reader.directory,
                "generations_verified": [],
                "chain_error": None,
                "recovered_tail_records": reader.recovered_tail,
                "requests": 1,
                "counts": counts,
                "outcomes": [outcome],
                "clean": outcome["status"] in ("ok", "skipped"),
            }
        else:
            result = replayer.replay_all(tenant=args.replay_tenant)
    if args.output == "json":
        print(replay_json_report(result))
    else:
        print(replay_table_report(result))
    return 0 if result["clean"] else 1


def _run_trace_tree(args) -> int:
    """-trace-tree TRACE_ID: the offline analyzer of the tracing
    subsystem — stitch one trace's spans from per-process JSONL logs
    into a tree (parent linkage only, never wall clock), compute the
    greedy critical path, and name the dominating contributor.  Exits
    by the verdict: 0 only when the trace was found and attribution
    was not refused."""
    from kubernetesclustercapacity_tpu.report import (
        trace_json_report,
        trace_table_report,
    )
    from kubernetesclustercapacity_tpu.telemetry.traceview import (
        analyze_trace,
    )

    if not args.trace_logs:
        print(
            "ERROR : -trace-tree needs -trace-logs DIR[,DIR...] "
            "(the per-process span logs to stitch)",
            file=sys.stderr,
        )
        return 1
    tree = analyze_trace(args.trace_logs, args.trace_tree)
    if args.output == "json":
        print(trace_json_report(tree))
    else:
        print(trace_table_report(tree))
    if not tree.get("found"):
        return 1
    return 0 if not tree["critical_path"].get("refused") else 1


def _run_profile(args) -> int:
    """-profile HOST:PORT: ask a running server's sampling profiler
    for a collapsed flamegraph window (``/debug/profile`` on its
    metrics port), write the fold, and summarize the phase attribution
    — the view that answers "WHICH frames inside serialize?"."""
    from urllib.request import urlopen

    from kubernetesclustercapacity_tpu.telemetry.profiler import (
        dominant_phase,
        phase_counts,
        top_frame,
    )

    addr = _parse_addr("-profile", args.profile)
    if addr is None:
        return 1
    seconds = max(float(args.profile_seconds), 0.0)
    url = (f"http://{addr[0]}:{addr[1]}/debug/profile"
           f"?seconds={seconds:g}")
    try:
        with urlopen(url, timeout=seconds + 30.0) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception as e:  # noqa: BLE001 - a CLI reports, never tracebacks
        print(f"ERROR : cannot fetch profile from "
              f"{addr[0]}:{addr[1]}: {e} (a server started with "
              "-metrics-port serves /debug/profile there)",
              file=sys.stderr)
        return 1
    if text.startswith("# profiler disabled"):
        print(text.strip(), file=sys.stderr)
        return 1
    counts = phase_counts(text)
    total = sum(counts.values())
    phase, share = dominant_phase(text)
    if args.profile_out:
        with open(args.profile_out, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"collapsed profile ({total} sample(s)) written to "
              f"{args.profile_out}", file=sys.stderr)
    if args.output == "json":
        print(json.dumps({
            "seconds": seconds,
            "samples": total,
            "phase_samples": counts,
            "dominant_phase": phase,
            "dominant_share": round(share, 4),
            "top_frame": top_frame(text),
            "top_frame_dominant_phase": (
                top_frame(text, phase) if phase else None
            ),
        }, indent=2, sort_keys=True))
    else:
        if not args.profile_out:
            sys.stdout.write(text)
        for name in sorted(counts, key=lambda p: -counts[p]):
            print(f"# phase {name}: {counts[name]} sample(s)",
                  file=sys.stderr)
        if phase is not None:
            print(f"# dominant phase: {phase} "
                  f"({share * 100:.1f}% of attributed samples; top "
                  f"frame {top_frame(text, phase)})", file=sys.stderr)
    return 0


def _run_bench_diff(args) -> int:
    """-bench-diff OLD NEW (or DIR): the typed comparator over bench
    artifacts — exit 1 only on a threshold-breaching regression on a
    comparable, parity-clean row; exit 2 on usage errors (bad JSON,
    bad thresholds, wrong argument shape)."""
    from kubernetesclustercapacity_tpu.analysis import benchdiff

    paths = args.bench_diff
    trajectory_dir = None
    if len(paths) == 1 and os.path.isdir(paths[0]):
        trajectory_dir = paths[0]
    elif len(paths) != 2:
        print("ERROR : -bench-diff wants OLD.json NEW.json (or one "
              "directory for trajectory mode)", file=sys.stderr)
        return 2
    th_path = args.bench_thresholds or None
    if th_path is None:
        anchor = trajectory_dir or os.path.dirname(
            os.path.abspath(paths[1])
        )
        cand = os.path.join(anchor, benchdiff.THRESHOLDS_FILENAME)
        if os.path.exists(cand):
            th_path = cand
    try:
        th = benchdiff.load_thresholds(th_path)
        if trajectory_dir is not None:
            diffs = benchdiff.trajectory(trajectory_dir, th)
        else:
            diffs = [benchdiff.diff_files(paths[0], paths[1], th)]
    except (OSError, ValueError) as e:
        print(f"ERROR : {e}", file=sys.stderr)
        return 2
    regressions = sum(len(d.regressions) for d in diffs)
    if args.output == "json":
        print(json.dumps({
            "thresholds": th_path,
            "pairs": [d.to_json() for d in diffs],
            "regressions": regressions,
            "clean": regressions == 0,
        }, indent=2))
    elif trajectory_dir is not None:
        print(benchdiff.render_trajectory(diffs))
    else:
        print(benchdiff.render(diffs[0]))
    return 1 if regressions else 0


def _run_explain(args, snapshot, scenario) -> int:
    """-explain: WHY the fit stops — binding attribution + marginals.

    Replaces the fit report (the reference transcript stays byte-exact
    on the normal path; explanation is a new view the reference never
    had).  Applies the same implicit strict-mode taint mask as every
    other surface, so it explains the numbers fit/sweep actually return.
    """
    from kubernetesclustercapacity_tpu.explain import explain_snapshot
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.report import (
        explain_json_report,
        explain_table_report,
    )
    from kubernetesclustercapacity_tpu.scenario import ScenarioGrid

    if args.backend != "tpu":
        print("ERROR : -explain runs on the JAX kernels (-backend tpu); "
              "cpu/native backends are fit-only cross-checks ...exiting")
        return 1
    grid = ScenarioGrid.from_scenarios([scenario])
    result = explain_snapshot(
        snapshot, grid, mode=args.semantics,
        node_mask=implicit_taint_mask(snapshot),
    )
    if args.output == "json":
        print(explain_json_report(result))
    else:
        print(explain_table_report(result))
    return 0


def _run_drain(args, fixture, snapshot) -> int:
    """-drain NODE: print the rehoming plan; exit by the verdict."""
    from kubernetesclustercapacity_tpu.models import CapacityModel

    if args.semantics != "strict":
        print("ERROR : -drain requires strict semantics "
              "(-semantics strict)")
        return 1
    # Live sources arrive WITH their fixture (_load_source lists once for
    # both); only an .npz checkpoint leaves it None, and the model's own
    # error explains that limitation.
    try:
        model = CapacityModel(snapshot, mode="strict", fixture=fixture)
        plan = model.drain(args.drain, policy=args.drain_policy)
    except ValueError as e:
        print(f"ERROR : {e}")
        return 1
    print(f"drain {plan.node}: {len(plan.pods)} pod(s) to rehome "
          f"(policy {plan.policy})")
    for pod, target in plan.by_pod().items():
        line = f"  {pod:<48} -> {target if target else 'UNPLACEABLE'}"
        if pod in plan.blocked:
            line += f"  [BLOCKED by PDB {', '.join(plan.blocked[pod])}]"
        print(line)
    if plan.evictable:
        print(f"verdict: {plan.node} is evictable")
        return 0
    stuck = sum(1 for a in plan.assignments if a is None)
    reasons = []
    if stuck:
        reasons.append(f"{stuck} pod(s) cannot be rehomed")
    if plan.blocked:
        reasons.append(
            f"{len(plan.blocked)} pod(s) blocked by disruption budgets"
        )
    print(f"verdict: {plan.node} is NOT evictable ({'; '.join(reasons)})")
    return 1


def _load_source(args):
    """Resolve the cluster source: fixture JSON, npz checkpoint, or live."""
    from kubernetesclustercapacity_tpu.snapshot import snapshot_from_live_cluster

    if args.snapshot:
        from kubernetesclustercapacity_tpu.sources import (
            SourceError,
            resolve_source,
        )

        try:
            fixture, snap, semantics = resolve_source(
                args.snapshot, args.semantics,
                extended_resources=_extended_names(args),
            )
        except SourceError as e:
            print(f"ERROR : {e}")
            return None, None
        args.semantics = semantics
        return fixture, snap
    if args.semantics is None:
        args.semantics = "reference"
    extended = _extended_names(args)
    if extended and args.semantics != "strict":
        # Same rule resolve_source owns for file sources: never silently
        # pack without the requested columns.
        print("ERROR : extended resources require strict semantics "
              "(reference semantics has no extended-column concept)")
        return None, None
    try:
        if args.drain:
            # -drain needs the raw objects too (per-pod requests): ONE
            # listing serves both the fixture and the packed snapshot, so
            # eviction candidates and target headroom are the same
            # instant of the cluster.
            from kubernetesclustercapacity_tpu.kubeapi import live_fixture
            from kubernetesclustercapacity_tpu.snapshot import (
                snapshot_from_fixture,
            )

            fixture = live_fixture(args.kubeconfig or None)
            return fixture, snapshot_from_fixture(
                fixture, semantics=args.semantics,
                extended_resources=extended,
            )
        return None, snapshot_from_live_cluster(
            args.kubeconfig or None, semantics=args.semantics,
            extended_resources=extended,
        )
    except Exception as e:  # mirrors the reference's panic on bad kubeconfig
        print(f"ERROR : cannot snapshot live cluster: {e}")
        print("hint: use -snapshot <fixture.json|checkpoint.npz> for offline runs")
        return None, None


def _extended_names(args) -> tuple[str, ...]:
    """Columns to pack: the -extended-resources list plus every
    -extended-request name (a requested resource must have a column)."""
    names = {
        r.strip() for r in args.extended_resources.split(",") if r.strip()
    }
    for spec in args.extended_requests:
        name = spec.partition("=")[0].strip()
        if name:
            names.add(name)
    return tuple(sorted(names))


def _parse_extended_requests(args) -> dict[str, int] | None:
    """``-extended-request name=qty`` pairs → {name: int} (strict grammar)."""
    from kubernetesclustercapacity_tpu.utils.quantity import (
        QuantityParseError,
        parse_quantity,
    )

    out: dict[str, int] = {}
    for spec in args.extended_requests:
        name, eq, qty = spec.partition("=")
        name = name.strip()
        if not name or not eq:
            print(f"ERROR : -extended-request wants NAME=QTY, got {spec!r} "
                  "...exiting")
            return None
        try:
            out[name] = parse_quantity(qty.strip()).value()
        except QuantityParseError as e:
            print(f"ERROR : -extended-request {name}: {e} ...exiting")
            return None
    return out


def _run_single(args, fixture, snapshot, scenario) -> int:
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.oracle import (
        ReferencePanic,
        fit_arrays_python,
        reference_run,
    )
    from kubernetesclustercapacity_tpu.ops.fit import fit_per_node
    from kubernetesclustercapacity_tpu.utils.quantity import int64_bits

    # Scenario CPU values are raw uint64 (codec wrap, printing parity);
    # the int64-carrier kernels and the native ABI take their bit
    # patterns — the same reinterpretation the snapshot arrays carry.
    cpu_req_bits = int64_bits(scenario.cpu_request_milli)

    ext_requests = _parse_extended_requests(args)
    if ext_requests is None:
        return 1
    if ext_requests:
        # R-dim fit: route through the model facade (R-way min + implicit
        # strict mask, same dispatch the service's fit op uses).  The
        # cpu/native backends implement the 2-resource walk only.
        if args.backend != "tpu":
            print("ERROR : -extended-request needs -backend tpu ...exiting")
            return 1
        from kubernetesclustercapacity_tpu.models import (
            CapacityModel,
            PodSpec,
        )

        try:
            result = CapacityModel(
                snapshot, mode=args.semantics, fixture=fixture
            ).evaluate(
                PodSpec(
                    cpu_request_milli=cpu_req_bits,
                    mem_request_bytes=scenario.mem_request_bytes,
                    replicas=scenario.replicas,
                    cpu_limit_milli=scenario.cpu_limit_milli,
                    mem_limit_bytes=scenario.mem_limit_bytes,
                    extended_requests=ext_requests,
                )
            )
        except (KeyError, ValueError) as e:
            print(f"ERROR : extended-resource fit failed: {e} ...exiting")
            return 1
        return _emit_report(args, snapshot, result.fits, scenario)

    if args.backend == "native":
        from kubernetesclustercapacity_tpu import native

        try:
            fits = native.fit_arrays(
                snapshot.alloc_cpu_milli,
                snapshot.alloc_mem_bytes,
                snapshot.alloc_pods,
                snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes,
                snapshot.pods_count,
                cpu_req_bits,
                scenario.mem_request_bytes,
                mode=args.semantics,
                healthy=snapshot.healthy,
            )
        except native.NativeUnavailable as e:
            print(f"ERROR : native backend unavailable: {e}")
            return 1
        except native.NativePanic as e:
            print(f"panic: {e}")
            return 2
    elif args.backend == "cpu":
        try:
            if fixture is not None and args.semantics == "reference":
                fits = np.array(
                    reference_run(fixture, scenario).fits, dtype=np.int64
                )
            else:
                fits = np.array(
                    fit_arrays_python(
                        snapshot.alloc_cpu_milli,
                        snapshot.alloc_mem_bytes,
                        snapshot.alloc_pods,
                        snapshot.used_cpu_req_milli,
                        snapshot.used_mem_req_bytes,
                        snapshot.pods_count,
                        scenario.cpu_request_milli,
                        scenario.mem_request_bytes,
                        mode=args.semantics,
                        healthy=snapshot.healthy,
                    ),
                    dtype=np.int64,
                )
        except ReferencePanic as e:
            print(f"panic: {e}")
            return 2
    else:
        fits = np.asarray(
            fit_per_node(
                snapshot.alloc_cpu_milli,
                snapshot.alloc_mem_bytes,
                snapshot.alloc_pods,
                snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes,
                snapshot.pods_count,
                snapshot.healthy,
                cpu_req_bits,
                scenario.mem_request_bytes,
                mode=args.semantics,
            )
        )

    # Strict semantics honors hard taints on every surface (service fit,
    # service sweep, -grid, and this single-spec path) — same mask, same
    # zeroing the fit kernel's node_mask performs, for all three backends.
    # None (so a no-op, preserving byte parity) under reference semantics.
    # (The extended-request path above returned already: CapacityModel
    # applies the identical implicit mask itself.)
    mask = implicit_taint_mask(snapshot)
    if mask is not None:
        fits = np.where(mask, fits, 0)
    return _emit_report(args, snapshot, fits, scenario)


def _emit_report(args, snapshot, fits, scenario) -> int:
    from kubernetesclustercapacity_tpu.report import (
        json_report,
        reference_report,
        table_report,
    )

    if args.output == "json":
        print(json_report(snapshot, fits, scenario))
    elif args.output == "table":
        print(table_report(snapshot, fits, scenario))
    else:
        print(reference_report(snapshot, fits, scenario), end="")
    return 0


def _run_grid(args, snapshot) -> int:
    from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
    from kubernetesclustercapacity_tpu.scenario import random_scenario_grid

    if args.backend != "tpu":
        # Silently running the JAX sweep under -backend cpu/native would
        # defeat a cross-check; the sequential backends are single-spec.
        print(
            "ERROR : -grid sweeps run on the TPU kernels (-backend tpu); "
            "cpu/native backends are single-spec cross-checks ...exiting"
        )
        return 1
    ext_requests = _parse_extended_requests(args)
    if ext_requests is None:
        return 1
    grid = random_scenario_grid(args.grid, seed=args.seed)
    # Strict grids honor hard taints exactly like single-spec strict fits
    # (and the service's fit/sweep ops) — one spec, one answer, any surface.
    mask = implicit_taint_mask(snapshot)
    if ext_requests:
        # Random cpu/mem grid with a CONSTANT extended request per name on
        # every scenario; dispatched through the R-dim auto kernel with
        # healthy/taint masking identical to the 2-resource path.
        from kubernetesclustercapacity_tpu.ops.pallas_multi import (
            sweep_multi_auto,
        )
        from kubernetesclustercapacity_tpu.scenario import MultiResourceGrid

        from kubernetesclustercapacity_tpu.scenario import ScenarioError

        mgrid = MultiResourceGrid.from_grid(
            grid,
            {
                name: np.full(grid.size, qty, dtype=np.int64)
                for name, qty in ext_requests.items()
            },
        )
        try:
            mgrid.validate()  # e.g. a negative -extended-request quantity
        except ScenarioError as e:
            print(f"ERROR : {e} ...exiting")
            return 1
        try:
            alloc_rn, used_rn = snapshot.resource_matrix(mgrid.resources)
        except KeyError as e:
            print(f"ERROR : snapshot has no extended column {e} ...exiting")
            return 1
        totals, sched, kernel = sweep_multi_auto(
            alloc_rn,
            used_rn,
            snapshot.alloc_pods,
            snapshot.pods_count,
            snapshot.healthy,
            mgrid.requests,
            mgrid.replicas,
            mode=args.semantics,
            node_masks=mask,
            force_exact=(args.kernel == "exact"),
        )
    else:
        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            sweep_snapshot_auto,
        )

        totals, sched, kernel = sweep_snapshot_auto(
            snapshot,
            grid,
            mode=args.semantics,
            kernel=args.kernel,
            node_mask=mask,
        )
    if args.output == "table":
        header = (
            f"{'CPU(m)':>8} {'MEM(MiB)':>10} {'REPLICAS':>9} "
            f"{'TOTAL':>8}  SCHED"
        )
        lines = [header, "-" * len(header)]
        mib = 1024 * 1024
        for i in range(grid.size):
            lines.append(
                f"{int(grid.cpu_request_milli[i]):>8} "
                f"{int(grid.mem_request_bytes[i]) // mib:>10} "
                f"{int(grid.replicas[i]):>9} "
                f"{int(totals[i]):>8}  "
                f"{'yes' if sched[i] else 'NO'}"
            )
        lines.append("-" * len(header))
        lines.append(
            f"kernel: {kernel}   schedulable: "
            f"{int(np.sum(sched))}/{grid.size}"
        )
        print("\n".join(lines))
        return 0
    summary = {
        "scenarios": args.grid,
        "seed": args.seed,
        "semantics": args.semantics,
        "kernel": kernel,
        **(
            {"extended_requests": ext_requests} if ext_requests else {}
        ),
        "totals": totals.tolist(),
        "schedulable": sched.tolist(),
        "totals_p50": float(np.percentile(totals, 50)),
        "schedulable_fraction": float(np.mean(sched)),
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
