"""PodDisruptionBudget accounting for the drain simulation.

``kubectl drain``'s other half — beyond finding room for rehomed pods —
is the eviction API's budget check: an eviction is REFUSED while the
covering PDB's ``allowedDisruptions`` is 0 ("cannot evict pod as it
would violate the pod's disruption budget").  The reference has no
eviction concept (`ClusterCapacity.go` never mutates the cluster);
this module gives the drain simulator the same gate.

Fixture schema extension — top-level ``"pdbs"``::

    {"pdbs": [{"name": "db", "namespace": "prod",
               "selector": {"matchLabels": {"app": "db"},
                            "matchExpressions": [...]},
               "minAvailable": 2}]}        # or "maxUnavailable": 1 / "25%"

Semantics mirror the disruption controller:

* ``expectedCount`` = pods matching the selector in the PDB's namespace
  (non-terminated).  ``currentHealthy`` = the assigned Running subset —
  the fixture schema carries no per-pod readiness, so Running stands in
  for Ready (documented proxy).
* Percentages scale by ``expectedCount`` and round UP (upstream
  ``GetScaledValueFromIntOrPercent(roundUp=true)`` for both fields).
* ``minAvailable``: ``desiredHealthy = minAvailable``;
  ``maxUnavailable``: ``desiredHealthy = expected - maxUnavailable``.
  A PDB carrying both is malformed (the API forbids it) — rejected.
* ``allowedDisruptions = max(currentHealthy - desiredHealthy, 0)``; an
  eviction is blocked when ANY matching PDB has 0 allowed (with
  multiple covering PDBs the real eviction API errors out — blocked
  here too).

This is the eviction API's *point-in-time* check: a real drain evicts
one pod at a time and waits for replacements to recover the budget, so
a node whose pods all rehome eventually empties even if several share
one PDB with allowance 1.  The simulator reports the instantaneous
gate, not the retry loop.
"""

from __future__ import annotations

from dataclasses import dataclass

from kubernetesclustercapacity_tpu.masks import _expr_matches
from kubernetesclustercapacity_tpu.snapshot import _STRICT_TERMINATED

__all__ = [
    "BudgetStatus",
    "budget_statuses",
    "blocked_evictions",
    "validate_selector",
]

# LabelSelector operators _expr_matches evaluates.  In/NotIn require a
# non-empty values list and Exists/DoesNotExist an empty one — upstream
# LabelSelectorRequirement validation, enforced here so a malformed
# selector fails at ADMISSION (store validation), not on a later drain.
_SELECTOR_OPS = frozenset(
    {"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"}
)


def validate_selector(selector: dict) -> None:
    """Structural validation of a full LabelSelector — every
    ``matchExpressions`` entry checked UNCONDITIONALLY (matching a probe
    pod can short-circuit on ``matchLabels`` and never evaluate the
    expressions, which is exactly how a malformed operator used to slip
    into the store).  Raises ValueError."""
    if not isinstance(selector, dict):
        raise ValueError(f"selector must be an object, got {selector!r}")
    match_labels = selector.get("matchLabels") or {}
    if not isinstance(match_labels, dict):
        raise ValueError(
            f"matchLabels must be an object, got {match_labels!r}"
        )
    exprs = selector.get("matchExpressions") or []
    if not isinstance(exprs, (list, tuple)):
        raise ValueError(
            f"matchExpressions must be a list, got {exprs!r}"
        )
    for expr in exprs:
        if not isinstance(expr, dict):
            raise ValueError(f"match expression must be an object: {expr!r}")
        op = expr.get("operator", "In")
        if op not in _SELECTOR_OPS:
            raise ValueError(f"unknown match-expression operator {op!r}")
        values = expr.get("values", [])
        if not isinstance(values, (list, tuple)):
            raise ValueError(
                f"match-expression values must be a list, got {values!r}"
            )
        if op in ("In", "NotIn") and not values:
            raise ValueError(
                f"operator {op} requires a non-empty values list"
            )
        if op in ("Exists", "DoesNotExist") and values:
            raise ValueError(
                f"operator {op} must not carry values, got {list(values)!r}"
            )


@dataclass(frozen=True)
class BudgetStatus:
    """One PDB's disruption arithmetic at this snapshot instant."""

    name: str
    namespace: str
    expected: int  # matching non-terminated pods
    healthy: int  # the assigned Running subset (readiness proxy)
    desired_healthy: int
    allowed_disruptions: int


def _selector_matches(selector: dict, labels: dict) -> bool:
    """Full LabelSelector: matchLabels AND-ed with matchExpressions.
    An empty selector matches everything in the namespace (the API's
    ``{}`` selector), like upstream."""
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    return all(
        _expr_matches(labels, e)
        for e in selector.get("matchExpressions") or []
    )


def _scaled(value, expected: int, field: str) -> int:
    """intstr: plain int, or "N%" scaled by expected, rounded UP.

    Negative values are rejected (the API validates both fields as
    non-negative): a negative ``minAvailable`` would otherwise silently
    yield ``allowed_disruptions == healthy`` — every eviction waved
    through by a budget that was supposed to protect the workload.
    """
    if isinstance(value, str) and value.endswith("%"):
        try:
            pct = int(value[:-1])
        except ValueError:
            raise ValueError(f"PDB {field}: bad percentage {value!r}") from None
        if pct < 0:
            raise ValueError(f"PDB {field}: must be >= 0, got {value!r}")
        return -(-pct * expected // 100)
    n = int(value)
    if n < 0:
        raise ValueError(f"PDB {field}: must be >= 0, got {n}")
    return n


def budget_statuses(fixture: dict) -> list[BudgetStatus]:
    """Evaluate every fixture PDB against the fixture's pods."""
    out = []
    for pdb in fixture.get("pdbs", []):
        name = pdb.get("name", "")
        namespace = pdb.get("namespace", "")
        selector = pdb.get("selector") or {}
        has_min = "minAvailable" in pdb
        has_max = "maxUnavailable" in pdb
        if has_min == has_max:
            raise ValueError(
                f"PDB {namespace}/{name}: exactly one of minAvailable / "
                "maxUnavailable (the API forbids both or neither)"
            )
        expected = healthy = 0
        for pod in fixture.get("pods", []):
            if pod.get("namespace", "") != namespace:
                continue
            if pod.get("phase") in _STRICT_TERMINATED:
                continue
            if not _selector_matches(selector, pod.get("labels") or {}):
                continue
            expected += 1
            if pod.get("phase") == "Running" and pod.get("nodeName"):
                healthy += 1
        if has_min:
            desired = _scaled(pdb["minAvailable"], expected, "minAvailable")
        else:
            desired = expected - _scaled(
                pdb["maxUnavailable"], expected, "maxUnavailable"
            )
        out.append(
            BudgetStatus(
                name=name,
                namespace=namespace,
                expected=expected,
                healthy=healthy,
                desired_healthy=desired,
                allowed_disruptions=max(healthy - desired, 0),
            )
        )
    return out


def blocked_evictions(
    fixture: dict, pod_keys: list[str]
) -> dict[str, list[str]]:
    """Which of ``pod_keys`` ("namespace/name") the eviction API would
    refuse right now, mapped to the responsible PDB names.

    Two refusal modes, both upstream behavior: a pod whose ONE covering
    budget has zero allowance ("would violate the pod's disruption
    budget"), and a pod covered by TWO OR MORE budgets — the eviction
    API errors out on multi-coverage regardless of allowances ("This
    pod has more than one PodDisruptionBudget").  Unblocked pods are
    absent from the result."""
    statuses = budget_statuses(fixture)
    if not statuses:
        return {}
    selectors = [
        (s, (fixture_pdb.get("selector") or {}))
        for s, fixture_pdb in zip(statuses, fixture.get("pdbs", []))
    ]
    by_key = {
        f"{p.get('namespace', '')}/{p.get('name', '')}": p
        for p in fixture.get("pods", [])
    }
    blocked: dict[str, list[str]] = {}
    for key in pod_keys:
        pod = by_key.get(key)
        if pod is None:
            continue
        covering = [
            s
            for s, selector in selectors
            if s.namespace == pod.get("namespace", "")
            and _selector_matches(selector, pod.get("labels") or {})
        ]
        if len(covering) >= 2 or (
            len(covering) == 1 and covering[0].allowed_disruptions <= 0
        ):
            blocked[key] = [s.name for s in covering]
    return blocked
