"""Cluster snapshot (L2): dense node-resource arrays the kernels consume.

The reference re-queries the apiserver ``1 + 2N + ΣP`` times per run
(SURVEY.md §3.4) and holds cluster state as a ``[]node`` of Go structs.  Here
the cluster is snapshotted ONCE into dense int64 arrays — the TPU-native
representation: every downstream evaluation (one scenario or a 1k-scenario
sweep) is pure array math with zero API calls on the hot path.

Two ingestion semantics, pinned by SURVEY.md §2.4:

* ``reference`` — bug-compatible: built on the oracle's own walk
  (:mod:`..oracle.reference`), so phantom zero-nodes, parse-fail→0 memory and
  the first-4-conditions health check land in the arrays exactly as the Go
  code would see them.  Kernel output on these arrays is bit-exact against
  the oracle by construction.
* ``strict`` — correct-mode: full Kubernetes quantity grammar, health =
  ``Ready == True`` and no pressure condition ``True``, pod usage counts all
  pods assigned to the node that are not Succeeded/Failed, and per-pod
  effective requests follow the scheduler rule
  ``max(sum(containers), max(initContainers))``.  Unhealthy nodes keep their
  real allocatables but are masked out via ``healthy``.

Extended resources (BASELINE config 4) ride along as extra named columns
parsed with the strict grammar (the reference has no concept of them).

The snapshot doubles as the framework's *checkpoint*: :meth:`ClusterSnapshot.save`
/ :func:`load_snapshot` serialize the arrays to ``.npz`` so sweeps re-run
offline and reproducibly (SURVEY.md §5 "checkpoint/resume").
"""

from __future__ import annotations

import functools
import json
import os
import threading
from dataclasses import dataclass, field, fields

import numpy as np

from kubernetesclustercapacity_tpu.native import ingest as _ingest
from kubernetesclustercapacity_tpu.oracle import reference as _oracle
from kubernetesclustercapacity_tpu.utils import quantity as _q

__all__ = [
    "ClusterSnapshot",
    "GroupedSnapshot",
    "snapshot_from_fixture",
    "synthetic_snapshot",
    "load_snapshot",
    "snapshot_from_live_cluster",
    "grouping_enabled",
    "group_min_count",
    "set_group_min_count",
    "grouped_for_dispatch",
    "publish_group_metrics",
    "GROUPING_NODE_FLOOR",
]

# Phases that never consume node capacity in strict mode (terminated pods).
_STRICT_TERMINATED = frozenset({"Succeeded", "Failed"})

# Default extended resources for config 4 (strict mode only).
DEFAULT_EXTENDED_RESOURCES = ("ephemeral-storage", "nvidia.com/gpu")


@dataclass
class ClusterSnapshot:
    """Dense ``(nodes,)`` arrays of allocatable vs. requested resources.

    All resource arrays are int64 (CPU in millicores, memory in bytes —
    matching the reference's unit choices at ``ClusterCapacity.go:41-46``).
    ``healthy`` is the first-class node-health mask (SURVEY.md §5 "failure
    detection"): in reference semantics unhealthy rows are ALSO zeroed
    (phantom nodes), in strict semantics they carry real values and the mask
    alone excludes them.

    ``extended`` maps resource name → ``(allocatable[N], used_requests[N])``
    int64 pairs in the resource's native unit (bytes for ephemeral-storage,
    count for GPUs).
    """

    names: list[str]
    alloc_cpu_milli: np.ndarray
    alloc_mem_bytes: np.ndarray
    alloc_pods: np.ndarray
    used_cpu_req_milli: np.ndarray
    used_cpu_lim_milli: np.ndarray
    used_mem_req_bytes: np.ndarray
    used_mem_lim_bytes: np.ndarray
    pods_count: np.ndarray
    healthy: np.ndarray
    semantics: str = "reference"
    extended: dict[str, tuple[np.ndarray, np.ndarray]] = field(default_factory=dict)
    labels: list[dict] = field(default_factory=list)
    taints: list[list] = field(default_factory=list)
    # Transcript provenance (reference packing only): the stdout side
    # effects the Go binary emits while building ITS view of the cluster,
    # replayed by report.reference_report for byte parity.  node_log is
    # the getHealthyNodes-phase event list in emission order — ("cpu_err",
    # stripped_string) for each allocatable-CPU codec failure
    # (ClusterCapacity.go:314-317) and ("skip", real_node_name) for each
    # unhealthy node (:215; the snapshot's phantom row keeps "" but Go
    # prints the REAL name).  pod_cpu_errs is the per-row lists of
    # container-CPU codec-failure payloads (limits before requests,
    # :279-284) printed just before each node's block in main's loop.
    node_log: list[tuple[str, str]] = field(default_factory=list)
    pod_cpu_errs: list[list[str]] = field(default_factory=list)

    def __post_init__(self) -> None:
        n = len(self.names)
        for f in (
            "alloc_cpu_milli",
            "alloc_mem_bytes",
            "alloc_pods",
            "used_cpu_req_milli",
            "used_cpu_lim_milli",
            "used_mem_req_bytes",
            "used_mem_lim_bytes",
            "pods_count",
        ):
            arr = np.asarray(getattr(self, f), dtype=np.int64)
            if arr.shape != (n,):
                raise ValueError(f"{f}: expected shape ({n},), got {arr.shape}")
            setattr(self, f, arr)
        self.healthy = np.asarray(self.healthy, dtype=np.bool_)
        if self.healthy.shape != (n,):
            raise ValueError("healthy mask shape mismatch")
        # Transcript provenance normalizes to tuples: entries are shared
        # across store-served snapshots, so they must be immutable — a
        # caller cannot append into the store's live state.  (tuple() of
        # a tuple is the same object: the store's already-tuple entries
        # normalize at C speed on the publish path.)
        self.node_log = [tuple(t) for t in self.node_log]
        self.pod_cpu_errs = [tuple(e) for e in self.pod_cpu_errs]

    @property
    def n_nodes(self) -> int:
        return len(self.names)

    def resource_matrix(
        self, resources: tuple[str, ...] = ("cpu", "memory")
    ) -> tuple[np.ndarray, np.ndarray]:
        """Assemble ``(alloc[R, N], used_req[R, N])`` for the R-dim fit kernel.

        Row order follows ``resources``; ``"cpu"`` and ``"memory"`` name the
        core columns, anything else must be a key of :attr:`extended`.

        Memoized per ``resources`` tuple on the (immutable) snapshot, so
        repeated sweeps stop re-stacking O(R*N) host arrays per request;
        the cached matrices are read-only to keep the memo honest.  A
        concurrent first call may build twice — both results are equal
        and either may win the cache slot.
        """
        resources = tuple(resources)
        cache = self.__dict__.setdefault("_matrix_cache", {})
        hit = cache.get(resources)
        if hit is not None:
            return hit
        alloc_rows, used_rows = [], []
        for r in resources:
            if r == "cpu":
                alloc_rows.append(self.alloc_cpu_milli)
                used_rows.append(self.used_cpu_req_milli)
            elif r == "memory":
                alloc_rows.append(self.alloc_mem_bytes)
                used_rows.append(self.used_mem_req_bytes)
            else:
                alloc, used = self.extended[r]
                alloc_rows.append(alloc)
                used_rows.append(used)
        alloc_rn, used_rn = np.stack(alloc_rows), np.stack(used_rows)
        alloc_rn.setflags(write=False)
        used_rn.setflags(write=False)
        cache[resources] = (alloc_rn, used_rn)
        return cache[resources]

    def grouped(self) -> "GroupedSnapshot":
        """The node-shape-compressed form: identical rows deduplicated
        into ``(shape, count)`` groups (ROADMAP item 1).

        The grouping key is every column the kernels consume —
        allocatable, usage (requests AND limits), pod counts, health, and
        all extended-resource columns — so two rows land in one group iff
        *every* fit-relevant value matches (duplicate shapes that differ
        only in health do NOT merge).  Capacity is a sum over nodes, so
        evaluating the ~100s of distinct shapes and weighting by count is
        *exact*, not approximate; the :attr:`GroupedSnapshot.group_index`
        map makes the compression invertible (any per-group array expands
        back to per-node by a gather).

        Memoized on the (immutable) snapshot: the ``np.unique`` row sort
        runs once per snapshot, shared by every dispatch and the publish
        gauges.  A concurrent first call may build twice; both results
        are equal and either may win the cache slot.
        """
        hit = self.__dict__.get("_grouped_cache")
        if hit is not None:
            return hit
        rows = self._group_rows()
        ext_names = sorted(self.extended)
        # Row-dedup via lexsort + boundary scan — semantically
        # ``np.unique(rows, axis=0, return_inverse, return_counts)``
        # (same lexicographic group order, column 0 most significant)
        # but ~5x faster at 1M rows: axis-0 unique sorts void-typed row
        # blobs with per-comparison overhead, while lexsort runs one
        # typed argsort per column.
        n = rows.shape[0]
        if n:
            order = np.lexsort(rows.T[::-1])
            sorted_rows = rows[order]
            boundary = np.empty(n, dtype=bool)
            boundary[0] = True
            np.any(
                sorted_rows[1:] != sorted_rows[:-1], axis=1,
                out=boundary[1:],
            )
            gid_sorted = np.cumsum(boundary) - 1
            inverse = np.empty(n, dtype=np.int64)
            inverse[order] = gid_sorted
            uniq = sorted_rows[boundary]
            counts = np.bincount(gid_sorted).astype(np.int64)
        else:
            uniq = rows
            inverse = np.zeros(0, dtype=np.int64)
            counts = np.zeros(0, dtype=np.int64)
        g = uniq.shape[0]
        # First-occurrence representative per group (stable: the lowest
        # node row index carrying the shape).
        representative = np.full(g, self.n_nodes, dtype=np.int64)
        if self.n_nodes:
            np.minimum.at(representative, inverse, np.arange(self.n_nodes))
        ext = {
            r: (
                uniq[:, 9 + 2 * e].copy(),
                uniq[:, 9 + 2 * e + 1].copy(),
            )
            for e, r in enumerate(ext_names)
        }
        grouped = GroupedSnapshot(
            snapshot=self,
            alloc_cpu_milli=uniq[:, 0].copy(),
            alloc_mem_bytes=uniq[:, 1].copy(),
            alloc_pods=uniq[:, 2].copy(),
            used_cpu_req_milli=uniq[:, 3].copy(),
            used_cpu_lim_milli=uniq[:, 4].copy(),
            used_mem_req_bytes=uniq[:, 5].copy(),
            used_mem_lim_bytes=uniq[:, 6].copy(),
            pods_count=uniq[:, 7].copy(),
            healthy=uniq[:, 8].astype(np.bool_),
            count=counts,
            group_index=inverse,
            representative=representative,
            extended=ext,
        )
        return self.__dict__.setdefault("_grouped_cache", grouped)

    def _group_rows(self) -> np.ndarray:
        """The ``[N, C]`` int64 grouping-key matrix: every fit-relevant
        column (allocatable, usage req+lim, pods, health, extended) in a
        fixed order — shared by :meth:`grouped` and the dispatch gate's
        hash pre-check so the two can never disagree on the key."""
        cols = [
            self.alloc_cpu_milli,
            self.alloc_mem_bytes,
            self.alloc_pods,
            self.used_cpu_req_milli,
            self.used_cpu_lim_milli,
            self.used_mem_req_bytes,
            self.used_mem_lim_bytes,
            self.pods_count,
            self.healthy.astype(np.int64),
        ]
        for r in sorted(self.extended):
            alloc, used = self.extended[r]
            cols.append(np.asarray(alloc, dtype=np.int64))
            cols.append(np.asarray(used, dtype=np.int64))
        if not self.n_nodes:
            return np.zeros((0, len(cols)), dtype=np.int64)
        return np.stack(cols, axis=1)

    def save(self, path: str) -> None:
        """Checkpoint to ``.npz`` (arrays + JSON metadata), reproducibly."""
        meta = {
            "names": self.names,
            "semantics": self.semantics,
            "labels": self.labels,
            "taints": self.taints,
            "extended_names": sorted(self.extended),
            "node_log": [list(t) for t in self.node_log],
            "pod_cpu_errs": self.pod_cpu_errs,
            "version": 1,
        }
        arrays = {
            f.name: getattr(self, f.name)
            for f in fields(self)
            if f.name
            not in (
                "names", "semantics", "extended", "labels", "taints",
                "node_log", "pod_cpu_errs",
            )
        }
        for r_name, (alloc, used) in self.extended.items():
            arrays[f"ext_alloc::{r_name}"] = alloc
            arrays[f"ext_used::{r_name}"] = used
        np.savez_compressed(path, __meta__=json.dumps(meta), **arrays)


@dataclass
class GroupedSnapshot:
    """Node-shape-compressed view of a :class:`ClusterSnapshot`.

    ``G`` groups of identical node rows: every per-group array is ``[G]``
    in the same column vocabulary as the parent snapshot, ``count[g]`` is
    how many node rows share shape ``g``, and the two index maps make the
    compression invertible:

    * :attr:`group_index` — ``[N]`` node row → its group (the gather
      ``per_group[group_index]`` expands any grouped result back to
      per-node, bit-exactly, because identical inputs produce identical
      kernel outputs);
    * :attr:`representative` — ``[G]`` group → the lowest node row index
      carrying the shape (so reports can name a real node per group).

    Built exclusively by :meth:`ClusterSnapshot.grouped`; treat as
    immutable, like the snapshot itself.
    """

    snapshot: ClusterSnapshot
    alloc_cpu_milli: np.ndarray
    alloc_mem_bytes: np.ndarray
    alloc_pods: np.ndarray
    used_cpu_req_milli: np.ndarray
    used_cpu_lim_milli: np.ndarray
    used_mem_req_bytes: np.ndarray
    used_mem_lim_bytes: np.ndarray
    pods_count: np.ndarray
    healthy: np.ndarray
    count: np.ndarray
    group_index: np.ndarray
    representative: np.ndarray
    extended: dict[str, tuple[np.ndarray, np.ndarray]] = field(
        default_factory=dict
    )

    @property
    def n_groups(self) -> int:
        return int(self.count.shape[0])

    @property
    def n_nodes(self) -> int:
        return self.snapshot.n_nodes

    @property
    def semantics(self) -> str:
        return self.snapshot.semantics

    @property
    def compression_ratio(self) -> float:
        """Nodes per group (1.0 = nothing merged)."""
        g = self.n_groups
        return (self.n_nodes / g) if g else 1.0

    def representative_names(self) -> list[str]:
        """One real node name per group (the first row with the shape)."""
        names = self.snapshot.names
        return [names[int(i)] for i in self.representative]

    def members(self, g: int) -> np.ndarray:
        """Node row indices belonging to group ``g`` (ascending)."""
        return np.flatnonzero(self.group_index == int(g))

    def effective_counts(self, node_mask=None) -> np.ndarray:
        """Per-group node multiplicity, optionally restricted to a
        ``[N]`` bool ``node_mask`` — the count-weighting the grouped
        kernels consume.  A masked-out node contributes fit 0 in every
        mode, so summing ``count_g(mask) * fit_g`` over groups equals the
        per-node masked sum exactly."""
        if node_mask is None:
            return self.count
        mask = np.asarray(node_mask, dtype=bool)
        if mask.shape != (self.n_nodes,):
            raise ValueError(
                f"node_mask: expected shape ({self.n_nodes},), "
                f"got {mask.shape}"
            )
        return np.bincount(
            self.group_index[mask], minlength=self.n_groups
        ).astype(np.int64)

    def expand(self, per_group: np.ndarray) -> np.ndarray:
        """Gather a per-group array (last axis ``[G]``) back to per-node
        (last axis ``[N]``) through :attr:`group_index`."""
        return np.asarray(per_group)[..., self.group_index]


# --- grouping dispatch gates -------------------------------------------
# KCCAP_GROUPING=0 is the escape hatch: every dispatch checks it, so the
# exact pre-grouping code path is restorable without a restart.  The
# grouped path only engages when it pays: clusters below the node floor
# fit comfortably in one kernel launch anyway, and a mean group
# occupancy below -group-min-count means the fleet is too heterogeneous
# for compression to shrink the kernel meaningfully.

#: Minimum cluster size for the grouped dispatch to engage — below this
#: the ungrouped kernel is already cheap and grouping only adds a gather.
GROUPING_NODE_FLOOR = 1024

#: Default minimum mean nodes-per-group (compression ratio) gate.
DEFAULT_GROUP_MIN_COUNT = 2

_group_lock = threading.Lock()
_group_min_count: int | None = None


def grouping_enabled() -> bool:
    """Process-wide grouping switch (``KCCAP_GROUPING=0`` disables).

    Checked per dispatch so the escape hatch works without a restart;
    off restores the exact pre-grouping dispatch byte-for-byte.
    """
    return os.environ.get("KCCAP_GROUPING", "1") != "0"


def group_min_count() -> int:
    """The active mean-occupancy gate (flag/env-configurable)."""
    global _group_min_count
    with _group_lock:
        if _group_min_count is None:
            try:
                env = int(os.environ.get("KCCAP_GROUP_MIN_COUNT", "0"))
            except ValueError:
                env = 0
            _group_min_count = (
                env if env > 0 else DEFAULT_GROUP_MIN_COUNT
            )
        return _group_min_count


def set_group_min_count(value: int) -> None:
    """Set the mean-occupancy gate (``-group-min-count`` flag)."""
    global _group_min_count
    if value < 1:
        raise ValueError("group min count must be >= 1")
    with _group_lock:
        _group_min_count = int(value)


def grouped_for_dispatch(snapshot: ClusterSnapshot) -> GroupedSnapshot | None:
    """The grouped form IFF the grouped kernels should serve this
    snapshot: grouping enabled, cluster at/above the node floor, and the
    compression ratio clears ``group_min_count()``.  ``None`` means
    "dispatch ungrouped" — the exact pre-grouping path.

    The decision memoizes per (snapshot, gate), and a heterogeneous
    fleet is rejected by a row-HASH pre-check before the full group sort
    is ever paid: distinct hash values never exceed the true group count
    (a collision can only merge groups), so ``N / distinct_hashes``
    UPPER-bounds the true compression ratio — when even that bound
    misses the gate, grouping provably would too.
    """
    if not grouping_enabled():
        return None
    n = snapshot.n_nodes
    if n < GROUPING_NODE_FLOOR:
        return None
    mc = group_min_count()
    hit = snapshot.__dict__.get("_grouping_decision")
    if hit is not None and hit[0] == mc:
        return hit[1]
    if "_grouped_cache" not in snapshot.__dict__:
        rows = snapshot._group_rows()
        # Odd multipliers keep the mod-2^64 mix bijective per column
        # (the golden-ratio constant, wrapped onto the int64 carrier).
        phi = np.uint64(0x9E3779B97F4A7C15).astype(np.int64)
        mult = np.arange(1, 2 * rows.shape[1], 2, dtype=np.int64) * phi
        h = rows @ mult  # wraps mod 2^64 — a hash, not a value
        if n < mc * np.unique(h).size:
            snapshot.__dict__["_grouping_decision"] = (mc, None)
            return None
    grouped = snapshot.grouped()
    result = grouped if n >= mc * grouped.n_groups else None
    snapshot.__dict__["_grouping_decision"] = (mc, result)
    return result


# Lazily-built gauges on the process registry (importing this module
# must register nothing; KCCAP_TELEMETRY=0 means zero registry calls —
# same policy as devcache).
_GROUP_MET: dict | None = None
_group_met_lock = threading.Lock()


def _group_metrics() -> dict:
    global _GROUP_MET
    if _GROUP_MET is None:
        with _group_met_lock:
            if _GROUP_MET is None:
                from kubernetesclustercapacity_tpu.telemetry.metrics import (
                    REGISTRY,
                )

                _GROUP_MET = {
                    "groups": REGISTRY.gauge(
                        "kccap_group_count",
                        "Distinct (shape, count) node groups in the "
                        "published snapshot.",
                    ),
                    "ratio": REGISTRY.gauge(
                        "kccap_compression_ratio",
                        "Nodes per group of the published snapshot "
                        "(1.0 = nothing merged).",
                    ),
                }
    return _GROUP_MET


def publish_group_metrics(snapshot: ClusterSnapshot) -> None:
    """Update the grouping gauges for a freshly published snapshot.

    Called on the publish path (server construction / snapshot swap),
    never per request.  No-op when telemetry or grouping is off; best
    effort — gauge publication must never fail a publish.
    """
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    if not _telemetry_enabled() or not grouping_enabled():
        return
    try:
        grouped = grouped_for_dispatch(snapshot)
        met = _group_metrics()
        if grouped is None:
            # Not engaged (small cluster / heterogeneous fleet): report
            # the sentinel rather than paying the full group sort just
            # for a gauge — 0 groups means "ungrouped dispatch".
            met["groups"].set(0)
            met["ratio"].set(1.0)
        else:
            met["groups"].set(grouped.n_groups)
            met["ratio"].set(round(grouped.compression_ratio, 4))
    except Exception:  # noqa: BLE001 - observability never fails publish
        pass


def load_snapshot(path: str) -> ClusterSnapshot:
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        extended = {
            r: (data[f"ext_alloc::{r}"], data[f"ext_used::{r}"])
            for r in meta["extended_names"]
        }
        return ClusterSnapshot(
            names=meta["names"],
            alloc_cpu_milli=data["alloc_cpu_milli"],
            alloc_mem_bytes=data["alloc_mem_bytes"],
            alloc_pods=data["alloc_pods"],
            used_cpu_req_milli=data["used_cpu_req_milli"],
            used_cpu_lim_milli=data["used_cpu_lim_milli"],
            used_mem_req_bytes=data["used_mem_req_bytes"],
            used_mem_lim_bytes=data["used_mem_lim_bytes"],
            pods_count=data["pods_count"],
            healthy=data["healthy"],
            semantics=meta["semantics"],
            extended=extended,
            labels=meta["labels"],
            taints=meta["taints"],
            node_log=[tuple(t) for t in meta.get("node_log", [])],
            pod_cpu_errs=meta.get("pod_cpu_errs")
            or [[] for _ in meta["names"]],
        )


def snapshot_from_fixture(
    fixture: dict,
    *,
    semantics: str = "reference",
    extended_resources: tuple[str, ...] = (),
) -> ClusterSnapshot:
    """Pack a node/pod fixture into dense arrays under the chosen semantics.

    ``extended_resources`` is strict-only, enforced HERE (the packer) so
    no front-end can silently produce a snapshot missing the columns a
    caller asked for — the reference semantics has no extended-column
    concept (its resource model is exactly cpu/memory/pods,
    ``ClusterCapacity.go:41-46``).
    """
    if extended_resources and semantics != "strict":
        raise ValueError(
            "extended resources require strict semantics (reference "
            "semantics has no extended-column concept)"
        )
    if semantics == "reference":
        return _pack_reference(fixture)
    if semantics == "strict":
        return _pack_strict(fixture, extended_resources)
    raise ValueError(f"unknown semantics {semantics!r} (want 'reference'|'strict')")


def _pack_reference(fixture: dict) -> ClusterSnapshot:
    """Reference-semantics packing — columnar, bit-exact vs. the oracle.

    Phantom nodes (unhealthy → zero-valued, ``ClusterCapacity.go:221-226``)
    keep their zero allocatables AND accumulate usage from pods with an empty
    ``nodeName`` — exactly what the degenerate field selector matches (Q4).

    Same intern-code/scatter-add technique as :func:`_pack_strict`, with the
    reference codecs in the lookup tables: one pod walk collects per
    container the interned cpu/mem strings plus a node-NAME group code; each
    distinct string parses once (uint64 cpu codec / ``Quantity.Value()``
    memory, both stored as int64 bit patterns); per-name usage totals are
    ``np.add.at`` scatter-adds whose int64 wraparound IS Go's mod-2^64
    uint64/int64 running-sum wrap (modular addition commutes, so numpy's
    accumulation order matching the oracle's is not required for equality);
    rows then gather their name's totals — rows sharing a name (phantom
    ``""`` rows, duplicate node names) get identical sums exactly as the
    oracle's per-row walk produces.  Pinned equal to the row-wise walk by
    ``tests/test_snapshot.py::TestReferenceColumnarParity``.
    """
    raw_nodes = fixture.get("nodes", [])
    n = len(raw_nodes)
    labels = [raw.get("labels", {}) for raw in raw_nodes]
    taints = [raw.get("taints", []) for raw in raw_nodes]
    snap = _empty_arrays(n)

    # Columnar node walk, pinned equal to the oracle's healthy_nodes walk
    # (via _pack_reference_rowwise) by TestReferenceColumnarParity.  Each
    # distinct (cpu, memory, pods) allocatable triple parses ONCE, at
    # first sight — parsing must happen inline (not in a post-walk LUT
    # pass) because the oracle parses each node's allocatables BEFORE its
    # conditions check: a bad cpu string on node 5 must raise before node
    # 7's <4-conditions panic, in exactly the rowwise order.
    names: list[str] = []
    node_log: list[tuple[str, str]] = []
    triple_vals: dict = {}  # triple -> (code, cpu, mem, pods, cpu_err)
    healthy_rows: list[int] = []
    row_codes: list[int] = []
    for i, raw in enumerate(raw_nodes):
        allocatable = raw.get("allocatable", {})
        triple = (
            allocatable.get("cpu", "0"),
            allocatable.get("memory", ""),
            allocatable.get("pods", "0"),
        )
        vals = triple_vals.get(triple)
        if vals is None:
            cpu, mem, pods, cpu_err = _oracle.node_allocatable_values(
                *triple
            )
            vals = triple_vals[triple] = (
                len(triple_vals), _clamp_i64(cpu), _clamp_i64(mem), pods,
                cpu_err,
            )
        if vals[4] is not None:  # codec error prints per OCCURRENCE
            node_log.append(("cpu_err", vals[4]))

        if _oracle.node_is_healthy_reference(raw):
            names.append(raw.get("name", ""))
            healthy_rows.append(i)
            row_codes.append(vals[0])
        else:
            # Phantom row (unhealthy → zero-valued node) keeps the empty
            # name and zero allocatables (ClusterCapacity.go:221-226);
            # the skip line prints the REAL name (:215).
            names.append("")
            node_log.append(("skip", raw.get("name", "")))

    if healthy_rows:
        lut = np.empty((len(triple_vals), 3), dtype=np.int64)
        for code, cpu, mem, pods, _err in triple_vals.values():
            lut[code] = (cpu, mem, pods)
        hr = np.asarray(healthy_rows, dtype=np.int64)
        rc = np.asarray(row_codes, dtype=np.int64)
        snap["alloc_cpu_milli"][hr] = lut[rc, 0]
        snap["alloc_mem_bytes"][hr] = lut[rc, 1]
        snap["alloc_pods"][hr] = lut[rc, 2]
    if n:
        snap["healthy"] = np.fromiter(
            (bool(nm) for nm in names), np.bool_, n
        )

    # -- columnar pod walk (the ΣP hot path) --
    # Each container's four quantity strings intern as ONE tuple key (a
    # cluster has few distinct request shapes — one dict lookup and one
    # append per container instead of four of each).  cpu slots carry the
    # rowwise walk's own `.get("cpu", "0")` default, so an explicit-null
    # cpu reaches the codec at LUT-build time and raises exactly as the
    # per-row oracle does; absent/null memory is Value() 0 on both paths.
    interned, name_gid, pod_gids, c_gids, c_codes = _walk_pods_reference(
        fixture.get("pods", [])
    )

    pod_cpu_errs: list[list[str]] = [[] for _ in range(n)]
    if name_gid and n:
        # Per-column LUTs over the distinct quads: each string parses once.
        lut = np.empty((4, len(interned)), dtype=np.int64)
        for qi, quad in enumerate(interned):
            lut[0, qi] = _clamp_i64(_q.cpu_to_milli_reference(quad[0]))
            lut[1, qi] = _clamp_i64(_q.cpu_to_milli_reference(quad[1]))
            lut[2, qi] = _clamp_i64(_oracle._mem_value(quad[2]))
            lut[3, qi] = _clamp_i64(_oracle._mem_value(quad[3]))
        g = len(name_gid)
        by_name = {
            k: np.zeros(g, dtype=np.int64)
            for k in ("creq", "clim", "mreq", "mlim", "count")
        }
        np.add.at(by_name["count"], np.asarray(pod_gids, np.int64), 1)
        cg = np.asarray(c_gids, np.int64)
        cc = np.asarray(c_codes, np.int64)
        for key, row in (
            ("creq", 0), ("clim", 1), ("mreq", 2), ("mlim", 3),
        ):
            np.add.at(by_name[key], cg, lut[row][cc])
        row_gid = np.fromiter(
            (name_gid.get(nm, -1) for nm in names), np.int64, n
        )
        hit = row_gid >= 0
        safe = np.where(hit, row_gid, 0)
        for field_name, key in (
            ("used_cpu_req_milli", "creq"),
            ("used_cpu_lim_milli", "clim"),
            ("used_mem_req_bytes", "mreq"),
            ("used_mem_lim_bytes", "mlim"),
            ("pods_count", "count"),
        ):
            snap[field_name] = np.where(hit, by_name[key][safe], 0)

        # Transcript events: container cpu strings that fail the codec
        # print once per OCCURRENCE, limits before requests
        # (ClusterCapacity.go:279-284), grouped before each node's block
        # in main's loop order.  Failing quads are known from the LUT
        # vocabulary; per-row lists replay through (c_gids, c_codes) with
        # no extra fixture walk.  Phantom rows share the "" group's list,
        # exactly as each phantom node's degenerate selector re-fetches
        # the same orphan pods.
        quad_errs: list[list[str]] = []
        any_err = False
        for quad in interned:
            errs = [
                p
                for p in (
                    _q.cpu_parse_error_payload(quad[1]),  # limits first
                    _q.cpu_parse_error_payload(quad[0]),
                )
                if p is not None
            ]
            quad_errs.append(errs)
            any_err = any_err or bool(errs)
        if any_err:
            gid_errs: dict[int, list[str]] = {}
            for gid_i, code_i in zip(c_gids, c_codes):
                errs = quad_errs[code_i]
                if errs:
                    gid_errs.setdefault(int(gid_i), []).extend(errs)
            for i in range(n):
                if hit[i]:
                    pod_cpu_errs[i] = list(
                        gid_errs.get(int(row_gid[i]), ())
                    )

    return ClusterSnapshot(
        names=names,
        semantics="reference",
        labels=labels,
        taints=taints,
        node_log=node_log,
        pod_cpu_errs=pod_cpu_errs,
        **snap,
    )


def container_cpu_error_payloads(pods) -> list[str]:
    """Codec-error payloads of the pods' containers, in the reference
    walk's emission order: per pod, per container, LIMITS before REQUESTS
    (``ClusterCapacity.go:279-284``), one entry per failing occurrence.
    The single source for the rowwise packer and the store's incremental
    rows (the columnar packer replays the same payloads through its
    interned-quad vocabulary).
    """
    errs: list[str] = []
    for pod in pods:
        for c in pod.get("containers", []):
            res = c.get("resources", {})
            req = res.get("requests", {})
            lim = res.get("limits", {})
            for s in (lim.get("cpu", "0"), req.get("cpu", "0")):
                p = _q.cpu_parse_error_payload(s)
                if p is not None:
                    errs.append(p)
    return errs


def _walk_pods_reference(pods):
    """Reference-mode columnar pod walk: the ΣP hot loop of packing.

    Returns ``(interned, name_gid, pod_gids, c_gids, c_codes)`` —
    insertion-ordered quad→code dict, nodeName→group dict, and the
    per-pod / per-container index vectors.  Runs the native C walk
    (:mod:`..native.ingest`) when available — same dict operations at C
    speed — and the pure-Python loop otherwise or whenever the native
    walk reports non-JSON-shaped input (``None``), so malformed fixtures
    raise exactly the pure path's exceptions.  Parity is pinned by
    ``tests/test_native_ingest.py``.
    """
    if _ingest.available():
        out = _ingest.walk_reference(pods, _oracle._EXCLUDED_PHASES)
        if out is not None:
            name_gid, interned, pg, cg, cc = out
            return (
                interned,
                name_gid,
                np.frombuffer(pg, dtype=np.int64),
                np.frombuffer(cg, dtype=np.int64),
                np.frombuffer(cc, dtype=np.int64),
            )
    interned: dict = {}  # quad tuple -> code; keys in insertion order
    name_gid: dict[str, int] = {}
    pod_gids: list[int] = []  # per surviving pod: its name group
    c_gids: list[int] = []  # per container: its pod's name group
    c_codes: list[int] = []  # per container: its quad code
    for pod in pods:
        if not _oracle._survives_field_selector(pod):
            continue
        gid = name_gid.setdefault(pod.get("nodeName", ""), len(name_gid))
        pod_gids.append(gid)
        for c in pod.get("containers", []):
            res = c.get("resources", {})
            req, lim = res.get("requests", {}), res.get("limits", {})
            quad = (
                req.get("cpu", "0"),
                lim.get("cpu", "0"),
                req.get("memory"),
                lim.get("memory"),
            )
            c_gids.append(gid)
            c_codes.append(interned.setdefault(quad, len(interned)))
    return interned, name_gid, pod_gids, c_gids, c_codes


def _pack_reference_rowwise(fixture: dict) -> ClusterSnapshot:
    """The original per-row oracle walk — kept as the parity comparator for
    the columnar packer (and as executable documentation of the per-node
    semantics the store's incremental updates follow, ``store.py``)."""
    nodes = _oracle.healthy_nodes(fixture)
    pods_by_node = _oracle.pods_by_node_index(fixture)

    n = len(nodes)
    rows = []
    names, labels, taints = [], [], []
    raw_nodes = fixture.get("nodes", [])
    node_log: list[tuple[str, str]] = []
    pod_cpu_errs: list[list[str]] = []
    for raw in raw_nodes:
        allocatable = raw.get("allocatable", {})
        payload = _oracle.node_allocatable_values(
            allocatable.get("cpu", "0"),
            allocatable.get("memory", ""),
            allocatable.get("pods", "0"),
        )[3]  # the single-sourced codec-error payload
        if payload is not None:
            node_log.append(("cpu_err", payload))
        if not _oracle.node_is_healthy_reference(raw):
            node_log.append(("skip", raw.get("name", "")))
    for i, node in enumerate(nodes):
        pods = pods_by_node.get(node.name, [])
        cpu_lim, cpu_req, mem_lim, mem_req = _oracle.pod_requests_limits(pods)
        names.append(node.name)
        rows.append(
            (
                _clamp_i64(node.allocatable_cpu),
                _clamp_i64(node.allocatable_memory),
                node.allocatable_pods,
                _clamp_i64(cpu_req),
                _clamp_i64(cpu_lim),
                mem_req,
                mem_lim,
                len(pods),
            )
        )
        labels.append(raw_nodes[i].get("labels", {}))
        taints.append(raw_nodes[i].get("taints", []))
        pod_cpu_errs.append(container_cpu_error_payloads(pods))

    mat = np.array(rows, dtype=np.int64).reshape(n, 8)
    snap = dict(
        zip(
            (
                "alloc_cpu_milli",
                "alloc_mem_bytes",
                "alloc_pods",
                "used_cpu_req_milli",
                "used_cpu_lim_milli",
                "used_mem_req_bytes",
                "used_mem_lim_bytes",
                "pods_count",
            ),
            mat.T.copy(),
        )
    )
    snap["healthy"] = np.array([bool(nm) for nm in names], dtype=np.bool_)

    return ClusterSnapshot(
        names=names,
        semantics="reference",
        labels=labels,
        taints=taints,
        node_log=node_log,
        pod_cpu_errs=pod_cpu_errs,
        **snap,
    )


def _pack_strict(
    fixture: dict, extended_resources: tuple[str, ...]
) -> ClusterSnapshot:
    """Correct-mode packing: real quantity grammar, scheduler-rule pod usage."""
    raw_nodes = fixture.get("nodes", [])
    n = len(raw_nodes)
    snap = _empty_arrays(n)
    ext = {
        r: (np.zeros(n, dtype=np.int64), np.zeros(n, dtype=np.int64))
        for r in extended_resources
    }
    names, labels, taints = [], [], []
    index = {}
    # Columnar node walk: each distinct allocatable tuple parses once into
    # a LUT row; nodes gather their row (clusters have few distinct node
    # shapes).  Pinned equal to the per-node assignments it replaced by
    # the strict packing tests + TestStrictColumnarParity.
    node_keys: dict = {}
    node_codes: list[int] = []
    healthy_list: list[bool] = []
    for i, raw in enumerate(raw_nodes):
        name = raw.get("name", "")
        names.append(name)
        index[name] = i
        labels.append(raw.get("labels", {}))
        taints.append(raw.get("taints", []))
        allocatable = raw.get("allocatable", {})
        key = (
            allocatable.get("cpu"),
            allocatable.get("memory"),
            allocatable.get("pods"),
            *(allocatable.get(r) for r in extended_resources),
        )
        node_codes.append(node_keys.setdefault(key, len(node_keys)))
        healthy_list.append(_strict_healthy(raw.get("conditions", [])))
    if n:
        n_cols = 3 + len(extended_resources)
        node_lut = np.empty((len(node_keys), n_cols), dtype=np.int64)
        for key, code in node_keys.items():
            node_lut[code, 0] = _strict_parse(key[0], milli=True)
            for k in range(1, n_cols):
                node_lut[code, k] = _strict_parse(key[k])
        codes = np.asarray(node_codes, dtype=np.int64)
        snap["alloc_cpu_milli"] = node_lut[codes, 0]
        snap["alloc_mem_bytes"] = node_lut[codes, 1]
        snap["alloc_pods"] = node_lut[codes, 2]
        snap["healthy"] = np.asarray(healthy_list, dtype=np.bool_)
        for e, r in enumerate(extended_resources):
            ext[r] = (node_lut[codes, 3 + e], ext[r][1])

    # Columnar pod ingestion — the 100k-pod hot path.  One Python walk
    # interns each container's quantity strings (cpu req/lim, mem
    # req/lim, extended requests) as ONE tuple key — a cluster has few
    # distinct request shapes, so this is one dict lookup and one append
    # per container; each distinct tuple then parses once into per-column
    # lookup tables, and every piece of arithmetic after that (per-pod
    # container sums, init-container peaks, the scheduler's
    # ``max(sum, init_peak)`` rule, per-node totals) is a numpy
    # gather/scatter.  Replaces a per-pod ``_effective_pod_resources``
    # walk (which remains the single-pod path for watch-event updates,
    # ``store.py``); semantics are pinned equal by
    # ``tests/test_snapshot.py::TestStrictColumnarParity``.
    interned, pod_nodes, c_pod, c_codes, i_pod, i_codes = _walk_pods_strict(
        fixture.get("pods", []), index, extended_resources
    )

    p = len(pod_nodes)
    if p:
        n_cols = 4 + len(extended_resources)
        lut = np.empty((n_cols, len(interned)), dtype=np.int64)
        for qi, quad in enumerate(interned):
            lut[0, qi] = _strict_parse(quad[0], milli=True)
            lut[1, qi] = _strict_parse(quad[1], milli=True)
            for k in range(2, n_cols):
                lut[k, qi] = _strict_parse(quad[k])
        idx = np.asarray(pod_nodes, dtype=np.int64)
        np.add.at(snap["pods_count"], idx, 1)
        cp = np.asarray(c_pod, dtype=np.int64)
        cc = np.asarray(c_codes, dtype=np.int64)
        ip = np.asarray(i_pod, dtype=np.int64)
        ic = np.asarray(i_codes, dtype=np.int64)
        i64min = np.iinfo(np.int64).min

        def effective(row: int) -> np.ndarray:
            """Per-pod ``max(sum(containers), max(initContainers))``."""
            acc = np.zeros(p, dtype=np.int64)
            np.add.at(acc, cp, lut[row][cc])
            if ip.size:
                # Peak starts at int64 min so untouched pods keep their
                # plain sum even for (degenerate) negative quantities —
                # exactly the per-pod running-max rule.
                peak = np.full(p, i64min, dtype=np.int64)
                np.maximum.at(peak, ip, lut[row][ic])
                acc = np.where(peak != i64min, np.maximum(acc, peak), acc)
            return acc

        for row, name in enumerate(
            ("used_cpu_req_milli", "used_cpu_lim_milli",
             "used_mem_req_bytes", "used_mem_lim_bytes")
        ):
            np.add.at(snap[name], idx, effective(row))
        for e, r_name in enumerate(extended_resources):
            np.add.at(ext[r_name][1], idx, effective(4 + e))

    return ClusterSnapshot(
        names=names,
        semantics="strict",
        extended=ext,
        labels=labels,
        taints=taints,
        **snap,
    )


def _walk_pods_strict(pods, index, extended_resources):
    """Strict-mode columnar pod walk (containers + initContainers).

    Returns ``(interned, pod_nodes, c_pod, c_codes, i_pod, i_codes)``.
    Native C walk when available, pure-Python loop otherwise or on
    non-JSON-shaped input — see :func:`_walk_pods_reference`.
    """
    if _ingest.available():
        out = _ingest.walk_strict(
            pods, index, _STRICT_TERMINATED, tuple(extended_resources)
        )
        if out is not None:
            interned, pn, cp, cc, ip, ic = out
            return (
                interned,
                np.frombuffer(pn, dtype=np.int64),
                np.frombuffer(cp, dtype=np.int64),
                np.frombuffer(cc, dtype=np.int64),
                np.frombuffer(ip, dtype=np.int64),
                np.frombuffer(ic, dtype=np.int64),
            )
    interned: dict = {}  # quad tuple -> code; keys in insertion order
    pod_nodes: list[int] = []
    c_pod: list[int] = []  # container -> pod ordinal
    c_codes: list[int] = []  # container -> quad code
    i_pod: list[int] = []
    i_codes: list[int] = []
    for pod in pods:
        node_name = pod.get("nodeName", "")
        if not node_name or node_name not in index:
            continue
        if pod.get("phase") in _STRICT_TERMINATED:
            continue
        pid = len(pod_nodes)
        pod_nodes.append(index[node_name])
        for kind_pod, kind_codes, key in (
            (c_pod, c_codes, "containers"),
            (i_pod, i_codes, "initContainers"),
        ):
            for c in pod.get(key, []):
                res = c.get("resources", {})
                req, lim = res.get("requests", {}), res.get("limits", {})
                quad = (
                    req.get("cpu"),
                    lim.get("cpu"),
                    req.get("memory"),
                    lim.get("memory"),
                    *(req.get(r) for r in extended_resources),
                )
                kind_pod.append(pid)
                kind_codes.append(
                    interned.setdefault(quad, len(interned))
                )
    return interned, pod_nodes, c_pod, c_codes, i_pod, i_codes


def _effective_pod_resources(
    pod: dict, extended_resources: tuple[str, ...]
) -> dict:
    """Scheduler-rule effective requests: ``max(sum(containers), max(inits))``.

    The reference ignores init containers entirely (Q7); real kube-scheduler
    reserves the max of the init-container peak and the steady-state sum.
    """

    # Flat accumulation in local ints (no per-container dicts): this runs
    # once per pod on the 100k-pod ingestion path.
    cpu_req = cpu_lim = mem_req = mem_lim = 0
    ext = dict.fromkeys(extended_resources, 0)
    for c in pod.get("containers", []):
        res = c.get("resources", {})
        req, lim = res.get("requests", {}), res.get("limits", {})
        cpu_req += _strict_parse(req.get("cpu"), milli=True)
        cpu_lim += _strict_parse(lim.get("cpu"), milli=True)
        mem_req += _strict_parse(req.get("memory"))
        mem_lim += _strict_parse(lim.get("memory"))
        for r in extended_resources:
            ext[r] += _strict_parse(req.get(r))
    for c in pod.get("initContainers", []):
        res = c.get("resources", {})
        req, lim = res.get("requests", {}), res.get("limits", {})
        cpu_req = max(cpu_req, _strict_parse(req.get("cpu"), milli=True))
        cpu_lim = max(cpu_lim, _strict_parse(lim.get("cpu"), milli=True))
        mem_req = max(mem_req, _strict_parse(req.get("memory")))
        mem_lim = max(mem_lim, _strict_parse(lim.get("memory")))
        for r in extended_resources:
            ext[r] = max(ext[r], _strict_parse(req.get(r)))
    return {
        "cpu_req": cpu_req,
        "cpu_lim": cpu_lim,
        "mem_req": mem_req,
        "mem_lim": mem_lim,
        "ext": ext,
    }


def _strict_healthy(conditions: list[dict]) -> bool:
    """Correct health predicate: Ready is True, no pressure condition is True."""
    ready = False
    for c in conditions:
        ctype, status = c.get("type", ""), c.get("status", "")
        if ctype == "Ready":
            ready = status == "True"
        elif status == "True":  # any pressure/problem condition firing
            return False
    return ready


@functools.lru_cache(maxsize=1 << 16)
def _strict_parse(s: str | None, *, milli: bool = False) -> int:
    """Strict-grammar parse with absent/invalid → 0; memoized (quantity
    strings repeat across a cluster — see ``utils.quantity``'s cache note)."""
    if s is None:
        return 0
    try:
        q = _q.parse_quantity(s)
    except _q.QuantityParseError:
        return 0
    return q.milli_value() if milli else q.value()


def _clamp_i64(u: int) -> int:
    """Reinterpret a Go uint64 as int64 (the kernels' array dtype)."""
    u %= 1 << 64
    return u - (1 << 64) if u >= 1 << 63 else u


def _empty_arrays(n: int) -> dict:
    return {
        "alloc_cpu_milli": np.zeros(n, dtype=np.int64),
        "alloc_mem_bytes": np.zeros(n, dtype=np.int64),
        "alloc_pods": np.zeros(n, dtype=np.int64),
        "used_cpu_req_milli": np.zeros(n, dtype=np.int64),
        "used_cpu_lim_milli": np.zeros(n, dtype=np.int64),
        "used_mem_req_bytes": np.zeros(n, dtype=np.int64),
        "used_mem_lim_bytes": np.zeros(n, dtype=np.int64),
        "pods_count": np.zeros(n, dtype=np.int64),
        "healthy": np.zeros(n, dtype=np.bool_),
    }


def synthetic_snapshot(
    n_nodes: int,
    *,
    seed: int = 0,
    mean_utilization: float = 0.4,
    alloc_pods: int = 110,
    kib_quantized: bool = True,
    shapes: int | None = None,
    topology: tuple[int, int] | None = None,
) -> ClusterSnapshot:
    """Array-level synthetic cluster — fast path for 1k/10k-node benches.

    Generates realistic allocatable/used distributions directly as arrays
    (no fixture objects), in O(N).  With ``kib_quantized=True`` all memory
    values are multiples of 1024 so the int32 KiB-rescaled fast kernel stays
    eligible; the values match what kubelets report (they publish ``Ki``).

    ``shapes=K`` draws only K distinct ``(allocatable, usage)`` rows and
    assigns every node one of them — the degenerate-fleet profile real
    clusters exhibit (a handful of machine shapes × thousands of
    replicas), which is what :meth:`ClusterSnapshot.grouped` compresses.
    ``None`` keeps the fully heterogeneous per-node draw.

    ``topology=(zones, racks_per_zone)`` attaches a zone/rack/host
    hierarchy as dense code COLUMNS (round-robin racks, nested zones,
    unique hosts) via :func:`~.topology.model.attach_topology` — no
    per-node label dicts are ever built, so hierarchical 1M-node
    fleets stay O(N) numpy; fixture-backed snapshots get the same
    hierarchy from real labels instead.
    """
    rng = np.random.default_rng(seed)
    n_draw = n_nodes if shapes is None else int(shapes)
    cores = rng.choice(np.array([2, 4, 8, 16, 32, 64]), size=n_draw)
    alloc_cpu = cores.astype(np.int64) * 1000
    mem_kib = cores.astype(np.int64) * 4 * 1024 * 1024 - rng.integers(
        0, 2**18, size=n_draw
    )
    alloc_mem = mem_kib * 1024
    if not kib_quantized:
        alloc_mem += rng.integers(0, 1024, size=n_draw)

    util_cpu = rng.beta(2, 3, size=n_draw) * 2 * mean_utilization
    util_mem = rng.beta(2, 3, size=n_draw) * 2 * mean_utilization
    used_cpu = (alloc_cpu * util_cpu).astype(np.int64)
    used_mem_kib = (mem_kib * util_mem).astype(np.int64)
    used_mem = used_mem_kib * 1024
    if not kib_quantized:
        used_mem += rng.integers(0, 1024, size=n_draw)
    pods = rng.integers(0, 60, size=n_draw).astype(np.int64)

    if shapes is not None:
        # Degenerate fleet: gather each node's row from the K-shape LUT
        # (numpy column builds — no per-node Python).
        assign = rng.integers(0, n_draw, size=n_nodes)
        alloc_cpu = alloc_cpu[assign]
        alloc_mem = alloc_mem[assign]
        used_cpu = used_cpu[assign]
        used_mem = used_mem[assign]
        pods = pods[assign]

    snap = ClusterSnapshot(
        names=[f"node-{i:05d}" for i in range(n_nodes)],
        alloc_cpu_milli=alloc_cpu,
        alloc_mem_bytes=alloc_mem,
        alloc_pods=np.full(n_nodes, alloc_pods, dtype=np.int64),
        used_cpu_req_milli=used_cpu,
        used_cpu_lim_milli=used_cpu * 2,
        used_mem_req_bytes=used_mem,
        used_mem_lim_bytes=used_mem * 2,
        pods_count=pods,
        healthy=np.ones(n_nodes, dtype=np.bool_),
        semantics="reference",
    )
    if topology is not None:
        from kubernetesclustercapacity_tpu.topology.model import (
            attach_topology,
        )

        t_zones, racks_per = topology
        if t_zones < 1 or racks_per < 1:
            raise ValueError(
                f"topology wants (zones >= 1, racks_per_zone >= 1), "
                f"got {topology!r}"
            )
        rack_code = np.arange(n_nodes, dtype=np.int64) % (
            t_zones * racks_per
        )
        attach_topology(snap, rack_code // racks_per, rack_code)
    return snap


def snapshot_from_live_cluster(
    kubeconfig: str | None = None,
    *,
    semantics: str = "strict",
    extended_resources: tuple[str, ...] = (),
) -> ClusterSnapshot:
    """Snapshot a live cluster via the Kubernetes Python client.

    Fixes the reference's N+1 query pattern (``1 + 2N + ΣP`` requests,
    SURVEY.md §3.4): exactly TWO paginated List calls — nodes and pods —
    then pure local packing.  Uses the optional ``kubernetes`` package when
    present (for its wider auth-provider support); otherwise falls back to
    the framework's own client (:mod:`..kubeapi`) — stdlib transport/auth
    plus PyYAML for the kubeconfig file, no Kubernetes client library.
    ``extended_resources`` names extra columns to pack (strict only).
    """
    try:
        from kubernetes import client, config  # type: ignore[import-not-found]
    except ImportError:
        from kubernetesclustercapacity_tpu.kubeapi import live_fixture

        return snapshot_from_fixture(
            live_fixture(kubeconfig),
            semantics=semantics,
            extended_resources=extended_resources,
        )

    config.load_kube_config(config_file=kubeconfig)  # pragma: no cover
    v1 = client.CoreV1Api()  # pragma: no cover

    def paginate(list_fn):  # pragma: no cover
        token = None
        while True:
            page = list_fn(limit=500, _continue=token)
            yield from page.items
            token = page.metadata._continue
            if not token:
                return

    def serialize_containers(containers):  # pragma: no cover
        out = []
        for c in containers or []:
            res = c.resources
            out.append(
                {
                    "resources": {
                        "requests": dict(res.requests or {}) if res else {},
                        "limits": dict(res.limits or {}) if res else {},
                    }
                }
            )
        return out

    fixture: dict = {"nodes": [], "pods": []}  # pragma: no cover
    for n in paginate(v1.list_node):  # pragma: no cover
        fixture["nodes"].append(
            {
                "name": n.metadata.name,
                "allocatable": dict(n.status.allocatable or {}),
                "conditions": [
                    {"type": c.type, "status": c.status}
                    for c in (n.status.conditions or [])
                ],
                "labels": dict(n.metadata.labels or {}),
                "taints": [
                    {"key": t.key, "value": t.value or "", "effect": t.effect}
                    for t in (n.spec.taints or [])
                ],
            }
        )
    for p in paginate(v1.list_pod_for_all_namespaces):  # pragma: no cover
        fixture["pods"].append(
            {
                "name": p.metadata.name,
                "namespace": p.metadata.namespace,
                "nodeName": p.spec.node_name or "",
                "phase": p.status.phase,
                "containers": serialize_containers(p.spec.containers),
                "initContainers": serialize_containers(p.spec.init_containers),
            }
        )
    return snapshot_from_fixture(  # pragma: no cover
        fixture, semantics=semantics, extended_resources=extended_resources
    )
