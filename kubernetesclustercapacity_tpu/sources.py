"""Shared cluster-source resolution for the CLI and the capacity service.

One place owns the rules for turning ``-snapshot``/``-semantics`` into a
packed snapshot, so the two front-ends cannot drift:

* ``.npz`` checkpoints carry the semantics they were packed with; an
  explicit conflicting request is an error (never silently mix packings);
* fixture ``.json`` re-packs under the requested semantics (default
  ``reference``).
"""

from __future__ import annotations

from kubernetesclustercapacity_tpu.fixtures import load_fixture
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    load_snapshot,
    snapshot_from_fixture,
)

__all__ = ["SourceError", "resolve_source"]


class SourceError(ValueError):
    """Unusable cluster source (missing file, semantics conflict)."""


def resolve_source(
    path: str, semantics: str | None
) -> tuple[dict | None, ClusterSnapshot, str]:
    """Load a fixture/.npz source → ``(fixture|None, snapshot, semantics)``.

    ``semantics=None`` means "not explicitly requested": adopt the
    checkpoint's stored packing for ``.npz``, default ``reference``
    otherwise.
    """
    import os

    if not os.path.exists(path):
        raise SourceError(f"snapshot file not found: {path}")
    if path.endswith(".npz"):
        snap = load_snapshot(path)
        if semantics is not None and semantics != snap.semantics:
            raise SourceError(
                f"snapshot {path} was packed with -semantics "
                f"{snap.semantics}; re-pack from a fixture to run {semantics}"
            )
        return None, snap, snap.semantics
    semantics = semantics or "reference"
    fixture = load_fixture(path)
    return fixture, snapshot_from_fixture(fixture, semantics=semantics), semantics
