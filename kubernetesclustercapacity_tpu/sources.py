"""Shared cluster-source resolution for the CLI and the capacity service.

One place owns the rules for turning ``-snapshot``/``-semantics`` into a
packed snapshot, so the two front-ends cannot drift:

* ``.npz`` checkpoints carry the semantics they were packed with; an
  explicit conflicting request is an error (never silently mix packings);
* fixture ``.json`` re-packs under the requested semantics (default
  ``reference``).
"""

from __future__ import annotations

from kubernetesclustercapacity_tpu.fixtures import load_fixture
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    load_snapshot,
    snapshot_from_fixture,
)

__all__ = ["SourceError", "resolve_source"]


class SourceError(ValueError):
    """Unusable cluster source (missing file, semantics conflict)."""


def resolve_source(
    path: str,
    semantics: str | None,
    extended_resources: tuple[str, ...] = (),
) -> tuple[dict | None, ClusterSnapshot, str]:
    """Load a fixture/.npz source → ``(fixture|None, snapshot, semantics)``.

    ``semantics=None`` means "not explicitly requested": adopt the
    checkpoint's stored packing for ``.npz``, default ``reference``
    otherwise.  ``extended_resources`` names extra columns to pack from a
    fixture (strict semantics only — reference has no concept of them);
    a ``.npz`` checkpoint must already CARRY every requested column
    (columns cannot be re-derived without the raw objects).
    """
    import os

    extended_resources = tuple(extended_resources)
    if not os.path.exists(path):
        raise SourceError(f"snapshot file not found: {path}")
    if path.endswith(".npz"):
        snap = load_snapshot(path)
        if semantics is not None and semantics != snap.semantics:
            raise SourceError(
                f"snapshot {path} was packed with -semantics "
                f"{snap.semantics}; re-pack from a fixture to run {semantics}"
            )
        missing = sorted(set(extended_resources) - set(snap.extended))
        if missing:
            raise SourceError(
                f"snapshot {path} carries no extended column(s) {missing}; "
                "re-pack from a fixture with -extended-resources"
            )
        return None, snap, snap.semantics
    semantics = semantics or "reference"
    if extended_resources and semantics != "strict":
        # The PACKER owns this rule (snapshot_from_fixture raises for
        # every fixture path); this pre-check only rewraps it as a
        # SourceError so front-ends report it like other source problems.
        raise SourceError(
            "extended resources require strict semantics (reference "
            "semantics has no extended-column concept)"
        )
    fixture = load_fixture(path)
    return (
        fixture,
        snapshot_from_fixture(
            fixture,
            semantics=semantics,
            extended_resources=extended_resources,
        ),
        semantics,
    )
