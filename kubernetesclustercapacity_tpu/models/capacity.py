"""The flagship capacity model: one object answering "will it schedule?".

:class:`CapacityModel` is the framework's user-facing composition of the
layers below it — snapshot arrays, constraint masks, and the jitted fit
kernels.  A :class:`PodSpec` describes the what-if pod (resources AND
scheduling constraints — everything the reference's six flags could not
express); ``evaluate`` answers one spec, ``sweep`` answers a grid.

The reference equivalent is the whole of ``main`` (``ClusterCapacity.go:
48-150``) minus flag parsing and printing; the constraint families have no
reference equivalent (it schedules anywhere resources allow).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kubernetesclustercapacity_tpu import masks as _masks
from kubernetesclustercapacity_tpu.ops.fit import (
    fit_per_node,
    fit_per_node_multi,
)
from kubernetesclustercapacity_tpu.scenario import (
    MultiResourceGrid,
    Scenario,
    ScenarioGrid,
)
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot

__all__ = [
    "PodSpec",
    "CapacityModel",
    "CapacityPlan",
    "CapacityResult",
    "DrainResult",
    "PlacementResult",
    "TopologySpreadResult",
]


@dataclass(frozen=True)
class PodSpec:
    """A what-if pod: resources plus the scheduling constraints it carries.

    ``extended_requests`` maps extra resource names (must exist in the
    snapshot's ``extended`` columns) to per-replica requests.  Constraint
    fields mirror the pod-spec fields kube-scheduler filters on; all are
    optional and default to unconstrained.  ``spread`` caps replicas per node
    (self-anti-affinity over the hostname topology; 1 = classic one-per-node
    spread, ``None`` = unlimited; must be >= 1 when set).

    ``priority`` (``None`` = no preemption) makes capacity
    preemption-aware: existing pods of strictly lower priority are
    treated as evictable, so only pods with ``priority >= this`` consume
    headroom (:mod:`..ops.preemption` — the kube-scheduler preemption
    upper bound).  Strict semantics only; needs the model's ``fixture``
    (pod priorities are not part of the dense snapshot).
    """

    cpu_request_milli: int
    mem_request_bytes: int
    replicas: int = 1
    cpu_limit_milli: int = 0
    mem_limit_bytes: int = 0
    extended_requests: dict[str, int] = field(default_factory=dict)
    tolerations: tuple = ()
    node_selector: dict = field(default_factory=dict)
    affinity_terms: tuple = ()
    anti_affinity_labels: dict = field(default_factory=dict)
    # Scopes anti_affinity_labels the way a PodAffinityTerm with no
    # namespaces field is scoped: to the incoming pod's own namespace.
    # None = match existing pods cluster-wide (a what-if spec that models
    # no namespace; real pods always have one).
    namespace: str | None = None
    spread: int | None = None
    priority: int | None = None

    def __post_init__(self) -> None:
        # CPU values may arrive as raw uint64 (the reference codec wraps
        # negatives mod 2^64, e.g. "-5" → 2^64−5000); normalize to the
        # int64 bit pattern every kernel / numpy array carries, HERE, so
        # no consumer (service fit/place, CLI, library users) can feed
        # an out-of-int64 Python int into jnp/np.int64 conversions.
        from kubernetesclustercapacity_tpu.utils.quantity import int64_bits

        object.__setattr__(
            self, "cpu_request_milli", int64_bits(self.cpu_request_milli)
        )
        object.__setattr__(
            self, "cpu_limit_milli", int64_bits(self.cpu_limit_milli)
        )
        if self.namespace is not None and not isinstance(self.namespace, str):
            # A non-string namespace would compare unequal to every
            # existing pod's namespace and silently DISABLE anti-affinity
            # scoping — reject like every other malformed spec field.
            raise ValueError(
                f"namespace must be a string, got "
                f"{type(self.namespace).__name__}"
            )
        if self.replicas < 0:
            # Reference parity accepts negative replicas on the fit
            # VERDICT (total >= replicas); placement has no coherent
            # semantics for them (a lax.scan length must be >= 0) and
            # evaluate() reports schedulable correctly with replicas
            # normalized at the comparison — the spec itself stays the
            # single gate for the placement surfaces.
            raise ValueError(
                "replicas must be >= 0 for PodSpec surfaces (the reference"
                "-parity negative-replicas verdict is a Scenario/fit-path "
                "behavior)"
            )
        if self.spread is not None and self.spread < 1:
            raise ValueError("spread must be >= 1 (or None for unlimited)")
        if self.priority is not None and not isinstance(self.priority, int):
            # A non-int priority would compare incoherently against the
            # table's int64 levels (bool is fine: it IS an int).
            raise ValueError(
                f"priority must be an int, got "
                f"{type(self.priority).__name__}"
            )
        for name, qty in self.extended_requests.items():
            if name in ("cpu", "memory"):
                # These alias the core columns: resource_matrix would
                # build a DUPLICATE row with a conflicting request and
                # silently constrain the resource twice.
                raise ValueError(
                    f"extended request {name!r} aliases a core resource — "
                    "use cpu_request_milli / mem_request_bytes"
                )
            # Zero means "does not consume"; negative has no coherent
            # semantics and the kernels disagree on it (the fit kernel
            # divides as-is, placement would treat it as non-consuming) —
            # reject at the spec so every surface stays consistent.
            if int(qty) < 0:
                raise ValueError(
                    f"extended request {name!r} must be >= 0, got {qty}"
                )

    @classmethod
    def from_scenario(cls, s: Scenario) -> "PodSpec":
        return cls(
            cpu_request_milli=s.cpu_request_milli,
            mem_request_bytes=s.mem_request_bytes,
            replicas=s.replicas,
            cpu_limit_milli=s.cpu_limit_milli,
            mem_limit_bytes=s.mem_limit_bytes,
        )

    @property
    def constrained(self) -> bool:
        return bool(
            self.tolerations
            or self.node_selector
            or self.affinity_terms
            or self.anti_affinity_labels
            or self.spread is not None
        )


@dataclass
class PlacementResult:
    """Outcome of a placement simulation: node assignment per replica.

    ``assignments`` is ``None`` when the counts-only bulk engine answered
    (per-replica order not requested): ``per_node`` then carries the full
    result — identical counts to what the scan would produce.
    """

    assignments: np.ndarray | None  # [R] node index, -1 = unplaceable
    per_node: np.ndarray  # [N] replicas landed on each node
    node_names: list[str]
    policy: str
    requested: int = 0
    engine: str = "scan"  # "scan" (lax.scan), "trace" or "bulk" (closed form)

    @property
    def placed(self) -> int:
        if self.assignments is None:
            return int(np.sum(self.per_node))
        return int(np.sum(self.assignments >= 0))

    @property
    def all_placed(self) -> bool:
        return self.placed >= self.requested

    def by_node(self) -> dict[str, int]:
        """Non-zero placements keyed by node name."""
        return {
            self.node_names[i]: int(c)
            for i, c in enumerate(self.per_node)
            if c
        }


@dataclass
class DrainResult:
    """Outcome of a drain simulation: a rehoming target per evicted pod.

    ``assignments[i]`` is the node name that takes ``pods[i]`` (placed in
    the order given, size-descending), or ``None`` if no remaining node
    can.  ``blocked`` maps pods whose eviction the disruption-budget
    gate refuses right now to the exhausted PDB names covering them
    (:mod:`..pdb`); ``evictable`` is the drain verdict — every pod has a
    home AND none is budget-blocked.
    """

    node: str
    pods: list[str]  # "namespace/name" keys, in placement order
    assignments: list[str | None]
    per_node: np.ndarray  # [N] rehomed-pod counts (0 at the drained node)
    policy: str
    blocked: dict[str, list[str]] = field(default_factory=dict)

    @property
    def evictable(self) -> bool:
        return not self.blocked and all(
            a is not None for a in self.assignments
        )

    def by_pod(self) -> dict[str, str | None]:
        return dict(zip(self.pods, self.assignments))


@dataclass
class TopologySpreadResult:
    """Capacity under a PodTopologySpreadConstraint (DoNotSchedule).

    ``zones`` maps each eligible topology domain to its raw capacity
    (sum of per-node fits); ``allowed`` to the replicas it may actually
    take under the skew bound — ``min(c_z, min_zone_capacity +
    max_skew)``, the reachable optimum for identical replicas filling
    round-robin.  A domain with zero remaining capacity still anchors
    the global minimum, capping every other domain at ``max_skew`` —
    exactly kube-scheduler's skew arithmetic.  ``unkeyed_nodes`` counts
    eligible nodes missing the topology key (excluded from domains and
    from capacity, the constraint's default node-inclusion behavior).
    """

    topology_key: str
    max_skew: int
    zones: dict[str, int]
    allowed: dict[str, int]
    total: int
    replicas_requested: int
    unkeyed_nodes: int

    @property
    def schedulable(self) -> bool:
        return self.total >= self.replicas_requested


@dataclass
class CapacityPlan:
    """Outcome of a scale-up plan: nodes to add so the spec fits.

    ``nodes_needed`` is ``0`` when current capacity already suffices and
    ``None`` when no count of template nodes can help (the template
    itself fits 0 replicas — wrong shape, untolerated taint, selector
    mismatch, …).
    """

    replicas_requested: int
    current_total: int
    per_node_fit: int  # replicas ONE empty template node takes
    nodes_needed: int | None

    @property
    def satisfiable(self) -> bool:
        return self.nodes_needed is not None


@dataclass
class CapacityResult:
    """Outcome of one evaluation: per-node fits, total, and the verdict."""

    fits: np.ndarray
    total: int
    replicas_requested: int
    mode: str

    @property
    def schedulable(self) -> bool:
        return self.total >= self.replicas_requested  # :144 inclusive >=


class CapacityModel:
    """Evaluate pod specs against one snapshot, with optional constraints.

    ``mode="reference"`` restricts to the bit-exact 2-resource kernel (and
    rejects constraints the reference cannot express unless
    ``allow_extensions``); ``mode="strict"`` uses corrected semantics and the
    full constraint/multi-resource surface.  ``fixture`` is only needed for
    anti-affinity against existing pods (pod labels aren't in the arrays).
    """

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        *,
        mode: str = "strict",
        fixture: dict | None = None,
        allow_extensions: bool = True,
        priority_table=None,
    ) -> None:
        self.snapshot = snapshot
        self.mode = mode
        self.fixture = fixture
        self.allow_extensions = allow_extensions
        # Lazy PriorityTable (preemption surfaces); a caller that already
        # holds the fixture's table (the service's cross-request cache)
        # seeds it to skip the O(pods) fixture walk.
        self._ptable = priority_table

    # -- mask assembly -----------------------------------------------------
    def _mask_parts(
        self, spec: PodSpec
    ) -> tuple[np.ndarray | None, np.ndarray | None, np.ndarray | None]:
        """``(taint, node_affinity, pod_anti_affinity)`` masks — split
        the way topology-spread domain discovery needs them: the
        node-affinity family (selector + affinity) filters domains under
        the default ``nodeAffinityPolicy: Honor``, taints by
        ``node_taints_policy``, while inter-pod anti-affinity is a
        separate predicate that never filters domains."""
        snap = self.snapshot
        has_taints = bool(snap.taints) and any(snap.taints)
        taint = None
        if has_taints and (self.mode == "strict" or spec.tolerations):
            taint = _masks.tolerations_mask(snap, list(spec.tolerations))
        affinity_parts = []
        if spec.node_selector:
            affinity_parts.append(
                _masks.node_selector_mask(snap, spec.node_selector)
            )
        if spec.affinity_terms:
            affinity_parts.append(
                _masks.node_affinity_mask(snap, list(spec.affinity_terms))
            )
        anti = None
        if spec.anti_affinity_labels:
            if self.fixture is None:
                raise ValueError(
                    "anti-affinity vs existing pods needs the source fixture "
                    "(pod labels are not part of the dense snapshot)"
                )
            anti = _masks.anti_affinity_existing_mask(
                snap,
                self.fixture,
                spec.anti_affinity_labels,
                namespace=spec.namespace,
            )
        return taint, _masks.combine_masks(*affinity_parts), anti

    def _masks_for(self, spec: PodSpec) -> np.ndarray | None:
        """Mask policy, by mode.

        * ``strict``: the taint mask ALWAYS applies (a real scheduler never
          places an untolerating pod on a hard-tainted node); the other
          families apply when the spec carries them.
        * ``reference``: the reference ignores constraints entirely, so no
          mask is implicit; explicitly-carried constraints are an extension
          and require ``allow_extensions`` (else :meth:`evaluate` raised
          already).
        """
        return _masks.combine_masks(*self._mask_parts(spec))

    def _require_strict(self, feature: str) -> None:
        """One wording for every strict-only surface's gate."""
        if self.mode != "strict":
            raise ValueError(
                f"{feature} requires strict semantics (the reference "
                "cannot express it)"
            )

    @staticmethod
    def _check_spread_args(max_skew: int, node_taints_policy: str) -> None:
        if max_skew < 1:
            raise ValueError("max_skew must be >= 1")
        if node_taints_policy not in ("ignore", "honor"):
            raise ValueError(
                f"node_taints_policy must be 'ignore' or 'honor', got "
                f"{node_taints_policy!r}"
            )

    def _spread_masks(self, spec: PodSpec, node_taints_policy: str):
        """``(full_mask, domain_mask)`` for the topology-spread family:
        fits always see every family; domain discovery honors the
        node-affinity family, taints by policy, and never inter-pod
        anti-affinity (a separate predicate)."""
        taint_mask, affinity_mask, anti_mask = self._mask_parts(spec)
        full = _masks.combine_masks(taint_mask, affinity_mask, anti_mask)
        domain = (
            affinity_mask
            if node_taints_policy == "ignore"
            else _masks.combine_masks(taint_mask, affinity_mask)
        )
        return full, domain

    def _check_extensions(self, constrained: bool) -> None:
        if (
            constrained
            and self.mode == "reference"
            and not self.allow_extensions
        ):
            raise ValueError(
                "constraints/extended resources are extensions beyond "
                "reference semantics; pass allow_extensions=True"
            )

    # -- preemption (PodSpec.priority) -------------------------------------
    def _priority_table(self):
        """The snapshot's suffix-sum priority table, built once per model
        over ALL extended columns (any spec's subset gathers from it)."""
        if self._ptable is None:
            from kubernetesclustercapacity_tpu.ops.preemption import (
                build_priority_table,
            )

            self._ptable = build_priority_table(
                self.fixture,
                self.snapshot,
                tuple(sorted(self.snapshot.extended)),
            )
        return self._ptable

    def _check_preemption(self, spec: PodSpec) -> None:
        if spec.priority is None:
            return
        self._require_strict("preemption-aware capacity (PodSpec.priority)")
        if self.fixture is None:
            raise ValueError(
                "preemption needs the source fixture (pod priorities are "
                "not part of the dense snapshot)"
            )

    def _usage_arrays(self, spec: PodSpec):
        """``(used_cpu, used_mem, pods_count)`` the kernels should see:
        the snapshot's own arrays, or — when the spec carries a
        ``priority`` — the preemption table's threshold columns (pods of
        strictly lower priority treated as evictable)."""
        snap = self.snapshot
        if spec.priority is None:
            return (
                snap.used_cpu_req_milli,
                snap.used_mem_req_bytes,
                snap.pods_count,
            )
        return self._priority_table().columns(spec.priority)

    def _multi_fit_args(self, spec: PodSpec):
        """The R-dim kernel operands for a spec with extended requests —
        ONE definition of the row ordering and request vector, shared by
        :meth:`evaluate` and :meth:`place` (their agreement is a pinned
        invariant)."""
        resources = ("cpu", "memory", *sorted(spec.extended_requests))
        alloc_rn, used_rn = self.snapshot.resource_matrix(resources)
        if spec.priority is not None:
            # The preemption table's own row assembler (the typed
            # missing-column refusal lives there, shared with
            # ops.preemption.fit_with_preemption).
            used_rn, _ = self._priority_table().multi_columns(
                spec.priority, resources
            )
        reqs = np.array(
            [
                spec.cpu_request_milli,
                spec.mem_request_bytes,
                *(spec.extended_requests[r] for r in resources[2:]),
            ],
            dtype=np.int64,
        )
        return alloc_rn, used_rn, reqs

    # -- evaluation --------------------------------------------------------
    _MASK_UNSET = object()

    def evaluate(self, spec: PodSpec, *, _node_mask=_MASK_UNSET) -> CapacityResult:
        """One spec → per-node fits + verdict.

        Resource arithmetic always runs on the appropriate kernel: the
        bit-exact 2-resource kernel unless the spec requests extended
        resources (which need the R-dim generalization).  Constraint masks
        and the spread clamp compose around either kernel.
        (``_node_mask`` is an internal hook: a caller that already built
        the spec's mask — :meth:`topology_spread` needs its parts —
        passes it to skip the rebuild, which walks the fixture for
        anti-affinity specs.)
        """
        snap = self.snapshot
        self._check_extensions(spec.constrained or bool(spec.extended_requests))
        self._check_preemption(spec)
        mask = (
            self._masks_for(spec)
            if _node_mask is self._MASK_UNSET
            else _node_mask
        )

        if not spec.extended_requests:
            used_cpu, used_mem, pods_count = self._usage_arrays(spec)
            fits = np.asarray(
                fit_per_node(
                    snap.alloc_cpu_milli,
                    snap.alloc_mem_bytes,
                    snap.alloc_pods,
                    used_cpu,
                    used_mem,
                    pods_count,
                    snap.healthy,
                    spec.cpu_request_milli,
                    spec.mem_request_bytes,
                    mode=self.mode,
                    node_mask=mask,
                )
            )
            if spec.spread is not None:
                fits = np.minimum(fits, spec.spread)
                if mask is not None:  # keep masked nodes at 0 after the clamp
                    fits = np.where(mask, fits, 0)
        else:
            alloc_rn, used_rn, reqs = self._multi_fit_args(spec)
            # cpu/mem usage already rides used_rn; only the pod count
            # needs the (possibly preemption-adjusted) column here.
            pods_count = self._usage_arrays(spec)[2]
            fits = np.asarray(
                fit_per_node_multi(
                    alloc_rn,
                    used_rn,
                    snap.alloc_pods,
                    pods_count,
                    snap.healthy,
                    reqs,
                    mode=self.mode,
                    node_mask=mask,
                    max_per_node=spec.spread,
                )
            )
        return CapacityResult(
            fits=fits,
            total=int(fits.sum()),
            replicas_requested=spec.replicas,
            mode=self.mode,
        )

    # Above this replica count, "auto" placement switches from the R-step
    # scan to the closed-form bulk engine (identical counts, O(N) math) —
    # the scan's per-replica order is only worth its R dependent steps
    # when the caller actually reads it.
    PLACE_SCAN_MAX = 256

    def place(
        self,
        spec: PodSpec,
        *,
        policy: str = "first-fit",
        assignments: bool | str = "auto",
        topology_key: str | None = None,
        max_skew: int = 1,
        node_taints_policy: str = "ignore",
    ) -> PlacementResult:
        """Simulate WHERE each replica lands under a bin-packing policy.

        The fit kernels answer "how many"; this answers "which node gets
        replica k", each placement shrinking the headroom the next one
        sees (:mod:`..ops.placement`).  Strict feasibility semantics;
        constraint masks compose like :meth:`evaluate`; extended
        resources route to the R-resource engine family (below).

        ``assignments`` picks the engine:

        * ``True``  — the ``lax.scan`` scheduler; result carries the
          per-replica assignment order, computed on-device.
        * ``"trace"`` — the closed-form trace engine
          (:func:`..ops.placement.place_replicas_trace` /
          ``_trace_multi`` for extended resources): the scan's exact
          per-replica order in O(R log R) host math, no scan.  Raises
          for degenerate zero-request specs (scan only).
        * ``False`` — the closed-form bulk engine
          (:func:`..ops.placement.place_replicas_bulk`): identical
          per-node counts in O(N) instead of R dependent scan steps;
          ``result.assignments`` is ``None``.
        * ``"auto"`` (default) — scan up to :data:`PLACE_SCAN_MAX`
          replicas; beyond that the trace engine when eligible (same
          order, closed form), else bulk (counts only).

        A spec with ``extended_requests`` routes to the R-resource engines
        (:func:`..ops.placement.place_replicas_multi` / ``_bulk_multi`` /
        ``_trace_multi``) over the snapshot's extended columns — same
        policies, same engine-selection rule.

        A spec with ``priority`` places against the preemption-adjusted
        headroom (lower-priority pods treated as already evicted) — the
        "where would they land after preemption" upper bound; which
        specific victims a real scheduler would pick is out of scope.

        ``topology_key`` adds the PodTopologySpread DoNotSchedule gate:
        every placement is checked against ``max_skew`` over the key's
        domains (the same arithmetic kube-scheduler runs per pod), with
        domain discovery per :meth:`topology_spread`'s node-inclusion
        policies.  The skew couples placements globally, so only the
        scan engine applies (closed-form ``assignments`` modes raise);
        strict semantics, 2-resource specs.
        """
        from kubernetesclustercapacity_tpu.ops.placement import (
            place_replicas,
            place_replicas_bulk,
            place_replicas_bulk_multi,
            place_replicas_multi,
            place_replicas_trace,
            place_replicas_trace_multi,
        )

        self._check_extensions(
            spec.constrained or bool(spec.extended_requests)
        )
        self._check_preemption(spec)
        if topology_key is not None:
            return self._place_spread(
                spec,
                policy=policy,
                assignments=assignments,
                topology_key=topology_key,
                max_skew=max_skew,
                node_taints_policy=node_taints_policy,
            )
        if max_skew != 1 or node_taints_policy != "ignore":
            # A caller who set the skew knobs but forgot the key would
            # otherwise run a completely unconstrained placement.
            raise ValueError(
                "max_skew/node_taints_policy need topology_key — without "
                "it the placement has no spread constraint"
            )
        snap = self.snapshot
        mask = self._masks_for(spec)
        kwargs = dict(
            n_replicas=spec.replicas,
            policy=policy,
            node_mask=mask,
            max_per_node=spec.spread,
        )
        if spec.extended_requests:
            alloc_rn, used_rn, reqs = self._multi_fit_args(spec)
            args = (
                alloc_rn, used_rn, snap.alloc_pods,
                self._usage_arrays(spec)[2], snap.healthy, reqs,
            )
            scan_fn, bulk_fn = place_replicas_multi, place_replicas_bulk_multi
            # The bulk multi engine needs at least one positive request
            # row (the 2-resource rule generalized).
            bulk_ok = (reqs > 0).any() and (reqs >= 0).all()
        else:
            used_cpu, used_mem, pods_count = self._usage_arrays(spec)
            args = (
                snap.alloc_cpu_milli,
                snap.alloc_mem_bytes,
                snap.alloc_pods,
                used_cpu,
                used_mem,
                pods_count,
                snap.healthy,
                spec.cpu_request_milli,
                spec.mem_request_bytes,
            )
            scan_fn, bulk_fn = place_replicas, place_replicas_bulk
            # bulk requires positive requests; the scan tolerates 0 —
            # degenerate zero-request specs always take the scan so both
            # engine selections honor "identical per-node counts".
            bulk_ok = (
                spec.cpu_request_milli > 0 and spec.mem_request_bytes > 0
            )
        # The trace engines cover both resource families wherever the
        # bulk closed form is proven (bulk_ok); only degenerate
        # zero-request specs keep the scan route.
        trace_fn = (
            place_replicas_trace_multi
            if spec.extended_requests
            else place_replicas_trace
        )
        if assignments == "trace":
            if not bulk_ok:
                raise ValueError(
                    "trace engine needs positive cpu AND mem requests "
                    "(or, with extended resources, at least one positive "
                    "request row) — its closed form is proven there; use "
                    "assignments=True (scan) for degenerate specs"
                )
            engine = "trace"
        elif assignments is False and bulk_ok:
            engine = "bulk"
        elif (
            assignments == "auto"
            and spec.replicas > self.PLACE_SCAN_MAX
            and bulk_ok
        ):
            engine = "trace"
        else:
            engine = "scan"
        if engine == "trace":
            order, per_node, _ = trace_fn(*args, **kwargs)
        elif engine == "bulk":
            per_node, _ = bulk_fn(*args, **kwargs)
            order = None
        else:
            order, per_node = scan_fn(*args, **kwargs)
            order = np.asarray(order)
        return PlacementResult(
            assignments=order,
            per_node=np.asarray(per_node),
            node_names=list(snap.names),
            policy=policy,
            requested=spec.replicas,
            engine=engine,
        )

    def _place_spread(
        self,
        spec: PodSpec,
        *,
        policy: str,
        assignments,
        topology_key: str,
        max_skew: int,
        node_taints_policy: str,
    ) -> PlacementResult:
        """Placement under the per-step maxSkew gate — scan engine only
        (the moving skew minimum couples every placement)."""
        from kubernetesclustercapacity_tpu.ops.placement import (
            place_replicas_spread,
        )

        self._require_strict("topology spread")
        self._check_spread_args(max_skew, node_taints_policy)
        if spec.extended_requests:
            raise ValueError(
                "topology-spread placement covers cpu/memory specs "
                "(extended resources: place without the constraint, or "
                "evaluate capacity via topology_spread)"
            )
        if assignments in ("trace", False):
            raise ValueError(
                "the skew gate couples placements — closed-form engines "
                "cannot apply; use assignments=True/'auto' (scan)"
            )
        # Argument validation must not depend on cluster contents (the
        # zero-domain early return below never reaches the kernel's own
        # checks).
        from kubernetesclustercapacity_tpu.ops.placement import POLICIES

        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r} (want one of {POLICIES})"
            )
        snap = self.snapshot
        full_mask, domain_mask = self._spread_masks(spec, node_taints_policy)
        zone_ids, member, _ = self._zone_membership(topology_key, domain_mask)
        used_cpu, used_mem, pods_count = self._usage_arrays(spec)
        if not zone_ids:
            return PlacementResult(
                assignments=np.full(spec.replicas, -1, dtype=np.int64),
                per_node=np.zeros(snap.n_nodes, dtype=np.int64),
                node_names=list(snap.names),
                policy=policy,
                requested=spec.replicas,
                engine="scan",
            )
        order, per_node, _ = place_replicas_spread(
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            snap.alloc_pods,
            used_cpu,
            used_mem,
            pods_count,
            snap.healthy,
            spec.cpu_request_milli,
            spec.mem_request_bytes,
            member - 1,  # zone index, -1 = no domain
            n_replicas=spec.replicas,
            n_zones=len(zone_ids),
            policy=policy,
            max_skew=max_skew,
            node_mask=full_mask,
            max_per_node=spec.spread,
        )
        return PlacementResult(
            assignments=np.asarray(order),
            per_node=np.asarray(per_node),
            node_names=list(snap.names),
            policy=policy,
            requested=spec.replicas,
            engine="scan",
        )

    def drain(
        self, node_name: str, *, policy: str = "best-fit"
    ) -> DrainResult:
        """Simulate ``kubectl drain``: can this node's pods be rehomed?

        Collects the node's counted pods (strict rules: non-terminated,
        scheduler-effective requests), sorts them size-descending (the
        first-fit-decreasing heuristic), and places each — with its OWN
        requests — onto the remaining nodes via
        :func:`..ops.placement.place_pods`.  The drained node is masked
        out; hard-tainted nodes are excluded as rehoming targets (the
        conservative strict-mode assumption — evicted pods' tolerations
        are not part of the fixture schema).

        Strict semantics only; needs the model's ``fixture`` (per-pod
        requests are not recoverable from the dense per-node sums).
        Rehoming feasibility covers cpu/memory/pod slots, plus every
        extended column some evicted pod actually requests (GPU pods
        only land where GPUs are free).  PodDisruptionBudgets carried by
        the fixture (``"pdbs"``) gate evictions the way the eviction API
        would: a pod covered by a zero-allowance budget lands in
        ``result.blocked`` and the node is not evictable
        (:mod:`..pdb` documents the point-in-time semantics).
        DaemonSet pods are NOT distinguished (the fixture schema
        carries no ownerReferences) — a real ``kubectl drain`` skips
        them; filter the fixture first if that distinction matters.
        """
        from kubernetesclustercapacity_tpu.ops.placement import (
            place_pods_multi,
        )
        from kubernetesclustercapacity_tpu.snapshot import (
            _STRICT_TERMINATED,
            _effective_pod_resources,
            _strict_parse,
        )

        self._require_strict("drain simulation")
        if self.fixture is None:
            raise ValueError(
                "drain needs the source fixture (per-pod requests are not "
                "part of the dense snapshot)"
            )
        snap = self.snapshot
        try:
            node_idx = snap.names.index(node_name)
        except ValueError:
            raise ValueError(f"unknown node {node_name!r}") from None

        ext_names = tuple(sorted(snap.extended))
        pods: list[tuple[str, dict]] = []
        unpacked: dict[str, set[str]] = {}  # pod key -> unpacked resources
        for pod in self.fixture.get("pods", []):
            if pod.get("nodeName") != node_name:
                continue
            if pod.get("phase") in _STRICT_TERMINATED:
                continue
            key = f"{pod.get('namespace', '')}/{pod.get('name', '')}"
            # An evicted pod requesting an extended resource the snapshot
            # does not PACK (e.g. a GPU pod against extended=(), the CLI
            # -drain live default) must fail here: _effective_pod_resources
            # silently drops the request, and the plan would report the
            # pod rehomeable onto nodes with no free GPUs.
            for c in (
                *pod.get("containers", []), *pod.get("initContainers", [])
            ):
                for r, qty in (
                    (c.get("resources", {}).get("requests") or {})
                ).items():
                    if (
                        r in ("cpu", "memory", "ephemeral-storage")
                        or r.startswith("hugepages-")
                        or r in ext_names
                    ):
                        continue
                    if _strict_parse(qty) > 0:
                        unpacked.setdefault(key, set()).add(r)
            pods.append((key, _effective_pod_resources(pod, ext_names)))
        if unpacked:
            detail = "; ".join(
                f"{k} requests {', '.join(sorted(rs))}"
                for k, rs in sorted(unpacked.items())
            )
            raise ValueError(
                f"drain {node_name!r}: pods request extended resources "
                f"not packed in this snapshot ({detail}) — rehoming "
                "feasibility would be wrong; repack with "
                "extended_resources=(...) covering them"
            )
        # First-fit-decreasing order; name breaks ties so the plan is
        # deterministic across runs.
        pods.sort(
            key=lambda t: (-t[1]["cpu_req"], -t[1]["mem_req"], t[0])
        )

        if not pods:
            return DrainResult(
                node=node_name, pods=[], assignments=[],
                per_node=np.zeros(snap.n_nodes, dtype=np.int64),
                policy=policy,
            )
        from kubernetesclustercapacity_tpu.pdb import blocked_evictions

        blocked = blocked_evictions(self.fixture, [k for k, _ in pods])
        # Resource rows: cpu/mem plus only the extended columns the
        # evicted pods actually request (inactive rows change nothing
        # and would widen the compiled shape for every drain).
        live_ext = tuple(
            r for r in ext_names if any(e["ext"][r] > 0 for _, e in pods)
        )
        resources = ("cpu", "memory", *live_ext)
        alloc_rn, used_rn = snap.resource_matrix(resources)
        reqs_rp = np.array(
            [
                [e["cpu_req"] for _, e in pods],
                [e["mem_req"] for _, e in pods],
                *([e["ext"][r] for _, e in pods] for r in live_ext),
            ],
            dtype=np.int64,
        )

        mask = self._masks_for(
            PodSpec(cpu_request_milli=1, mem_request_bytes=1)
        )
        mask = np.ones(snap.n_nodes, dtype=bool) if mask is None else mask.copy()
        mask[node_idx] = False

        assignments, counts = place_pods_multi(
            alloc_rn,
            used_rn,
            snap.alloc_pods,
            snap.pods_count,
            snap.healthy,
            reqs_rp,
            policy=policy,
            node_mask=mask,
        )
        return DrainResult(
            node=node_name,
            pods=[k for k, _ in pods],
            assignments=[
                snap.names[i] if i >= 0 else None
                for i in assignments.tolist()
            ],
            per_node=np.asarray(counts),
            policy=policy,
            blocked=blocked,
        )

    def topology_spread(
        self,
        spec: PodSpec,
        *,
        topology_key: str,
        max_skew: int = 1,
        node_taints_policy: str = "ignore",
    ) -> TopologySpreadResult:
        """Capacity under a topology spread constraint — how many
        replicas fit when their counts across ``topology_key`` domains
        may differ by at most ``max_skew`` (the PodTopologySpread
        ``DoNotSchedule`` predicate).

        Closed form over the ordinary per-node fits (so every other
        surface — masks, taints, per-node ``spread``, extended
        resources, preemption ``priority`` — composes): group fits into
        zone capacities ``c_z``, then each zone may take
        ``min(c_z, min_z c_z + max_skew)``.  Domains are the key's
        values among domain-eligible nodes, so a selector that excludes
        a zone removes it from the skew minimum, and a full-but-eligible
        zone anchors it at 0.  Domain filtering mirrors upstream's
        node-inclusion policies: the node-affinity family (selector +
        affinity) filters domains (``nodeAffinityPolicy: Honor``, the
        default); ``node_taints_policy`` mirrors the constraint field —
        the upstream default ``"ignore"`` keeps a zone whose only nodes
        are hard-tainted as a 0-capacity domain (the classic
        pending-pods surprise), ``"honor"`` drops it; inter-pod
        anti-affinity never filters domains (it is a separate predicate
        — an anti-affinity-excluded zone stays and anchors the
        minimum).  Counts new replicas only — the fresh-deployment
        model, where the constraint's selector matches just the spec's
        own pods.

        Strict semantics only.
        """
        self._require_strict("topology spread")
        self._check_spread_args(max_skew, node_taints_policy)
        full_mask, domain_mask = self._spread_masks(spec, node_taints_policy)
        fits = self.evaluate(spec, _node_mask=full_mask).fits
        zone_ids, member, unkeyed = self._zone_membership(
            topology_key, domain_mask
        )
        # One int64 scatter-add pass (bincount's float64 weights could
        # lose exactness on adversarial fit magnitudes); slot 0 absorbs
        # non-members.
        sums = np.zeros(len(zone_ids) + 1, dtype=np.int64)
        np.add.at(sums, member, np.asarray(fits, dtype=np.int64))
        zones = {z: int(sums[i + 1]) for z, i in zone_ids.items()}
        if not zones:
            allowed: dict[str, int] = {}
            total = 0
        else:
            floor = min(zones.values())
            allowed = {
                z: min(c, floor + max_skew) for z, c in zones.items()
            }
            total = sum(allowed.values())
        return TopologySpreadResult(
            topology_key=topology_key,
            max_skew=max_skew,
            zones=zones,
            allowed=allowed,
            total=total,
            replicas_requested=spec.replicas,
            unkeyed_nodes=unkeyed,
        )

    def _zone_membership(
        self, topology_key: str, domain_mask
    ) -> tuple[dict[str, int], np.ndarray, int]:
        """THE topology-domain membership rule, shared by the scalar and
        grid paths (they must never disagree): a node belongs to a domain
        iff it is healthy, domain-mask-eligible, and carries the key.
        Returns ``(zone→index, member[N] = index+1 or 0, unkeyed_count)``
        — ``unkeyed`` counts eligible nodes missing the key.

        Domain discovery delegates to the topology subsystem's shared
        label→code helper with the EXCLUDED missing-label policy (an
        unkeyed node joins no domain and anchors no skew minimum —
        PodTopologySpread's default node-inclusion behavior, pinned by
        ``tests/test_topology_gang.py`` so this call site and the gang
        model can never drift on what "missing" means)."""
        from kubernetesclustercapacity_tpu.topology.model import label_codes

        snap = self.snapshot
        eligible = np.asarray(snap.healthy, dtype=bool)
        if domain_mask is not None:
            eligible = eligible & np.asarray(domain_mask, dtype=bool)
        codes, domains, unkeyed = label_codes(
            snap.labels or [],
            topology_key,
            missing="exclude",
            eligible=eligible,
            n_nodes=snap.n_nodes,
        )
        zone_ids = {z: i for i, z in enumerate(domains)}
        return zone_ids, codes + 1, unkeyed

    def topology_spread_grid(
        self,
        grid: ScenarioGrid,
        *,
        topology_key: str,
        max_skew: int = 1,
        node_taints_policy: str = "ignore",
        tolerations: tuple = (),
        node_selector: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized :meth:`topology_spread` over a scenario grid.

        One per-node sweep gives ``fits[S, N]``; zone aggregation is a
        ``[S, N] @ [N, Z]`` one-hot matmul (the MXU-shaped form of the
        group-by), then the skew clamp is elementwise row math.  Shared
        constraints compose like :meth:`sweep`.  Returns
        ``(totals[S], schedulable[S])``.
        """
        from kubernetesclustercapacity_tpu.ops.fit import sweep_grid

        self._require_strict("topology spread")
        self._check_spread_args(max_skew, node_taints_policy)
        grid.validate()
        snap = self.snapshot
        shared_spec = PodSpec(
            cpu_request_milli=1,
            mem_request_bytes=1,
            tolerations=tolerations,
            node_selector=node_selector or {},
        )
        self._check_extensions(shared_spec.constrained)
        full_mask, domain_mask = self._spread_masks(
            shared_spec, node_taints_policy
        )
        zone_ids, member, _ = self._zone_membership(topology_key, domain_mask)
        n_zones = len(zone_ids)
        s = grid.size
        if n_zones == 0:
            return (
                np.zeros(s, dtype=np.int64),
                grid.replicas.astype(np.int64) <= 0,
            )
        _, _, fits = sweep_grid(
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            snap.alloc_pods,
            snap.used_cpu_req_milli,
            snap.used_mem_req_bytes,
            snap.pods_count,
            snap.healthy,
            grid.cpu_request_milli,
            grid.mem_request_bytes,
            grid.replicas,
            mode="strict",
            node_mask=full_mask,
            return_per_node=True,
        )
        onehot = np.zeros((snap.n_nodes, n_zones), dtype=np.int64)
        keyed = member > 0
        onehot[np.arange(snap.n_nodes)[keyed], member[keyed] - 1] = 1
        c = np.asarray(fits, dtype=np.int64) @ onehot  # [S, Z]
        floor = c.min(axis=1)
        allowed = np.minimum(c, (floor + max_skew)[:, None])
        totals = allowed.sum(axis=1)
        return totals, totals >= grid.replicas.astype(np.int64)

    def _template_model(self, node_template: dict) -> "CapacityModel":
        """A one-node model over an EMPTY template node — the
        scale-planning unit.  Built through the ordinary packer, so the
        per-node fit inherits every surface for free: strict quantity
        grammar, health, taints vs the spec's tolerations, selectors,
        spread, extended columns."""
        from kubernetesclustercapacity_tpu.snapshot import (
            snapshot_from_fixture,
        )

        template = dict(node_template)
        template.setdefault("name", "template-node")
        template.setdefault(
            "conditions", [{"type": "Ready", "status": "True"}]
        )
        fixture = {"nodes": [template], "pods": []}
        snap = snapshot_from_fixture(
            fixture, semantics="strict",
            extended_resources=tuple(sorted(self.snapshot.extended)),
        )
        return CapacityModel(snap, mode="strict", fixture=fixture)

    def nodes_needed(
        self, spec: PodSpec, node_template: dict
    ) -> CapacityPlan:
        """Scale-up planning: how many ``node_template`` nodes must be
        added so ``spec.replicas`` fit? — the cluster-autoscaler what-if.

        ``node_template`` is a fixture-schema node dict (``allocatable``
        plus optional ``labels``/``taints``/``conditions``).  Closed
        form: the deficit over current capacity divided by one empty
        template node's fit for this spec (ceil); constraints bind both
        sides (a selector the template's labels miss, or a template
        taint the spec does not tolerate, makes the plan unsatisfiable).
        Strict semantics only.
        """
        self._require_strict("capacity planning")
        current = int(self.evaluate(spec).total)
        per_node = int(self._template_model(node_template).evaluate(spec).total)
        deficit = spec.replicas - current
        if deficit <= 0:
            needed = 0
        elif per_node <= 0:
            needed = None
        else:
            needed = -(-deficit // per_node)  # ceil
        return CapacityPlan(
            replicas_requested=spec.replicas,
            current_total=current,
            per_node_fit=per_node,
            nodes_needed=needed,
        )

    def nodes_needed_grid(
        self,
        grid: ScenarioGrid,
        node_template: dict,
        *,
        tolerations: tuple = (),
        node_selector: dict | None = None,
    ) -> np.ndarray:
        """Vectorized :meth:`nodes_needed` over a scenario grid.

        Returns ``needed[S]`` int64: ``0`` = already fits, ``-1`` =
        unsatisfiable with this template, else the node count.  Two
        sweeps total — the cluster and the one-node template — then
        elementwise closed form.  The shared constraints bind both
        sweeps (a tolerated template taint stays satisfiable here, like
        the scalar path's ``PodSpec`` constraints).
        """
        self._require_strict("capacity planning")
        shared = dict(tolerations=tolerations, node_selector=node_selector)
        totals, _ = self.sweep(grid, **shared)
        per_node, _ = self._template_model(node_template).sweep(grid, **shared)
        deficit = grid.replicas.astype(np.int64) - totals
        ceil_div = -(-deficit // np.maximum(per_node, 1))
        return np.where(
            deficit <= 0,
            np.int64(0),
            np.where(per_node > 0, ceil_div, np.int64(-1)),
        )

    def sweep(
        self,
        grid: ScenarioGrid,
        *,
        tolerations: tuple = (),
        node_selector: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Grid sweep with optional shared constraints.

        Dispatches through the auto kernel chooser
        (:func:`..ops.pallas_fit.sweep_auto`): eligible sweeps — either
        mode, masked or not — run the fused Pallas int32 kernel, the rest
        the exact int64 XLA kernel; both are bit-exact.  The shared mask
        (same for every scenario) is applied inside the kernel.
        Per-scenario constraint grids go through
        :func:`..ops.fit.sweep_grid_multi` directly.
        """
        from kubernetesclustercapacity_tpu.ops.pallas_fit import sweep_auto

        grid.validate()
        snap = self.snapshot
        shared_spec = PodSpec(
            cpu_request_milli=1,
            mem_request_bytes=1,
            tolerations=tolerations,
            node_selector=node_selector or {},
        )
        self._check_extensions(shared_spec.constrained)
        mask = self._masks_for(shared_spec)
        totals, sched, _ = sweep_auto(
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            snap.alloc_pods,
            snap.used_cpu_req_milli,
            snap.used_mem_req_bytes,
            snap.pods_count,
            snap.healthy,
            grid.cpu_request_milli,
            grid.mem_request_bytes,
            grid.replicas,
            mode=self.mode,
            node_mask=mask,
        )
        return np.asarray(totals), np.asarray(sched)

    def sweep_preemption(
        self,
        grid: ScenarioGrid,
        priorities,
        *,
        tolerations: tuple = (),
        node_selector: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Preemption-aware grid sweep: scenario ``s`` evicts pods of
        priority below ``priorities[s]``.

        The ``[S]`` priority vector rides the scenario axis — an
        in-graph ``searchsorted`` over the table's levels plus a
        per-scenario column gather (:func:`..ops.preemption
        .sweep_preemption`); strict semantics only, needs the model's
        ``fixture``.  Shared constraints compose like :meth:`sweep`.
        """
        from kubernetesclustercapacity_tpu.ops.preemption import (
            sweep_preemption,
        )

        grid.validate()
        priorities = np.asarray(priorities, dtype=np.int64)
        if priorities.shape != (grid.size,):
            raise ValueError(
                f"priorities: expected shape ({grid.size},), got "
                f"{priorities.shape}"
            )
        # Reuse the spec gate with a minimal carrier spec: same errors,
        # one wording.
        self._check_preemption(
            PodSpec(cpu_request_milli=1, mem_request_bytes=1, priority=0)
        )
        snap = self.snapshot
        shared_spec = PodSpec(
            cpu_request_milli=1,
            mem_request_bytes=1,
            tolerations=tolerations,
            node_selector=node_selector or {},
        )
        self._check_extensions(shared_spec.constrained)
        mask = self._masks_for(shared_spec)
        t = self._priority_table()
        totals, sched = sweep_preemption(
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            snap.alloc_pods,
            snap.healthy,
            t.levels,
            t.used_cpu_ge,
            t.used_mem_ge,
            t.pods_ge,
            grid.cpu_request_milli,
            grid.mem_request_bytes,
            priorities,
            grid.replicas,
            mode=self.mode,
            node_mask=mask,
        )
        return np.asarray(totals), np.asarray(sched)

    def sweep_multi(
        self,
        grid: MultiResourceGrid,
        *,
        tolerations: tuple = (),
        node_selector: dict | None = None,
        spread: int | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """R-resource grid sweep (BASELINE config 4) with shared constraints.

        ``grid.resources`` selects snapshot columns (``cpu``/``memory`` plus
        any :attr:`ClusterSnapshot.extended` names); dispatch goes through
        :func:`..ops.pallas_multi.sweep_multi_auto` — the fused R-dim
        Pallas kernel when eligibility is proven, the exact int64 kernel
        otherwise, bit-exact either way.  The shared mask composes exactly
        like :meth:`sweep`; ``spread`` caps per-node replicas (forces the
        exact kernel).
        """
        from kubernetesclustercapacity_tpu.ops.pallas_multi import (
            sweep_multi_auto,
        )

        grid.validate()
        snap = self.snapshot
        shared_spec = PodSpec(
            cpu_request_milli=1,
            mem_request_bytes=1,
            tolerations=tolerations,
            node_selector=node_selector or {},
            extended_requests=dict.fromkeys(
                (r for r in grid.resources if r not in ("cpu", "memory")), 1
            ),
        )
        self._check_extensions(
            shared_spec.constrained or bool(shared_spec.extended_requests)
        )
        mask = self._masks_for(shared_spec)
        alloc_rn, used_rn = snap.resource_matrix(grid.resources)
        totals, sched, _ = sweep_multi_auto(
            alloc_rn,
            used_rn,
            snap.alloc_pods,
            snap.pods_count,
            snap.healthy,
            grid.requests,
            grid.replicas,
            mode=self.mode,
            node_masks=mask,
            max_per_node=spread,
        )
        return np.asarray(totals), np.asarray(sched)
