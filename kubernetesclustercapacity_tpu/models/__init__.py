"""Capacity models: user-facing facades composing snapshot, kernel and masks."""

from kubernetesclustercapacity_tpu.models.capacity import (  # noqa: F401
    CapacityModel,
    CapacityPlan,
    CapacityResult,
    DrainResult,
    PlacementResult,
    PodSpec,
    TopologySpreadResult,
)
