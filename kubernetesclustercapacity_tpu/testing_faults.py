"""Deterministic fault injection at the service protocol boundary.

An in-process TCP proxy that sits between a :class:`~.service.client
.CapacityClient` and a :class:`~.service.server.CapacityServer` and
injects transport faults *per request frame*: connection drops, partial
writes, garbage frames, and stalls past the caller's deadline.  The
chaos suite (``tests/test_resilience.py``) drives a scripted op
sequence through it and asserts the results are bit-identical to a
fault-free run — the resilience layer's acceptance bar.

Faults are scripted, not sampled at injection time: a :class:`FaultPlan`
is either an explicit per-request sequence (exhausted → pass-through)
or generated up front from a seed, so every chaos run is exactly
reproducible.  The plan consumes one decision per *client request
frame* observed, across all connections, in arrival order.

Fault vocabulary (``FAULTS``):

``drop_pre``
    Close the client connection *without* forwarding the request — the
    server never sees it (safe to inject on non-idempotent ops; used to
    prove ``update``/``reload`` are never auto-retried).
``drop_post``
    Forward the request, read the server's reply, then close without
    sending any of it — the op executed but the client cannot know.
``partial``
    Forward, then send only the first half of the reply frame and close
    (a mid-frame transport loss).
``garbage``
    Forward, discard the real reply, send a well-framed body that is not
    valid JSON, and close.
``stall``
    Sleep ``stall_s`` before forwarding — long enough for the client's
    read timeout or deadline to fire first.

Stream mode (``FaultProxy(..., stream=True)``) adapts the proxy to
one-request-many-replies protocols — the serving plane's pub-sub stream
(:mod:`.service.plane`), where a subscriber sends one hello frame and
then receives an unbounded frame stream.  The client's first frame is
always forwarded intact; the plan then consumes one decision per
SERVER frame, in arrival order: ``drop_pre`` silently swallows the
frame (the subscriber sees a gap — its digest chain breaks and it must
resync), ``garbage``/``partial`` corrupt it, ``stall`` delays it, and
``drop_post`` cuts the connection after delivering it.

Runtime partition control (:meth:`FaultProxy.partition` /
:meth:`FaultProxy.heal`) models a network partition ORTHOGONALLY to the
scripted plan: while partitioned, every frame crossing the cut
direction(s) is silently swallowed — connections stay up, bytes just
never arrive, exactly what a partition looks like from an endpoint.
``direction`` selects symmetric (``"both"``) or asymmetric one-way
drops (``"to_server"`` / ``"to_client"``); both methods are safe to
call from the test thread mid-traffic without restarting the proxy, and
partitioned frames consume NO plan decisions (a scripted fault schedule
stays aligned to the frames that actually cross).
"""

from __future__ import annotations

import random
import socket
import struct
import threading

from kubernetesclustercapacity_tpu.utils.threads import supervised
import time

__all__ = ["FAULTS", "PARTITION_DIRECTIONS", "FaultPlan", "FaultProxy"]

FAULTS = ("drop_pre", "drop_post", "partial", "garbage", "stall")

#: Valid :meth:`FaultProxy.partition` directions: symmetric, or the two
#: asymmetric one-way cuts (frames dropped only on the named leg).
PARTITION_DIRECTIONS = ("both", "to_server", "to_client")

_GARBAGE_BODY = b"\x00\xff\xfe{not json"


class FaultPlan:
    """A deterministic per-request fault schedule.

    ``sequence`` entries are fault names from :data:`FAULTS` or ``None``
    (pass through).  Once exhausted every further request passes through
    — so a finite burst of faults always lets the run complete.
    Thread-safe (connections are handled concurrently).
    """

    def __init__(self, sequence=()) -> None:
        seq = list(sequence)
        for f in seq:
            if f is not None and f not in FAULTS:
                raise ValueError(f"unknown fault {f!r} (known: {FAULTS})")
        self._seq = seq
        self._i = 0
        self._lock = threading.Lock()
        #: injected-fault counts, by fault name (observability for tests).
        self.injected: dict[str, int] = {f: 0 for f in FAULTS}
        #: requests forwarded to the upstream server.
        self.forwarded = 0

    @classmethod
    def seeded(
        cls,
        seed: int,
        n: int,
        *,
        fault_rate: float = 0.3,
        faults: tuple[str, ...] = ("drop_pre", "partial", "garbage"),
    ) -> "FaultPlan":
        """``n`` decisions drawn up front from ``random.Random(seed)`` —
        the schedule is fixed before any traffic flows, so a seeded
        chaos run replays exactly."""
        rng = random.Random(seed)
        seq = [
            rng.choice(faults) if rng.random() < fault_rate else None
            for _ in range(n)
        ]
        return cls(seq)

    def next_fault(self) -> str | None:
        with self._lock:
            if self._i >= len(self._seq):
                return None
            fault = self._seq[self._i]
            self._i += 1
            return fault

    def count(self, fault: str) -> None:
        with self._lock:
            self.injected[fault] += 1

    def count_forwarded(self) -> None:
        with self._lock:
            self.forwarded += 1


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """``n`` bytes or ``None`` on EOF/reset at any point (the proxy
    treats a vanished peer as end-of-conversation, never an error)."""
    chunks = []
    while n:
        try:
            chunk = sock.recv(min(n, 1 << 20))
        except OSError:
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _read_frame(sock: socket.socket) -> bytes | None:
    """One length-prefixed frame (header + body), or None on EOF."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return header + body


class FaultProxy:
    """An in-process TCP proxy injecting :class:`FaultPlan` faults.

    Usage::

        plan = FaultPlan(["drop_pre", None, "garbage", None])
        with FaultProxy(server.address, plan) as proxy:
            client = CapacityClient(*proxy.address, retry=RetryPolicy())
            ...

    Each accepted client connection gets its own upstream connection and
    handler thread; frames are forwarded one request/response pair at a
    time so the plan maps 1:1 onto client calls.
    """

    def __init__(
        self,
        upstream: tuple[str, int],
        plan: FaultPlan,
        *,
        host: str = "127.0.0.1",
        stall_s: float = 1.0,
        stream: bool = False,
    ) -> None:
        self._upstream = upstream
        self.plan = plan
        self._stall_s = float(stall_s)
        self._stream = bool(stream)
        # Runtime partition state (None = healed), toggled from the test
        # thread; _partition_dropped counts swallowed frames so a test
        # can assert the cut actually intercepted traffic.
        self._state_lock = threading.Lock()
        self._partition_dir: str | None = None
        self._partition_dropped = 0
        self._stop = threading.Event()
        self._listener = socket.create_server((host, 0))
        self._listener.settimeout(0.2)
        self._threads: list[threading.Thread] = []
        self._conns_lock = threading.Lock()
        self._conns: set[socket.socket] = set()
        self._accept_thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._listener.getsockname()

    # -- runtime partition control (test-thread API) -----------------------
    def partition(self, direction: str = "both") -> None:
        """Cut the link mid-run (no proxy restart): frames crossing the
        named direction(s) are silently swallowed from now until
        :meth:`heal`.  Connections stay up — endpoints observe silence,
        not resets — and the scripted :class:`FaultPlan` is NOT consumed
        by swallowed frames, so a seeded fault schedule replays
        identically around the partition window."""
        if direction not in PARTITION_DIRECTIONS:
            raise ValueError(
                f"unknown partition direction {direction!r} "
                f"(known: {PARTITION_DIRECTIONS})"
            )
        with self._state_lock:
            self._partition_dir = direction

    def heal(self) -> None:
        """End the partition: traffic flows (and the plan resumes
        deciding) from the next frame on.  Idempotent."""
        with self._state_lock:
            self._partition_dir = None

    @property
    def partitioned(self) -> str | None:
        """The active partition direction, or ``None`` when healed."""
        with self._state_lock:
            return self._partition_dir

    @property
    def partition_dropped(self) -> int:
        """Frames swallowed by the partition so far (both directions)."""
        with self._state_lock:
            return self._partition_dropped

    def _cut(self, direction: str) -> bool:
        """True (and counted) when the active partition swallows a frame
        headed ``direction``."""
        with self._state_lock:
            p = self._partition_dir
            hit = p is not None and (p == "both" or p == direction)
            if hit:
                self._partition_dropped += 1
            return hit

    def start(self) -> "FaultProxy":
        self._accept_thread = threading.Thread(
            target=supervised(self._accept_loop, name="kccap-proxy-accept"),
            daemon=True,
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self) -> "FaultProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed by stop()
            t = threading.Thread(
                target=supervised(self._handle, name="kccap-proxy-conn"),
                args=(conn,),
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _track(self, sock: socket.socket) -> None:
        with self._conns_lock:
            self._conns.add(sock)

    def _untrack(self, sock: socket.socket) -> None:
        with self._conns_lock:
            self._conns.discard(sock)
        try:
            # shutdown BEFORE close: another proxy thread may be blocked
            # in recv on this socket, and CPython defers the real fd
            # close until that recv returns — without the shutdown no
            # FIN ever reaches the peer and a half-delivered fault
            # becomes an accidental stall instead of a cut link.
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _handle(self, client: socket.socket) -> None:
        if self._stream:
            self._handle_stream(client)
            return
        self._track(client)
        up: socket.socket | None = None
        try:
            while not self._stop.is_set():
                frame = _read_frame(client)
                if frame is None:
                    return
                # Partition check BEFORE the plan decision: swallowed
                # frames must not shift a seeded fault schedule.
                if self._cut("to_server"):
                    continue  # request never crosses; client times out
                fault = self.plan.next_fault()
                if fault == "drop_pre":
                    self.plan.count(fault)
                    return  # close WITHOUT forwarding
                if fault == "stall":
                    self.plan.count(fault)
                    # Interruptible sleep: stop() must not hang on us.
                    self._stop.wait(self._stall_s)
                    # Fall through: forward late (the client has usually
                    # timed out and gone; send errors are swallowed).
                if up is None:
                    try:
                        up = socket.create_connection(self._upstream)
                    except OSError:
                        return  # upstream dead (killed server): drop client
                    self._track(up)
                try:
                    up.sendall(frame)
                except OSError:
                    return
                self.plan.count_forwarded()
                reply = _read_frame(up)
                if reply is None:
                    return  # upstream died; drop the client too
                if self._cut("to_client"):
                    # Asymmetric cut: the server executed, the reply
                    # never arrives — the client cannot distinguish this
                    # from drop_post except that it is runtime-driven.
                    continue
                if fault == "drop_post":
                    self.plan.count(fault)
                    return  # executed upstream, reply withheld
                if fault == "partial":
                    self.plan.count(fault)
                    try:
                        client.sendall(reply[: max(5, len(reply) // 2)])
                    except OSError:
                        pass
                    return
                if fault == "garbage":
                    self.plan.count(fault)
                    try:
                        client.sendall(
                            struct.pack(">I", len(_GARBAGE_BODY))
                            + _GARBAGE_BODY
                        )
                    except OSError:
                        pass
                    return
                try:
                    client.sendall(reply)
                except OSError:
                    return
                if fault == "stall":
                    # Stalled but the client was still there: it got a
                    # late (correct) reply; nothing more to do.
                    continue
        finally:
            self._untrack(client)
            if up is not None:
                self._untrack(up)

    def _handle_stream(self, client: socket.socket) -> None:
        """Stream mode: forward the client's hello intact, then pump
        SERVER frames client-ward with one plan decision each.  Client→
        server frames after the hello (there are none in the plane
        protocol, but EOF matters) are pumped transparently on a side
        thread so a vanished subscriber is noticed upstream."""
        self._track(client)
        up: socket.socket | None = None
        try:
            while True:
                hello = _read_frame(client)
                if hello is None:
                    return
                # A partitioned hello never reaches the upstream: the
                # subscriber observes silence and retries after heal.
                if not self._cut("to_server"):
                    break
            up = socket.create_connection(self._upstream)
            self._track(up)
            up.sendall(hello)
            self.plan.count_forwarded()

            upstream = up  # for the closure below

            def _pump_client_to_up() -> None:
                while not self._stop.is_set():
                    frame = _read_frame(client)
                    if frame is None:
                        # Subscriber went away: propagate the EOF so the
                        # publisher deregisters it.
                        try:
                            upstream.shutdown(socket.SHUT_WR)
                        except OSError:
                            pass
                        return
                    if self._cut("to_server"):
                        continue  # one-way cut: the frame never crosses
                    try:
                        upstream.sendall(frame)
                    except OSError:
                        return

            side = threading.Thread(
                target=supervised(
                    _pump_client_to_up, name="kccap-proxy-pump"
                ),
                daemon=True,
            )
            side.start()
            while not self._stop.is_set():
                frame = _read_frame(up)
                if frame is None:
                    return  # upstream closed; drop the client too
                # Partition check BEFORE the plan decision (same rule as
                # request mode): a cut must not shift the seeded
                # schedule for the frames that flow after heal.
                if self._cut("to_client"):
                    continue  # stream gaps; the digest chain will say so
                fault = self.plan.next_fault()
                if fault == "drop_pre":
                    self.plan.count(fault)
                    continue  # swallow this frame: the stream gaps
                if fault == "stall":
                    self.plan.count(fault)
                    self._stop.wait(self._stall_s)
                if fault == "garbage":
                    self.plan.count(fault)
                    try:
                        client.sendall(
                            struct.pack(">I", len(_GARBAGE_BODY))
                            + _GARBAGE_BODY
                        )
                    except OSError:
                        return
                    continue
                if fault == "partial":
                    self.plan.count(fault)
                    try:
                        client.sendall(frame[: max(5, len(frame) // 2)])
                    except OSError:
                        pass
                    return  # a torn frame desyncs the stream: cut it
                try:
                    client.sendall(frame)
                except OSError:
                    return
                self.plan.count_forwarded()
                if fault == "drop_post":
                    self.plan.count(fault)
                    return  # delivered, then cut
        except OSError:
            return
        finally:
            self._untrack(client)
            if up is not None:
                self._untrack(up)

    # Convenience for assertions ------------------------------------------
    def wait_quiesced(self, timeout_s: float = 5.0) -> None:
        """Best-effort wait for in-flight handler threads to finish."""
        deadline = time.monotonic() + timeout_s
        for t in list(self._threads):
            t.join(timeout=max(0.0, deadline - time.monotonic()))
