"""Replica packing as a certified linear program (LP/PDHG on TPU).

The question is the sweep's — "how many replicas of this shape fit?" —
but answered by *optimization* instead of a first-fit walk, which buys
two things the walk cannot give:

* a **bound**: the LP optimum is an upper bound on ANY packing, so the
  gap between it and the integral packing is a measured distance from
  optimal, not a hope;
* **prices**: the LP's dual variables are per-resource shadow prices —
  "memory is the priced-out resource on 60% of capacity" — the
  principled input to `explain` and admission control.

Formulation (over PR 9's (shape, count) node groups, so a 1M-node
fleet is ~100s of variables; each node of group ``g`` contributes its
clamped headroom, count-weighted)::

    max  Σ_g x_g                              x_g = replicas on group g
    s.t. req_r · x_g  <=  count_g · head_{g,r}   ∀ g, r ∈ {cpu, mem, pods}
         Σ_g x_g      <=  demand                 (the demand row)
         x >= 0

All masks fold in exactly like the grouped sweep kernels: ``node_mask``
and (in strict mode) node health restrict the per-group counts;
reference-mode unhealthy nodes are already zero-capacity phantom rows.
Headrooms are the *sane* clamped int64 view (``max(alloc - used, 0)``)
— the optimizer prices real capacity; where the reference's uint64/Q1
quirks let the bug-compatible walk overshoot this model, the result
says so (``ffd_exceeds_bound``) instead of silently averaging it away.

Solver: a diagonally-preconditioned primal-dual hybrid gradient
(PDHG / Chambolle–Pock — the first-order family CvxCluster/PDLP use)
in pure ``jnp``, one ``lax.fori_loop`` jitted once per (group, scenario)
shape bucket and batched across the whole ``[S]`` scenario axis.  The
iteration is projected gradient steps on the Lagrangian: ascend the
duals on constraint violation, descend the primal on reduced cost —
matmul/elementwise-shaped throughout, nothing host-side in the loop.

Certification is **host-side numpy** and cannot lie:

* the reported primal is repaired to *exact* feasibility (clip to the
  per-group caps, scale into the demand row), so its value is a true
  achievable lower bound;
* the reported ``lp_bound`` is the *dual* objective after lifting the
  demand dual by the worst reduced-cost violation — dual-feasible by
  construction, hence a true upper bound by weak duality *regardless of
  solver state*;
* ``certified`` means the two meet within tolerance.  A solve that
  cannot close the gap reports ``uncertified`` — the bound is still
  valid, only loose — never a silently-wrong answer.

Integral answer: per-group per-node integer caps are exact int64 floor
division, the LP solution is floored and repaired to fill remaining
demand in group order — so the rounded *total* is closed-form
deterministic (audit/replay digests pin it across hosts) while the
per-group split follows the LP.  ``verify_rounded_packing`` re-checks
feasibility against the sequential :func:`~..oracle.fit_arrays_python`
ground truth.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    grouped_for_dispatch,
)

__all__ = [
    "DEFAULT_MAX_ITERS",
    "DEFAULT_TOL",
    "OPT_RESOURCES",
    "OptimizeError",
    "OptimizeResult",
    "lp_bound_oracle",
    "opt_max_iters",
    "opt_tol",
    "optimize_snapshot",
    "verify_rounded_packing",
]

#: Constraint-row order of the LP (and of every per-resource report
#: field).  ``pods`` is the remaining-pod-slot row (request 1 per
#: replica, the strict-mode cap).
OPT_RESOURCES = ("cpu", "memory", "pods")

#: Iteration budget across all chunks (``KCCAP_OPT_ITERS`` overrides).
DEFAULT_MAX_ITERS = 20_000

#: Relative certificate tolerance (``KCCAP_OPT_TOL`` overrides): a
#: solve certifies when duality gap and feasibility residuals are all
#: within this fraction of the answer's scale.
DEFAULT_TOL = 1e-6

#: Iterations per jitted chunk — the certificate is re-checked between
#: chunks so an easy instance exits early and a hard one keeps going.
_CHUNK_ITERS = 500

_MAX_ITERS_CAP = 1 << 20
_EPS = 1e-300


class OptimizeError(ValueError):
    """Malformed optimize request (bad backend, bad knobs)."""


def opt_max_iters() -> int:
    """Process iteration budget (``KCCAP_OPT_ITERS``, else 20000).

    Read per solve (host-side only); junk or out-of-range values fall
    back to the default rather than failing a solve.
    """
    try:
        env = int(os.environ.get("KCCAP_OPT_ITERS", "0"))
    except ValueError:
        env = 0
    return env if _CHUNK_ITERS <= env <= _MAX_ITERS_CAP else DEFAULT_MAX_ITERS


def opt_tol() -> float:
    """Process certificate tolerance (``KCCAP_OPT_TOL``, else 1e-6)."""
    try:
        env = float(os.environ.get("KCCAP_OPT_TOL", "0"))
    except ValueError:
        env = 0.0
    return env if 0.0 < env <= 1e-2 else DEFAULT_TOL


def _pow2_at_least(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


@partial(jax.jit, static_argnames=("iters",))
def _pdhg_chunk(caps, demand, scale, x, lam, mu, *, iters: int):
    """``iters`` preconditioned PDHG steps, batched over scenarios.

    ``caps[S, G, R]`` are the per-group per-resource capacities in
    replica units, ``demand[S]`` the demand row, ``scale[S]`` the
    per-scenario normalization (≈ the LP optimum, so the normalized
    primal is O(1) and step sizes are shape-free).  State: primal
    ``x[S, G]`` (normalized units), duals ``lam[S, G, R]`` / ``mu[S]``
    (unit-free — valid across chunks, so warm restarts compose).
    Pure ``jnp``: one ``fori_loop``, no host work, no telemetry.
    """
    g = x.shape[1]
    r = caps.shape[2]
    caps_n = caps / scale[:, None, None]
    demand_n = demand / scale
    # Diagonal preconditioning: every constraint row touches one x_g
    # (resource rows) or all G (demand row); sigma·tau·row-norms < 1.
    # The dual step runs hot (16x) — the primal converges in a handful
    # of steps from the normalized start, the dual tail dominates.
    sig = 16.0
    tau = 1.0 / ((r + 1.0) * sig)
    sig_d = sig / g

    def body(_, state):
        x, lam, mu, xbar = state
        lam = jnp.maximum(lam + sig * (xbar[:, :, None] - caps_n), 0.0)
        mu = jnp.maximum(mu + sig_d * (jnp.sum(xbar, axis=1) - demand_n), 0.0)
        reduced = jnp.sum(lam, axis=2) + mu[:, None] - 1.0
        x_new = jnp.maximum(x - tau * reduced, 0.0)
        return (x_new, lam, mu, 2.0 * x_new - x)

    x, lam, mu, _ = lax.fori_loop(0, iters, body, (x, lam, mu, x))
    return x, lam, mu


def _certify(caps, demand, x_n, lam, mu, scale, tol):
    """Host-side certificate — numpy f64, never traced, independent of
    whatever the device computed.

    The certificate covers what is REPORTED, not the raw iterate: the
    primal is first repaired to exact feasibility (clip into the
    per-group caps, scale into the demand row), the dual is lifted to
    exact dual feasibility (the demand dual absorbs the worst
    reduced-cost violation).  ``D`` then upper bounds the LP optimum by
    weak duality *regardless of solver state*, ``P`` lower bounds it,
    and ``certified`` means they meet within tolerance.

    Returns ``(x_feas[S, G], P, D, gap, primal_residual,
    dual_residual, mu_lift, certified)``.  ``primal_residual`` is the
    repaired solution's residual (≈ float rounding; part of the
    certificate), ``dual_residual`` the reduced-cost violation the
    lift absorbed (a solver-quality diagnostic — its cost is already
    priced into ``D``, and any repair loss widens ``gap`` itself, so
    nothing is hidden).
    """
    x = np.asarray(x_n, dtype=np.float64) * scale[:, None]
    u = caps.min(axis=2)  # [S, G] per-group box bound
    x_feas = np.clip(x, 0.0, u)
    tot = x_feas.sum(axis=1)
    shrink = np.where(
        tot > demand, demand / np.maximum(tot, _EPS), 1.0
    )
    x_feas = x_feas * shrink[:, None]
    primal = x_feas.sum(axis=1)
    scale1 = 1.0 + np.abs(scale)
    primal_res = (
        np.maximum(
            np.max(
                np.maximum(x_feas[:, :, None] - caps, 0.0),
                axis=(1, 2),
                initial=0.0,
            ),
            np.maximum(x_feas.sum(axis=1) - demand, 0.0),
        )
        / scale1
    )
    lam = np.asarray(lam, dtype=np.float64)
    mu = np.asarray(mu, dtype=np.float64)
    viol = np.maximum(1.0 - lam.sum(axis=2) - mu[:, None], 0.0)
    dual_res = np.max(viol, axis=1, initial=0.0)
    mu_lift = mu + dual_res
    dual = (lam * caps).sum(axis=(1, 2)) + mu_lift * demand
    gap = (dual - primal) / (1.0 + np.abs(dual) + np.abs(primal))
    certified = (gap <= tol) & (primal_res <= tol)
    return x_feas, primal, dual, gap, primal_res, dual_res, mu_lift, certified


def _packing_operands(
    snapshot: ClusterSnapshot, *, mode: str, node_mask=None
):
    """The LP's node-side data: ``(head[G, 3] i64, counts[G] i64,
    grouped | None)``.

    Grouping follows the sweep dispatch gate exactly
    (:func:`~..snapshot.grouped_for_dispatch`, so ``KCCAP_GROUPING=0``
    and the heterogeneity/floor gates behave identically); when the
    gate declines, every node is its own group (``counts`` of 0/1).
    Headrooms are clamped sane capacity — negative or wrapped carriers
    price as zero, never as 2^64 phantom headroom.  Eligibility
    (``node_mask``, strict-mode health) zeroes COUNTS, mirroring
    ``effective_counts``: a masked node contributes no capacity.
    """
    if mode not in ("reference", "strict"):
        raise ValueError(f"unknown mode {mode!r}")
    n = snapshot.n_nodes
    eligible = None
    if node_mask is not None:
        mask = np.asarray(node_mask, dtype=bool)
        if mask.shape != (n,):
            raise ValueError(
                f"node_mask: expected shape ({n},), got {mask.shape}"
            )
        eligible = mask
    if mode == "strict":
        healthy = np.asarray(snapshot.healthy, dtype=bool)
        eligible = healthy if eligible is None else (eligible & healthy)

    grouped = grouped_for_dispatch(snapshot)

    def head_of(alloc, used, pods=False):
        alloc = np.maximum(np.asarray(alloc, dtype=np.int64), 0)
        used = np.maximum(np.asarray(used, dtype=np.int64), 0)
        return np.where(alloc <= used, np.int64(0), alloc - used)

    if grouped is not None:
        head = np.stack(
            [
                head_of(grouped.alloc_cpu_milli, grouped.used_cpu_req_milli),
                head_of(grouped.alloc_mem_bytes, grouped.used_mem_req_bytes),
                head_of(grouped.alloc_pods, grouped.pods_count),
            ],
            axis=1,
        )
        counts = grouped.effective_counts(eligible)
        return head, counts, grouped
    head = np.stack(
        [
            head_of(snapshot.alloc_cpu_milli, snapshot.used_cpu_req_milli),
            head_of(snapshot.alloc_mem_bytes, snapshot.used_mem_req_bytes),
            head_of(snapshot.alloc_pods, snapshot.pods_count),
        ],
        axis=1,
    )
    counts = (
        np.ones(n, dtype=np.int64)
        if eligible is None
        else eligible.astype(np.int64)
    )
    return head, counts, None


def _req_matrix(grid: ScenarioGrid) -> np.ndarray:
    """``[S, 3]`` per-replica request in :data:`OPT_RESOURCES` order
    (pods row: one slot per replica).  A non-positive int64 request is
    a wrapped-uint64 carrier — the sane model cannot pack it, which
    the caps builder prices as zero capacity."""
    s = grid.size
    reqs = np.empty((s, 3), dtype=np.int64)
    reqs[:, 0] = np.asarray(grid.cpu_request_milli, dtype=np.int64)
    reqs[:, 1] = np.asarray(grid.mem_request_bytes, dtype=np.int64)
    reqs[:, 2] = 1
    return reqs


def _integer_caps(head: np.ndarray, reqs: np.ndarray) -> np.ndarray:
    """Per-node integral replica cap per group — ``[S, G]`` int64:
    ``min_r floor(head_gr / req_sr)`` with non-positive requests
    capping at zero (exact integer floor division, no floats)."""
    s, g = reqs.shape[0], head.shape[0]
    k = np.full((s, g), np.iinfo(np.int64).max, dtype=np.int64)
    for r in range(head.shape[1]):
        req = reqs[:, r]
        good = req > 0
        per = np.where(
            good[:, None],
            head[None, :, r] // np.maximum(req, 1)[:, None],
            np.int64(0),
        )
        k = np.minimum(k, per)
    return k


def _float_caps(head, counts, reqs) -> np.ndarray:
    """``caps[S, G, R]`` in f64 replica units: ``count_g·head_gr/req_r``
    (zero where the request is non-positive)."""
    head_f = head.astype(np.float64)
    counts_f = counts.astype(np.float64)
    reqs_f = reqs.astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        caps = counts_f[None, :, None] * head_f[None, :, :] / reqs_f[:, None, :]
    return np.where(reqs_f[:, None, :] > 0, caps, 0.0)


def lp_bound_oracle(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    *,
    mode: str | None = None,
    node_mask=None,
) -> np.ndarray:
    """The LP optimum in closed form — ``[S]`` f64.

    This structured LP's exact optimum is the demand-capped sum of
    per-group box bounds: ``min(demand, Σ_g min_r caps_gr)``.  The
    solver never consults it (it runs the generic primal-dual
    iteration); tests and bench use it as the independent ground truth
    the certificates must agree with.
    """
    mode = mode or snapshot.semantics
    head, counts, _ = _packing_operands(
        snapshot, mode=mode, node_mask=node_mask
    )
    caps = _float_caps(head, counts, _req_matrix(grid))
    u = caps.min(axis=2) if caps.shape[1] else np.zeros((grid.size, 0))
    demand = np.asarray(grid.replicas, dtype=np.int64).astype(np.float64)
    return np.minimum(demand, u.sum(axis=1))


@dataclass
class OptimizeResult:
    """One certified packing solve (numpy arrays, ``[S]`` leading).

    ``lp_bound`` is the *certified dual* upper bound (valid even when
    ``certified`` is False — then it is merely loose); ``rounded`` the
    integral packing after feasibility repair; ``ffd`` the
    bug-compatible first-fit baseline (the production fit path's
    placed count).  ``shadow`` carries the per-scenario dual story.
    """

    mode: str
    demand: np.ndarray  # [S] int64
    lp_bound: np.ndarray  # [S] f64 (certified dual bound)
    primal_value: np.ndarray  # [S] f64 (exact-feasible primal)
    rounded: np.ndarray  # [S] int64
    rounded_alloc: np.ndarray  # [S, G] int64 per-group integral packing
    ffd: np.ndarray  # [S] int64 — first-fit placed count
    ffd_totals: np.ndarray  # [S] int64 — raw fit-path totals
    certified: np.ndarray  # [S] bool
    duality_gap: np.ndarray  # [S] f64 (relative)
    primal_residual: np.ndarray  # [S] f64
    dual_residual: np.ndarray  # [S] f64
    shadow: list  # [S] dicts (shares / priced_out / demand_price)
    iterations: int
    tol: float
    solve_seconds: float
    groups: int
    nodes: int
    grouping_engaged: bool
    verified: np.ndarray | None = None  # [S] bool, when verify ran
    backend: str = "lp"
    group_index: np.ndarray | None = field(default=None, repr=False)

    @property
    def size(self) -> int:
        return int(self.demand.shape[0])

    @property
    def schedulable(self) -> np.ndarray:
        """Integral verdict: does the rounded packing meet demand?"""
        return self.rounded >= self.demand

    @property
    def all_certified(self) -> bool:
        return bool(np.all(self.certified))

    @property
    def gap_pct(self) -> np.ndarray:
        """LP-vs-integral optimality gap, percent of the bound."""
        return (
            (self.lp_bound - self.rounded.astype(np.float64))
            / np.maximum(self.lp_bound, 1.0)
            * 100.0
        )

    @property
    def ffd_exceeds_bound(self) -> np.ndarray:
        """True where the bug-compatible walk overshoots the certified
        bound — only reachable through reference quirks the sane model
        deliberately refuses to price: fits uncapped by pod slots
        (reference applies the slot cap only via the Q1 overwrite) and
        wrapped uint64 carriers read as huge headroom.  In strict mode
        this is always False (the strict walk obeys all three rows)."""
        return self.ffd.astype(np.float64) > self.lp_bound * (1.0 + self.tol)

    def to_wire(self) -> dict:
        return {
            "backend": self.backend,
            "mode": self.mode,
            "scenarios": self.size,
            "demand": self.demand.tolist(),
            "lp_bound": [round(float(v), 6) for v in self.lp_bound],
            "rounded": self.rounded.tolist(),
            "ffd": self.ffd.tolist(),
            "schedulable": [bool(v) for v in self.schedulable],
            "gap_pct": [round(float(v), 4) for v in self.gap_pct],
            "status": [
                "certified" if bool(c) else "uncertified"
                for c in self.certified
            ],
            "certified": self.all_certified,
            "duality_gap": [float(v) for v in self.duality_gap],
            "primal_residual": [float(v) for v in self.primal_residual],
            "dual_residual": [float(v) for v in self.dual_residual],
            "iterations": self.iterations,
            "tol": self.tol,
            "solve_seconds": round(self.solve_seconds, 6),
            "groups": self.groups,
            "nodes": self.nodes,
            "grouping_engaged": self.grouping_engaged,
            "shadow_prices": self.shadow,
            "ffd_exceeds_bound": [bool(v) for v in self.ffd_exceeds_bound],
            **(
                {"verified": [bool(v) for v in self.verified]}
                if self.verified is not None
                else {}
            ),
        }


def _shadow_report(lam, mu_lift, caps, counts, demand, tol) -> list:
    """Per-scenario dual story, wire-shaped.

    ``shares``: fraction of the dual bound's capacity mass priced on
    each resource row; ``priced_out``: count-weighted fraction of
    nodes whose binding (priced) resource is each name — "memory is
    the priced-out resource on 60% of capacity"; ``demand_price``: the
    demand row's dual (1 ⇒ one more replica of demand would not fit
    anyway — capacity-bound 0 ⇒ demand-bound); ``capacity_share``:
    fraction of the whole bound attributed to capacity rows (the
    admission controller's shed-by-shadow-price signal).
    """
    out = []
    counts_f = counts.astype(np.float64)
    total_nodes = counts_f.sum()
    for s in range(lam.shape[0]):
        mass_r = (lam[s] * caps[s]).sum(axis=0)  # [R]
        cap_mass = float(mass_r.sum())
        demand_mass = float(mu_lift[s] * demand[s])
        denom = cap_mass + demand_mass
        shares = {
            name: (float(mass_r[r]) / denom if denom > 0 else 0.0)
            for r, name in enumerate(OPT_RESOURCES)
        }
        row_max = lam[s].max(axis=1)  # [G]
        priced = row_max > tol
        frac = {}
        for r, name in enumerate(OPT_RESOURCES):
            sel = priced & (lam[s].argmax(axis=1) == r)
            frac[name] = (
                float(counts_f[sel].sum() / total_nodes)
                if total_nodes > 0
                else 0.0
            )
        out.append(
            {
                "shares": {k: round(v, 6) for k, v in shares.items()},
                "priced_out": {k: round(v, 6) for k, v in frac.items()},
                "demand_price": round(float(mu_lift[s]), 6),
                "capacity_share": round(
                    cap_mass / denom if denom > 0 else 0.0, 6
                ),
            }
        )
    return out


def _round_with_repair(x_feas, k_caps, counts, demand):
    """LP solution → integral packing — ``[S, G]`` int64.

    Floor the per-group LP mass (never above the group's exact integer
    capacity ``count_g · k_g``), then repair: fill remaining demand in
    ascending group order up to each group's integer capacity.  The
    repair makes the TOTAL closed-form (``min(demand, Σ count·k)``) —
    deterministic across hosts and float paths — while the per-group
    split follows the LP where it can.
    """
    cap_int = counts[None, :] * k_caps  # [S, G] int64
    y = np.minimum(np.floor(x_feas).astype(np.int64), cap_int)
    y = np.maximum(y, 0)
    deficit = np.asarray(demand, dtype=np.int64) - y.sum(axis=1)
    room = cap_int - y
    # Vectorized in-order fill: give group g min(room_g, deficit left
    # after groups < g) — a running-prefix formulation of the greedy.
    take_prefix = np.cumsum(room, axis=1)
    before = take_prefix - room
    add = np.clip(deficit[:, None] - before, 0, room)
    return y + add


def verify_rounded_packing(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    result: "OptimizeResult",
    *,
    node_mask=None,
) -> np.ndarray:
    """Re-check the integral packing against the sequential oracle —
    ``[S]`` bool.

    For every scenario: distribute each group's replicas over its
    member nodes as evenly as possible and require each node's share
    to fit within :func:`~..oracle.fit_arrays_python`'s strict
    per-node capacity (phantom/unhealthy/masked rows must carry 0).
    Walks group *representatives*, so the check is O(G) oracle rows,
    not O(N).
    """
    from kubernetesclustercapacity_tpu.oracle import fit_arrays_python

    head, counts, grouped = _packing_operands(
        snapshot, mode=result.mode, node_mask=node_mask
    )
    if grouped is not None:
        reps = grouped.representative
        alloc_cpu = snapshot.alloc_cpu_milli[reps]
        alloc_mem = snapshot.alloc_mem_bytes[reps]
        alloc_pods = snapshot.alloc_pods[reps]
        used_cpu = snapshot.used_cpu_req_milli[reps]
        used_mem = snapshot.used_mem_req_bytes[reps]
        pods_count = snapshot.pods_count[reps]
        healthy = snapshot.healthy[reps]
    else:
        alloc_cpu = snapshot.alloc_cpu_milli
        alloc_mem = snapshot.alloc_mem_bytes
        alloc_pods = snapshot.alloc_pods
        used_cpu = snapshot.used_cpu_req_milli
        used_mem = snapshot.used_mem_req_bytes
        pods_count = snapshot.pods_count
        healthy = snapshot.healthy
    reqs = _req_matrix(grid)
    ok = np.ones(result.size, dtype=bool)
    for s in range(result.size):
        if reqs[s, 0] <= 0 or reqs[s, 1] <= 0:
            # Wrapped carrier: the sane model packs nothing; feasible
            # iff the rounding agreed.
            ok[s] = bool((result.rounded_alloc[s] == 0).all())
            continue
        oracle = np.asarray(
            fit_arrays_python(
                alloc_cpu,
                alloc_mem,
                alloc_pods,
                used_cpu,
                used_mem,
                pods_count,
                int(reqs[s, 0]),
                int(reqs[s, 1]),
                mode="strict",
                healthy=healthy,
            ),
            dtype=np.int64,
        )
        alloc = result.rounded_alloc[s]
        used_any = alloc > 0
        # Even split over count_g members: the largest per-node share.
        share = np.zeros_like(alloc)
        nz = counts > 0
        share[nz] = -(-alloc[nz] // counts[nz])  # ceil div
        if (alloc[~nz] != 0).any():
            ok[s] = False
            continue
        ok[s] = bool(np.all(~used_any | (share <= oracle)))
    return ok


# --- telemetry funnel (host-side, registered lazily, switchable) -------
_OPT_MET: dict | None = None
_opt_met_lock = threading.Lock()


def _opt_metrics() -> dict:
    global _OPT_MET
    if _OPT_MET is None:
        with _opt_met_lock:
            if _OPT_MET is None:
                from kubernetesclustercapacity_tpu.telemetry.metrics import (
                    REGISTRY,
                )

                _OPT_MET = {
                    "iterations": REGISTRY.gauge(
                        "kccap_opt_iterations",
                        "PDHG iterations the last optimize solve ran.",
                    ),
                    "gap": REGISTRY.gauge(
                        "kccap_opt_duality_gap",
                        "Worst relative duality gap of the last "
                        "optimize solve.",
                    ),
                    "seconds": REGISTRY.histogram(
                        "kccap_opt_solve_seconds",
                        "End-to-end optimize solve latency "
                        "(formulation + iterations + certification).",
                    ),
                    "certified": REGISTRY.counter(
                        "kccap_opt_certified_total",
                        "Optimize solves by certificate outcome.",
                        ("status",),
                    ),
                }
    return _OPT_MET


def _publish_opt_metrics(result: "OptimizeResult") -> None:
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    if not _telemetry_enabled():
        return
    try:
        met = _opt_metrics()
        met["iterations"].set(result.iterations)
        met["gap"].set(float(np.max(result.duality_gap, initial=0.0)))
        met["seconds"].observe(result.solve_seconds)
        met["certified"].labels(
            status="certified" if result.all_certified else "uncertified"
        ).inc()
    except Exception:  # noqa: BLE001 - observability never fails a solve
        pass


def optimize_snapshot(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    *,
    mode: str | None = None,
    node_mask=None,
    max_iters: int | None = None,
    tol: float | None = None,
    verify: bool = True,
) -> OptimizeResult:
    """Solve the packing LP for every grid scenario, certified.

    One warm-started chunked PDHG run (the jitted iteration compiles
    once per padded (group, scenario) shape bucket and is reused across
    solves); the certificate is re-checked host-side between chunks so
    the solver stops as soon as every scenario certifies.  The FFD
    baseline rides the production fit path (:func:`~..ops.fit.
    sweep_snapshot` — devcache, bucket ladder, grouped kernels), so the
    comparison is against what the service actually serves.
    """
    mode = mode or snapshot.semantics
    grid.validate()
    max_iters = opt_max_iters() if max_iters is None else int(max_iters)
    if not 1 <= max_iters <= _MAX_ITERS_CAP:
        raise OptimizeError(
            f"max_iters must be in [1, {_MAX_ITERS_CAP}], got {max_iters}"
        )
    tol = opt_tol() if tol is None else float(tol)
    if not 0.0 < tol <= 1e-2:
        raise OptimizeError(f"tol must be in (0, 1e-2], got {tol}")

    t0 = time.perf_counter()
    head, counts, grouped = _packing_operands(
        snapshot, mode=mode, node_mask=node_mask
    )
    reqs = _req_matrix(grid)
    demand = np.asarray(grid.replicas, dtype=np.int64)
    demand_f = np.maximum(demand, 0).astype(np.float64)
    s, g = grid.size, head.shape[0]

    caps = _float_caps(head, counts, reqs)  # [S, G, R]
    u = caps.min(axis=2) if g else np.zeros((s, 0))
    scale = np.maximum(1.0, np.minimum(demand_f, u.sum(axis=1)))

    # Shape-bucketed solve: pad groups and scenarios up a pow2 ladder
    # (zero-capacity groups and zero-demand probe scenarios are inert)
    # so ±1 group or scenario reuses the compiled iteration.
    gb = _pow2_at_least(max(g, 1), 8)
    sb = _pow2_at_least(max(s, 1), 8)
    caps_p = np.zeros((sb, gb, len(OPT_RESOURCES)), dtype=np.float64)
    caps_p[:s, :g] = caps
    demand_p = np.zeros(sb, dtype=np.float64)
    demand_p[:s] = demand_f
    scale_p = np.ones(sb, dtype=np.float64)
    scale_p[:s] = scale

    caps_j = jnp.asarray(caps_p)
    demand_j = jnp.asarray(demand_p)
    scale_j = jnp.asarray(scale_p)
    x = jnp.zeros((sb, gb), dtype=jnp.float64)
    lam = jnp.zeros((sb, gb, len(OPT_RESOURCES)), dtype=jnp.float64)
    mu = jnp.zeros(sb, dtype=jnp.float64)

    iterations = 0
    cert = None
    t_solve = time.perf_counter()
    while iterations < max_iters:
        chunk = min(_CHUNK_ITERS, max_iters - iterations)
        x, lam, mu = _pdhg_chunk(
            caps_j, demand_j, scale_j, x, lam, mu, iters=chunk
        )
        iterations += chunk
        cert = _certify(
            caps,
            demand_f,
            np.asarray(x)[:s, :g],
            np.asarray(lam)[:s, :g],
            np.asarray(mu)[:s],
            scale,
            tol,
        )
        if bool(np.all(cert[7])):
            break
    solve_s = time.perf_counter() - t_solve
    (
        x_feas,
        primal,
        dual,
        gap,
        primal_res,
        dual_res,
        mu_lift,
        certified,
    ) = cert

    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    if _telemetry_enabled():
        from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
            observe_dispatch,
        )

        observe_dispatch(f"opt_pdhg@g{gb}s{sb}", solve_s)

    # Integral rounding + repair (exact int64 throughout).
    k_caps = _integer_caps(head, reqs)  # [S, G]
    rounded_alloc = _round_with_repair(x_feas, k_caps, counts, demand)
    rounded = rounded_alloc.sum(axis=1)

    # The bug-compatible baseline: the production fit path's totals,
    # capped into a placed count (a packer cannot place a negative or
    # beyond-demand fit).
    from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot

    ffd_totals, _ = sweep_snapshot(
        snapshot, grid, mode=mode, node_mask=node_mask
    )[:2]
    ffd_totals = np.asarray(ffd_totals, dtype=np.int64)
    ffd = np.clip(ffd_totals, 0, demand)

    lam_h = np.asarray(lam)[:s, :g]
    result = OptimizeResult(
        mode=mode,
        demand=demand,
        lp_bound=dual,
        primal_value=primal,
        rounded=rounded,
        rounded_alloc=rounded_alloc,
        ffd=ffd,
        ffd_totals=ffd_totals,
        certified=certified,
        duality_gap=gap,
        primal_residual=primal_res,
        dual_residual=dual_res,
        shadow=_shadow_report(lam_h, mu_lift, caps, counts, demand_f, tol),
        iterations=iterations,
        tol=tol,
        solve_seconds=time.perf_counter() - t0,
        groups=g,
        nodes=snapshot.n_nodes,
        grouping_engaged=grouped is not None,
        group_index=None if grouped is None else grouped.group_index,
    )
    if verify:
        result.verified = verify_rounded_packing(
            snapshot, grid, result, node_mask=node_mask
        )
    _publish_opt_metrics(result)
    return result
