"""Optimization-based packing backend (ROADMAP item 3).

The first-fit packer answers "how many fit" by walking nodes; it can
neither bound its distance from optimal nor *price* capacity.  This
package formulates replica placement as a linear program over the
(shape, count) node groups (PR 9) and solves it with a jit-compiled,
scenario-batched primal-dual iteration (:mod:`.lp`), emitting a
**duality certificate** (a solve that cannot certify says
``uncertified``, never a silently-wrong bound) and per-resource
**shadow prices** for every answer.
"""

from kubernetesclustercapacity_tpu.optimize.lp import (
    DEFAULT_MAX_ITERS,
    DEFAULT_TOL,
    OPT_RESOURCES,
    OptimizeError,
    OptimizeResult,
    lp_bound_oracle,
    opt_max_iters,
    opt_tol,
    optimize_snapshot,
    verify_rounded_packing,
)

__all__ = [
    "DEFAULT_MAX_ITERS",
    "DEFAULT_TOL",
    "OPT_RESOURCES",
    "OptimizeError",
    "OptimizeResult",
    "lp_bound_oracle",
    "opt_max_iters",
    "opt_tol",
    "optimize_snapshot",
    "verify_rounded_packing",
]
