"""kubernetesclustercapacity_tpu — a TPU-native cluster-capacity simulation framework.

A brand-new JAX/XLA framework with the capabilities of the reference Go CLI
``AshutoshNirkhe/KubernetesClusterCapacity`` (see ``SURVEY.md``): given a pod
spec (CPU/memory requests + limits) and a replica count, compute how many
replicas a Kubernetes cluster can still schedule.  Instead of the reference's
sequential per-node loop against a live apiserver
(``src/KubeAPI/ClusterCapacity.go:105-140``), this framework snapshots cluster
state once into dense ``(nodes, resources)`` arrays and evaluates thousands of
what-if ``(cpuRequests, memRequests, replicas)`` scenarios in parallel as a
vectorized bin-packing kernel, sharded over a TPU device mesh.

Layer map (TPU-first redesign of SURVEY.md §1):

===========  ====================================================================
Layer        Module
===========  ====================================================================
L4 CLI       :mod:`kubernetesclustercapacity_tpu.cli` (6 reference flags + TPU flags)
L3 codecs    :mod:`kubernetesclustercapacity_tpu.utils.quantity`
L2 snapshot  :mod:`kubernetesclustercapacity_tpu.snapshot` (dense arrays; fixture /
             synthetic / live constructors — 2 paginated Lists, not N+1)
L1 kernel    :mod:`kubernetesclustercapacity_tpu.ops.fit` (vmap/jit fit kernel),
             :mod:`kubernetesclustercapacity_tpu.parallel` (Mesh + shard_map + psum)
L0 report    :mod:`kubernetesclustercapacity_tpu.report` (verdict + structured output)
oracle       :mod:`kubernetesclustercapacity_tpu.oracle` (bug-for-bug reference
             semantics — the bit-exactness gate)
===========  ====================================================================

Integer exactness: replica counts are 64-bit integer math (Go ``uint64``/
``int64`` in the reference).  JAX's x64 mode is enabled at import so int64
survives tracing; on TPU, XLA lowers int64 to 32-bit pairs — the optional
Pallas fast path (:mod:`.ops.pallas_fit`) avoids that via exactness-checked
KiB rescaling to int32.
"""

import os as _os

import jax as _jax

# Must happen before any jnp array is created anywhere in the framework:
# without x64, jnp silently downcasts int64 -> int32 and memory-bytes
# arithmetic (node memory ~2^34) overflows, breaking bit-exactness.
_jax.config.update("jax_enable_x64", True)

# Restore standard JAX env semantics: an explicit JAX_PLATFORMS (e.g. cpu
# for hosts without an accelerator) must win even where a TPU-plugin
# sitecustomize re-pins jax_platforms at interpreter startup.
if _os.environ.get("JAX_PLATFORMS"):
    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except RuntimeError:  # pragma: no cover - backends already initialized
        pass

# Keep in lockstep with pyproject.toml's [project] version.
__version__ = "0.4.0"

from kubernetesclustercapacity_tpu.utils import quantity  # noqa: E402,F401
from kubernetesclustercapacity_tpu.snapshot import (  # noqa: E402,F401
    ClusterSnapshot,
    load_snapshot,
    snapshot_from_fixture,
    synthetic_snapshot,
)
from kubernetesclustercapacity_tpu.scenario import (  # noqa: E402,F401
    MultiResourceGrid,
    Scenario,
    ScenarioGrid,
    random_scenario_grid,
    scenario_from_flags,
)
from kubernetesclustercapacity_tpu.ops.fit import (  # noqa: E402,F401
    fit_per_node,
    fit_totals,
    sweep_grid,
    sweep_snapshot,
)
from kubernetesclustercapacity_tpu.ops.preemption import (  # noqa: E402,F401
    PriorityTable,
    build_priority_table,
    fit_with_preemption,
)
from kubernetesclustercapacity_tpu.store import ClusterStore  # noqa: E402,F401
from kubernetesclustercapacity_tpu.follower import ClusterFollower  # noqa: E402,F401
from kubernetesclustercapacity_tpu.explain import (  # noqa: E402,F401
    ExplainResult,
    explain_snapshot,
)
from kubernetesclustercapacity_tpu.timeline import (  # noqa: E402,F401
    CapacityTimeline,
    load_watchlist,
)
from kubernetesclustercapacity_tpu.stochastic import (  # noqa: E402,F401
    CaRResult,
    StochasticSpec,
    UsageDistribution,
    capacity_at_risk,
    extract_usage_history,
    load_stochastic_spec,
)
