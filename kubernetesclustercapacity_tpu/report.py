"""Verdict reporting (L0): reference-parity text and structured output.

The reference's entire observability story is ``fmt.Printf`` to stdout
(SURVEY.md §5).  This module reproduces that text byte-for-byte — including
the typos ("allocatbale", "scehdule") and Go's float rendering of NaN/±Inf —
so transcript-level parity can be asserted, and adds what the reference
lacks: structured JSON and a compact table for humans.

All formatting is host-side numpy/python; percentages are display-only in the
reference too (``ClusterCapacity.go:113-117`` — they never influence the
fit).
"""

from __future__ import annotations

import json
import math

import numpy as np

from kubernetesclustercapacity_tpu.scenario import Scenario
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot

__all__ = [
    "reference_report",
    "json_report",
    "table_report",
    "explain_table_report",
    "explain_json_report",
    "timeline_table_report",
    "timeline_json_report",
    "slo_table_report",
    "slo_json_report",
    "dump_table_report",
    "dump_json_report",
    "car_table_report",
    "car_json_report",
    "car_status_table_report",
    "car_status_json_report",
    "gang_table_report",
    "gang_json_report",
    "gang_status_table_report",
    "gang_status_json_report",
    "fed_status_table_report",
    "fed_status_json_report",
    "fed_sweep_table_report",
    "fed_sweep_json_report",
    "forecast_table_report",
    "forecast_json_report",
    "forecast_status_table_report",
    "forecast_status_json_report",
    "plan_table_report",
    "plan_json_report",
    "trace_table_report",
    "trace_json_report",
]

_RULE = "=" * 110  # the reference prints 110 '=' (ClusterCapacity.go:142,149)


def _go_float(x: float) -> str:
    """Render a float the way Go ``%.2f`` does (NaN, ±Inf spellings)."""
    if math.isnan(x):
        return "NaN"
    if math.isinf(x):
        return "+Inf" if x > 0 else "-Inf"
    return f"{x:.2f}"


def _go_percent(num: int, den: int) -> float:
    """Go float64 division semantics: x/0 → ±Inf, 0/0 → NaN."""
    if den == 0:
        if num == 0:
            return math.nan
        return math.inf if num > 0 else -math.inf
    return float(num) * 100 / float(den)


def _u64(v: int) -> int:
    """The unsigned view of an int64 bit pattern.

    Go keeps allocatable CPU and the CPU request/limit sums in uint64
    (``ClusterCapacity.go:41-46,255-258``) and prints/divides them as such;
    the snapshot arrays carry the same bits in int64, so wrapped sums
    (>= 2^63) must be reinterpreted before rendering.  Memory is int64 in
    Go too — it stays signed.
    """
    return v & ((1 << 64) - 1) if v < 0 else v


_CPU_CODEC_ERR = "\nError converting string to int for %s\n"


def reference_report(
    snapshot: ClusterSnapshot,
    fits: np.ndarray,
    scenario: Scenario,
    *,
    include_preamble: bool = True,
) -> str:
    """The reference's stdout transcript, reconstructed from arrays.

    Mirrors ``main``'s prints in order: the flag-codec error lines
    (``:64-65`` → ``:316``), the parsed-input line (``:85``), the node
    count (``:174``) followed by getHealthyNodes' codec-error/skip lines
    (``:215,316``), per-node blocks (``:107-137``) each preceded by its
    pods' codec-error lines (``:279-284``), and the final verdict
    (``:142-149``).  The per-node struct print matches Go's ``%v`` of the
    ``node`` struct: ``{name cpu mem pods}``.  CPU quantities render as
    uint64 (see :func:`_u64`).
    """
    out = []
    pod_errs = snapshot.pod_cpu_errs
    if include_preamble:
        for payload in getattr(scenario, "input_cpu_error_payloads", ()):
            out.append(_CPU_CODEC_ERR % payload)
        out.append(
            "\nCPU limits, requests, Memory limits, requests and replicas "
            f"parsed from input : {_u64(scenario.cpu_limit_milli)} "
            f"{_u64(scenario.cpu_request_milli)} {scenario.mem_limit_bytes} "
            f"{scenario.mem_request_bytes} {scenario.replicas}\n"
        )
        out.append(
            f"\nThere are total {snapshot.n_nodes} nodes in the cluster\n\n"
        )
        for kind, payload in snapshot.node_log:
            if kind == "cpu_err":
                out.append(_CPU_CODEC_ERR % payload)
            else:  # "skip" — Go prints the REAL name of the phantom row
                out.append(f"Skipping node {payload} as it is not healthy\n")

    total = 0
    for i in range(snapshot.n_nodes):
        name = snapshot.names[i]
        alloc_cpu = _u64(int(snapshot.alloc_cpu_milli[i]))
        alloc_mem = int(snapshot.alloc_mem_bytes[i])
        cpu_lim = _u64(int(snapshot.used_cpu_lim_milli[i]))
        cpu_req = _u64(int(snapshot.used_cpu_req_milli[i]))
        mem_lim = int(snapshot.used_mem_lim_bytes[i])
        mem_req = int(snapshot.used_mem_req_bytes[i])
        if i < len(pod_errs):  # the pod walk's codec errors print first
            for payload in pod_errs[i]:
                out.append(_CPU_CODEC_ERR % payload)
        out.append(
            f"\n{{{name} {alloc_cpu} {alloc_mem} "
            f"{int(snapshot.alloc_pods[i])}}} - "
            f"Current non-terminated pods : {int(snapshot.pods_count[i])}"
        )
        out.append(
            "\nSum of CPU Limits, Requests and Memory Limits, Requests for "
            f"all pods : {cpu_lim} {cpu_req} {mem_lim} {mem_req}"
        )
        out.append(
            f"\nTotal allocatbale CPU and Memory : {alloc_cpu}, {alloc_mem}"
        )
        out.append(
            "\nCPU Limits, Requests and Memory Limits, Requests used "
            "percentage till now : "
            f"{_go_float(_go_percent(cpu_lim, alloc_cpu))} "
            f"{_go_float(_go_percent(cpu_req, alloc_cpu))} "
            f"{_go_float(_go_percent(mem_lim, alloc_mem))} "
            f"{_go_float(_go_percent(mem_req, alloc_mem))}"
        )
        out.append(f"\nMax replicas : {int(fits[i])}\n")
        total += int(fits[i])

    out.append(_RULE + "\n")
    out.append(
        "\n\t Total possible replicas for the pod with required input specs "
        f": {total}"
    )
    if total >= scenario.replicas:
        out.append(
            f"\n\t So you can go ahead with deployment of {scenario.replicas} "
            "pod replicas in the Kubernetes cluster!!\n\n"
        )
    else:
        out.append(
            f"\n\t Unfortunately Kubernetes cluster can't scehdule "
            f"{scenario.replicas} replicas. Please try again by reducing the "
            "number of replicas or/and cpu/memory resource requests. "
            "Exiting!!\n\n"
        )
    out.append(_RULE + "\n")
    return "".join(out)


def json_report(
    snapshot: ClusterSnapshot, fits: np.ndarray, scenario: Scenario
) -> str:
    """Structured output: the same quantities the reference prints, as JSON."""
    total = int(np.sum(fits))
    nodes = []
    for i in range(snapshot.n_nodes):
        # CPU fields are uint64 in Go (see _u64); memory is int64.
        alloc_cpu = _u64(int(snapshot.alloc_cpu_milli[i]))
        alloc_mem = int(snapshot.alloc_mem_bytes[i])
        cpu_req = _u64(int(snapshot.used_cpu_req_milli[i]))
        mem_req = int(snapshot.used_mem_req_bytes[i])
        nodes.append(
            {
                "name": snapshot.names[i],
                "healthy": bool(snapshot.healthy[i]),
                "allocatable": {
                    "cpu_milli": alloc_cpu,
                    "memory_bytes": alloc_mem,
                    "pods": int(snapshot.alloc_pods[i]),
                },
                "used_requests": {
                    "cpu_milli": cpu_req,
                    "memory_bytes": mem_req,
                },
                "used_limits": {
                    "cpu_milli": _u64(int(snapshot.used_cpu_lim_milli[i])),
                    "memory_bytes": int(snapshot.used_mem_lim_bytes[i]),
                },
                "pods_count": int(snapshot.pods_count[i]),
                "utilization_pct": {
                    "cpu_requests": _nan_to_none(
                        _go_percent(cpu_req, alloc_cpu)
                    ),
                    "memory_requests": _nan_to_none(
                        _go_percent(mem_req, alloc_mem)
                    ),
                },
                "max_replicas": int(fits[i]),
            }
        )
    return json.dumps(
        {
            "scenario": {
                "cpu_request_milli": scenario.cpu_request_milli,
                "cpu_limit_milli": scenario.cpu_limit_milli,
                "mem_request_bytes": scenario.mem_request_bytes,
                "mem_limit_bytes": scenario.mem_limit_bytes,
                "replicas": scenario.replicas,
            },
            "nodes": nodes,
            "total_possible_replicas": total,
            "schedulable": total >= scenario.replicas,
        },
        indent=2,
    )


def _nan_to_none(x: float):
    if math.isnan(x) or math.isinf(x):
        return None
    return round(x, 2)


def _marginal_line(resource: str, m: dict | None) -> str:
    """One human line per resource of the marginal analysis."""
    if m is None:
        return f"  {resource:<8} no single-node increment yields +1"
    unit = {"milli": "m", "bytes": "B", "slots": " pod slot(s)"}.get(
        m["unit"], m["unit"]
    )
    return (
        f"  {resource:<8} +{m['delta']}{unit} on {m['node'] or '<phantom>'}"
        " -> +1 replica"
    )


def explain_table_report(result, s: int = 0) -> str:
    """Bottleneck attribution as a compact table + marginal summary.

    ``result`` is an :class:`~..explain.ExplainResult`; ``s`` selects the
    scenario.  The reference transcript is untouched by design — this is
    a NEW view (the reference's percentages never influence the fit,
    ``ClusterCapacity.go:113-117``); the summary block names the binding
    constraint per node, the binding histogram, and the smallest
    single-node capacity increment that buys one more replica.
    """
    snapshot = result.snapshot
    fits = result.fits[s]
    names = result.binding_names(s)
    header = (
        f"{'NODE':<24} {'HEALTHY':<8} {'BINDING':<10} {'FIT':>7} "
        f"{'CPU_FIT':>9} {'MEM_FIT':>9} {'POD_SLOTS':>10}"
    )
    lines = [header, "-" * len(header)]
    for i in range(snapshot.n_nodes):
        lines.append(
            f"{snapshot.names[i] or '<phantom>':<24} "
            f"{'yes' if snapshot.healthy[i] else 'NO':<8} "
            f"{names[i]:<10} "
            f"{int(fits[i]):>7} "
            f"{int(result.cpu_fit[s][i]):>9} "
            f"{int(result.mem_fit[s][i]):>9} "
            f"{int(result.slots[s][i]):>10}"
        )
    lines.append("-" * len(header))
    counts = result.binding_counts(s)
    lines.append(
        "binding: "
        + "  ".join(f"{k}={v}" for k, v in counts.items() if v)
    )
    total = int(np.sum(fits))
    replicas = int(result.replicas[s])
    verdict = "SCHEDULABLE" if total >= replicas else "NOT SCHEDULABLE"
    lines.append(
        f"total possible replicas: {total}   requested: {replicas}   "
        f"verdict: {verdict}"
    )
    lines.append("marginal (+1 replica):")
    for resource, m in result.marginal(s).items():
        lines.append(_marginal_line(resource, m))
    return "\n".join(lines)


def explain_json_report(result, s: int = 0) -> str:
    """The same explanation as structured JSON (machine surface)."""
    snapshot = result.snapshot
    fits = result.fits[s]
    names = result.binding_names(s)
    total = int(np.sum(fits))
    nodes = [
        {
            "name": snapshot.names[i],
            "healthy": bool(snapshot.healthy[i]),
            "binding": names[i],
            "fit": int(fits[i]),
            "cpu_fit": int(result.cpu_fit[s][i]),
            "mem_fit": int(result.mem_fit[s][i]),
            "pod_slots": int(result.slots[s][i]),
        }
        for i in range(snapshot.n_nodes)
    ]
    return json.dumps(
        {
            "mode": result.mode,
            "scenario": {
                "cpu_request_milli": int(result.cpu_request_milli[s]),
                "mem_request_bytes": int(result.mem_request_bytes[s]),
                "replicas": int(result.replicas[s]),
            },
            "nodes": nodes,
            "binding_counts": result.binding_counts(s),
            "marginal": result.marginal(s),
            "saturation": result.saturation(s),
            "total_possible_replicas": total,
            "schedulable": total >= int(result.replicas[s]),
        },
        indent=2,
    )


def timeline_table_report(timeline: dict) -> str:
    """The ``timeline`` op's response as operator-readable text.

    Three blocks: per-generation watch capacities (one row per
    generation, one column per watch — the drift at a glance), the
    attributed deltas (the "what changed and why" one-liners the diff
    engine + binding-shift analysis produce), and current alert states.
    """
    if not timeline.get("enabled", False):
        return "timeline: not enabled on this server (-watch/-timeline-depth)"
    watches = [w["name"] for w in timeline.get("watchlist", [])]
    lines = [
        f"capacity timeline: {timeline['count']} generation(s) held "
        f"(depth {timeline['depth']}), serving generation "
        f"{timeline['generation']}"
    ]
    records = timeline.get("records", [])
    if records:
        header = f"{'GEN':>5} {'NODES':>7} {'HEALTHY':>8} {'DIGEST':<18}"
        for w in watches:
            header += f" {w[:14]:>14}"
        lines += ["", header, "-" * len(header)]
        for rec in records:
            row = (
                f"{rec['generation']:>5} {rec['nodes']:>7} "
                f"{rec['healthy_nodes']:>8} {rec['digest']:<18}"
            )
            for w in watches:
                wr = rec["watches"].get(w)
                cell = "-" if wr is None else (
                    f"{wr['total']}{'!' if wr['breached'] else ''}"
                )
                row += f" {cell:>14}"
            lines.append(row)
        lines.append("-" * len(header))
        if any(
            r["watches"].get(w, {}).get("breached")
            for r in records
            for w in watches
        ):
            lines.append("('!' = below the watch's min_replicas)")
    deltas = timeline.get("deltas", [])
    if deltas:
        lines += ["", "deltas:"]
        for d in deltas:
            lines.append(
                f"  gen {d['from_generation']}→{d['to_generation']}: "
                f"+{len(d['nodes_added'])} node(s), "
                f"-{len(d['nodes_removed'])}, "
                f"{d['nodes_changed']} changed"
            )
            for w in sorted(d.get("watches", {})):
                lines.append(f"    {d['watches'][w]['summary']}")
    alerts = timeline.get("alerts", {})
    if alerts:
        lines += ["", "alerts:"]
        for name in sorted(alerts):
            a = alerts[name]
            line = f"  {name:<24} {a['state']}"
            if a["min_replicas"] is not None:
                line += (
                    f"  (min_replicas={a['min_replicas']}, "
                    f"last={a['last_total']}, breaches={a['breaches']})"
                )
            lines.append(line)
    if records:
        fc_rows = [
            (w, wr)
            for w, wr in sorted(records[-1].get("watches", {}).items())
            if wr is not None and wr.get("horizon_s") is not None
        ]
        if fc_rows:
            lines += ["", "forecast (latest generation):"]
            for w, wr in fc_rows:
                hmin = wr.get("horizon_min_capacity")
                line = (
                    f"  {w:<24} horizon {wr['horizon_s']:g}s  "
                    f"min {'-' if hmin is None else hmin}  "
                    f"ttb {_ttb_cell(wr.get('time_to_breach_s'))}"
                )
                if wr.get("degraded_time_axis"):
                    line += "  [degraded time axis]"
                lines.append(line)
    return "\n".join(lines)


def timeline_json_report(timeline: dict) -> str:
    """The ``timeline`` op's response, pretty-printed (machine surface —
    the wire shape verbatim, so scripts parse one schema)."""
    return json.dumps(timeline, indent=2)


def _burn_cell(v) -> str:
    """One burn-rate cell: '-' before two samples exist, else 'N.NNx'."""
    return "-" if v is None else f"{v:.2f}x"


def slo_table_report(status: dict) -> str:
    """The ``slo`` op's response as operator-readable text: one row per
    objective (state, short/long-window burn vs the fast-burn
    threshold), then the one-line verdict a pager would carry."""
    if not status.get("enabled", False):
        return "slo: not enabled on this server (-slo FILE)"
    header = (
        f"{'SLO':<20} {'OBJECTIVE':<26} {'OP':<8} {'STATE':<10} "
        f"{'BURN(short)':>12} {'BURN(long)':>11} {'THRESH':>7}"
    )
    lines = [header, "-" * len(header)]
    for name in sorted(status.get("status", {})):
        s = status["status"][name]
        lines.append(
            f"{name:<20} {s['objective']:<26} {s['op'] or '*':<8} "
            f"{s['state']:<10} "
            f"{_burn_cell(s['short_burn']):>12} "
            f"{_burn_cell(s['long_burn']):>11} "
            f"{s['fast_burn']:>6.1f}x"
        )
    lines.append("-" * len(header))
    breached = [
        n for n, s in status.get("status", {}).items()
        if s.get("state") == "breached"
    ]
    if breached:
        lines.append(
            "verdict: FAST BURN — error budget burning on "
            + ", ".join(sorted(breached))
        )
    else:
        lines.append(
            "verdict: ok — every objective within its error budget "
            f"({status.get('evaluations', 0)} evaluation(s))"
        )
    return "\n".join(lines)


def slo_json_report(status: dict) -> str:
    """``kccap -slo-status -output json``: the wire shape verbatim."""
    return json.dumps(status, indent=2, sort_keys=True)


def _phases_cell(phases: dict | None) -> str:
    """A record's per-phase breakdown as ``phase=ms`` pairs, largest
    first — the part that makes a pasted slow request self-explaining."""
    if not phases:
        return ""
    parts = sorted(phases.items(), key=lambda kv: (-kv[1], kv[0]))
    return " ".join(f"{k}={v:g}ms" for k, v in parts)


def dump_table_report(dump: dict) -> str:
    """The ``dump`` op's response as operator-readable text: one line
    per flight record (latency + status), each followed by its phase
    decomposition when the record carries one."""
    records = dump.get("records", [])
    lines = [
        f"flight recorder: {dump.get('count', len(records))} record(s) "
        f"(capacity {dump.get('capacity')}, dropped {dump.get('dropped')}), "
        f"serving generation {dump.get('generation')}"
    ]
    for r in records:
        line = (
            f"  #{r.get('seq'):<6} {r.get('op'):<16} "
            f"gen={r.get('generation'):<5} "
            f"{r.get('latency_ms'):>9}ms  {r.get('status')}"
        )
        if r.get("error"):
            line += f"  [{r['error']}]"
        lines.append(line)
        phases = _phases_cell(r.get("phases"))
        if phases:
            lines.append(f"          phases: {phases}")
    return "\n".join(lines)


def dump_json_report(dump: dict) -> str:
    """``kccap -dump -output json``: the wire shape verbatim."""
    return json.dumps(dump, indent=2, sort_keys=True)


def car_table_report(car: dict) -> str:
    """One capacity-at-risk evaluation (the ``car`` op's wire shape /
    ``CaRResult.to_wire()``) as operator-readable text: the quantile
    ladder with per-quantile binding attribution, then the
    probability-of-fit verdict a deployment gate would script on."""
    lines = [
        f"capacity at risk ({car.get('mode')} semantics, "
        f"{car.get('samples')} samples, seed {car.get('seed')})"
    ]
    binding = car.get("binding", {})
    header = f"{'QUANTILE':<10} {'CAPACITY':>10}  BINDING"
    lines += [header, "-" * len(header)]
    for label in sorted(
        car.get("quantiles", {}),
        key=lambda p: float(p[1:]),
    ):
        counts = binding.get(label, {})
        bind = "  ".join(
            f"{k}={v}" for k, v in counts.items() if v
        )
        lines.append(
            f"{label:<10} {car['quantiles'][label]:>10}  {bind}"
        )
    lines.append("-" * len(header))
    lines.append(
        f"mean capacity: {car.get('mean')}   sample range: "
        f"[{car.get('min_total')}, {car.get('max_total')}]"
    )
    replicas = car.get("replicas")
    prob = car.get("prob_fit")
    confidence = car.get("confidence")
    verdict = (
        "SCHEDULABLE" if car.get("schedulable") else "NOT SCHEDULABLE"
    )
    lines.append(
        f"P(fit {replicas} replicas) = {prob}   required confidence: "
        f"{confidence}   verdict: {verdict}"
    )
    return "\n".join(lines)


def car_json_report(car: dict) -> str:
    """``kccap -car-spec -output json``: the wire shape verbatim."""
    return json.dumps(car, indent=2, sort_keys=True)


def car_status_table_report(status: dict) -> str:
    """The ``car`` op's watch-status form as operator-readable text:
    one row per quantile watch (capacity at its confidence, the
    probability-of-fit, the alert state)."""
    if not status.get("enabled", False):
        return (
            "capacity at risk: no quantile watches on this server "
            "(-watch entries need a quantile: field)"
        )
    header = (
        f"{'WATCH':<24} {'QUANTILE':>9} {'CAPACITY':>9} {'MIN':>6} "
        f"{'P(FIT)':>8} {'SAMPLES':>8}  STATE"
    )
    lines = [
        f"capacity at risk: serving generation {status.get('generation')}",
        header,
        "-" * len(header),
    ]
    def _cell(v):
        return "-" if v is None else v

    for name in sorted(status.get("watches", {})):
        w = status["watches"][name]
        alert = w.get("alert", {})
        qlabel = f"p{w['quantile'] * 100:g}"
        lines.append(
            f"{name:<24} "
            f"{qlabel:>9} "
            f"{_cell(w.get('last_total')):>9} "
            f"{_cell(w.get('min_replicas')):>6} "
            f"{_cell(w.get('prob_fit')):>8} "
            f"{w.get('samples'):>8}  {alert.get('state')}"
        )
    lines.append("-" * len(header))
    breached = status.get("breached", [])
    lines.append(
        "verdict: "
        + (
            "BREACHED — " + ", ".join(breached)
            if breached
            else "ok — every quantile watch above its threshold"
        )
    )
    return "\n".join(lines)


def car_status_json_report(status: dict) -> str:
    """``kccap -car -output json``: the wire shape verbatim."""
    return json.dumps(status, indent=2, sort_keys=True)


def gang_table_report(gang: dict) -> str:
    """A gang evaluation (the ``gang`` op's wire shape / ``kccap
    -gang-spec``) as operator-readable text: the whole-gang verdict,
    the constraint vocabulary in force, and the binding-level
    explanation when present."""
    spread = (
        f"{gang.get('spread_level')}<={gang.get('max_ranks_per_domain')}"
        if gang.get("spread_level")
        else ("host<=1" if gang.get("anti_affinity_host") else "-")
    )
    gangs = gang.get("gangs", [])
    sched = gang.get("schedulable", [])
    lines = [
        f"gang capacity: {gang.get('ranks')} rank(s)/gang, "
        f"{gang.get('count')} gang(s) requested  "
        f"[colocate={gang.get('colocate') or 'cluster'} spread={spread} "
        f"mode={gang.get('mode')} engine={gang.get('engine')}]",
    ]
    for s, (g, ok) in enumerate(zip(gangs, sched)):
        pods = gang.get("pod_totals", [None] * len(gangs))[s]
        lines.append(
            f"  scenario {s}: {g} whole gang(s) fit "
            f"(pod capacity {pods}) — "
            + ("schedulable" if ok else "NOT schedulable")
        )
    ex = gang.get("explain")
    if ex:
        lines.append(f"  {ex.get('summary')}")
        largest = ex.get("largest_domain") or {}
        if largest.get("name") is not None:
            lines.append(
                f"  largest {ex.get('colocate') or 'domain'}: "
                f"{largest.get('name')} holds {largest.get('capacity')} "
                f"rank(s) = {largest.get('whole_gangs')} whole gang(s)"
            )
        if ex.get("excluded_nodes"):
            lines.append(
                f"  excluded nodes (missing topology labels): "
                f"{ex['excluded_nodes']}"
            )
    return "\n".join(lines)


def gang_json_report(gang: dict) -> str:
    """``-output json``: the wire shape verbatim."""
    return json.dumps(gang, indent=2, sort_keys=True)


def optimize_table_report(opt: dict) -> str:
    """An optimize evaluation (the ``optimize`` op's wire shape /
    ``kccap -optimize``) as operator-readable text: per scenario the
    certified LP bound vs the rounded integral packing vs the
    first-fit baseline, the certificate verdict, and the shadow-price
    story ("memory is the priced-out resource on 60% of capacity")."""
    if opt.get("backend") == "ffd":
        lines = [
            f"packing (first-fit reference, mode={opt.get('mode')}):",
        ]
        for s in range(opt.get("scenarios", 0)):
            lines.append(
                f"  scenario {s}: placed {opt['ffd'][s]} of "
                f"{opt['demand'][s]} requested (fit total "
                f"{opt['totals'][s]}) — "
                + (
                    "schedulable"
                    if opt["schedulable"][s]
                    else "NOT schedulable"
                )
            )
        return "\n".join(lines)
    header = (
        f"{'S':>3} {'DEMAND':>9} {'LP BOUND':>12} {'ROUNDED':>9} "
        f"{'FFD':>9} {'GAP%':>7}  STATUS"
    )
    lines = [
        f"optimized packing (LP/PDHG, mode={opt.get('mode')}): "
        f"{opt.get('groups')} group(s) over {opt.get('nodes')} node(s)"
        + (
            " [grouped]"
            if opt.get("grouping_engaged")
            else " [ungrouped]"
        ),
        f"solver: {opt.get('iterations')} iteration(s), tol "
        f"{opt.get('tol')}, {opt.get('solve_seconds')}s",
        header,
        "-" * len(header),
    ]
    for s in range(opt.get("scenarios", 0)):
        flags = ""
        if opt.get("ffd_exceeds_bound", [False] * (s + 1))[s]:
            flags = " (ffd exceeds sane bound: reference quirk)"
        verified = opt.get("verified")
        if verified is not None and not verified[s]:
            flags += " (ROUNDING UNVERIFIED)"
        lines.append(
            f"{s:>3} {opt['demand'][s]:>9} {opt['lp_bound'][s]:>12.2f} "
            f"{opt['rounded'][s]:>9} {opt['ffd'][s]:>9} "
            f"{opt['gap_pct'][s]:>7.3f}  {opt['status'][s]}" + flags
        )
    lines.append("-" * len(header))
    for s, shadow in enumerate(opt.get("shadow_prices", [])):
        priced = shadow.get("priced_out", {})
        top = max(priced, key=priced.get) if priced else None
        if top is not None and priced[top] > 0:
            lines.append(
                f"  scenario {s}: {top} is the priced-out resource on "
                f"{priced[top] * 100:.0f}% of capacity "
                f"(demand price {shadow.get('demand_price')})"
            )
        else:
            lines.append(
                f"  scenario {s}: demand-bound — no capacity is "
                f"priced (demand price {shadow.get('demand_price')})"
            )
    lines.append(
        "verdict: "
        + (
            "certified — every bound carries a duality certificate"
            if opt.get("certified")
            else "UNCERTIFIED — bound(s) valid but loose; raise "
            "KCCAP_OPT_ITERS or tol"
        )
    )
    return "\n".join(lines)


def optimize_json_report(opt: dict) -> str:
    """``-output json``: the wire shape verbatim."""
    return json.dumps(opt, indent=2, sort_keys=True)


def gang_status_table_report(status: dict) -> str:
    """The ``gang`` op's watch-status form (``kccap -gang HOST:PORT``):
    one row per gang watch — last whole-gang count, binding level,
    alert state — and the scriptable verdict line."""
    if not status.get("enabled", False):
        return (
            "gang capacity: no gang watches on this server "
            "(-watch entries need a gang: block)"
        )
    header = (
        f"{'WATCH':<24} {'RANKS':>6} {'WANT':>5} {'GANGS':>6} "
        f"{'MIN':>5} {'BINDS':>8}  STATE"
    )
    lines = [
        f"gang capacity: serving generation {status.get('generation')}",
        header,
        "-" * len(header),
    ]

    def _cell(v):
        return "-" if v is None else v

    for name in sorted(status.get("watches", {})):
        w = status["watches"][name]
        alert = w.get("alert", {})
        lines.append(
            f"{name:<24} "
            f"{w.get('ranks'):>6} "
            f"{w.get('count'):>5} "
            f"{_cell(w.get('last_gangs')):>6} "
            f"{_cell(w.get('min_replicas')):>5} "
            f"{_cell(w.get('binding')):>8}  {alert.get('state')}"
        )
    lines.append("-" * len(header))
    breached = status.get("breached", [])
    lines.append(
        "verdict: "
        + (
            "BREACHED — " + ", ".join(breached)
            if breached
            else "ok — every gang watch above its threshold"
        )
    )
    return "\n".join(lines)


def gang_status_json_report(status: dict) -> str:
    """``kccap -gang -output json``: the wire shape verbatim."""
    return json.dumps(status, indent=2, sort_keys=True)


def _ttb_cell(ttb) -> str:
    """Render a ``time_to_breach_s`` value: seconds (with an hour
    translation when it earns one) or ``-`` for "no breach within the
    horizon"."""
    if ttb is None:
        return "-"
    s = float(ttb)
    if s >= 3600.0:
        return f"{s:.0f}s (~{s / 3600.0:.1f}h)"
    return f"{s:.0f}s"


def forecast_table_report(fc: dict) -> str:
    """One horizon projection (the ``forecast`` op's wire shape /
    ``HorizonResult.to_wire()``) as operator-readable text: per
    quantile the current capacity, the horizon minimum, and the
    time-to-breach verdict an autoscaler would script on."""
    growth = fc.get("growth", {})
    lines = [
        f"capacity forecast ({fc.get('mode')} semantics, "
        f"{fc.get('samples')} samples, seed {fc.get('seed')}): "
        f"{fc.get('steps')} step(s) x {fc.get('step_s')}s = "
        f"{fc.get('horizon_s')}s horizon",
        f"growth: cpu {growth.get('cpu_per_s')}/s   "
        f"memory {growth.get('memory_per_s')}/s   "
        f"threshold: {fc.get('threshold')} replicas",
    ]
    if fc.get("degraded_time_axis"):
        lines.append(
            "WARNING: degraded time axis — trend fitted on record "
            "ordinals, not timestamps"
        )
    header = (
        f"{'QUANTILE':<10} {'NOW':>10} {'HORIZON MIN':>12}  "
        f"TIME TO BREACH"
    )
    lines += [header, "-" * len(header)]
    ttb = fc.get("time_to_breach_s", {})
    now = fc.get("now", {})
    for label in sorted(fc.get("quantiles", {}), key=lambda p: float(p[1:])):
        ladder = fc["quantiles"][label]
        lines.append(
            f"{label:<10} {now.get(label):>10} {min(ladder):>12}  "
            f"{_ttb_cell(ttb.get(label))}"
        )
    lines.append("-" * len(header))
    breached = fc.get("breached_within_horizon", [])
    lines.append(
        "verdict: "
        + (
            "BREACH WITHIN HORIZON — " + ", ".join(breached)
            if breached
            else "ok — no quantile crosses the threshold within the horizon"
        )
    )
    return "\n".join(lines)


def forecast_json_report(fc: dict) -> str:
    """``kccap -forecast-spec -output json``: the wire shape verbatim."""
    return json.dumps(fc, indent=2, sort_keys=True)


def forecast_status_table_report(status: dict) -> str:
    """The ``forecast`` op's watch-status form as operator-readable
    text: one row per horizon watch (current capacity at its quantile,
    the projected horizon minimum, time-to-breach, the alert state)."""
    if not status.get("enabled", False):
        return (
            "capacity forecast: no horizon watches on this server "
            "(-watch entries need a horizon: block)"
        )
    header = (
        f"{'WATCH':<24} {'QUANTILE':>9} {'NOW':>9} {'HMIN':>9} "
        f"{'MIN':>6} {'TTB':>14}  STATE"
    )
    lines = [
        f"capacity forecast: serving generation {status.get('generation')}",
        header,
        "-" * len(header),
    ]

    def _cell(v):
        return "-" if v is None else v

    for name in sorted(status.get("watches", {})):
        w = status["watches"][name]
        alert = w.get("alert", {})
        qlabel = f"p{w['quantile'] * 100:g}"
        ttb = w.get("time_to_breach_s")
        lines.append(
            f"{name:<24} "
            f"{qlabel:>9} "
            f"{_cell(w.get('last_total')):>9} "
            f"{_cell(w.get('horizon_min_capacity')):>9} "
            f"{_cell(w.get('min_replicas')):>6} "
            f"{_ttb_cell(ttb):>14}  {alert.get('state')}"
        )
    lines.append("-" * len(header))
    breached = status.get("breached", [])
    lines.append(
        "verdict: "
        + (
            "BREACHED — " + ", ".join(breached)
            if breached
            else "ok — every horizon watch above its threshold"
        )
    )
    return "\n".join(lines)


def forecast_status_json_report(status: dict) -> str:
    """``kccap -forecast -output json``: the wire shape verbatim."""
    return json.dumps(status, indent=2, sort_keys=True)


def plan_table_report(plan: dict) -> str:
    """One capacity plan (the ``plan`` op's catalog form /
    ``PlanResult.to_wire()``) as operator-readable text: the purchase
    list with the certified-vs-LP-bound gap, the shadow-price
    attribution, and the drain dual when requested."""
    lines = [
        f"capacity plan ({plan.get('mode')} semantics, "
        f"{plan.get('samples')} samples, seed {plan.get('seed')}): "
        f"target {plan.get('target')} replicas at "
        f"{plan.get('quantile')}",
        f"base {plan.get('quantile')} capacity: "
        f"{plan.get('base_quantile_capacity')}   projected: "
        f"{plan.get('projected_quantile_capacity')}",
    ]
    buy = plan.get("buy", [])
    if buy:
        header = f"{'SHAPE':<20} {'COUNT':>6} {'UNIT COST':>10} {'COST':>10}"
        lines += [header, "-" * len(header)]
        for row in buy:
            lines.append(
                f"{row.get('shape'):<20} {row.get('count'):>6} "
                f"{row.get('unit_cost'):>10} {row.get('cost'):>10}"
            )
        lines.append("-" * len(header))
    else:
        lines.append("buy: nothing — the target already holds")
    lp = plan.get("lp_bound")
    lines.append(
        f"total cost: {plan.get('total_cost')}   LP bound: "
        f"{'-' if lp is None else lp}   gap: {plan.get('gap_pct')}%"
    )
    shadow = plan.get("shadow_prices", {})
    if shadow:
        lines.append(
            "shadow prices: "
            + "  ".join(f"{k}={v}" for k, v in sorted(shadow.items()))
        )
    if plan.get("demand_price") is not None:
        lines.append(
            f"marginal replica price: {plan.get('demand_price')}"
        )
    drain = plan.get("drain")
    if drain:
        lines.append(
            f"drain: {drain.get('free_count')} node(s) free "
            f"(verified={drain.get('free_verified')}), "
            f"{drain.get('surplus_count')} more drainable holding "
            f"{plan.get('quantile')} >= target "
            f"(capacity after: {drain.get('quantile_after_drain')})"
        )
        if drain.get("free_nodes"):
            lines.append(f"  free: {', '.join(drain['free_nodes'])}")
        if drain.get("surplus_nodes"):
            lines.append(
                f"  surplus: {', '.join(drain['surplus_nodes'])}"
            )
    verdict = plan.get("status", "uncertified").upper()
    reason = plan.get("uncertified_reason")
    lines.append(
        f"verdict: {verdict}"
        + (f" — {reason}" if reason else "")
    )
    return "\n".join(lines)


def plan_json_report(plan: dict) -> str:
    """``kccap -plan ... -output json``: the wire shape verbatim."""
    return json.dumps(plan, indent=2, sort_keys=True)


def fed_status_table_report(status: dict) -> str:
    """``kccap -fed-status`` as operator-readable text: one row per
    cluster with its generation watermark, verified age, and
    fresh/stale/lost state — the degradation contract at a glance."""
    if not status.get("enabled", False):
        return "federation: no clusters attached to this endpoint"
    header = f"{'CLUSTER':<24} {'GENERATION':>11} {'AGE_S':>9}  STATE"
    lines = [
        (
            f"federation: {status['counts']['total']} cluster(s) "
            f"(stale>{status.get('stale_after_s'):g}s, "
            f"lost>{status.get('evict_after_s'):g}s)"
        ),
        header,
        "-" * len(header),
    ]
    for name in sorted(status.get("clusters", {})):
        c = status["clusters"][name]
        age = c.get("age_s")
        lines.append(
            f"{name:<24} {c.get('generation'):>11} "
            f"{'-' if age is None else age:>9}  {c.get('state')}"
        )
    lines.append("-" * len(header))
    excluded = status.get("excluded", [])
    lines.append(
        "verdict: "
        + (
            "DEGRADED — lost: " + ", ".join(excluded)
            if excluded
            else (
                "ok — every cluster within the staleness bound"
                if status["counts"].get("stale", 0) == 0
                else "STALE — "
                + str(status["counts"]["stale"])
                + " cluster(s) serving explicitly-stale views"
            )
        )
    )
    return "\n".join(lines)


def fed_status_json_report(status: dict) -> str:
    """``kccap -fed-status -output json``: the wire shape verbatim."""
    return json.dumps(status, indent=2, sort_keys=True)


def fed_sweep_table_report(result: dict) -> str:
    """``kccap -fed-sweep`` as operator-readable text: the fleet total
    per scenario, the per-cluster split (each row carrying its stamped
    generation and state), and the named exclusions — a lost cluster is
    never a silent hole in a sum."""
    header = f"{'CLUSTER':<24} {'GENERATION':>11}  {'STATE':<6}  TOTALS"
    lines = [header, "-" * len(header)]
    clusters = result.get("clusters", {})
    for name in sorted(result.get("per_cluster", {})):
        c = clusters.get(name, {})
        totals = result["per_cluster"][name]
        lines.append(
            f"{name:<24} {c.get('generation'):>11}  "
            f"{c.get('state'):<6}  {totals}"
        )
    for name in result.get("excluded", []):
        c = clusters.get(name, {})
        lines.append(
            f"{name:<24} {c.get('generation'):>11}  "
            f"{'lost':<6}  EXCLUDED from totals"
        )
    lines.append("-" * len(header))
    lines.append(f"fleet totals      : {result.get('totals')}")
    lines.append(f"schedulable       : {result.get('schedulable')}")
    excluded = result.get("excluded", [])
    lines.append(
        "verdict: "
        + (
            "DEGRADED — totals exclude lost cluster(s): "
            + ", ".join(excluded)
            if excluded
            else (
                "ok (some clusters explicitly stale)"
                if result.get("degraded")
                else "ok — every cluster fresh"
            )
        )
    )
    return "\n".join(lines)


def fed_sweep_json_report(result: dict) -> str:
    """``kccap -fed-sweep -output json``: the wire shape verbatim."""
    return json.dumps(result, indent=2, sort_keys=True)


def replay_table_report(result: dict) -> str:
    """``kccap -replay`` as operator-readable text: the chain verdict,
    the request tallies, and one line per non-ok outcome (a clean
    replay stays terse — the verdict IS the product)."""
    lines = [
        f"audit replay: {result['directory']}",
        f"  generations verified: {len(result['generations_verified'])}"
        + (
            f" (chain BROKEN: {result['chain_error']})"
            if result.get("chain_error")
            else ""
        ),
    ]
    if result.get("recovered_tail_records"):
        lines.append(
            f"  recovered: {result['recovered_tail_records']} torn tail "
            "record(s) dropped (crash-consistent load)"
        )
    c = result["counts"]
    lines.append(
        f"  requests replayed: {result['requests']}  "
        f"ok={c.get('ok', 0)} mismatch={c.get('mismatch', 0)} "
        f"skipped={c.get('skipped', 0)} error={c.get('error', 0)}"
    )
    for o in result["outcomes"]:
        if o["status"] == "ok":
            continue
        line = (
            f"  {o['status'].upper():<8} {o.get('op')} "
            f"gen={o.get('generation')} ref={o.get('ref')}"
        )
        if o["status"] == "mismatch":
            line += (
                f"  recorded={o.get('recorded_digest')} "
                f"replayed={o.get('replayed_digest', o.get('replayed_error'))}"
            )
        elif o.get("reason"):
            line += f"  ({o['reason']})"
        lines.append(line)
    lines.append(
        "verdict: "
        + ("CLEAN — every replay re-answered identically"
           if result["clean"] else "MISMATCH — see lines above")
    )
    return "\n".join(lines)


def replay_json_report(result: dict) -> str:
    """``kccap -replay -output json``: the replay summary verbatim."""
    return json.dumps(result, indent=2, sort_keys=True)


def trace_table_report(tree: dict) -> str:
    """``kccap -trace-tree`` as operator-readable text: the assembled
    span tree (parent linkage only — indentation IS causality), the
    greedy critical path with per-step self time, and the dominating
    contributor in the ``phases`` vocabulary.  A clock-skew refusal is
    reported as a refusal, never as a confident wrong answer."""
    tid = tree.get("trace_id", "")
    if not tree.get("found"):
        return (
            f"trace {tid}: no spans found in the given logs\n"
            "verdict: NOT FOUND — wrong -trace-logs directories, or the "
            "trace's bodies were dropped by tail sampling on every hop"
        )
    lines = [
        f"trace {tid}: {tree.get('spans', 0)} span(s) across "
        + (", ".join(tree.get("processes", [])) or "unknown processes")
        + (
            f"  (orphaned: {tree['orphans']})"
            if tree.get("orphans")
            else ""
        )
    ]
    skew = tree.get("clock_skew_spans", [])
    if skew:
        lines.append(
            f"clock skew: {len(skew)} span(s) with negative durations "
            "flagged (wall-clock stepped mid-span): " + ", ".join(skew)
        )
    in_flight = tree.get("in_flight", [])
    if in_flight:
        lines.append(
            f"in flight: {len(in_flight)} span(s) recorded without a "
            "usable duration (process died mid-request?) excluded "
            "from assembly: " + ", ".join(in_flight)
        )

    def _walk(node, depth, seen):
        if id(node) in seen or depth > 64:
            return
        seen.add(id(node))
        flags = []
        if node.get("clock_skew"):
            flags.append("CLOCK_SKEW")
        if node.get("status") not in (None, "ok"):
            flags.append(str(node.get("status")).upper())
        for key in ("hedge", "winner", "leader"):
            if node.get(key):
                flags.append(key)
        if node.get("failover_reason"):
            flags.append(f"failover={node['failover_reason']}")
        if node.get("cluster"):
            flags.append(f"cluster={node['cluster']}")
        if node.get("state") and node.get("state") != "fresh":
            flags.append(f"state={node['state']}")
        dur = node.get("duration_ms")
        lines.append(
            "  " * depth
            + f"- {node.get('op', '?')} [{node.get('service', '?')}] "
            + (f"{dur:g}ms" if isinstance(dur, (int, float)) else "?ms")
            + (("  " + " ".join(flags)) if flags else "")
        )
        for child in node.get("children", ()):
            _walk(child, depth + 1, seen)

    seen: set = set()
    for root in tree.get("roots", []):
        _walk(root, 1, seen)
    cp = tree.get("critical_path") or {}
    if cp.get("refused"):
        lines.append(
            "critical path: REFUSED ("
            + cp["refused"]
            + (
                ") — a poisoned (negative) duration is on the path; "
                "fix the host clock or read the raw spans"
                if cp["refused"] == "clock_skew"
                else ") — nothing to attribute"
            )
        )
        return "\n".join(lines)
    lines.append(f"critical path ({cp.get('total_ms', 0.0):g}ms end-to-end):")
    for step in cp.get("path", []):
        lines.append(
            f"  {step.get('op', '?'):<24} [{step.get('service', '?'):<10}] "
            f"{step.get('duration_ms', 0.0):>10g}ms  "
            f"self {step.get('self_ms', 0.0):g}ms"
            + (
                f"  {str(step.get('status')).upper()}"
                if step.get("status")
                else ""
            )
        )
    dom = cp.get("dominant")
    if dom:
        lines.append(
            f"verdict: dominated by {dom['name']} — {dom['ms']:g}ms "
            f"({dom['share'] * 100:.1f}% of end-to-end)"
        )
    return "\n".join(lines)


def trace_json_report(tree: dict) -> str:
    """``kccap -trace-tree -output json``: the assembled tree (nested
    ``children``) plus ``critical_path`` verbatim."""
    return json.dumps(tree, indent=2, sort_keys=True)


def table_report(
    snapshot: ClusterSnapshot, fits: np.ndarray, scenario: Scenario
) -> str:
    """Compact human-readable table (a view the reference never had)."""
    header = (
        f"{'NODE':<24} {'HEALTHY':<8} {'CPU USED/ALLOC (m)':<22} "
        f"{'MEM USED/ALLOC (MiB)':<24} {'PODS':<9} {'FIT':>6}"
    )
    lines = [header, "-" * len(header)]
    mib = 1024 * 1024
    for i in range(snapshot.n_nodes):
        lines.append(
            f"{snapshot.names[i] or '<phantom>':<24} "
            f"{'yes' if snapshot.healthy[i] else 'NO':<8} "
            f"{f'{int(snapshot.used_cpu_req_milli[i])}/{int(snapshot.alloc_cpu_milli[i])}':<22} "
            f"{f'{int(snapshot.used_mem_req_bytes[i]) // mib}/{int(snapshot.alloc_mem_bytes[i]) // mib}':<24} "
            f"{f'{int(snapshot.pods_count[i])}/{int(snapshot.alloc_pods[i])}':<9} "
            f"{int(fits[i]):>6}"
        )
    total = int(np.sum(fits))
    verdict = "SCHEDULABLE" if total >= scenario.replicas else "NOT SCHEDULABLE"
    lines.append("-" * len(header))
    lines.append(
        f"total possible replicas: {total}   requested: {scenario.replicas}   "
        f"verdict: {verdict}"
    )
    return "\n".join(lines)
