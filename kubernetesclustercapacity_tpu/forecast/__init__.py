"""Capacity forecasting & autoscaler planning (ROADMAP follow-on to the
stochastic engine): time-to-breach and certified "what to buy", derived
from verified history.

Three layers, each pinned against an independent oracle:

* :mod:`.trend` — robust Theil–Sen demand/supply trends replayed from
  the audit log's digest-verified generations (timestamps from the
  records, never the wall clock — the same history always fits the same
  trend);
* :mod:`.horizon` — the trend composed with the counter-based sampler:
  P50/P95/P99 capacity projected over an ``[H]``-step horizon as ONE
  batched ``[H×S]`` sweep dispatch through the production kernel path,
  reduced host-side to ``time_to_breach_s`` per quantile;
* :mod:`.planner` — the LP-duality answer to "cheapest node set that
  restores P95 headroom" over a declarative shape catalog, plus the
  scale-down dual ("which nodes drain for free"), with cannot-lie
  host-side certification: a plan is ``certified`` or explicitly not,
  never silently wrong.
"""

from kubernetesclustercapacity_tpu.forecast.horizon import (  # noqa: F401
    DEFAULT_STEP_S,
    DEFAULT_STEPS,
    HorizonResult,
    horizon_oracle,
    max_steps,
    project_horizon,
)
from kubernetesclustercapacity_tpu.forecast.planner import (  # noqa: F401
    CatalogShape,
    PlannerError,
    PlanResult,
    apply_plan,
    load_catalog,
    parse_catalog,
    plan_capacity,
)
from kubernetesclustercapacity_tpu.forecast.trend import (  # noqa: F401
    TrendFit,
    fit_trend,
    trend_from_audit,
    trend_oracle,
)
