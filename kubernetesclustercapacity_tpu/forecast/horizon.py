"""Forward capacity projection: ONE batched [H×S] sweep over a horizon.

"Capacity at risk" answers *how many fit now with 95% confidence*; this
module answers *when that stops being true*.  It composes the robust
trend (:mod:`.trend`) with the counter-based stochastic sampler
(:mod:`~..stochastic.distributions`): the trend's relative growth rate
scales the per-pod usage samples at each of ``H`` horizon steps, and the
whole ``[H, S]`` projection is flattened into ONE
:class:`~..scenario.ScenarioGrid` of ``H·S`` rows and dispatched as a
single ``sweep_snapshot`` call — the device cache, the shape-bucket
ladder, and the (shape, count) grouped kernels ride unchanged, so a
32-step × 64-sample forecast costs one dispatch, not 2048.

Scaling rule (shared with the numpy oracle, documented so both sides
implement it independently): at step ``h`` (``h = 0`` is now) the growth
factor is ``g_h = max(0, 1 + rate·h·step_s)`` and each int64 usage
sample ``u`` becomes ``clip(rint(float64(u)·g_h), 1, MAX_USAGE)`` —
float64 multiply, round-half-even, clamp into the sampler's own domain.
Per step the capacity quantiles reduce with the exact order-statistic
rule capacity-at-risk documents (:func:`~..stochastic.car.
quantile_index`), and ``time_to_breach_s`` is the first step whose
quantile capacity falls below the threshold, in seconds (``0.0`` =
breached already, ``None`` = no breach within the horizon).

Determinism: samples are drawn once from the spec's explicit seed and
scaled host-side — the projection is a pure function of (snapshot, spec,
growth, steps, step_s), bit-exact across grouped/ungrouped/cached paths
because the underlying sweep is, and therefore audit-replayable.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import numpy as np

from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot
from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
from kubernetesclustercapacity_tpu.stochastic.car import (
    DEFAULT_QUANTILES,
    fit_totals_numpy,
    quantile_index,
    quantile_label,
)
from kubernetesclustercapacity_tpu.stochastic.distributions import (
    MAX_USAGE,
    StochasticSpec,
    sample_key,
    sample_usage,
)

__all__ = [
    "DEFAULT_STEPS",
    "DEFAULT_STEP_S",
    "HorizonResult",
    "horizon_oracle",
    "max_steps",
    "project_horizon",
]

#: Default projection: 16 steps of one hour — a working day of warning
#: with the evening still ahead.
DEFAULT_STEPS = 16
DEFAULT_STEP_S = 3600.0


def max_steps() -> int:
    """Upper bound on horizon steps per projection (the [H·S] grid is
    one dispatch — H·S rows of device memory).  Overridable via
    ``KCCAP_FORECAST_MAX_STEPS`` for deliberate long-range studies."""
    try:
        return max(int(os.environ.get("KCCAP_FORECAST_MAX_STEPS", 512)), 1)
    except ValueError:
        return 512


def _growth_factors(rate_per_s: float, steps: int, step_s: float) -> np.ndarray:
    """``[H]`` float64 multiplicative factors, ``g_0 = 1`` exactly."""
    h = np.arange(steps, dtype=np.float64)
    return np.maximum(1.0 + float(rate_per_s) * h * float(step_s), 0.0)


def _scale_samples(samples: np.ndarray, factors: np.ndarray) -> np.ndarray:
    """Apply the documented scaling rule: ``[S]`` int64 × ``[H]``
    factors → ``[H, S]`` int64 (float64 multiply, rint, clamp to the
    sampler domain ``[1, MAX_USAGE]``)."""
    scaled = np.rint(
        samples.astype(np.float64)[None, :] * factors[:, None]
    )
    return np.clip(scaled, 1.0, float(MAX_USAGE)).astype(np.int64)


@dataclass
class HorizonResult:
    """One forward projection (numpy arrays throughout).

    ``totals`` is the ``[H, S]`` per-step per-sample capacity;
    ``quantiles`` maps confidence → ``[H]`` int64 capacity ladder;
    ``time_to_breach_s`` maps confidence → seconds until that quantile
    capacity first drops below ``threshold`` (``None``: never within
    the horizon).
    """

    spec: StochasticSpec
    mode: str
    steps: int
    step_s: float
    n_samples: int
    threshold: int
    growth_cpu_per_s: float
    growth_mem_per_s: float
    totals: np.ndarray  # [H, S] int64
    quantiles: dict[float, np.ndarray]  # q -> [H] int64
    time_to_breach_s: dict[float, float | None]
    degraded_time_axis: bool = False
    eval_ms: float = 0.0
    trend: dict = field(default_factory=dict)

    @property
    def horizon_s(self) -> float:
        return (self.steps - 1) * self.step_s

    def min_capacity(self, q: float) -> int:
        """The worst projected capacity at confidence ``q`` anywhere in
        the horizon — what a breach-within-horizon alert keys on."""
        return int(self.quantiles[q].min())

    def breached_within_horizon(self, q: float) -> bool:
        return self.time_to_breach_s[q] is not None

    def to_wire(self) -> dict:
        return {
            "mode": self.mode,
            "samples": self.n_samples,
            "seed": self.spec.seed,
            "replicas": self.spec.replicas,
            "threshold": self.threshold,
            "steps": self.steps,
            "step_s": self.step_s,
            "horizon_s": self.horizon_s,
            "growth": {
                "cpu_per_s": float(self.growth_cpu_per_s),
                "memory_per_s": float(self.growth_mem_per_s),
            },
            "degraded_time_axis": self.degraded_time_axis,
            "quantiles": {
                quantile_label(q): [int(v) for v in ladder]
                for q, ladder in sorted(self.quantiles.items())
            },
            "now": {
                quantile_label(q): int(ladder[0])
                for q, ladder in sorted(self.quantiles.items())
            },
            "time_to_breach_s": {
                quantile_label(q): (
                    None if ttb is None else round(float(ttb), 3)
                )
                for q, ttb in sorted(self.time_to_breach_s.items())
            },
            "breached_within_horizon": sorted(
                quantile_label(q)
                for q, ttb in self.time_to_breach_s.items()
                if ttb is not None
            ),
            **({"trend": self.trend} if self.trend else {}),
        }


def _validate_projection(steps: int, step_s: float) -> None:
    if isinstance(steps, bool) or not isinstance(steps, int) or steps < 1:
        raise ValueError(f"steps must be a positive int, got {steps!r}")
    cap = max_steps()
    if steps > cap:
        raise ValueError(
            f"steps={steps} exceeds the horizon cap {cap} "
            "(KCCAP_FORECAST_MAX_STEPS)"
        )
    if not isinstance(step_s, (int, float)) or isinstance(step_s, bool) or (
        not float(step_s) > 0.0
    ):
        raise ValueError(f"step_s must be > 0 seconds, got {step_s!r}")


def _reduce_ladders(
    totals: np.ndarray,
    quantiles: tuple[float, ...],
    threshold: int,
    step_s: float,
) -> tuple[dict[float, np.ndarray], dict[float, float | None]]:
    """Per-step order-statistic reduction + first-breach search.

    ``totals`` is ``[H, S]``; per step the samples sort ascending and
    each quantile picks its documented index.  Shared verbatim by the
    dispatch path and the oracle ON PURPOSE: the reduction is exact
    integer selection (nothing to diverge), while the sweeps it reduces
    are the independently-implemented halves under test.
    """
    h, s = totals.shape
    sorted_totals = np.sort(totals, axis=1)
    ladders: dict[float, np.ndarray] = {}
    ttb: dict[float, float | None] = {}
    for q in quantiles:
        ladder = sorted_totals[:, quantile_index(s, q)].astype(np.int64)
        ladders[q] = ladder
        below = np.flatnonzero(ladder < int(threshold))
        ttb[q] = float(below[0] * step_s) if below.size else None
    return ladders, ttb


def project_horizon(
    snapshot: ClusterSnapshot,
    spec: StochasticSpec,
    *,
    steps: int = DEFAULT_STEPS,
    step_s: float = DEFAULT_STEP_S,
    growth_cpu_per_s: float = 0.0,
    growth_mem_per_s: float = 0.0,
    mode: str | None = None,
    node_mask=None,
    quantiles: tuple[float, ...] = DEFAULT_QUANTILES,
    threshold: int | None = None,
    degraded_time_axis: bool = False,
) -> HorizonResult:
    """Project capacity quantiles ``steps`` steps forward.

    Draws the spec's ``S`` usage samples once (explicit seed, streams 0
    and 1 exactly like capacity-at-risk), scales them per step by the
    relative growth rates, and evaluates the whole ``[H, S]`` grid as
    ONE production sweep dispatch.  ``threshold`` defaults to the
    spec's requested replicas — "when does the q-quantile stop fitting
    what we asked for".
    """
    mode = mode or snapshot.semantics
    _validate_projection(steps, step_s)
    threshold = int(spec.replicas if threshold is None else threshold)
    n = spec.n_samples()
    t0 = time.perf_counter()
    cpu = sample_usage(spec.cpu, n, sample_key(spec.seed, 0))
    mem = sample_usage(spec.memory, n, sample_key(spec.seed, 1))
    cpu_grid = _scale_samples(cpu, _growth_factors(growth_cpu_per_s, steps, step_s))
    mem_grid = _scale_samples(mem, _growth_factors(growth_mem_per_s, steps, step_s))
    grid = ScenarioGrid(
        cpu_request_milli=cpu_grid.reshape(-1),
        mem_request_bytes=mem_grid.reshape(-1),
        replicas=np.full(steps * n, int(spec.replicas), dtype=np.int64),
    )
    totals = np.asarray(
        sweep_snapshot(snapshot, grid, mode=mode, node_mask=node_mask)[0],
        dtype=np.int64,
    ).reshape(steps, n)
    ladders, ttb = _reduce_ladders(totals, quantiles, threshold, step_s)
    return HorizonResult(
        spec=spec,
        mode=mode,
        steps=steps,
        step_s=float(step_s),
        n_samples=n,
        threshold=threshold,
        growth_cpu_per_s=float(growth_cpu_per_s),
        growth_mem_per_s=float(growth_mem_per_s),
        totals=totals,
        quantiles=ladders,
        time_to_breach_s=ttb,
        degraded_time_axis=degraded_time_axis,
        eval_ms=(time.perf_counter() - t0) * 1e3,
    )


def horizon_oracle(
    snapshot: ClusterSnapshot,
    spec: StochasticSpec,
    *,
    steps: int = DEFAULT_STEPS,
    step_s: float = DEFAULT_STEP_S,
    growth_cpu_per_s: float = 0.0,
    growth_mem_per_s: float = 0.0,
    mode: str | None = None,
    node_mask=None,
    quantiles: tuple[float, ...] = DEFAULT_QUANTILES,
    threshold: int | None = None,
) -> HorizonResult:
    """The pure-numpy seed-replay oracle: identical draws from the
    identical seed, the documented scaling rule re-applied, and every
    step's totals computed by :func:`~..stochastic.car.
    fit_totals_numpy` (ungrouped, unbucketed, no JAX) — so
    ``forecast_parity_diffs == 0`` pins the one-dispatch path at any
    scale the kernels serve."""
    mode = mode or snapshot.semantics
    _validate_projection(steps, step_s)
    threshold = int(spec.replicas if threshold is None else threshold)
    n = spec.n_samples()
    cpu = sample_usage(spec.cpu, n, sample_key(spec.seed, 0))
    mem = sample_usage(spec.memory, n, sample_key(spec.seed, 1))
    totals = np.empty((steps, n), dtype=np.int64)
    for h in range(steps):
        g_cpu = max(1.0 + float(growth_cpu_per_s) * h * float(step_s), 0.0)
        g_mem = max(1.0 + float(growth_mem_per_s) * h * float(step_s), 0.0)
        cpu_h = np.clip(
            np.rint(cpu.astype(np.float64) * g_cpu), 1.0, float(MAX_USAGE)
        ).astype(np.int64)
        mem_h = np.clip(
            np.rint(mem.astype(np.float64) * g_mem), 1.0, float(MAX_USAGE)
        ).astype(np.int64)
        totals[h] = fit_totals_numpy(
            snapshot.alloc_cpu_milli,
            snapshot.alloc_mem_bytes,
            snapshot.alloc_pods,
            snapshot.used_cpu_req_milli,
            snapshot.used_mem_req_bytes,
            snapshot.pods_count,
            snapshot.healthy,
            cpu_h,
            mem_h,
            mode=mode,
            node_mask=node_mask,
        )
    ladders, ttb = _reduce_ladders(totals, quantiles, threshold, step_s)
    return HorizonResult(
        spec=spec,
        mode=mode,
        steps=steps,
        step_s=float(step_s),
        n_samples=n,
        threshold=threshold,
        growth_cpu_per_s=float(growth_cpu_per_s),
        growth_mem_per_s=float(growth_mem_per_s),
        totals=totals,
        quantiles=ladders,
        time_to_breach_s=ttb,
    )
