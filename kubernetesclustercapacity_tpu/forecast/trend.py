"""Robust demand/supply trends from verified history: the "when" layer.

A capacity forecast starts with a trend, and a trend fitted by least
squares on operational telemetry is a footgun — one garbage-collected
node or one batch job spikes the slope and the pager.  This module fits
**Theil–Sen** instead: the slope is the median of all pairwise slopes,
the intercept the median of the slope-adjusted values, and the spread a
median absolute deviation — every statistic an order statistic, so the
fit has a 29% breakdown point AND is exactly reproducible (no float
accumulation order dependence beyond the pairwise quotients themselves,
which are computed identically everywhere).

Determinism contract: timestamps come from the records (the audit log's
generation stamps, or the timeline ring's observation stamps) — never
from the wall clock at fit time, and nothing here traces or jits.  The
same series always yields the same fit, and a fit recorded in the audit
log re-answers identically on replay.

:func:`fit_trend` is the production fit (vectorized numpy);
:func:`trend_oracle` re-derives the identical statistics with scalar
Python loops + :mod:`statistics` medians — the independent comparator
the randomized property tests pin every fit against.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetesclustercapacity_tpu.stochastic.history import (
    InsufficientHistoryError,
    SeriesHistory,
    extract_series,
)

__all__ = [
    "TrendFit",
    "fit_trend",
    "trend_from_audit",
    "trend_oracle",
]

#: Pairwise-slope fitting is O(T^2); the audit log can hold far more
#: generations than a trend needs.  Series longer than this keep their
#: most recent _MAX_FIT_POINTS points (the recent past predicts the
#: near future; ancient history only dilutes the breakdown point).
_MAX_FIT_POINTS = 2048


@dataclass(frozen=True)
class TrendFit:
    """One robust linear fit ``y ≈ intercept + slope·(t - t0)``.

    ``slope_per_s`` is in series units per second (per record when the
    time axis is degraded), ``intercept`` the fitted value at ``t0``
    (the series' first timestamp), ``mad`` the median absolute residual
    (the fit's spread), and ``level`` the fitted value at the LAST
    timestamp — the trend's "now", which is what forward projection
    grows from.
    """

    slope_per_s: float
    intercept: float
    mad: float
    n: int
    t0: float
    span_s: float
    degraded_time_axis: bool = False

    @property
    def level(self) -> float:
        """The fitted value at the newest observation."""
        return self.intercept + self.slope_per_s * self.span_s

    @property
    def relative_slope_per_s(self) -> float:
        """Growth per second as a fraction of the current level — the
        multiplier the horizon projection applies to usage samples.
        Zero when the trend's level is non-positive (a series that fits
        to nothing has no meaningful relative growth)."""
        lvl = self.level
        if lvl <= 0.0:
            return 0.0
        return self.slope_per_s / lvl

    def value_at(self, t_s: float) -> float:
        """The fitted value ``t_s`` seconds after ``t0``."""
        return self.intercept + self.slope_per_s * t_s

    def to_wire(self) -> dict:
        return {
            "slope_per_s": float(self.slope_per_s),
            "intercept": float(self.intercept),
            "level": float(self.level),
            "mad": float(self.mad),
            "points": self.n,
            "span_s": float(self.span_s),
            "degraded_time_axis": self.degraded_time_axis,
        }


def _validated_series(ts, ys) -> tuple[np.ndarray, np.ndarray]:
    t = np.asarray(ts, dtype=np.float64)
    y = np.asarray(ys, dtype=np.float64)
    if t.ndim != 1 or y.ndim != 1 or t.shape[0] != y.shape[0]:
        raise ValueError(
            f"ts and ys must be equal-length 1-D series, got "
            f"{t.shape} vs {y.shape}"
        )
    if t.shape[0] > _MAX_FIT_POINTS:
        t = t[-_MAX_FIT_POINTS:]
        y = y[-_MAX_FIT_POINTS:]
    if t.shape[0] < 2:
        raise InsufficientHistoryError(
            f"a trend fit needs >= 2 observations, got {t.shape[0]}",
            observations=int(t.shape[0]),
        )
    if np.any(np.diff(t) < 0):
        raise ValueError("trend timestamps must be non-decreasing")
    if t[-1] <= t[0]:
        raise InsufficientHistoryError(
            "trend timestamps span zero seconds "
            "(every observation is simultaneous)",
            observations=int(t.shape[0]),
        )
    return t, y


def fit_trend(
    ts, ys, *, degraded_time_axis: bool = False
) -> TrendFit:
    """Theil–Sen fit of one series (vectorized numpy).

    ``ts``/``ys`` are equal-length 1-D arrays; timestamps must be
    non-decreasing with positive span (the series loaders guarantee
    both, degrading to record order when the recorded stamps cannot).
    Pairs with equal timestamps contribute no slope (their quotient is
    undefined, not infinite).  Raises
    :class:`~..stochastic.history.InsufficientHistoryError` on fewer
    than two observations or a zero-span axis.
    """
    t, y = _validated_series(ts, ys)
    n = int(t.shape[0])
    i, j = np.triu_indices(n, k=1)
    dt = t[j] - t[i]
    keep = dt > 0
    slopes = (y[j][keep] - y[i][keep]) / dt[keep]
    slope = float(np.median(slopes))
    t0 = float(t[0])
    intercept = float(np.median(y - slope * (t - t0)))
    residuals = y - (intercept + slope * (t - t0))
    mad = float(np.median(np.abs(residuals)))
    return TrendFit(
        slope_per_s=slope,
        intercept=intercept,
        mad=mad,
        n=n,
        t0=t0,
        span_s=float(t[-1] - t0),
        degraded_time_axis=degraded_time_axis,
    )


def trend_oracle(
    ts, ys, *, degraded_time_axis: bool = False
) -> TrendFit:
    """The independent comparator: the same statistics derived with
    scalar Python loops and :func:`statistics.median` — no shared
    vectorized code with :func:`fit_trend`, so agreement pins the
    production fit, not a common bug."""
    import statistics

    t, y = _validated_series(ts, ys)
    n = int(t.shape[0])
    slopes = []
    for a in range(n):
        for b in range(a + 1, n):
            dt = float(t[b]) - float(t[a])
            if dt > 0:
                slopes.append((float(y[b]) - float(y[a])) / dt)
    slope = statistics.median(slopes)
    t0 = float(t[0])
    intercept = statistics.median(
        float(y[k]) - slope * (float(t[k]) - t0) for k in range(n)
    )
    mad = statistics.median(
        abs(float(y[k]) - (intercept + slope * (float(t[k]) - t0)))
        for k in range(n)
    )
    return TrendFit(
        slope_per_s=slope,
        intercept=intercept,
        mad=mad,
        n=n,
        t0=t0,
        span_s=float(t[-1]) - t0,
        degraded_time_axis=degraded_time_axis,
    )


def trend_from_audit(
    source,
    resource: str = "cpu",
    kind: str = "usage",
    *,
    min_points: int = 3,
) -> tuple[TrendFit, SeriesHistory]:
    """Fit a trend straight off an audit log: walk the digest-verified
    generations into a :class:`~..stochastic.history.SeriesHistory`
    (demand or supply, see ``kind``) and Theil–Sen fit it.  Returns the
    fit alongside the series it was fitted on, so callers can report
    provenance ("fitted over N generations spanning S seconds")."""
    series = extract_series(
        source, resource, kind, min_points=min_points
    )
    fit = fit_trend(
        series.ts,
        series.totals,
        degraded_time_axis=series.degraded_time_axis,
    )
    return fit, series
