"""Certified autoscaler planning: "what to buy" and "what drains free".

The forecast says P95 capacity crosses the threshold in six days; this
module closes the loop with the two questions an autoscaler (or a
budget meeting) actually asks:

* **scale-up** — the cheapest multiset of catalog node shapes whose
  purchase restores the q-quantile capacity to at least ``target``;
* **scale-down** — which existing nodes can be drained *for free*:
  zero contribution to capacity at every Monte Carlo sample (the
  stochastic analog of a zero shadow price), plus the surplus nodes a
  greedy drain can remove while the exact quantile stays at target.

Certification contract (the PR-14 cannot-lie rule, carried over): a
plan is ``certified`` only when host-side exact integer arithmetic —
the pure-numpy oracle sweep, NOT the dispatch path that proposed the
plan — confirms the purchased capacity restores the quantile, and every
catalog shape's closed-form per-sample fit column agrees with the same
oracle.  Anything less (unsatisfiable targets, exhausted ``max_count``
bounds, a dispatch/oracle disagreement) is reported ``uncertified``
with the reason; the answer is never silently wrong.

The cost lower bound is closed-form LP duality over the order-statistic
constraint: restoring the q-quantile to ``target`` means lifting at
least ``k = ceil(q·S)`` samples to it; lifting sample ``s`` alone costs
at least ``deficit_s · min_j(cost_j / fit_js)`` (the single-constraint
LP optimum), and any feasible set of ``k`` samples pays at least its
most expensive member — so the k-th smallest per-sample bound is a
valid lower bound on ANY fractional plan.  ``gap_pct`` reports how far
the integral plan sits above it.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

import numpy as np

from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot
from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
from kubernetesclustercapacity_tpu.stochastic.car import (
    fit_totals_numpy,
    quantile_index,
    quantile_label,
)
from kubernetesclustercapacity_tpu.stochastic.distributions import (
    StochasticSpec,
    sample_key,
    sample_usage,
)
from kubernetesclustercapacity_tpu.utils.quantity import (
    QuantityParseError,
    cpu_parse_error_payload,
    cpu_to_milli_reference,
    to_bytes_reference,
)

__all__ = [
    "CatalogShape",
    "PlanResult",
    "PlannerError",
    "apply_plan",
    "load_catalog",
    "parse_catalog",
    "plan_capacity",
]

_RESOURCE_ORDER = ("cpu", "memory", "pods")

#: Per-shape purchase ceiling when the catalog does not set one, and the
#: overall node budget a greedy mix may spend before declaring the
#: target unreachable — both explicit in the result, never silent.
_DEFAULT_MAX_COUNT = 10_000
_MAX_TOTAL_NODES = 100_000


class PlannerError(ValueError):
    """Malformed catalog or plan request (bad shape quantities, bad
    target, an empty catalog) — grammar errors, typed like the
    stochastic spec's."""


@dataclass(frozen=True)
class CatalogShape:
    """One purchasable node shape: the fit columns plus its price."""

    name: str
    cpu_milli: int
    mem_bytes: int
    pods: int
    unit_cost: float
    max_count: int = _DEFAULT_MAX_COUNT

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "cpu_milli": self.cpu_milli,
            "mem_bytes": self.mem_bytes,
            "pods": self.pods,
            "unit_cost": self.unit_cost,
            "max_count": self.max_count,
        }


def _quantity(resource: str, v, *, field_name: str) -> int:
    if isinstance(v, bool):
        raise PlannerError(f"{field_name}: expected a quantity, got {v!r}")
    if isinstance(v, (int, float)):
        if isinstance(v, float) and not v.is_integer():
            raise PlannerError(
                f"{field_name}: native-unit quantities must be integers, "
                f"got {v!r}"
            )
        return int(v)
    if not isinstance(v, str):
        raise PlannerError(f"{field_name}: expected a quantity, got {v!r}")
    if resource == "cpu":
        if cpu_parse_error_payload(v) is not None:
            raise PlannerError(f"{field_name}: bad cpu quantity {v!r}")
        return cpu_to_milli_reference(v)
    try:
        return to_bytes_reference(v)
    except QuantityParseError as e:
        raise PlannerError(
            f"{field_name}: bad memory quantity {v!r}: {e}"
        ) from e


def parse_catalog(data) -> tuple[CatalogShape, ...]:
    """``{"shapes": [...]}`` (or a bare list) → validated shapes.

    Each entry: ``name``, ``cpu`` (millicores or ``"8"``/``"8000m"``),
    ``memory`` (bytes or ``"32gb"``), ``pods`` (int), ``unit_cost``
    (positive number, any currency — only ratios matter), optional
    ``max_count``.  Names must be unique; quantities parse through the
    reference codecs so a catalog file speaks the same dialect as every
    other operator file.
    """
    if isinstance(data, dict):
        data = data.get("shapes")
    if not isinstance(data, list) or not data:
        raise PlannerError(
            "catalog wants a non-empty 'shapes' list of node shapes"
        )
    shapes: list[CatalogShape] = []
    seen: set[str] = set()
    for i, entry in enumerate(data):
        where = f"catalog shape[{i}]"
        if not isinstance(entry, dict):
            raise PlannerError(f"{where}: expected an object, got {entry!r}")
        unknown = set(entry) - {
            "name", "cpu", "memory", "pods", "unit_cost", "max_count",
        }
        if unknown:
            raise PlannerError(
                f"{where}: unknown key(s) {sorted(unknown)}"
            )
        name = entry.get("name")
        if not isinstance(name, str) or not name:
            raise PlannerError(f"{where}: wants a non-empty name")
        if name in seen:
            raise PlannerError(f"{where}: duplicate shape name {name!r}")
        seen.add(name)
        cpu = _quantity("cpu", entry.get("cpu"), field_name=f"{where}.cpu")
        mem = _quantity(
            "memory", entry.get("memory"), field_name=f"{where}.memory"
        )
        pods = entry.get("pods", 110)
        if isinstance(pods, bool) or not isinstance(pods, int) or pods < 1:
            raise PlannerError(
                f"{where}.pods: wants a positive int, got {pods!r}"
            )
        cost = entry.get("unit_cost")
        if (
            isinstance(cost, bool)
            or not isinstance(cost, (int, float))
            or not float(cost) > 0.0
        ):
            raise PlannerError(
                f"{where}.unit_cost: wants a positive number, got {cost!r}"
            )
        max_count = entry.get("max_count", _DEFAULT_MAX_COUNT)
        if (
            isinstance(max_count, bool)
            or not isinstance(max_count, int)
            or max_count < 0
        ):
            raise PlannerError(
                f"{where}.max_count: wants an int >= 0, got {max_count!r}"
            )
        if cpu < 1 or mem < 1:
            raise PlannerError(
                f"{where}: cpu and memory must be positive quantities"
            )
        shapes.append(
            CatalogShape(
                name=name,
                cpu_milli=cpu,
                mem_bytes=mem,
                pods=pods,
                unit_cost=float(cost),
                max_count=max_count,
            )
        )
    return tuple(shapes)


def load_catalog(path: str) -> tuple[CatalogShape, ...]:
    """Load a catalog file (YAML when PyYAML is present, else strict
    JSON — the same loader split as every other operator file)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml  # type: ignore[import-untyped]

        data = yaml.safe_load(text)
    except ImportError:
        try:
            data = json.loads(text)
        except ValueError as e:
            raise PlannerError(
                f"{path}: not valid JSON (and PyYAML is unavailable): {e}"
            ) from e
    except Exception as e:  # yaml.YAMLError — malformed document
        raise PlannerError(f"{path}: cannot parse: {e}") from e
    return parse_catalog(data)


def _fresh_node_fits(
    shape: CatalogShape, cpu_reqs: np.ndarray, mem_reqs: np.ndarray
) -> np.ndarray:
    """``[S]`` int64 per-sample fit of ONE empty healthy node of this
    shape — closed form.  With ``used = 0`` and ``pods_count = 0`` the
    reference's conditional pod-cap overwrite and strict mode's
    slots-and-health clamp reduce to the same expression:
    ``min(cpu // req, mem // req, pods)``."""
    cpu = np.maximum(cpu_reqs.astype(np.int64), 1)
    cpu_fit = np.where(
        shape.cpu_milli <= 0, 0, shape.cpu_milli // cpu
    )
    mem = np.maximum(mem_reqs.astype(np.int64), 1)
    mem_fit = np.where(
        shape.mem_bytes <= 0, 0, shape.mem_bytes // mem
    )
    return np.minimum(
        np.minimum(cpu_fit, mem_fit), np.int64(max(shape.pods, 0))
    ).astype(np.int64)


def _oracle_shape_fits(
    shape: CatalogShape,
    cpu_reqs: np.ndarray,
    mem_reqs: np.ndarray,
    mode: str,
) -> np.ndarray:
    """The same column through :func:`~..stochastic.car.
    fit_totals_numpy` on a synthetic 1-node snapshot — the independent
    derivation certification compares against."""
    one = np.array([1], dtype=np.int64)
    return fit_totals_numpy(
        np.array([shape.cpu_milli], dtype=np.int64),
        np.array([shape.mem_bytes], dtype=np.int64),
        np.array([shape.pods], dtype=np.int64),
        one * 0,
        one * 0,
        one * 0,
        np.array([True]),
        cpu_reqs,
        mem_reqs,
        mode=mode,
    )


def _quantile_value(totals: np.ndarray, q: float) -> int:
    s = int(totals.shape[0])
    return int(np.sort(totals, kind="stable")[quantile_index(s, q)])


@dataclass
class PlanResult:
    """One planning answer (scale-up buy list + optional drain set)."""

    mode: str
    quantile: float
    target: int
    n_samples: int
    seed: int
    shapes: tuple[CatalogShape, ...]
    buy: dict[str, int]
    base_quantile_capacity: int
    projected_quantile_capacity: int
    total_cost: float
    lp_bound: float
    satisfiable: bool
    certified: bool
    uncertified_reason: str | None = None
    shadow_prices: dict[str, float] = field(default_factory=dict)
    demand_price: float | None = None
    drain: dict | None = None
    eval_ms: float = 0.0

    @property
    def status(self) -> str:
        return "certified" if self.certified else "uncertified"

    @property
    def gap_pct(self) -> float:
        if self.total_cost <= 0.0 or not np.isfinite(self.lp_bound):
            return 0.0
        return max(
            (self.total_cost - self.lp_bound) / self.total_cost * 100.0,
            0.0,
        )

    def to_wire(self) -> dict:
        out = {
            "mode": self.mode,
            "quantile": quantile_label(self.quantile),
            "target": self.target,
            "samples": self.n_samples,
            "seed": self.seed,
            "catalog": [s.to_wire() for s in self.shapes],
            "buy": [
                {
                    "shape": s.name,
                    "count": int(self.buy.get(s.name, 0)),
                    "unit_cost": s.unit_cost,
                    "cost": round(
                        s.unit_cost * self.buy.get(s.name, 0), 6
                    ),
                }
                for s in self.shapes
                if self.buy.get(s.name, 0)
            ],
            "nodes_bought": int(sum(self.buy.values())),
            "base_quantile_capacity": self.base_quantile_capacity,
            "projected_quantile_capacity": (
                self.projected_quantile_capacity
            ),
            "total_cost": round(self.total_cost, 6),
            "lp_bound": (
                round(self.lp_bound, 6)
                if np.isfinite(self.lp_bound)
                else None
            ),
            "gap_pct": round(self.gap_pct, 3),
            "satisfiable": self.satisfiable,
            "certified": self.certified,
            "status": self.status,
            "shadow_prices": {
                k: round(v, 6) for k, v in self.shadow_prices.items()
            },
            "demand_price": (
                None
                if self.demand_price is None
                else round(self.demand_price, 6)
            ),
        }
        if self.uncertified_reason:
            out["uncertified_reason"] = self.uncertified_reason
        if self.drain is not None:
            out["drain"] = self.drain
        return out


def _lp_bound(
    deficits: np.ndarray, fits: np.ndarray, costs: np.ndarray, need: int
) -> float:
    """The closed-form dual bound documented in the module docstring:
    k-th smallest per-sample single-constraint LP optimum."""
    s = deficits.shape[0]
    per_sample = np.zeros(s, dtype=np.float64)
    lifted = deficits > 0
    if lifted.any():
        with np.errstate(divide="ignore"):
            price = np.where(
                fits > 0, costs[:, None] / fits, np.inf
            ).min(axis=0)
        per_sample[lifted] = deficits[lifted] * price[lifted]
    return float(np.sort(per_sample)[min(max(need, 1), s) - 1])


def _greedy_mix(
    base: np.ndarray,
    fits: np.ndarray,
    shapes: tuple[CatalogShape, ...],
    q: float,
    target: int,
) -> dict[str, int] | None:
    """Add one node at a time, always the best exact marginal quantile
    gain per unit cost (progress-per-cost tiebreak: mean lift over the
    still-deficient samples).  Returns None when the target is
    unreachable within the catalog's bounds."""
    j_n = len(shapes)
    x = np.zeros(j_n, dtype=np.int64)
    totals = base.copy()
    budget = _MAX_TOTAL_NODES
    while _quantile_value(totals, q) < target and budget > 0:
        best = None
        deficient = totals < target
        for j in range(j_n):
            if x[j] >= shapes[j].max_count:
                continue
            cand = totals + fits[j]
            gain = _quantile_value(cand, q) - _quantile_value(totals, q)
            progress = float(fits[j][deficient].mean()) if (
                deficient.any()
            ) else float(fits[j].mean())
            score = (
                gain / shapes[j].unit_cost,
                progress / shapes[j].unit_cost,
                -shapes[j].unit_cost,
            )
            if best is None or score > best[0]:
                best = (score, j)
        if best is None or (
            best[0][0] <= 0 and best[0][1] <= 0
        ):
            return None  # no shape lifts anything: unreachable
        j = best[1]
        x[j] += 1
        totals += fits[j]
        budget -= 1
    if _quantile_value(totals, q) < target:
        return None
    return {shapes[j].name: int(x[j]) for j in range(j_n) if x[j]}


def _single_shape_plans(
    base: np.ndarray,
    fits: np.ndarray,
    shapes: tuple[CatalogShape, ...],
    q: float,
    target: int,
) -> list[dict[str, int]]:
    """Minimal feasible count per shape via binary search (capacity is
    monotone in the count)."""
    plans: list[dict[str, int]] = []
    for j, shape in enumerate(shapes):
        hi = shape.max_count
        if hi < 1:
            continue
        if _quantile_value(base + hi * fits[j], q) < target:
            continue
        lo = 1
        while lo < hi:
            mid = (lo + hi) // 2
            if _quantile_value(base + mid * fits[j], q) >= target:
                hi = mid
            else:
                lo = mid + 1
        plans.append({shape.name: lo})
    return plans


def _trim(
    plan: dict[str, int],
    base: np.ndarray,
    fits_by_name: dict[str, np.ndarray],
    shapes_by_name: dict[str, CatalogShape],
    q: float,
    target: int,
) -> dict[str, int]:
    """Drop greedy overshoot: walk shapes most-expensive-first and
    decrement while the exact quantile holds the target."""
    plan = dict(plan)
    totals = base.copy()
    for name, count in plan.items():
        totals = totals + count * fits_by_name[name]
    for name in sorted(
        plan, key=lambda n: -shapes_by_name[n].unit_cost
    ):
        while plan[name] > 0:
            cand = totals - fits_by_name[name]
            if _quantile_value(cand, q) < target:
                break
            plan[name] -= 1
            totals = cand
    return {n: c for n, c in plan.items() if c}


def _plan_cost(
    plan: dict[str, int], shapes_by_name: dict[str, CatalogShape]
) -> float:
    return float(
        sum(shapes_by_name[n].unit_cost * c for n, c in plan.items())
    )


def _shadow_report(
    plan: dict[str, int],
    shapes_by_name: dict[str, CatalogShape],
    cpu_s: int,
    mem_s: int,
) -> tuple[dict[str, float], float | None]:
    """Which resource binds the purchased capacity at the
    quantile-realizing sample, as count-weighted fractions, plus the
    marginal cost of one more replica there."""
    weights = {r: 0.0 for r in _RESOURCE_ORDER}
    total = 0
    demand_price = None
    for name, count in plan.items():
        shape = shapes_by_name[name]
        cpu_fit = shape.cpu_milli // max(cpu_s, 1)
        mem_fit = shape.mem_bytes // max(mem_s, 1)
        by = {"cpu": cpu_fit, "memory": mem_fit, "pods": shape.pods}
        binding = min(_RESOURCE_ORDER, key=lambda r: (by[r], _RESOURCE_ORDER.index(r)))
        weights[binding] += count
        total += count
        fit = min(by.values())
        if fit > 0:
            price = shape.unit_cost / fit
            if demand_price is None or price < demand_price:
                demand_price = price
    if total:
        weights = {r: w / total for r, w in weights.items()}
    return weights, demand_price


def _drain_analysis(
    snapshot: ClusterSnapshot,
    cpu: np.ndarray,
    mem: np.ndarray,
    mode: str,
    node_mask,
    q: float,
    target: int,
    *,
    max_nodes: int = 200_000,
    max_list: int = 20,
) -> dict:
    """The scale-down dual: per-node per-sample fits (pure numpy, the
    oracle arithmetic), nodes with zero contribution at EVERY sample
    (drainable for free — the stochastic zero shadow price), then a
    greedy surplus drain holding the exact quantile at ``target``.
    Every drained set is re-verified by exact recomputation before it
    is reported."""
    n = snapshot.n_nodes
    if n > max_nodes:
        return {
            "evaluated": False,
            "reason": f"{n} nodes exceeds the drain analysis cap "
            f"{max_nodes}",
        }
    fits = _per_node_fits(snapshot, cpu, mem, mode, node_mask)
    totals = fits.sum(axis=1, dtype=np.int64)
    zero = ~fits.any(axis=0)
    if node_mask is not None:
        zero &= np.asarray(node_mask, dtype=bool)  # masked-out ≠ drainable
    free_idx = np.flatnonzero(zero)
    # Oracle verification: removing the free set must not move ANY
    # sample's total (their columns are zero by construction — assert
    # it, because "verified drainable" is the contract, not a comment).
    active = totals - fits[:, free_idx].sum(axis=1, dtype=np.int64)
    verified_free = bool(np.array_equal(active, totals))
    drained: list[int] = []
    running = totals.copy()
    if verified_free:
        order = np.argsort(fits.sum(axis=0), kind="stable")
        for i in order:
            if zero[i]:
                continue
            cand = running - fits[:, i]
            if _quantile_value(cand, q) < target:
                continue
            running = cand
            drained.append(int(i))
    names = list(snapshot.names)
    return {
        "evaluated": True,
        "free_count": int(free_idx.shape[0]),
        "free_verified": verified_free,
        "free_nodes": [names[int(i)] for i in free_idx[:max_list]],
        "surplus_count": len(drained),
        "surplus_nodes": [names[i] for i in drained[:max_list]],
        "quantile_after_drain": (
            _quantile_value(running, q) if verified_free else None
        ),
    }


def _per_node_fits(
    snapshot: ClusterSnapshot,
    cpu_reqs: np.ndarray,
    mem_reqs: np.ndarray,
    mode: str,
    node_mask,
    chunk: int = 8,
) -> np.ndarray:
    """``[S, N]`` per-node fits with the exact oracle arithmetic of
    :func:`~..stochastic.car.fit_totals_numpy`, reduction omitted."""
    alloc_cpu_u = np.asarray(snapshot.alloc_cpu_milli, dtype=np.int64).astype(
        np.uint64
    )
    used_cpu_u = np.asarray(
        snapshot.used_cpu_req_milli, dtype=np.int64
    ).astype(np.uint64)
    alloc_mem = np.asarray(snapshot.alloc_mem_bytes, dtype=np.int64)
    used_mem = np.asarray(snapshot.used_mem_req_bytes, dtype=np.int64)
    alloc_pods = np.asarray(snapshot.alloc_pods, dtype=np.int64)
    pods_count = np.asarray(snapshot.pods_count, dtype=np.int64)
    healthy_b = np.asarray(snapshot.healthy, dtype=bool)
    cpu_reqs = np.asarray(cpu_reqs, dtype=np.int64)
    mem_reqs = np.asarray(mem_reqs, dtype=np.int64)
    mask = None if node_mask is None else np.asarray(node_mask, dtype=bool)
    s = cpu_reqs.shape[0]
    out = np.empty((s, alloc_cpu_u.shape[0]), dtype=np.int64)
    mem_head = alloc_mem - used_mem
    with np.errstate(over="ignore"):
        for lo in range(0, s, max(chunk, 1)):
            hi = min(lo + max(chunk, 1), s)
            cr = cpu_reqs[lo:hi].astype(np.uint64)[:, None]
            cr = np.maximum(cr, np.uint64(1))
            mr = mem_reqs[lo:hi][:, None]
            cpu_fit = np.where(
                alloc_cpu_u[None, :] <= used_cpu_u[None, :],
                np.uint64(0),
                (alloc_cpu_u[None, :] - used_cpu_u[None, :]) // cr,
            ).astype(np.int64)
            den = np.where(mr == 0, np.int64(1), mr)
            quot = mem_head[None, :] // den
            rem = mem_head[None, :] - quot * den
            fix = (rem != 0) & ((mem_head[None, :] < 0) != (den < 0))
            mem_fit = np.where(
                alloc_mem[None, :] <= used_mem[None, :],
                np.int64(0),
                quot + fix.astype(np.int64),
            )
            fit = np.minimum(cpu_fit, mem_fit)
            if mode == "reference":
                fit = np.where(
                    fit >= alloc_pods[None, :],
                    alloc_pods[None, :] - pods_count[None, :],
                    fit,
                )
            elif mode == "strict":
                slots = np.maximum(
                    alloc_pods[None, :] - pods_count[None, :], np.int64(0)
                )
                fit = np.maximum(np.minimum(fit, slots), np.int64(0))
                fit = np.where(healthy_b[None, :], fit, np.int64(0))
            else:
                raise ValueError(f"unknown mode {mode!r}")
            if mask is not None:
                fit = np.where(mask[None, :], fit, np.int64(0))
            out[lo:hi] = fit
    return out


def plan_capacity(
    snapshot: ClusterSnapshot,
    spec: StochasticSpec,
    catalog: tuple[CatalogShape, ...],
    *,
    target: int | None = None,
    quantile: float = 0.95,
    mode: str | None = None,
    node_mask=None,
    drain: bool = False,
) -> PlanResult:
    """Answer "cheapest node set restoring the q-quantile ≥ target".

    Draws the spec's samples (same seed streams as capacity-at-risk),
    evaluates the CURRENT base capacity as one production sweep
    dispatch, then plans over the catalog with exact integer
    evaluation: minimal single-shape plans by binary search, a greedy
    best-gain-per-cost mix, an overshoot trim — cheapest feasible plan
    wins.  Certification re-derives base totals AND shape columns with
    the pure-numpy oracle and confirms the purchase restores the
    quantile; see the module docstring for the contract and the
    ``lp_bound`` derivation.  ``target`` defaults to the spec's
    requested replicas; ``drain=True`` adds the scale-down analysis.
    """
    if not catalog:
        raise PlannerError("catalog wants at least one node shape")
    if not 0.0 < quantile < 1.0:
        raise PlannerError(
            f"quantile must be in (0, 1), got {quantile!r}"
        )
    mode = mode or snapshot.semantics
    target = int(spec.replicas if target is None else target)
    if target < 1:
        raise PlannerError(f"target must be >= 1, got {target}")
    t0 = time.perf_counter()
    n = spec.n_samples()
    cpu = sample_usage(spec.cpu, n, sample_key(spec.seed, 0))
    mem = sample_usage(spec.memory, n, sample_key(spec.seed, 1))
    grid = ScenarioGrid(
        cpu_request_milli=cpu,
        mem_request_bytes=mem,
        replicas=np.full(n, int(spec.replicas), dtype=np.int64),
    )
    base = np.asarray(
        sweep_snapshot(snapshot, grid, mode=mode, node_mask=node_mask)[0],
        dtype=np.int64,
    )
    fits = np.stack([_fresh_node_fits(s, cpu, mem) for s in catalog])
    costs = np.array([s.unit_cost for s in catalog], dtype=np.float64)
    shapes_by_name = {s.name: s for s in catalog}
    fits_by_name = {s.name: fits[j] for j, s in enumerate(catalog)}

    base_q = _quantile_value(base, quantile)
    deficits = np.maximum(target - base, 0).astype(np.float64)
    need = n - quantile_index(n, quantile)
    bound = _lp_bound(deficits, fits.astype(np.float64), costs, need)

    candidates = _single_shape_plans(base, fits, catalog, quantile, target)
    mix = _greedy_mix(base, fits, catalog, quantile, target)
    if mix is not None:
        candidates.append(mix)
    candidates = [
        _trim(p, base, fits_by_name, shapes_by_name, quantile, target)
        for p in candidates
    ]
    plan: dict[str, int] = {}
    satisfiable = base_q >= target
    if base_q < target and candidates:
        plan = min(
            candidates,
            key=lambda p: (_plan_cost(p, shapes_by_name), sorted(p.items())),
        )
        satisfiable = True
    cost = _plan_cost(plan, shapes_by_name)

    # -- cannot-lie certification: pure-numpy re-derivation ------------
    certified = False
    reason: str | None = None
    projected_q = base_q
    if not satisfiable:
        reason = (
            f"target {target} unreachable within the catalog's "
            "max_count bounds"
        )
    else:
        base_oracle = fit_totals_numpy(
            snapshot.alloc_cpu_milli,
            snapshot.alloc_mem_bytes,
            snapshot.alloc_pods,
            snapshot.used_cpu_req_milli,
            snapshot.used_mem_req_bytes,
            snapshot.pods_count,
            snapshot.healthy,
            cpu,
            mem,
            mode=mode,
            node_mask=node_mask,
        )
        if not np.array_equal(base_oracle, base):
            reason = (
                "dispatch/oracle divergence on the base sweep — the "
                "plan was proposed from totals the oracle disputes"
            )
        else:
            columns_ok = all(
                np.array_equal(
                    fits[j], _oracle_shape_fits(s, cpu, mem, mode)
                )
                for j, s in enumerate(catalog)
            )
            if not columns_ok:
                reason = (
                    "catalog fit column disagrees with the numpy oracle"
                )
            else:
                projected = base_oracle.copy()
                for name, count in plan.items():
                    projected = projected + count * fits_by_name[name]
                projected_q = _quantile_value(projected, quantile)
                if projected_q >= target:
                    certified = True
                else:
                    reason = (
                        f"exact re-evaluation reaches only "
                        f"{projected_q} < target {target}"
                    )
    if satisfiable and plan:
        projected_totals = base.copy()
        for name, count in plan.items():
            projected_totals = projected_totals + count * fits_by_name[name]
        projected_q = _quantile_value(projected_totals, quantile)

    s_idx = np.argsort(base, kind="stable")[quantile_index(n, quantile)]
    shadow, demand_price = _shadow_report(
        plan or {s.name: 1 for s in catalog},
        shapes_by_name,
        int(cpu[s_idx]),
        int(mem[s_idx]),
    )
    drain_report = None
    if drain:
        drain_report = _drain_analysis(
            snapshot, np.asarray(cpu), np.asarray(mem), mode, node_mask,
            quantile, min(target, base_q),
        )
    return PlanResult(
        mode=mode,
        quantile=quantile,
        target=target,
        n_samples=n,
        seed=spec.seed,
        shapes=catalog,
        buy=plan,
        base_quantile_capacity=base_q,
        projected_quantile_capacity=projected_q,
        total_cost=cost,
        lp_bound=bound if satisfiable else float("inf"),
        satisfiable=satisfiable,
        certified=certified,
        uncertified_reason=reason,
        shadow_prices=shadow,
        demand_price=demand_price,
        drain=drain_report,
        eval_ms=(time.perf_counter() - t0) * 1e3,
    )


def apply_plan(
    snapshot: ClusterSnapshot,
    catalog: tuple[CatalogShape, ...],
    buy: dict[str, int],
) -> ClusterSnapshot:
    """The purchase applied: a new snapshot with ``buy``'s nodes
    appended as empty, healthy rows (what the cluster looks like after
    the autoscaler acts) — the recovery half of the forecast funnel."""
    shapes_by_name = {s.name: s for s in catalog}
    names = list(snapshot.names)
    cols = {
        f: [int(v) for v in np.asarray(getattr(snapshot, f))]
        for f in (
            "alloc_cpu_milli",
            "alloc_mem_bytes",
            "alloc_pods",
            "used_cpu_req_milli",
            "used_cpu_lim_milli",
            "used_mem_req_bytes",
            "used_mem_lim_bytes",
            "pods_count",
        )
    }
    healthy = [bool(v) for v in np.asarray(snapshot.healthy)]
    labels = list(snapshot.labels)
    taints = list(snapshot.taints)
    extended = {
        r: (np.asarray(a), np.asarray(u))
        for r, (a, u) in snapshot.extended.items()
    }
    added = 0
    for shape_name in sorted(buy):
        count = int(buy[shape_name])
        shape = shapes_by_name.get(shape_name)
        if shape is None:
            raise PlannerError(
                f"buy names unknown catalog shape {shape_name!r}"
            )
        for k in range(count):
            names.append(f"{shape.name}-plan-{k}")
            cols["alloc_cpu_milli"].append(shape.cpu_milli)
            cols["alloc_mem_bytes"].append(shape.mem_bytes)
            cols["alloc_pods"].append(shape.pods)
            for f in (
                "used_cpu_req_milli",
                "used_cpu_lim_milli",
                "used_mem_req_bytes",
                "used_mem_lim_bytes",
                "pods_count",
            ):
                cols[f].append(0)
            healthy.append(True)
            if labels:
                labels.append({})
            if taints:
                taints.append([])
            added += 1
    if extended and added:
        extended = {
            r: (
                np.concatenate([a, np.zeros(added, dtype=np.int64)]),
                np.concatenate([u, np.zeros(added, dtype=np.int64)]),
            )
            for r, (a, u) in extended.items()
        }
    return ClusterSnapshot(
        names=names,
        alloc_cpu_milli=np.asarray(cols["alloc_cpu_milli"], dtype=np.int64),
        alloc_mem_bytes=np.asarray(cols["alloc_mem_bytes"], dtype=np.int64),
        alloc_pods=np.asarray(cols["alloc_pods"], dtype=np.int64),
        used_cpu_req_milli=np.asarray(
            cols["used_cpu_req_milli"], dtype=np.int64
        ),
        used_cpu_lim_milli=np.asarray(
            cols["used_cpu_lim_milli"], dtype=np.int64
        ),
        used_mem_req_bytes=np.asarray(
            cols["used_mem_req_bytes"], dtype=np.int64
        ),
        used_mem_lim_bytes=np.asarray(
            cols["used_mem_lim_bytes"], dtype=np.int64
        ),
        pods_count=np.asarray(cols["pods_count"], dtype=np.int64),
        healthy=np.asarray(healthy, dtype=bool),
        semantics=snapshot.semantics,
        extended=extended,
        labels=labels,
        taints=taints,
        node_log=list(snapshot.node_log),
        pod_cpu_errs=list(snapshot.pod_cpu_errs),
    )
