"""Scheduling-constraint masks (BASELINE config 5).

The reference ignores taints, selectors, and affinity entirely — a pod "fits"
anywhere resources allow.  Real scheduling gates placement on them, and the
TPU-native encoding is simple: every constraint family reduces to a boolean
node mask ``[N]`` (or per-scenario ``[S, N]``) built host-side from snapshot
metadata, ANDed together, and applied inside the fit kernel — a free
elementwise op on device.

Implemented families (the hard predicates kube-scheduler enforces):

* taints × tolerations (``NoSchedule``/``NoExecute``; ``PreferNoSchedule`` is
  a soft preference and is ignored, as the scheduler's filter phase does);
* ``nodeSelector`` (exact label subset match);
* node affinity ``requiredDuringSchedulingIgnoredDuringExecution`` match
  expressions (``In``/``NotIn``/``Exists``/``DoesNotExist``/``Gt``/``Lt``);
* pod anti-affinity against *existing* pods by label selector over the
  hostname topology, plus self-anti-affinity (replicas of the scenario pod
  repel each other → at most one replica per node, a per-node fit clamp
  rather than a mask).
"""

from __future__ import annotations

import numpy as np

from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot

__all__ = [
    "tolerations_mask",
    "node_selector_mask",
    "node_affinity_mask",
    "anti_affinity_existing_mask",
    "combine_masks",
    "implicit_taint_mask",
]

_HARD_EFFECTS = ("NoSchedule", "NoExecute")


def _toleration_matches(tol: dict, taint: dict) -> bool:
    """Kubernetes toleration-matches-taint predicate.

    ``operator: Exists`` with an empty key tolerates every taint; otherwise
    keys must match, ``Equal`` (the default operator) also requires value
    equality, and an empty toleration effect matches all effects.
    """
    t_effect = tol.get("effect", "")
    if t_effect and t_effect != taint.get("effect", ""):
        return False
    op = tol.get("operator", "Equal")
    key = tol.get("key", "")
    if op == "Exists":
        return key == "" or key == taint.get("key", "")
    return key == taint.get("key", "") and tol.get("value", "") == taint.get(
        "value", ""
    )


def tolerations_mask(
    snapshot: ClusterSnapshot, tolerations: list[dict] | None
) -> np.ndarray:
    """``mask[n]`` — every hard taint on node ``n`` is tolerated."""
    tolerations = tolerations or []
    mask = np.ones(snapshot.n_nodes, dtype=np.bool_)
    for i, taints in enumerate(snapshot.taints):
        for taint in taints or []:
            if taint.get("effect") not in _HARD_EFFECTS:
                continue
            if not any(_toleration_matches(t, taint) for t in tolerations):
                mask[i] = False
                break
    return mask


def node_selector_mask(
    snapshot: ClusterSnapshot, node_selector: dict | None
) -> np.ndarray:
    """``mask[n]`` — node labels contain every (key, value) of the selector."""
    if not node_selector:
        return np.ones(snapshot.n_nodes, dtype=np.bool_)
    mask = np.empty(snapshot.n_nodes, dtype=np.bool_)
    for i, labels in enumerate(snapshot.labels):
        labels = labels or {}
        mask[i] = all(labels.get(k) == v for k, v in node_selector.items())
    return mask


def _expr_matches(labels: dict, expr: dict) -> bool:
    key = expr.get("key", "")
    op = expr.get("operator", "In")
    values = expr.get("values", [])
    present = key in labels
    if op == "In":
        return present and labels[key] in values
    if op == "NotIn":
        return not present or labels[key] not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    if op in ("Gt", "Lt"):
        if not present or not values:
            return False
        try:
            label_val = int(labels[key])
            bound = int(values[0])
        except ValueError:
            return False
        return label_val > bound if op == "Gt" else label_val < bound
    raise ValueError(f"unknown match-expression operator {op!r}")


def _field_matches(node_name: str, expr: dict) -> bool:
    """``matchFields`` expression against the one field Kubernetes
    supports: ``metadata.name`` with ``In``/``NotIn`` (the DaemonSet
    controller's node-pinning pattern).  Anything else is a malformed
    spec kube-scheduler rejects — raise, like :func:`_expr_matches`
    does for unknown operators, never silently match nothing."""
    key = expr.get("key")
    if key != "metadata.name":
        raise ValueError(
            f"unsupported matchFields key {key!r} (only metadata.name)"
        )
    op = expr.get("operator", "In")
    values = expr.get("values", [])
    if op == "In":
        return node_name in values
    if op == "NotIn":
        return node_name not in values
    raise ValueError(f"unknown matchFields operator {op!r}")


def node_affinity_mask(
    snapshot: ClusterSnapshot, node_selector_terms: list[dict] | None
) -> np.ndarray:
    """Required node-affinity: terms OR-ed; a term's ``matchExpressions``
    AND ``matchFields`` must ALL hold (kube-scheduler ANDs the two lists).

    An empty term (neither list) matches NO nodes — kube-scheduler's
    nodeaffinity helper treats a nil term as selecting nothing, not as a
    match-everything wildcard.  ``matchFields`` supports the one field
    the API defines, ``metadata.name`` (the DaemonSet controller's
    node-pinning pattern).
    """
    if not node_selector_terms:
        return np.ones(snapshot.n_nodes, dtype=np.bool_)

    def term_matches(term: dict, labels: dict, node_name: str) -> bool:
        exprs = term.get("matchExpressions") or []
        fields = term.get("matchFields") or []
        if not exprs and not fields:
            return False  # nil term selects nothing
        return all(_expr_matches(labels, e) for e in exprs) and all(
            _field_matches(node_name, f) for f in fields
        )

    mask = np.zeros(snapshot.n_nodes, dtype=np.bool_)
    for i, labels in enumerate(snapshot.labels):
        labels = labels or {}
        mask[i] = any(
            term_matches(term, labels, snapshot.names[i])
            for term in node_selector_terms
        )
    return mask


def anti_affinity_existing_mask(
    snapshot: ClusterSnapshot,
    fixture: dict,
    label_selector: dict,
    *,
    namespace: str | None = None,
) -> np.ndarray:
    """Anti-affinity vs existing pods: exclude nodes hosting a matching pod.

    Hostname topology (the overwhelmingly common case): a node is infeasible
    if any non-terminated pod already on it carries all the selector labels.
    Label data comes from the fixture's pods (``labels`` key, optional).

    ``namespace`` scopes the match the way a real ``PodAffinityTerm`` with
    no ``namespaces`` field does — to the INCOMING pod's own namespace
    (an ``app=db`` pod in another namespace does not repel).  ``None``
    matches cluster-wide, for what-if specs that model no namespace
    (documented divergence: kube-scheduler has no namespace-less pods).

    Hostname-topology identity routes through the topology subsystem's
    shared helper (:func:`~.topology.model.node_name_index`): a pod
    whose ``nodeName`` resolves to no snapshot row is EXCLUDED from the
    topology (it repels nothing), and duplicate names keep the last row
    — both pinned by ``tests/test_topology_gang.py`` so this mask and
    the gang model share one identity rule.
    """
    from kubernetesclustercapacity_tpu.topology.model import (
        node_name_index,
    )

    node_index = node_name_index(snapshot)
    mask = np.ones(snapshot.n_nodes, dtype=np.bool_)
    for pod in fixture.get("pods", []):
        if pod.get("phase") in ("Succeeded", "Failed"):
            continue
        if namespace is not None and pod.get("namespace", "") != namespace:
            continue
        i = node_index.get(pod.get("nodeName", ""))
        if i is None:
            continue
        pod_labels = pod.get("labels", {}) or {}
        if all(pod_labels.get(k) == v for k, v in label_selector.items()):
            mask[i] = False
    return mask


def combine_masks(*masks: np.ndarray | None) -> np.ndarray | None:
    """AND together any number of optional ``[N]`` masks (None = all-true)."""
    out = None
    for m in masks:
        if m is None:
            continue
        out = m.copy() if out is None else (out & m)
    return out


def implicit_taint_mask(snap: ClusterSnapshot) -> np.ndarray | None:
    """Strict semantics honors hard taints even on plain-flag queries (an
    untolerating pod never lands on a NoSchedule node — the eligibility
    role of the reference's health filter, ``ClusterCapacity.go:212-219``,
    extended to taints).  ``None`` when nothing is tainted or semantics is
    reference (the reference ignores taints entirely).

    Every strict surface that evaluates a plain flag/grid spec — service
    ``fit`` AND ``sweep``, the CLI ``-grid`` path — must apply this same
    mask, or identical specs would report different totals depending on
    which surface answered.  Depends only on the snapshot: compute once
    per snapshot swap, not per request (the taint walk is O(N) Python).
    """
    if snap.semantics != "strict" or not any(snap.taints or []):
        return None
    return tolerations_mask(snap, [])
