"""Stdlib-only Kubernetes API client — live-cluster snapshot ingestion.

The reference bootstraps ``k8s.io/client-go`` from a kubeconfig
(``ClusterCapacity.go:88-97``, ``$HOME`` fallback at ``:152-157``) and then
issues ``1 + 2N + ΣP`` sequential requests (SURVEY.md §3.4).  This module is
the new framework's C2 equivalent with two deliberate differences:

* **no Kubernetes client dependency** — TLS, auth, transport, and
  pagination are pure stdlib (``ssl``/``http.client``); the only import
  beyond the stdlib is PyYAML for the kubeconfig file itself (the optional
  ``kubernetes`` package, when present, is used instead purely for its
  broader auth-provider support);
* **exactly TWO paginated List calls** — ``GET /api/v1/nodes`` and
  ``GET /api/v1/pods`` — then all packing is local, fixing the reference's
  N+1 query pattern.

Auth support: bearer token (inline or ``tokenFile``), client certificates
(inline base64 ``*-data`` or file paths), HTTP basic auth, ``exec``
credential plugins (the EKS/GKE pattern), and the ``oidc`` auth-provider
stanza including token *refresh* (a fresh id-token is fetched through the
issuer's discovery + token endpoints when the cached one is expired).
TLS verifies against the cluster's ``certificate-authority(-data)``
unless ``insecure-skip-tls-verify`` is set.  ``HTTPS_PROXY`` /
``NO_PROXY`` are honored for the apiserver connection (CONNECT
tunneling; the OIDC refresh request goes through ``urllib`` which obeys
them natively).

Known limits vs client-go's stack (recorded in PARITY.md "Architecture
divergences"): the legacy ``azure``/``gcp`` auth-provider stanzas are
rejected with a pointer to exec plugins (client-go removed them in
v1.26), and plain-``http`` apiservers do not proxy (real apiservers are
https).
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import ssl
import subprocess
import tempfile
import time
import urllib.parse
import urllib.request

__all__ = [
    "KubeConfigError",
    "KubeAPIError",
    "KubeConfig",
    "KubeClient",
    "default_kubeconfig_path",
    "default_kubeconfig_paths",
    "live_fixture",
    "node_to_fixture",
    "pod_to_fixture",
]


# Watch liveness watchdog: client read timeout = timeoutSeconds + this.
# The server must end the window within timeoutSeconds; the grace covers
# scheduling/transit slack before a silent dead peer is declared.
_WATCH_GRACE_SECONDS = 30.0


class KubeConfigError(ValueError):
    """Unusable kubeconfig (missing file/context/credentials)."""


class KubeAPIError(RuntimeError):
    """Non-2xx apiserver response or transport failure.

    ``status`` carries the HTTP status (or a watch ERROR event's ``code``)
    when one exists — consumers distinguish e.g. 410 Gone (relist
    required) from transport loss (re-watch suffices).
    """

    def __init__(self, message: str, *, status: int | None = None) -> None:
        super().__init__(message)
        self.status = status


def default_kubeconfig_paths() -> list[str]:
    """``$KUBECONFIG`` entries if set (all of them — client-go merges the
    list), else ``$HOME/.kube/config`` with the reference's HOME/USERPROFILE
    fallback (``ClusterCapacity.go:152-157``)."""
    env = os.environ.get("KUBECONFIG")
    if env:
        return [p for p in env.split(os.pathsep) if p]
    home = os.environ.get("HOME") or os.environ.get("USERPROFILE") or ""
    return [os.path.join(home, ".kube", "config")] if home else []


def default_kubeconfig_path() -> str:
    """First default path entry — display/single-file use; :meth:`KubeConfig.
    load` merges every entry like client-go does."""
    paths = default_kubeconfig_paths()
    return paths[0] if paths else ""


def _b64_or_file(data_b64: str | None, path: str | None, what: str) -> bytes | None:
    if data_b64:
        try:
            return base64.b64decode(data_b64)
        except Exception as e:
            raise KubeConfigError(f"invalid base64 in {what}-data: {e}") from e
    if path:
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError as e:
            raise KubeConfigError(f"cannot read {what} file {path}: {e}") from e
    return None


class KubeConfig:
    """The subset of a kubeconfig one context needs: server + TLS + creds."""

    def __init__(
        self,
        server: str,
        *,
        ca_pem: bytes | None = None,
        insecure: bool = False,
        client_cert_pem: bytes | None = None,
        client_key_pem: bytes | None = None,
        token: str | None = None,
        username: str | None = None,
        password: str | None = None,
    ):
        self.server = server.rstrip("/")
        self.ca_pem = ca_pem
        self.insecure = insecure
        self.client_cert_pem = client_cert_pem
        self.client_key_pem = client_key_pem
        self.token = token
        self.username = username
        self.password = password

    @classmethod
    def load(cls, path: str | None = None, context: str | None = None) -> "KubeConfig":
        """Parse a kubeconfig file and resolve one context to credentials."""
        try:
            import yaml
        except ImportError as e:  # pragma: no cover - yaml is baked in here
            raise KubeConfigError(
                "live-cluster ingestion needs PyYAML to read the kubeconfig "
                "(pip install pyyaml), or use snapshot_from_fixture()/"
                "load_snapshot() for offline operation"
            ) from e

        # client-go merge semantics: an explicit path is a single file
        # (missing → error); $KUBECONFIG lists several, missing entries are
        # skipped, and for every map (contexts/clusters/users by name,
        # current-context) the FIRST file to define a key wins.
        if path:
            paths = [path]
        else:
            paths = default_kubeconfig_paths()
        docs: list[tuple[str, dict]] = []
        for p in paths:
            if not os.path.exists(p):
                if path:  # explicit single file must exist
                    raise KubeConfigError(f"kubeconfig not found: {p!r}")
                continue
            with open(p) as f:
                try:
                    docs.append((p, yaml.safe_load(f) or {}))
                except yaml.YAMLError as e:
                    raise KubeConfigError(
                        f"cannot parse kubeconfig {p}: {e}"
                    ) from e
        if not docs:
            raise KubeConfigError(
                f"kubeconfig not found: {paths if paths else '(no path)'}"
            )

        def by_name(section: str, name: str) -> tuple[dict, str, dict]:
            """First entry named ``name`` across the merged files — returns
            ``(body, owning_path, owning_doc)`` so credential write-backs
            land in the file that defined the stanza."""
            for p, d in docs:
                for entry in d.get(section) or []:
                    if entry.get("name") == name:
                        return entry.get(section.rstrip("s"), {}) or {}, p, d
            raise KubeConfigError(
                f"kubeconfig has no {section[:-1]} named {name!r}"
            )

        ctx_name = context or next(
            (d.get("current-context") for _, d in docs
             if d.get("current-context")),
            None,
        )
        if not ctx_name:
            raise KubeConfigError("kubeconfig has no current-context")
        ctx, _, _ = by_name("contexts", ctx_name)
        cluster, _, _ = by_name("clusters", ctx.get("cluster", ""))
        user, user_path, user_doc = (
            by_name("users", ctx.get("user", ""))
            if ctx.get("user")
            else ({}, docs[0][0], docs[0][1])
        )

        server = cluster.get("server")
        if not server:
            raise KubeConfigError(f"context {ctx_name!r}: cluster has no server")

        token = user.get("token")
        if not token and user.get("tokenFile"):
            token = _b64_or_file(None, user["tokenFile"], "token")
            token = token.decode().strip() if token else None
        if not token and user.get("exec"):
            token = _exec_credential_token(user["exec"])
        # The auth-provider stanza is consulted only when no other working
        # credential exists: a leftover legacy stanza next to client certs
        # or basic auth (common in old GKE kubeconfigs) must not block a
        # cluster that is otherwise reachable.
        has_cert = bool(
            user.get("client-certificate-data")
            or user.get("client-certificate")
        )
        has_basic = (
            user.get("username") is not None
            and user.get("password") is not None
        )
        if (
            not token
            and not has_cert
            and not has_basic
            and user.get("auth-provider")
        ):
            provider = user["auth-provider"] or {}
            name = provider.get("name")
            if name == "oidc":

                def _persist(new_id: str, new_refresh: str | None) -> None:
                    # client-go's oidc plugin persists rotated tokens back
                    # into the kubeconfig; IdPs with refresh-token rotation
                    # invalidate the old one on first use, so dropping the
                    # rotation would brick every later run.  `provider` is
                    # a live reference into the FILE that defined the user
                    # stanza (`user_doc`/`user_path` — under $KUBECONFIG
                    # merging that may not be the first file).  Write
                    # atomically (temp file + rename in the same
                    # directory): an in-place truncating write that dies
                    # mid-dump would destroy the kubeconfig — which holds
                    # credentials for every cluster — with the old refresh
                    # token already consumed server-side.
                    block = provider.setdefault("config", {})
                    block["id-token"] = new_id
                    if new_refresh:
                        block["refresh-token"] = new_refresh
                    try:
                        d = os.path.dirname(os.path.abspath(user_path))
                        fd, tmp = tempfile.mkstemp(
                            dir=d, prefix=".kubeconfig-"
                        )
                        try:
                            with os.fdopen(fd, "w") as f:
                                yaml.safe_dump(user_doc, f)
                            os.replace(tmp, user_path)
                        except BaseException:
                            os.unlink(tmp)
                            raise
                    except OSError as e:
                        # Read-only kubeconfig: this run still gets the
                        # fresh token, but a rotated refresh token is now
                        # LOST — say so, or the next run's invalid_grant
                        # is undiagnosable.
                        import sys

                        print(
                            "warning: could not persist refreshed OIDC "
                            f"tokens to {user_path}: {e} (if your IdP "
                            "rotates refresh tokens, the next run will "
                            "need to re-authenticate)",
                            file=sys.stderr,
                        )

                token = _oidc_id_token(
                    provider.get("config") or {}, persist=_persist
                )
            else:
                raise KubeConfigError(
                    f"unsupported auth-provider {name!r} (the legacy "
                    "azure/gcp providers were removed from client-go in "
                    "v1.26 — migrate the kubeconfig to an exec plugin)"
                )

        client_cert_pem = _b64_or_file(
            user.get("client-certificate-data"),
            user.get("client-certificate"),
            "client-certificate",
        )
        client_key_pem = _b64_or_file(
            user.get("client-key-data"), user.get("client-key"), "client-key"
        )
        if bool(client_cert_pem) != bool(client_key_pem):
            # A half-present mTLS credential must fail loudly (client-go:
            # "client-cert specified without client-key") — silently
            # connecting anonymously turns a config typo into an opaque
            # 401 from the apiserver.
            have, missing = (
                ("client-certificate", "client-key")
                if client_cert_pem
                else ("client-key", "client-certificate")
            )
            raise KubeConfigError(
                f"kubeconfig user has {have} but no {missing}"
            )
        return cls(
            server,
            ca_pem=_b64_or_file(
                cluster.get("certificate-authority-data"),
                cluster.get("certificate-authority"),
                "certificate-authority",
            ),
            insecure=bool(cluster.get("insecure-skip-tls-verify")),
            client_cert_pem=client_cert_pem,
            client_key_pem=client_key_pem,
            token=token,
            username=user.get("username"),
            password=user.get("password"),
        )

    def ssl_context(self) -> ssl.SSLContext:
        # A kubeconfig CA is the ONLY trust root (client-go semantics):
        # create_default_context(cadata=...) skips the system store, so a
        # publicly-trusted interception cert for the apiserver host fails
        # closed instead of silently receiving the bearer credentials.
        if self.ca_pem and not self.insecure:
            ctx = ssl.create_default_context(cadata=_cadata(self.ca_pem))
        else:
            ctx = ssl.create_default_context()
        if self.insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.client_cert_pem and self.client_key_pem:
            # load_cert_chain only takes paths; stage the PEMs in a private
            # temp dir for the duration of the load.
            with tempfile.TemporaryDirectory() as d:
                cert_p = os.path.join(d, "client.crt")
                key_p = os.path.join(d, "client.key")
                with open(cert_p, "wb") as f:
                    f.write(self.client_cert_pem)
                with open(key_p, "wb") as f:
                    f.write(self.client_key_pem)
                os.chmod(key_p, 0o600)
                ctx.load_cert_chain(cert_p, key_p)
        return ctx

    def auth_headers(self) -> dict:
        if self.token:
            return {"Authorization": f"Bearer {self.token}"}
        if self.username is not None and self.password is not None:
            basic = base64.b64encode(
                f"{self.username}:{self.password}".encode()
            ).decode()
            return {"Authorization": f"Basic {basic}"}
        return {}


def _cadata(ca: bytes):
    """``load_verify_locations``-ready CA material: PEM decodes to str,
    anything undecodable is passed as bytes (DER) — never an uncaught
    UnicodeDecodeError for a Windows-exported ``.cer``."""
    try:
        return ca.decode()
    except UnicodeDecodeError:
        return ca


def _exec_credential_token(spec: dict) -> str:
    """Run a client-go ``exec`` credential plugin and return its token."""
    cmd = [spec.get("command", "")] + list(spec.get("args") or [])
    env = dict(os.environ)
    for pair in spec.get("env") or []:
        env[pair.get("name", "")] = pair.get("value", "")
    # Always OVERWRITE (client-go does): a stale KUBERNETES_EXEC_INFO
    # inherited from the parent environment must not steer the plugin to
    # another cluster/apiVersion.
    env["KUBERNETES_EXEC_INFO"] = (
        json.dumps(
            {
                "apiVersion": spec.get(
                    "apiVersion", "client.authentication.k8s.io/v1"
                ),
                "kind": "ExecCredential",
                "spec": {"interactive": False},
            }
        )
    )
    try:
        out = subprocess.run(
            cmd, env=env, capture_output=True, timeout=60, check=True
        ).stdout
        cred = json.loads(out)
        token = cred.get("status", {}).get("token")
    except (OSError, subprocess.SubprocessError, ValueError) as e:
        # The plugin's own stderr is the actionable diagnostic ("Unable to
        # locate credentials...") — client-go passes it through; so do we.
        stderr = getattr(e, "stderr", b"") or b""
        detail = stderr.decode(errors="replace").strip()
        raise KubeConfigError(
            "exec credential plugin failed: "
            f"{e}{': ' + detail if detail else ''}"
        ) from e
    if not token:
        raise KubeConfigError("exec credential plugin returned no status.token")
    return str(token)


def _jwt_expired(token: str, *, skew_s: float = 30.0) -> bool:
    """True iff the JWT's ``exp`` claim is within ``skew_s`` of now.

    Claims are decoded WITHOUT signature verification — expiry here only
    decides whether to spend a refresh round-trip (client-go's oidc plugin
    does the same); the apiserver is the party that verifies the token.
    A token that does not parse as a JWT is treated as expired (refresh).
    """
    try:
        payload_b64 = token.split(".")[1]
        payload_b64 += "=" * (-len(payload_b64) % 4)
        claims = json.loads(base64.urlsafe_b64decode(payload_b64))
        exp = float(claims["exp"])
    except (IndexError, KeyError, ValueError, TypeError):
        return True
    return exp - skew_s <= time.time()


def _oidc_ssl_context(cfg: dict) -> ssl.SSLContext:
    ca = _b64_or_file(
        cfg.get("idp-certificate-authority-data"),
        cfg.get("idp-certificate-authority"),
        "idp-certificate-authority",
    )
    if ca:  # pinned: the idp CA is the only root (see ssl_context)
        return ssl.create_default_context(cadata=_cadata(ca))
    return ssl.create_default_context()


def _oidc_http_json(
    url: str, ctx: ssl.SSLContext, data: bytes | None = None
) -> dict:
    """GET/POST JSON from the identity provider (urllib honors
    HTTP(S)_PROXY/NO_PROXY natively, matching the transport the refreshed
    token will ultimately ride)."""
    req = urllib.request.Request(
        url,
        data=data,
        headers=(
            {"Content-Type": "application/x-www-form-urlencoded"}
            if data is not None
            else {}
        ),
    )
    try:
        with urllib.request.urlopen(req, timeout=30, context=ctx) as resp:
            return json.loads(resp.read())
    except (OSError, ValueError) as e:
        raise KubeConfigError(f"OIDC request to {url} failed: {e}") from e


def _oidc_id_token(cfg: dict, persist=None) -> str:
    """client-go's ``oidc`` auth-provider: cached id-token, refreshed when
    expired via OIDC discovery + the token endpoint.

    ``persist(new_id_token, new_refresh_token_or_None)`` is invoked after a
    successful refresh so the caller can write rotated tokens back to the
    kubeconfig (rotation-enabled IdPs invalidate the consumed refresh
    token; without write-back every later run would fail invalid_grant).
    """
    id_token = cfg.get("id-token")
    if id_token and not _jwt_expired(str(id_token)):
        return str(id_token)
    issuer = (cfg.get("idp-issuer-url") or "").rstrip("/")
    refresh = cfg.get("refresh-token")
    if not issuer or not refresh:
        raise KubeConfigError(
            "oidc auth-provider: id-token expired or absent and no "
            "idp-issuer-url + refresh-token to refresh with"
        )
    ctx = _oidc_ssl_context(cfg)
    discovery = _oidc_http_json(
        issuer + "/.well-known/openid-configuration", ctx
    )
    endpoint = discovery.get("token_endpoint")
    if not endpoint:
        raise KubeConfigError(
            "oidc auth-provider: issuer discovery has no token_endpoint"
        )
    # Empty client_id/client_secret are OMITTED, not sent blank: strict
    # IdPs treat a present client_secret as secret-based client auth and
    # reject public clients (x/oauth2, which client-go uses, omits too).
    fields = {
        "grant_type": "refresh_token",
        "refresh_token": refresh,
        "client_id": cfg.get("client-id"),
        "client_secret": cfg.get("client-secret"),
    }
    form = urllib.parse.urlencode(
        {k: v for k, v in fields.items() if v}
    ).encode()
    tokens = _oidc_http_json(endpoint, ctx, data=form)
    fresh = tokens.get("id_token")
    if not fresh:
        raise KubeConfigError(
            "oidc auth-provider: token endpoint returned no id_token"
        )
    if persist is not None:
        persist(str(fresh), tokens.get("refresh_token"))
    return str(fresh)


def _proxy_for(scheme: str, host: str, port: int) -> str | None:
    """The proxy URL to tunnel through, or None (honors NO_PROXY).

    The bypass probe carries the port: urllib only matches a ported
    NO_PROXY entry (``api.example:6443``) when the probe string does too.
    """
    try:
        if urllib.request.proxy_bypass(f"{host}:{port}"):
            return None
    except OSError:  # pragma: no cover - platform lookup failure
        pass
    return urllib.request.getproxies().get(scheme)


class KubeClient:
    """Minimal apiserver GET client with pagination over a kubeconfig."""

    def __init__(self, config: KubeConfig, *, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        u = urllib.parse.urlsplit(config.server)
        if u.scheme not in ("http", "https"):
            raise KubeConfigError(f"unsupported server scheme: {config.server!r}")
        self._scheme = u.scheme
        self._host = u.hostname or ""
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._prefix = u.path.rstrip("/")
        self._ssl = config.ssl_context() if u.scheme == "https" else None
        self._conn: http.client.HTTPConnection | None = None

    def _connect(
        self, *, timeout: float | None = -1.0
    ) -> http.client.HTTPConnection:
        if timeout == -1.0:
            timeout = self.timeout
        if self._scheme == "https":
            proxy = _proxy_for("https", self._host, self._port)
            if proxy:
                # CONNECT tunnel: TCP (+ optional basic auth) to the proxy,
                # then TLS end-to-end to the apiserver through it — the
                # proxy never sees plaintext.
                pu = urllib.parse.urlsplit(proxy)
                if not pu.hostname:  # "host:port" with no scheme
                    pu = urllib.parse.urlsplit("http://" + proxy)
                if pu.scheme == "https":
                    # set_tunnel sends the CONNECT in plaintext before any
                    # TLS wrap; a TLS-terminating proxy would hang/reset
                    # opaquely — fail with a diagnosis instead.
                    raise KubeConfigError(
                        f"HTTPS_PROXY {proxy!r}: TLS-to-proxy is not "
                        "supported; use an http:// CONNECT proxy"
                    )
                headers = {}
                if pu.username:
                    cred = (
                        f"{urllib.parse.unquote(pu.username)}:"
                        f"{urllib.parse.unquote(pu.password or '')}"
                    )
                    headers["Proxy-Authorization"] = (
                        "Basic " + base64.b64encode(cred.encode()).decode()
                    )
                conn = http.client.HTTPSConnection(
                    pu.hostname or "",
                    # Portless proxy URLs default to 80 like urllib/curl/
                    # client-go (and this module's own OIDC refresh path).
                    pu.port or 80,
                    timeout=timeout,
                    context=self._ssl,
                )
                conn.set_tunnel(self._host, self._port, headers=headers)
                return conn
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ssl
            )
        return http.client.HTTPConnection(
            self._host, self._port, timeout=timeout
        )

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _get_once(self, url: str) -> tuple[int, str, bytes]:
        if self._conn is None:
            self._conn = self._connect()
        conn = self._conn
        try:
            conn.request(
                "GET",
                url,
                headers={"Accept": "application/json", **self.config.auth_headers()},
            )
            resp = conn.getresponse()
            return resp.status, resp.reason or "", resp.read()
        except (OSError, http.client.HTTPException):
            self.close()
            raise

    def get_json(self, path: str, params: dict | None = None) -> dict:
        """GET over a persistent keep-alive connection (one TLS handshake
        per client, not per page); a stale connection is retried once."""
        query = urllib.parse.urlencode(
            {k: v for k, v in (params or {}).items() if v is not None}
        )
        url = self._prefix + path + (f"?{query}" if query else "")
        try:
            fresh = self._conn is None
            try:
                status, reason, body = self._get_once(url)
            except (OSError, http.client.HTTPException):
                if fresh:
                    raise
                # Keep-alive connection idled out since the last page —
                # reconnect once; a failure on a fresh socket is real.
                status, reason, body = self._get_once(url)
        except (OSError, http.client.HTTPException) as e:
            raise KubeAPIError(f"GET {path} failed: {e}") from e
        if status // 100 != 2:
            raise KubeAPIError(
                f"GET {path} -> {status} {reason}: "
                f"{body[:200].decode(errors='replace')}",
                status=status,
            )
        try:
            return json.loads(body)
        except ValueError as e:
            raise KubeAPIError(f"GET {path}: invalid JSON response: {e}") from e

    def _pages(self, path: str, limit: int, field_selector: str | None):
        """Yield ``(items, metadata)`` per page, following ``continue``."""
        token: str | None = None
        while True:
            page = self.get_json(
                path,
                {"limit": limit, "continue": token, "fieldSelector": field_selector},
            )
            meta = page.get("metadata") or {}
            yield page.get("items") or [], meta
            token = meta.get("continue")
            if not token:
                return

    def list_all(
        self, path: str, *, limit: int = 500, field_selector: str | None = None
    ):
        """Paginated List, streamed: one page of raw items in memory at a
        time (a 100k-pod cluster must not be materialized twice)."""
        for items, _ in self._pages(path, limit, field_selector):
            yield from items

    def list_with_version(
        self, path: str, *, limit: int = 500, field_selector: str | None = None
    ) -> tuple[list, str]:
        """Paginated List returning ``(items, resourceVersion)``.

        The resourceVersion of the final page is the point a subsequent
        watch resumes from (the standard list+watch contract).
        """
        items: list = []
        version = ""
        for page_items, meta in self._pages(path, limit, field_selector):
            items.extend(page_items)
            version = meta.get("resourceVersion") or version
        return items, version

    def watch_events(
        self,
        path: str,
        *,
        resource_version: str | None = None,
        field_selector: str | None = None,
        timeout_seconds: int | None = 300,
        read_timeout: float | None = None,
    ):
        """Stream watch events for one resource until the server ends it.

        Yields the decoded ``{"type": ..., "object": ...}`` dicts of the
        Kubernetes watch protocol (newline-delimited JSON over a chunked
        response).  The generator exits when the server closes the stream;
        callers re-watch from the last seen
        ``object.metadata.resourceVersion``.  A dedicated client should own
        a watch — the connection is occupied for the stream's lifetime.

        Idle-cluster handling: the window is bounded *server-side* via
        ``timeoutSeconds`` (which ends the stream cleanly), and the client
        socket carries a read timeout of ``timeoutSeconds`` plus a grace
        period as a liveness watchdog — if the apiserver or an LB dies
        without sending FIN, the server-side bound can never fire, and
        without the watchdog a reader would block on the dead socket
        forever.  A watchdog trip *while streaming* is treated as a clean
        end-of-window (the caller re-watches, exactly as after a normal
        window close), not a transport failure; pass ``read_timeout``
        explicitly to override, or ``timeout_seconds=None`` for an
        unbounded watch with no watchdog.
        """
        if read_timeout is None and timeout_seconds is not None:
            read_timeout = timeout_seconds + _WATCH_GRACE_SECONDS
        query = urllib.parse.urlencode(
            {
                k: v
                for k, v in {
                    "watch": "1",
                    "resourceVersion": resource_version,
                    "fieldSelector": field_selector,
                    "allowWatchBookmarks": "true",
                    "timeoutSeconds": timeout_seconds,
                }.items()
                if v is not None
            }
        )
        url = f"{self._prefix}{path}?{query}"
        self.close()  # a watch always runs on its own fresh connection
        conn = self._connect(timeout=read_timeout)
        # Register the stream's connection as the client's: close() from
        # another thread (follower.stop()) must be able to sever a reader
        # blocked in readline() instead of waiting out the watchdog.
        self._conn = conn
        # Transport-error conversion wraps ONLY the transport calls, never
        # a yield: an exception the CONSUMER raises while processing an
        # event re-enters the generator at the yield, and converting it
        # would mask a caller bug as a stream failure.
        try:
            try:
                conn.request(
                    "GET",
                    url,
                    headers={
                        "Accept": "application/json",
                        **self.config.auth_headers(),
                    },
                )
                resp = conn.getresponse()
                if resp.status // 100 != 2:
                    body = resp.read()
                    raise KubeAPIError(
                        f"WATCH {path} -> {resp.status} {resp.reason}: "
                        f"{body[:200].decode(errors='replace')}",
                        status=resp.status,
                    )
            except (OSError, http.client.HTTPException) as e:
                raise KubeAPIError(f"WATCH {path} failed: {e}") from e
            while True:
                try:
                    line = resp.readline()
                except TimeoutError:
                    # Liveness watchdog: the stream outlived timeoutSeconds
                    # + grace, so the server-side window bound is never
                    # coming (dead peer, no FIN).  Clean end-of-window —
                    # the caller re-watches on a fresh connection.
                    return
                except (OSError, http.client.HTTPException, ValueError) as e:
                    # ValueError: readline() on a response another thread
                    # close()d between events ("readline of closed file")
                    # — a severed stream, same taxonomy as a socket error.
                    raise KubeAPIError(f"WATCH {path} failed: {e}") from e
                if not line:
                    return  # server closed the watch window
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError as e:
                    raise KubeAPIError(
                        f"WATCH {path}: invalid event frame: {e}"
                    ) from e
                yield event
        finally:
            conn.close()
            if self._conn is conn:
                self._conn = None


def _containers_fixture(containers: list | None) -> list:
    out = []
    for c in containers or []:
        res = c.get("resources") or {}
        out.append(
            {
                "resources": {
                    "requests": dict(res.get("requests") or {}),
                    "limits": dict(res.get("limits") or {}),
                }
            }
        )
    return out


def node_to_fixture(n: dict) -> dict:
    """K8s REST Node object → the framework's fixture-schema node."""
    status = n.get("status") or {}
    spec = n.get("spec") or {}
    meta = n.get("metadata") or {}
    return {
        "name": meta.get("name", ""),
        "allocatable": {
            k: str(v) for k, v in (status.get("allocatable") or {}).items()
        },
        "conditions": [
            {"type": c.get("type", ""), "status": c.get("status", "")}
            for c in (status.get("conditions") or [])
        ],
        "labels": dict(meta.get("labels") or {}),
        "taints": [
            {
                "key": t.get("key", ""),
                "value": t.get("value", "") or "",
                "effect": t.get("effect", ""),
            }
            for t in (spec.get("taints") or [])
        ],
    }


def pod_to_fixture(p: dict) -> dict:
    """K8s REST Pod object → the framework's fixture-schema pod."""
    meta = p.get("metadata") or {}
    spec = p.get("spec") or {}
    status = p.get("status") or {}
    out = {
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", ""),
        "nodeName": spec.get("nodeName") or "",
        "phase": status.get("phase", ""),
        # Pod labels feed the anti-affinity-vs-existing-pods mask.
        "labels": dict(meta.get("labels") or {}),
        "containers": _containers_fixture(spec.get("containers")),
        "initContainers": _containers_fixture(spec.get("initContainers")),
    }
    # The admission-resolved integer priority feeds preemption-aware
    # capacity (ops/preemption.py); absent stays absent (fixture readers
    # default it to 0, the no-global-default-PriorityClass value).
    if spec.get("priority") is not None:
        out["priority"] = spec["priority"]
    return out


def pdb_to_fixture(b: dict) -> dict:
    """K8s REST PodDisruptionBudget → the fixture-schema pdb dict.

    Exactly one of minAvailable/maxUnavailable survives (the API
    enforces that on its side; :mod:`..pdb` re-validates)."""
    meta = b.get("metadata") or {}
    spec = b.get("spec") or {}
    out = {
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", ""),
        "selector": spec.get("selector") or {},
    }
    for key in ("minAvailable", "maxUnavailable"):
        if spec.get(key) is not None:
            out[key] = spec[key]
    return out


PDB_PATH = "/apis/policy/v1/poddisruptionbudgets"


def list_pdbs(client: "KubeClient", *, page_limit: int = 500) -> list[dict]:
    """List every PDB in fixture schema, degrading to ``[]`` only when
    this principal cannot read the policy API (403) or the apiserver
    lacks it (404) — budgets are an optional safety surface there.
    Transport loss and server errors still raise: silently dropping the
    eviction gate on a flaky connection would turn a PDB-blocked drain
    verdict into "evictable"."""
    try:
        return [
            pdb_to_fixture(b)
            for b in client.list_all(PDB_PATH, limit=page_limit)
        ]
    except KubeAPIError as e:
        if e.status in (403, 404):
            return []
        raise


def live_fixture(
    kubeconfig: str | None = None,
    *,
    context: str | None = None,
    client: KubeClient | None = None,
    page_limit: int = 500,
) -> dict:
    """Snapshot a live cluster into the framework's fixture schema.

    Three paginated Lists total (vs. the reference's ``1 + 2N + ΣP``
    pattern, ``ClusterCapacity.go:168,183,238,264``).  Pods are fetched
    across all namespaces with **no** phase field-selector: phases travel
    in the fixture so reference/strict filtering stays a local, testable
    decision (PARITY.md Q7).  PodDisruptionBudgets feed the drain
    simulator's eviction gate; clusters where the policy API is
    unreadable (403/404) degrade to a budget-less fixture — see
    :func:`list_pdbs`.
    """
    own_client = client is None
    if client is None:
        client = KubeClient(KubeConfig.load(kubeconfig, context=context))

    fixture: dict = {"nodes": [], "pods": []}
    try:
        for n in client.list_all("/api/v1/nodes", limit=page_limit):
            fixture["nodes"].append(node_to_fixture(n))
        for p in client.list_all("/api/v1/pods", limit=page_limit):
            fixture["pods"].append(pod_to_fixture(p))
        pdbs = list_pdbs(client, page_limit=page_limit)
        if pdbs:
            fixture["pdbs"] = pdbs
    finally:
        # Error paths must not leak the TLS connection (a token expiring
        # mid-pagination would otherwise strand a socket per retry).
        if own_client:
            client.close()
    return fixture
