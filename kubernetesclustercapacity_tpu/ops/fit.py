"""The capacity-fit kernel: the reference's per-node loop, vectorized.

The reference computes one scenario with a sequential Go loop
(``ClusterCapacity.go:105-140``).  Here the same arithmetic is a branchless
elementwise kernel over the node axis, ``vmap``-ed over a scenario axis and
``jit``-compiled — XLA fuses the whole thing into a couple of elementwise
passes, and the sharded variants in :mod:`..parallel` lay it out across a TPU
mesh.

Bit-exactness notes (the "hard parts" of SURVEY.md §7):

* CPU math is Go ``uint64``: comparison and division happen on uint64 views
  (int64 bit patterns reinterpreted), so wrapped values from the reference
  codec compare/divide exactly as Go does, and the quotient is cast back to
  int64 the way Go's ``int(...)`` cast does.
* Memory math is Go ``int64``: subtraction relies on two's-complement wrap
  (both Go and XLA wrap), and division truncates toward zero (Go) rather
  than flooring (default ``//``) — emulated branchlessly with a sign split.
* The conditional pod cap (Q1) is a ``where``, not a 3-way min: it OVERWRITES
  the fit with ``alloc_pods - pods_count`` (which may be negative) only when
  ``fit >= alloc_pods``.

Modes (SURVEY.md §2.4 parity decisions):

* ``"reference"`` — bug-compatible; bit-exact vs. the oracle.
* ``"strict"``    — corrected semantics: 3-way min including remaining pod
  slots, clamped at 0, unhealthy nodes contribute nothing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    GroupedSnapshot,
    grouped_for_dispatch,
)

__all__ = [
    "fit_per_node",
    "fit_totals",
    "sweep_grid",
    "sweep_grid_bucketed",
    "sweep_grid_grouped",
    "sweep_grouped_bucketed",
    "sweep_snapshot",
    "snapshot_device_arrays",
    "grouped_device_arrays",
    "fit_per_node_multi",
    "sweep_grid_multi",
    "sweep_explain_grid",
    "sweep_explain_grouped",
    "sweep_quantiles_grid",
    "sweep_quantiles_grouped",
    "sweep_quantiles_snapshot",
]

_INT64_MAX = np.iinfo(np.int64).max

MODES = ("reference", "strict")


def _trunc_div(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Go int64 division: truncate toward zero (``//`` floors for negatives).

    Implemented as floor-div plus a remainder correction rather than via
    ``abs`` — ``abs(INT64_MIN)`` would wrap back to INT64_MIN and flip the
    result sign, which matters because wrapped memory headrooms can land
    exactly on INT64_MIN.
    """
    q = num // den
    r = num - q * den
    fixup = ((r != 0) & ((num < 0) != (den < 0))).astype(q.dtype)
    return q + fixup


@partial(jax.jit, static_argnames=("mode",))
def fit_per_node(
    alloc_cpu: jnp.ndarray,
    alloc_mem: jnp.ndarray,
    alloc_pods: jnp.ndarray,
    used_cpu: jnp.ndarray,
    used_mem: jnp.ndarray,
    pods_count: jnp.ndarray,
    healthy: jnp.ndarray,
    cpu_req,
    mem_req,
    *,
    mode: str = "reference",
    node_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Per-node replica fit for ONE scenario — ``[N]`` int64.

    Inputs are the snapshot's int64 node arrays and scalar int64 requests.
    ``cpu_req``/``mem_req`` must be nonzero (validated upstream — the
    reference would panic, SURVEY.md §2.4 Q8); the kernel itself is total.
    ``node_mask`` (``[N]`` bool, optional) zeroes constraint-infeasible nodes
    after the mode epilogue — an extension (the reference has no constraint
    concept), applied on the uint64-faithful kernel so resource arithmetic
    parity is preserved for the unmasked nodes.
    """
    alloc_cpu = jnp.asarray(alloc_cpu, jnp.int64)
    alloc_mem = jnp.asarray(alloc_mem, jnp.int64)
    alloc_pods = jnp.asarray(alloc_pods, jnp.int64)
    used_cpu = jnp.asarray(used_cpu, jnp.int64)
    used_mem = jnp.asarray(used_mem, jnp.int64)
    pods_count = jnp.asarray(pods_count, jnp.int64)
    cpu_req = jnp.asarray(cpu_req, jnp.int64)
    mem_req = jnp.asarray(mem_req, jnp.int64)

    # --- CPU: Go uint64 compare/divide on the raw bit patterns (:119-123).
    alloc_cpu_u = alloc_cpu.astype(jnp.uint64)
    used_cpu_u = used_cpu.astype(jnp.uint64)
    cpu_req_u = jnp.maximum(cpu_req.astype(jnp.uint64), jnp.uint64(1))
    cpu_fit = jnp.where(
        alloc_cpu_u <= used_cpu_u,
        jnp.uint64(0),
        (alloc_cpu_u - used_cpu_u) // cpu_req_u,
    ).astype(jnp.int64)

    # --- Memory: Go int64 wrap-around subtraction + truncating div (:125-129).
    mem_head = alloc_mem - used_mem  # wraps like Go int64
    mem_fit = jnp.where(
        alloc_mem <= used_mem,
        jnp.int64(0),
        _trunc_div(mem_head, jnp.where(mem_req == 0, jnp.int64(1), mem_req)),
    )

    fit = jnp.minimum(cpu_fit, mem_fit)  # findMin (:159-164)
    fit = _apply_mode(fit, alloc_pods, pods_count, healthy, mode)
    if node_mask is not None:
        fit = jnp.where(jnp.asarray(node_mask, jnp.bool_), fit, 0)
    return fit


def _apply_mode(fit, alloc_pods, pods_count, healthy, mode: str):
    """The pod-count epilogue, shared by the 2-resource and R-dim kernels."""
    if mode == "reference":
        # Q1: conditional overwrite — only when fit >= allocatablePods, and
        # the replacement ignores that cpu/mem may bind tighter (:134-136).
        return jnp.where(fit >= alloc_pods, alloc_pods - pods_count, fit)
    if mode == "strict":
        slots = jnp.maximum(alloc_pods - pods_count, 0)
        fit = jnp.maximum(jnp.minimum(fit, slots), 0)
        return jnp.where(jnp.asarray(healthy, jnp.bool_), fit, 0)
    raise ValueError(f"unknown mode {mode!r}")


@partial(jax.jit, static_argnames=("mode",))
def fit_totals(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req,
    mem_req,
    *,
    mode: str = "reference",
):
    """Cluster total for one scenario: ``sum_n fit[n]`` — scalar int64."""
    return jnp.sum(
        fit_per_node(
            alloc_cpu,
            alloc_mem,
            alloc_pods,
            used_cpu,
            used_mem,
            pods_count,
            healthy,
            cpu_req,
            mem_req,
            mode=mode,
        )
    )


@partial(jax.jit, static_argnames=("mode", "return_per_node"))
def sweep_grid(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
    return_per_node: bool = False,
):
    """Evaluate S scenarios against N nodes in one compiled program.

    ``vmap`` over the scenario axis of ``(cpu_reqs[S], mem_reqs[S])``;
    returns ``(totals[S], schedulable[S])`` — and ``fits[S, N]`` too when
    ``return_per_node`` (kept optional so the 10k×1k sweep reduces in-register
    instead of materializing a 10M-cell intermediate in HBM).  ``node_mask``
    is an optional shared ``[N]`` constraint mask.
    """
    per_scenario = jax.vmap(
        lambda c, m: fit_per_node(
            alloc_cpu,
            alloc_mem,
            alloc_pods,
            used_cpu,
            used_mem,
            pods_count,
            healthy,
            c,
            m,
            mode=mode,
            node_mask=node_mask,
        )
    )
    fits = per_scenario(jnp.asarray(cpu_reqs, jnp.int64), jnp.asarray(mem_reqs, jnp.int64))
    totals = jnp.sum(fits, axis=1)
    schedulable = totals >= jnp.asarray(replicas, jnp.int64)
    if return_per_node:
        return totals, schedulable, fits
    return totals, schedulable


@partial(jax.jit, static_argnames=("mode",))
def fit_per_node_multi(
    alloc_rn: jnp.ndarray,
    used_rn: jnp.ndarray,
    alloc_pods: jnp.ndarray,
    pods_count: jnp.ndarray,
    healthy: jnp.ndarray,
    reqs_r: jnp.ndarray,
    *,
    mode: str = "strict",
    node_mask: jnp.ndarray | None = None,
    max_per_node: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """R-dimensional fit (BASELINE config 4): ``min`` over resource rows.

    ``alloc_rn``/``used_rn`` are ``[R, N]`` int64 (rows in the caller's
    resource order — e.g. cpu milli, memory bytes, ephemeral-storage bytes,
    GPU count); ``reqs_r`` is the scenario's ``[R]`` request vector.  A zero
    request means "does not consume this resource": that row is excluded
    from the min (``+inf`` fit) rather than dividing by zero — the natural
    generalization, since the reference's 2-resource kernel treats a zero
    request as fatal (SURVEY.md §2.4 Q8).

    All rows use int64 semantics (the generalized kernel is an extension —
    the bit-exactness contract vs. the Go path applies to the 2-resource
    :func:`fit_per_node`, which carries Go's uint64-CPU quirk).

    ``node_mask`` (``[N]`` bool) zeroes constraint-infeasible nodes;
    ``max_per_node`` (scalar) clamps per-node replicas (self-anti-affinity:
    spread pods repel each other → at most k per topology domain).
    """
    alloc_rn = jnp.asarray(alloc_rn, jnp.int64)
    used_rn = jnp.asarray(used_rn, jnp.int64)
    reqs = jnp.asarray(reqs_r, jnp.int64)[:, None]  # [R, 1]
    alloc_pods = jnp.asarray(alloc_pods, jnp.int64)
    pods_count = jnp.asarray(pods_count, jnp.int64)

    head = alloc_rn - used_rn
    per_resource = jnp.where(
        reqs == 0,
        jnp.int64(_INT64_MAX),
        jnp.where(
            alloc_rn <= used_rn,
            jnp.int64(0),
            # Zero-only divisor guard (the zero row is excluded above);
            # negative requests divide as-is, matching fit_per_node.
            _trunc_div(head, jnp.where(reqs == 0, jnp.int64(1), reqs)),
        ),
    )  # [R, N]
    fit = jnp.min(per_resource, axis=0)
    fit = _apply_mode(fit, alloc_pods, pods_count, healthy, mode)

    if max_per_node is not None:
        fit = jnp.minimum(fit, jnp.asarray(max_per_node, jnp.int64))
    if node_mask is not None:
        fit = jnp.where(jnp.asarray(node_mask, jnp.bool_), fit, 0)
    return fit


@partial(jax.jit, static_argnames=("mode", "return_per_node"))
def sweep_grid_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_sr,
    replicas,
    *,
    mode: str = "strict",
    node_masks=None,
    max_per_node=None,
    return_per_node: bool = False,
):
    """S scenarios × R resources sweep: ``reqs_sr`` is ``[S, R]``.

    ``node_masks`` may be ``None``, a shared ``[N]`` mask, or per-scenario
    ``[S, N]``; ``max_per_node`` may be ``None``, a scalar, or ``[S]``.
    """
    reqs_sr = jnp.asarray(reqs_sr, jnp.int64)

    def one(req_r, mask, cap):
        return fit_per_node_multi(
            alloc_rn,
            used_rn,
            alloc_pods,
            pods_count,
            healthy,
            req_r,
            mode=mode,
            node_mask=mask,
            max_per_node=cap,
        )

    mask_axis = None
    if node_masks is not None:
        node_masks = jnp.asarray(node_masks, jnp.bool_)
        mask_axis = 0 if node_masks.ndim == 2 else None
    cap_axis = None
    if max_per_node is not None:
        max_per_node = jnp.asarray(max_per_node, jnp.int64)
        cap_axis = 0 if max_per_node.ndim == 1 else None

    fits = jax.vmap(one, in_axes=(0, mask_axis, cap_axis))(
        reqs_sr, node_masks, max_per_node
    )  # [S, N]
    totals = jnp.sum(fits, axis=1)
    schedulable = totals >= jnp.asarray(replicas, jnp.int64)
    if return_per_node:
        return totals, schedulable, fits
    return totals, schedulable


@partial(jax.jit, static_argnames=("mode", "return_per_group"))
def sweep_grid_grouped(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    counts,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    return_per_group: bool = False,
):
    """S scenarios against G node-shape GROUPS, weighted by multiplicity.

    The node-shape-compression kernel (ROADMAP item 1): inputs are the
    grouped snapshot's ``[G]`` arrays plus ``counts[G]`` — how many
    identical node rows each group stands for.  Per-group fits are the
    ordinary :func:`fit_per_node` (identical inputs ⇒ identical outputs,
    so a group's fit IS every member's fit) and the cluster total is
    ``Σ_g count_g · fit_g``.  That weighted sum equals the per-node sum
    *bit-exactly* even on wrapped int64 carriers: XLA's int64 multiply
    and add are both mod-2^64, and ``n·x mod 2^64`` is ``x`` added ``n``
    times mod 2^64.  Zero-count rows (bucket padding, masked-out groups)
    contribute nothing by the same arithmetic.

    Returns ``(totals[S], schedulable[S])`` and, with
    ``return_per_group``, ``fits[S, G]`` for the caller to expand
    through the group→node index map.
    """
    per_scenario = jax.vmap(
        lambda c, m: fit_per_node(
            alloc_cpu,
            alloc_mem,
            alloc_pods,
            used_cpu,
            used_mem,
            pods_count,
            healthy,
            c,
            m,
            mode=mode,
        )
    )
    fits = per_scenario(
        jnp.asarray(cpu_reqs, jnp.int64), jnp.asarray(mem_reqs, jnp.int64)
    )  # [S, G]
    counts = jnp.asarray(counts, jnp.int64)
    totals = jnp.sum(fits * counts[None, :], axis=1)
    schedulable = totals >= jnp.asarray(replicas, jnp.int64)
    if return_per_group:
        return totals, schedulable, fits
    return totals, schedulable


def grouped_device_arrays(grouped: GroupedSnapshot) -> tuple:
    """The 8 grouped-kernel inputs (7 columns + counts) on device once."""
    return tuple(
        jnp.asarray(a)
        for a in (
            grouped.alloc_cpu_milli,
            grouped.alloc_mem_bytes,
            grouped.alloc_pods,
            grouped.used_cpu_req_milli,
            grouped.used_mem_req_bytes,
            grouped.pods_count,
            grouped.healthy,
            grouped.count,
        )
    )


def sweep_grouped_bucketed(
    grouped: GroupedSnapshot,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
    return_per_node: bool = False,
):
    """Shape-bucketed GROUPED sweep: the exact kernel over ``G`` group
    rows instead of ``N`` node rows, results expanded back to per-node
    where asked.

    The pow2 bucket ladder now buckets *groups*: the padded device
    arrays are ``O(G)`` (orders of magnitude below ``O(N)`` on a
    degenerate fleet) and cache under the ``"grouped"`` devcache form.
    ``node_mask`` folds into the per-group counts (a masked node's fit
    is zeroed in every mode, so dropping it from its group's count is
    the same sum) — per-group fits stay mask-independent and per-node
    expansion re-applies the mask.  Bit-exact against the ungrouped
    :func:`sweep_grid_bucketed` by the weighted-sum argument on
    :func:`sweep_grid_grouped`.  Returns numpy arrays.
    """
    import time as _time

    from kubernetesclustercapacity_tpu import devcache as _devcache
    from kubernetesclustercapacity_tpu.telemetry import phases as _phases
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    g = grouped.n_groups
    s = int(np.asarray(cpu_reqs).shape[0])
    counts = grouped.effective_counts(node_mask)
    clk = _phases.current()

    if not _devcache.enabled():
        t0 = _time.perf_counter() if clk else 0.0
        with clk.live("device_exec"):
            out = sweep_grid_grouped(
                grouped.alloc_cpu_milli, grouped.alloc_mem_bytes,
                grouped.alloc_pods, grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes, grouped.pods_count,
                grouped.healthy, counts, cpu_reqs, mem_reqs, replicas,
                mode=mode, return_per_group=return_per_node,
            )
        if clk:
            t1 = _time.perf_counter()
            clk.record("device_exec", t1 - t0)
            with clk.live("fetch"):
                out = tuple(np.asarray(o) for o in out)
            clk.record("fetch", _time.perf_counter() - t1)
        else:
            out = tuple(np.asarray(o) for o in out)
        return _expand_grouped_result(
            out, grouped, node_mask, s, return_per_node
        )

    staged = _devcache.CACHE.grouped_arrays(grouped)
    arrays = staged[:7]
    bucket = int(arrays[0].shape[0])
    if node_mask is None:
        counts_p = staged[7]  # device-resident base counts
    else:
        counts_p = np.pad(counts, (0, bucket - g)) if bucket > g else counts
    cpu_p, mem_p, rep_p = _pad_scenarios_bucketed(
        cpu_reqs, mem_reqs, replicas, _devcache.scenario_bucket(s)
    )
    t0 = _time.perf_counter()
    with clk.live("device_exec"):
        out = sweep_grid_grouped(
            *arrays, counts_p, cpu_p, mem_p, rep_p,
            mode=mode, return_per_group=return_per_node,
        )
    t_launch = _time.perf_counter()
    with clk.live("fetch"):
        out = tuple(np.asarray(o) for o in out)
    t_done = _time.perf_counter()
    kind = None
    if _telemetry_enabled():
        from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
            observe_dispatch,
        )

        kind = observe_dispatch(f"xla_int64_grouped@g{bucket}", t_done - t0)
    if clk:
        if kind == "compile":
            clk.record("compile", t_done - t0)
        else:
            clk.record("device_exec", t_launch - t0)
            clk.record("fetch", t_done - t_launch)
    out = (out[0][:s], out[1][:s]) + (
        (out[2][:s, :g],) if return_per_node else ()
    )
    return _expand_grouped_result(out, grouped, node_mask, s, return_per_node)


def _expand_grouped_result(out, grouped, node_mask, s, return_per_node):
    """Slice/expand a grouped sweep's outputs to the caller's shapes:
    totals/schedulable ``[S]``, plus per-node fits gathered through
    ``group_index`` (mask re-applied) when asked."""
    totals, sched = out[0][:s], out[1][:s]
    if not return_per_node:
        return totals, sched
    fits = grouped.expand(out[2][:s])
    if node_mask is not None:
        fits = np.where(np.asarray(node_mask, dtype=bool)[None, :], fits, 0)
    return totals, sched, fits


def snapshot_device_arrays(snapshot: ClusterSnapshot) -> tuple:
    """Put a snapshot's kernel inputs on device once (reused across sweeps)."""
    return tuple(
        jnp.asarray(a)
        for a in (
            snapshot.alloc_cpu_milli,
            snapshot.alloc_mem_bytes,
            snapshot.alloc_pods,
            snapshot.used_cpu_req_milli,
            snapshot.used_mem_req_bytes,
            snapshot.pods_count,
            snapshot.healthy,
        )
    )


def _pad_scenarios_bucketed(cpu_reqs, mem_reqs, replicas, s_pad: int):
    """Pad scenario arrays to ``s_pad`` with harmless (1 milli, 1 byte)
    probes (replicas 0) — same semantics as ``parallel/sweep``'s padding;
    the probe outputs are sliced off by the caller."""
    cpu_reqs = np.asarray(cpu_reqs, dtype=np.int64)
    mem_reqs = np.asarray(mem_reqs, dtype=np.int64)
    replicas = np.asarray(replicas, dtype=np.int64)
    pad = s_pad - cpu_reqs.shape[0]
    if pad:
        cpu_reqs = np.pad(cpu_reqs, (0, pad), constant_values=1)
        mem_reqs = np.pad(mem_reqs, (0, pad), constant_values=1)
        replicas = np.pad(replicas, (0, pad), constant_values=0)
    return cpu_reqs, mem_reqs, replicas


def sweep_grid_bucketed(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
    return_per_node: bool = False,
    snapshot: ClusterSnapshot | None = None,
    sync: bool = True,
):
    """Shape-bucketed exact sweep: :func:`sweep_grid` behind the bucket
    ladder, sliced back to the true ``[S]``/``[S, N]`` shapes.

    Both axes pad up the geometric ladder (``devcache.node_bucket`` /
    ``devcache.scenario_bucket``) so a ±1 change in node count or grid
    size within a bucket reuses the compiled executable.  Zero node rows
    yield fit 0 in both modes and scenario probes are sliced off, so the
    result is bit-exact against the unbucketed dispatch.  When
    ``snapshot`` is given, the padded node arrays come device-resident
    from the :mod:`..devcache` (the per-request host→device upload
    disappears); with ``KCCAP_DEVCACHE=0`` this is exactly the plain
    :func:`sweep_grid` call.  Returns numpy arrays.

    ``sync=False`` requests ASYNC dispatch: the jitted call's device
    arrays are returned unsynced (wrapped to host-slice to the true
    shapes at materialization — never a device-side slice program) so
    the caller can overlap the device→host wait with other host work
    and record it as the ``fetch_overlap`` phase at materialization.
    The async route only engages on the devcache path for a kernel
    label compilewatch has already seen (a first dispatch must be
    timed whole to classify as compile) — otherwise this falls back to
    the synchronous path and returns numpy as usual, so callers must
    branch on the returned array type, and the values are bit-identical
    either way (same jit, same inputs; only the sync point moves).
    """
    import time as _time

    from kubernetesclustercapacity_tpu import devcache as _devcache
    from kubernetesclustercapacity_tpu.telemetry import phases as _phases
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    clk = _phases.current()
    if not _devcache.enabled():
        t0 = _time.perf_counter() if clk else 0.0
        with clk.live("device_exec"):
            out = sweep_grid(
                alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
                pods_count, healthy, cpu_reqs, mem_reqs, replicas,
                mode=mode, node_mask=node_mask,
                return_per_node=return_per_node,
            )
        if clk:
            t1 = _time.perf_counter()
            clk.record("device_exec", t1 - t0)
            with clk.live("fetch"):
                out = tuple(np.asarray(o) for o in out)
            clk.record("fetch", _time.perf_counter() - t1)
            return out
        return tuple(np.asarray(o) for o in out)

    n = int(np.asarray(alloc_cpu).shape[0])
    s = int(np.asarray(cpu_reqs).shape[0])
    if snapshot is not None:
        arrays = _devcache.CACHE.exact_arrays(snapshot)
        bucket = int(arrays[0].shape[0])
    else:
        bucket = _devcache.node_bucket(n)
        pad = bucket - n
        arrays = tuple(
            np.pad(np.asarray(a), (0, pad)) if pad else np.asarray(a)
            for a in (
                alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
                pods_count, healthy,
            )
        )
    mask = node_mask
    if mask is not None:
        mask = np.asarray(mask, dtype=bool)
        if bucket > n:
            mask = np.pad(mask, (0, bucket - n))  # padded rows masked out
    cpu_p, mem_p, rep_p = _pad_scenarios_bucketed(
        cpu_reqs, mem_reqs, replicas, _devcache.scenario_bucket(s)
    )
    label = f"xla_int64@n{bucket}"
    if not sync:
        # Async route: launch and hand back the device arrays without
        # the block_until_ready sync — the caller materializes later
        # under ``fetch_overlap``.  Only once the label is steady-state
        # (or telemetry is off entirely): a first dispatch per padded
        # shape must be host-timed through the sync to classify as
        # compile, so it stays on the synchronous path below.
        allow_async = True
        if _telemetry_enabled():
            from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
                seen_kernels,
            )

            allow_async = label in seen_kernels()
        if allow_async:
            t0 = _time.perf_counter() if clk else 0.0
            with clk.live("device_exec"):
                out = sweep_grid(
                    *arrays, cpu_p, mem_p, rep_p,
                    mode=mode, node_mask=mask,
                    return_per_node=return_per_node,
                )
            if clk:
                clk.record("device_exec", _time.perf_counter() - t0)
            result = (
                _AsyncView(out[0], slice(None, s)),
                _AsyncView(out[1], slice(None, s)),
            )
            if return_per_node:
                result += (
                    _AsyncView(out[2], (slice(None, s), slice(None, n))),
                )
            return result
    t0 = _time.perf_counter()
    with clk.live("device_exec"):
        out = sweep_grid(
            *arrays, cpu_p, mem_p, rep_p,
            mode=mode, node_mask=mask, return_per_node=return_per_node,
        )
    # The jitted call returns asynchronously-dispatched device arrays;
    # the numpy materialization below is the block_until_ready sync.
    # Timed apart so the phase clock can split launch (device_exec)
    # from the device→host wait+transfer (fetch).
    t_launch = _time.perf_counter()
    with clk.live("fetch"):
        out = tuple(np.asarray(o) for o in out)
    t_done = _time.perf_counter()
    kind = None
    if _telemetry_enabled():
        # Per-bucket compile visibility: "first observation per label"
        # now means "first per padded shape", so a ±1 node change inside
        # a bucket provably adds no compile to the scrape.
        from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
            observe_dispatch,
        )

        kind = observe_dispatch(label, t_done - t0)
    if clk:
        if kind == "compile":
            # First dispatch of this padded shape: the wall time is
            # dominated by trace + XLA compile, not kernel runtime —
            # attribute the whole interval to the compile phase so a
            # cold start never reads as a device_exec regression.
            clk.record("compile", t_done - t0)
        else:
            clk.record("device_exec", t_launch - t0)
            clk.record("fetch", t_done - t_launch)
    result = (out[0][:s], out[1][:s])
    if return_per_node:
        result += (out[2][:s, :n],)
    return result


class _AsyncView:
    """An unsynced device result, host-sliced to its true shape at
    materialization (the numpy ``__array__`` protocol, so the caller's
    ``np.asarray`` is the sync point).  Slicing the *device* array to
    the true shape instead would dispatch a fresh XLA slice program per
    (bucket, true-shape) pair — a first-sight compile that dwarfs the
    launch the async route exists to overlap."""

    __slots__ = ("_dev", "_key")

    def __init__(self, dev, key) -> None:
        self._dev = dev
        self._key = key

    def __array__(self, dtype=None, copy=None):
        host = np.asarray(self._dev)[self._key]
        return host if dtype is None else np.asarray(host, dtype)


def sweep_snapshot(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    *,
    mode: str = "reference",
    return_per_node: bool = False,
    node_mask=None,
    sync: bool = True,
):
    """Convenience wrapper: ``ClusterSnapshot`` × ``ScenarioGrid`` → results.

    Validates the grid the way the reference's flag layer would (nonzero
    requests), then dispatches the jitted sweep through the device cache
    and shape-bucket ladder (:func:`sweep_grid_bucketed`): repeated
    sweeps of one snapshot reuse its device-resident padded arrays, and
    node/scenario counts recompile only when they cross a bucket edge.
    ``node_mask`` ([N] bool, optional) zeroes constraint-infeasible
    nodes for every scenario.  Returns numpy arrays.

    Degenerate fleets dispatch through the node-shape-compressed form
    (:func:`sweep_grouped_bucketed`) when
    :func:`..snapshot.grouped_for_dispatch` says it pays —
    ``KCCAP_GROUPING=0`` restores the ungrouped dispatch exactly.

    ``sync=False`` requests async dispatch on the ungrouped devcache
    path (see :func:`sweep_grid_bucketed`): the return MAY be unsynced
    ``jax.Array`` futures for the caller to materialize under
    ``fetch_overlap``; the grouped route always materializes (its
    group→node bookkeeping is host-side anyway).  Values are
    bit-identical either way.
    """
    import time as _time

    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    grid.validate()
    grouped = grouped_for_dispatch(snapshot)
    if grouped is not None:
        t0 = _time.perf_counter()
        out = sweep_grouped_bucketed(
            grouped,
            grid.cpu_request_milli,
            grid.mem_request_bytes,
            grid.replicas,
            mode=mode,
            node_mask=node_mask,
            return_per_node=return_per_node,
        )
        if _telemetry_enabled():
            from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
                observe_dispatch,
            )

            observe_dispatch(
                "xla_int64_grouped", _time.perf_counter() - t0
            )
        return out
    t0 = _time.perf_counter()
    out = sweep_grid_bucketed(
        snapshot.alloc_cpu_milli,
        snapshot.alloc_mem_bytes,
        snapshot.alloc_pods,
        snapshot.used_cpu_req_milli,
        snapshot.used_mem_req_bytes,
        snapshot.pods_count,
        snapshot.healthy,
        grid.cpu_request_milli,
        grid.mem_request_bytes,
        grid.replicas,
        mode=mode,
        return_per_node=return_per_node,
        node_mask=node_mask,
        snapshot=snapshot,
        sync=sync,
    )
    if _telemetry_enabled() and isinstance(out[0], np.ndarray):
        # Host-side, after the np.asarray sync — the first dispatch per
        # kernel label lands as compile time, the rest as steady-state
        # (telemetry/compilewatch; never called inside jitted code).
        # An async dispatch (device arrays returned) skips the coarse
        # label: its host-timed interval excludes the device wait, and
        # the per-bucket label already carries the compile story.
        from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
            observe_dispatch,
        )

        observe_dispatch("xla_int64", _time.perf_counter() - t0)
    return out


# -- fused super-kernels ----------------------------------------------------
#
# The "how many fit and what binds" question used to cost two-three
# launches (sweep, then explain, then sometimes a quantile reduce on
# host).  Each fused kernel below is ONE jitted program answering the
# combined question, so a folded micro-batch that mixes sweep and
# explain members — or a capacity-at-risk evaluation — pays a single
# dispatch.  Fusion is at the XLA level: the explain attribution needs
# the full int64 per-resource quotients, which the Pallas i32 fast path
# cannot carry, so the fused programs ride the exact kernel's arithmetic
# (bit-exactness against the sequential two-op path is therefore by
# construction — the fits ARE fit_per_node's, pinned in tests).


@partial(jax.jit, static_argnames=("mode",))
def sweep_explain_grid(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
):
    """Fused sweep+explain: one launch → totals, schedulability AND the
    per-node binding attribution for every scenario.

    Returns ``(totals[S], schedulable[S], fits[S, N], code[S, N],
    cpu_fit[S, N], mem_fit[S, N], slots[S, N])`` — the first two are
    exactly :func:`sweep_grid`'s outputs (the explain kernel's fit is
    pinned bit-identical to :func:`fit_per_node`), the rest exactly
    :func:`..explain.explain_grid`'s.  The late import keeps the
    ``explain → ops.fit`` dependency acyclic (it runs at trace time).
    """
    from kubernetesclustercapacity_tpu.explain import explain_grid

    fits, code, cpu_fit, mem_fit, slots = explain_grid(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
        pods_count, healthy, cpu_reqs, mem_reqs,
        mode=mode, node_mask=node_mask,
    )
    totals = jnp.sum(fits, axis=1)
    schedulable = totals >= jnp.asarray(replicas, jnp.int64)
    return totals, schedulable, fits, code, cpu_fit, mem_fit, slots


@partial(jax.jit, static_argnames=("mode",))
def sweep_explain_grouped(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    counts,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
):
    """Grouped fused sweep+explain: attribution over ``G`` node-shape
    groups with count-weighted totals (the same weighted-sum bit-exactness
    argument as :func:`sweep_grid_grouped`; a node_mask folds into
    ``counts`` upstream and re-applies per node after expansion).
    Outputs are ``[S]`` / ``[S, G]``.
    """
    from kubernetesclustercapacity_tpu.explain import explain_grid

    fits, code, cpu_fit, mem_fit, slots = explain_grid(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
        pods_count, healthy, cpu_reqs, mem_reqs, mode=mode,
    )
    counts = jnp.asarray(counts, jnp.int64)
    totals = jnp.sum(fits * counts[None, :], axis=1)
    schedulable = totals >= jnp.asarray(replicas, jnp.int64)
    return totals, schedulable, fits, code, cpu_fit, mem_fit, slots


@partial(jax.jit, static_argnames=("mode", "q_indices"))
def sweep_quantiles_grid(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    q_indices: tuple = (),
    node_mask=None,
):
    """Fused sweep+quantile: the Monte Carlo sample sweep AND the order
    statistics in one launch (the capacity-at-risk hot path).

    ``q_indices`` is the STATIC tuple of sorted-ascending order-statistic
    indices (:func:`..stochastic.car.quantile_index` per quantile — the
    host computes them from ``(S, q)`` alone).  The sort is a stable
    argsort, so the realizing sample index under ties is the SAME
    permutation numpy's stable host-side argsort yields — quantile
    values and sample attribution are bit-identical to the unfused
    reduction, pinned by test.  Returns ``(totals[S], schedulable[S],
    qvals[len(q)], qidx[len(q)])``.
    """
    totals, schedulable = sweep_grid(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
        pods_count, healthy, cpu_reqs, mem_reqs, replicas,
        mode=mode, node_mask=node_mask,
    )
    order = jnp.argsort(totals, stable=True)
    qi = jnp.asarray(q_indices, jnp.int32)
    return totals, schedulable, totals[order][qi], order[qi]


@partial(jax.jit, static_argnames=("mode", "q_indices"))
def sweep_quantiles_grouped(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    counts,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    q_indices: tuple = (),
):
    """Grouped twin of :func:`sweep_quantiles_grid` (count-weighted
    totals; a node_mask folds into ``counts`` upstream)."""
    totals, schedulable = sweep_grid_grouped(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
        pods_count, healthy, counts, cpu_reqs, mem_reqs, replicas,
        mode=mode,
    )
    order = jnp.argsort(totals, stable=True)
    qi = jnp.asarray(q_indices, jnp.int32)
    return totals, schedulable, totals[order][qi], order[qi]


def sweep_quantiles_snapshot(
    snapshot: ClusterSnapshot,
    grid: ScenarioGrid,
    *,
    mode: str | None = None,
    node_mask=None,
    q_indices: tuple = (),
):
    """Dispatch entry for the fused sweep+quantile kernel: devcache
    node staging, the grouped route when it pays, compilewatch labels —
    the same ladder as :func:`sweep_snapshot`, minus scenario-axis
    padding (pad probes would enter the SORT; the sample count is fixed
    per spec, so there is no shape churn to bucket away).  Returns
    numpy ``(totals[S], schedulable[S], qvals, qidx, kernel_name)``.
    """
    import time as _time

    from kubernetesclustercapacity_tpu import devcache as _devcache
    from kubernetesclustercapacity_tpu.telemetry import phases as _phases
    from kubernetesclustercapacity_tpu.telemetry.compilewatch import (
        observe_dispatch,
    )
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    mode = mode or snapshot.semantics
    grid.validate()
    q_indices = tuple(int(i) for i in q_indices)
    clk = _phases.current()
    grouped = grouped_for_dispatch(snapshot)
    if grouped is not None:
        g = grouped.n_groups
        counts = grouped.effective_counts(node_mask)
        if _devcache.enabled():
            staged = _devcache.CACHE.grouped_arrays(grouped)
            arrays = staged[:7]
            bucket = int(arrays[0].shape[0])
            if node_mask is None:
                counts_p = staged[7]
            else:
                counts_p = (
                    np.pad(counts, (0, bucket - g)) if bucket > g else counts
                )
            label = f"xla_int64_sweep_qtile_grouped@g{bucket}"
        else:
            arrays = (
                grouped.alloc_cpu_milli, grouped.alloc_mem_bytes,
                grouped.alloc_pods, grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes, grouped.pods_count,
                grouped.healthy,
            )
            counts_p = counts
            label = "xla_int64_sweep_qtile_grouped"
        t0 = _time.perf_counter()
        with clk.live("device_exec"):
            out = sweep_quantiles_grouped(
                *arrays, counts_p,
                grid.cpu_request_milli, grid.mem_request_bytes,
                grid.replicas,
                mode=mode, q_indices=q_indices,
            )
        kernel = "xla_int64_sweep_qtile_grouped"
    else:
        if _devcache.enabled():
            arrays = _devcache.CACHE.exact_arrays(snapshot)
            bucket = int(arrays[0].shape[0])
            n = snapshot.n_nodes
            mask = node_mask
            if mask is not None:
                mask = np.asarray(mask, dtype=bool)
                if bucket > n:
                    mask = np.pad(mask, (0, bucket - n))
            label = f"xla_int64_sweep_qtile@n{bucket}"
        else:
            arrays = (
                snapshot.alloc_cpu_milli, snapshot.alloc_mem_bytes,
                snapshot.alloc_pods, snapshot.used_cpu_req_milli,
                snapshot.used_mem_req_bytes, snapshot.pods_count,
                snapshot.healthy,
            )
            mask = node_mask
            label = "xla_int64_sweep_qtile"
        t0 = _time.perf_counter()
        with clk.live("device_exec"):
            out = sweep_quantiles_grid(
                *arrays,
                grid.cpu_request_milli, grid.mem_request_bytes,
                grid.replicas,
                mode=mode, q_indices=q_indices, node_mask=mask,
            )
        kernel = "xla_int64_sweep_qtile"
    t_launch = _time.perf_counter()
    with clk.live("fetch"):
        out = tuple(np.asarray(o) for o in out)
    t_done = _time.perf_counter()
    kind = None
    if _telemetry_enabled():
        kind = observe_dispatch(label, t_done - t0)
    if clk:
        if kind == "compile":
            clk.record("compile", t_done - t0)
        else:
            clk.record("device_exec", t_launch - t0)
            clk.record("fetch", t_done - t_launch)
    return out[0], out[1], out[2], out[3], kernel
