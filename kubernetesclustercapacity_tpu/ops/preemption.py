"""Preemption-aware capacity: priority-threshold suffix tables + fit.

The reference has no notion of pod priority — every Running pod consumes
capacity unconditionally (`ClusterCapacity.go:105-140` sums all of them).
Real kube-scheduler may *preempt*: a pending pod of priority ``p`` can
evict pods of strictly lower priority to make room.  The capacity
question this module answers is the preemption-aware upper bound:

    "how many replicas of a priority-``p`` pod could the cluster hold if
     every lower-priority pod may be evicted?"

Survivors are exactly the pods with ``priority >= p``, so the usable
per-node headroom is ``alloc - used_by(priority >= p)`` — a *suffix sum*
over the sorted distinct priority levels present in the cluster.  That
shape is TPU-friendly by construction:

* :func:`build_priority_table` walks the fixture once (host side, same
  strict-semantics rules as the packer: assigned, non-terminated pods,
  ``max(sum(containers), max(initContainers))`` effective resources) and
  materializes dense ``[N, K+1]`` tables — one suffix-summed column per
  distinct priority level plus a final all-zero column for thresholds
  above every level.
* Any threshold is then ONE gathered column, and the standard fit kernel
  (:func:`..fit.fit_per_node`) runs unchanged on the adjusted arrays —
  preemption composes with masks, spread, and extended resources because
  it only substitutes the ``used``/``pods_count`` operands.
* The scenario axis extends naturally: a ``[S]`` priority vector becomes
  ``searchsorted`` + a per-scenario column gather under ``vmap``
  (:func:`sweep_preemption`) — the same compiled shape as every other
  sweep in the framework.

This is a strict-semantics extension (the reference cannot express it);
:class:`..models.capacity.CapacityModel` gates it accordingly.  Pod
priority is read from the fixture pod dict's ``"priority"`` key (the
admission-resolved ``pod.spec.priority`` integer; absent → 0, matching
the cluster default when no global-default PriorityClass exists).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesclustercapacity_tpu.ops.fit import (
    fit_per_node,
    fit_per_node_multi,
)
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    _STRICT_TERMINATED,
    _effective_pod_resources,
)

__all__ = [
    "PreemptionExtendedError",
    "PriorityTable",
    "build_priority_table",
    "fit_with_preemption",
    "sweep_preemption",
]


class PreemptionExtendedError(ValueError):
    """An extended resource was requested that the priority table (or
    snapshot) carries no columns for — the preemptive fit would
    silently ignore the eviction gains on that resource, so it refuses
    instead."""


@dataclass
class PriorityTable:
    """Dense suffix-sum usage tables keyed by priority threshold.

    ``levels`` is the ascending ``[K]`` vector of distinct priorities
    present among counted pods.  Every usage array is ``[N, K+1]`` int64:
    column ``k`` holds the resources consumed by pods with
    ``priority >= levels[k]``; the extra final column is all zeros (a
    threshold above every level evicts everything).  Column 0 therefore
    equals the snapshot's plain strict usage — pinned by
    ``tests/test_preemption.py``.  :func:`column_index` maps a threshold
    to its column.
    """

    levels: np.ndarray  # [K] int64, ascending
    used_cpu_ge: np.ndarray  # [N, K+1] int64
    used_mem_ge: np.ndarray  # [N, K+1] int64
    pods_ge: np.ndarray  # [N, K+1] int64
    used_ext_ge: dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def n_nodes(self) -> int:
        return self.used_cpu_ge.shape[0]

    def column_index(self, priority: int) -> int:
        """Column for threshold ``priority``: the first level >= it
        (``side='left'``), or the zero column when it exceeds them all."""
        return int(np.searchsorted(self.levels, int(priority), side="left"))

    def columns(self, priority: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """``(used_cpu[N], used_mem[N], pods_count[N])`` for one threshold."""
        k = self.column_index(priority)
        return self.used_cpu_ge[:, k], self.used_mem_ge[:, k], self.pods_ge[:, k]

    def multi_columns(
        self, priority: int, resources: tuple[str, ...]
    ) -> tuple[np.ndarray, np.ndarray]:
        """``(used_rn[R, N], pods_count[N])`` for one threshold, rows in
        ``resources`` order (``"cpu"``/``"memory"`` name the core
        columns, anything else gathers from :attr:`used_ext_ge`).

        The ONE definition of how extended-resource eviction gains
        reach the R-dim kernel — :func:`fit_with_preemption`, the
        extended :func:`sweep_preemption` operands, and
        :class:`~..models.capacity.CapacityModel` all assemble through
        it.  A resource the table carries no suffix sums for raises
        :class:`PreemptionExtendedError` (the fit would otherwise
        silently charge full non-evictable usage on that resource).
        """
        k = self.column_index(priority)
        rows = []
        for r in resources:
            if r == "cpu":
                rows.append(self.used_cpu_ge[:, k])
            elif r == "memory":
                rows.append(self.used_mem_ge[:, k])
            elif r in self.used_ext_ge:
                rows.append(self.used_ext_ge[r][:, k])
            else:
                raise PreemptionExtendedError(
                    f"priority table has no extended-resource columns "
                    f"for {r!r} (built with "
                    f"{tuple(sorted(self.used_ext_ge))}); rebuild with "
                    f"extended_resources including it"
                )
        return np.stack(rows), self.pods_ge[:, k]


def _suffix_sum(per_level: np.ndarray) -> np.ndarray:
    """``[N, K]`` per-level sums → ``[N, K+1]`` suffix sums + zero column."""
    n = per_level.shape[0]
    ge = np.cumsum(per_level[:, ::-1], axis=1)[:, ::-1]
    return np.concatenate([ge, np.zeros((n, 1), dtype=np.int64)], axis=1)


def build_priority_table(
    fixture: dict,
    snapshot: ClusterSnapshot,
    extended_resources: tuple[str, ...] = (),
) -> PriorityTable:
    """One host-side fixture walk → the dense ``[N, K+1]`` tables.

    Pod filtering and effective-resource math mirror the strict packer
    exactly (assigned to a known node, phase not terminated,
    ``max(sum(containers), max(initContainers))`` — the walk shares
    :func:`..snapshot._effective_pod_resources`), so column 0 reproduces
    the snapshot's ``used_*``/``pods_count`` arrays bit-for-bit.
    """
    index = {name: i for i, name in enumerate(snapshot.names)}
    n = snapshot.n_nodes
    node_idx: list[int] = []
    prios: list[int] = []
    cpu_eff: list[int] = []
    mem_eff: list[int] = []
    ext_eff: dict[str, list[int]] = {r: [] for r in extended_resources}
    for pod in fixture.get("pods", []):
        node_name = pod.get("nodeName", "")
        if not node_name or node_name not in index:
            continue
        if pod.get("phase") in _STRICT_TERMINATED:
            continue
        eff = _effective_pod_resources(pod, extended_resources)
        node_idx.append(index[node_name])
        prios.append(int(pod.get("priority", 0)))
        cpu_eff.append(eff["cpu_req"])
        mem_eff.append(eff["mem_req"])
        for r in extended_resources:
            ext_eff[r].append(eff["ext"][r])

    levels = np.array(sorted(set(prios)), dtype=np.int64)  # [K]
    k = levels.shape[0]
    idx = np.asarray(node_idx, dtype=np.int64)
    li = np.searchsorted(levels, np.asarray(prios, dtype=np.int64))

    def table_for(values: list[int]) -> np.ndarray:
        per_level = np.zeros((n, k), dtype=np.int64)
        np.add.at(per_level, (idx, li), np.asarray(values, dtype=np.int64))
        return _suffix_sum(per_level)

    return PriorityTable(
        levels=levels,
        used_cpu_ge=table_for(cpu_eff),
        used_mem_ge=table_for(mem_eff),
        pods_ge=table_for([1] * len(node_idx)),
        used_ext_ge={r: table_for(ext_eff[r]) for r in extended_resources},
    )


def fit_with_preemption(
    snapshot: ClusterSnapshot,
    table: PriorityTable,
    cpu_req,
    mem_req,
    priority: int,
    *,
    mode: str = "strict",
    node_mask=None,
    extended_requests: dict[str, int] | None = None,
) -> np.ndarray:
    """Per-node preemptive fit for ONE spec — ``[N]`` int64.

    Substitutes the threshold's usage columns into the standard kernel;
    everything else (mode epilogue, mask) is :func:`..fit.fit_per_node`
    unchanged.  With ``extended_requests`` the eviction gains on those
    columns count too: the table's per-threshold extended suffix sums
    ride the R-dim kernel (:func:`..fit.fit_per_node_multi` — int64
    rows, the same kernel non-preemptive extended fits use).  A
    resource absent from the snapshot or the table raises
    :class:`PreemptionExtendedError` rather than pricing it as
    non-evictable.
    """
    if extended_requests:
        resources = ("cpu", "memory", *sorted(extended_requests))
        missing = [
            r for r in resources[2:] if r not in snapshot.extended
        ]
        if missing:
            raise PreemptionExtendedError(
                f"snapshot has no extended columns for "
                f"{', '.join(map(repr, missing))} (packed with "
                f"{tuple(sorted(snapshot.extended))})"
            )
        alloc_rn, _ = snapshot.resource_matrix(resources)
        used_rn, pods_count = table.multi_columns(priority, resources)
        reqs = np.array(
            [
                int(cpu_req),
                int(mem_req),
                *(int(extended_requests[r]) for r in resources[2:]),
            ],
            dtype=np.int64,
        )
        return np.asarray(
            fit_per_node_multi(
                alloc_rn,
                used_rn,
                snapshot.alloc_pods,
                pods_count,
                snapshot.healthy,
                reqs,
                mode=mode,
                node_mask=node_mask,
            )
        )
    used_cpu, used_mem, pods_count = table.columns(priority)
    return np.asarray(
        fit_per_node(
            snapshot.alloc_cpu_milli,
            snapshot.alloc_mem_bytes,
            snapshot.alloc_pods,
            used_cpu,
            used_mem,
            pods_count,
            snapshot.healthy,
            cpu_req,
            mem_req,
            mode=mode,
            node_mask=node_mask,
        )
    )


@partial(jax.jit, static_argnames=("mode",))
def sweep_preemption(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    healthy,
    levels,
    used_cpu_ge,
    used_mem_ge,
    pods_ge,
    cpu_reqs,
    mem_reqs,
    priorities,
    replicas,
    *,
    mode: str = "strict",
    node_mask=None,
    ext_alloc=None,
    ext_used_ge=None,
    ext_reqs=None,
):
    """S preemption scenarios in one compiled program.

    ``priorities[S]`` maps to table columns via an in-graph
    ``searchsorted`` over ``levels[K]``; each scenario gathers its
    ``[N]`` usage columns and runs the standard fit — ``vmap`` over
    ``(cpu_reqs, mem_reqs, priorities)``.  Returns
    ``(totals[S], schedulable[S])``.

    Extended resources ride three optional operands (all-or-nothing,
    rows assembled through :meth:`PriorityTable.multi_columns` order):
    ``ext_alloc[E, N]`` allocatable columns, ``ext_used_ge[E, N, K+1]``
    the table's per-threshold suffix sums, ``ext_reqs[S, E]``
    per-scenario requests.  Each scenario then runs the R-dim kernel
    (int64 rows, matching the non-preemptive extended fit path) with
    its gathered eviction-adjusted usage.
    """
    levels = jnp.asarray(levels, jnp.int64)
    used_cpu_ge = jnp.asarray(used_cpu_ge, jnp.int64)
    used_mem_ge = jnp.asarray(used_mem_ge, jnp.int64)
    pods_ge = jnp.asarray(pods_ge, jnp.int64)
    kidx = jnp.searchsorted(
        levels, jnp.asarray(priorities, jnp.int64), side="left"
    )

    if ext_used_ge is not None:
        ext_alloc_rn = jnp.asarray(ext_alloc, jnp.int64)  # [E, N]
        ext_used = jnp.asarray(ext_used_ge, jnp.int64)  # [E, N, K+1]
        ext_req_se = jnp.asarray(ext_reqs, jnp.int64)  # [S, E]
        alloc_rn = jnp.concatenate(
            [
                jnp.asarray(alloc_cpu, jnp.int64)[None],
                jnp.asarray(alloc_mem, jnp.int64)[None],
                ext_alloc_rn,
            ],
            axis=0,
        )

        def one_ext(c, m, k, er):
            used_rn = jnp.concatenate(
                [
                    used_cpu_ge[None, :, k],
                    used_mem_ge[None, :, k],
                    ext_used[:, :, k],
                ],
                axis=0,
            )
            return fit_per_node_multi(
                alloc_rn,
                used_rn,
                alloc_pods,
                pods_ge[:, k],
                healthy,
                jnp.concatenate([jnp.stack([c, m]), er]),
                mode=mode,
                node_mask=node_mask,
            )

        fits = jax.vmap(one_ext)(
            jnp.asarray(cpu_reqs, jnp.int64),
            jnp.asarray(mem_reqs, jnp.int64),
            kidx,
            ext_req_se,
        )
        totals = jnp.sum(fits, axis=1)
        return totals, totals >= jnp.asarray(replicas, jnp.int64)

    def one(c, m, k):
        return fit_per_node(
            alloc_cpu,
            alloc_mem,
            alloc_pods,
            used_cpu_ge[:, k],
            used_mem_ge[:, k],
            pods_ge[:, k],
            healthy,
            c,
            m,
            mode=mode,
            node_mask=node_mask,
        )

    fits = jax.vmap(one)(
        jnp.asarray(cpu_reqs, jnp.int64),
        jnp.asarray(mem_reqs, jnp.int64),
        kidx,
    )
    totals = jnp.sum(fits, axis=1)
    schedulable = totals >= jnp.asarray(replicas, jnp.int64)
    return totals, schedulable
