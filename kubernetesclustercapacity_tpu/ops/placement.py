"""Replica placement simulator — a sequential scheduler as a ``lax.scan``.

The reference (and this framework's fit kernels) answer *how many* replicas
fit by treating nodes independently (``ClusterCapacity.go:105-140``); a real
scheduler answers *where each replica lands*, and every placement changes
the feasibility of the next.  That sequential dependence is exactly what
``lax.scan`` expresses on TPU: the loop body is a branchless
score→argmin→subtract step over dense ``[N]`` arrays, compiled once per
(policy, replica-count) pair — no data-dependent Python control flow.

Policies (the classic bin-packing family):

* ``first-fit``  — lowest-index feasible node (kube-scheduler's default
  behavior is closer to scored spreading, but first-fit is the canonical
  baseline);
* ``best-fit``   — the feasible node left with the LEAST normalized
  headroom after placement (packs tightly, frees whole nodes);
* ``spread``     — the feasible node left with the MOST normalized
  headroom (worst-fit; balances load like the scheduler's
  ``LeastAllocated`` scoring).

Invariant (tested): for identical replicas every work-conserving greedy
policy places exactly ``min(R, sum(strict per-node fits))`` — placement
*order* differs, capacity does not.  This pins the simulator to the
bit-exactness chain anchored at the fit kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "place_replicas",
    "place_replicas_bulk",
    "place_replicas_trace",
    "place_replicas_python",
    "place_pods",
    "place_pods_python",
    "place_pods_multi",
    "place_pods_multi_python",
    "place_replicas_spread",
    "place_replicas_multi",
    "place_replicas_bulk_multi",
    "place_replicas_trace_multi",
    "place_replicas_multi_python",
    "POLICIES",
]

POLICIES = ("first-fit", "best-fit", "spread")


def _normalized_headroom(hc, hm, alloc_cpu, alloc_mem):
    """Score in [0, 2]: how empty a node would remain (f64 for ordering
    only — never feeds back into the integer feasibility state)."""
    safe = lambda num, den: jnp.where(  # noqa: E731 - local two-liner
        den > 0, num.astype(jnp.float64) / den.astype(jnp.float64), 0.0
    )
    return safe(hc, alloc_cpu) + safe(hm, alloc_mem)


def _assemble_trace(counts, placed, n_replicas, policy, score0, key_of):
    """Per-replica assignment sequence from closed-form counts — the
    shared skeleton of both trace engines.

    ``score0`` is the [N] initial after-placement score (ignored for
    first-fit); ``key_of(i_arr, t_arr)`` computes the spread multiset
    keys.  The order arguments live in :func:`place_replicas_trace`'s
    docstring; this helper only assembles.
    """
    r = int(n_replicas)
    assignments = np.full(r, -1, dtype=np.int64)
    if placed == 0:
        return assignments
    idx = np.arange(counts.shape[0])
    if policy in ("first-fit", "best-fit"):
        order = idx if policy == "first-fit" else np.lexsort((idx, score0))
        order = order[counts[order] > 0]
        assignments[:placed] = np.repeat(order, counts[order])
        return assignments
    # spread: expand each placed node's (i, t) elements and sort by
    # (key desc, node index asc, t asc).
    i_arr = np.repeat(idx, counts)
    ends = np.cumsum(counts)
    t_arr = np.arange(placed) - np.repeat(ends - counts, counts)
    key = key_of(i_arr, t_arr)
    order = np.lexsort((t_arr, i_arr, -key))
    assignments[:placed] = i_arr[order]
    return assignments


def _np_score_after_multi(h0, alloc_rn, reqs, sel, j):
    """R-row left-fold ``score_after(j)`` for the selected node columns.

    The ONE definition of the host-side R-resource score math (the
    analog of :func:`_np_score_after` for the multi family): the bulk
    engine's order/waterline search and the trace engine's keys both
    call it, so their f64 values are bit-identical — same per-row
    guarded divide, same left-to-right fold order as the scan's
    ``score_of``.  ``sel`` is an index array of node columns; ``j``
    broadcasts against it.
    """
    j1 = np.asarray(j, dtype=np.int64) + 1
    sel = np.asarray(sel)
    acc = np.zeros(np.broadcast(sel, j1).shape, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        for r in range(alloc_rn.shape[0]):
            sub = int(reqs[r]) if reqs[r] > 0 else 0
            acc = acc + np.where(
                alloc_rn[r, sel] > 0,
                (h0[r, sel] - j1 * sub).astype(np.float64)
                / alloc_rn[r, sel].astype(np.float64),
                0.0,
            )
    return acc


def _np_score_after(hc0, hm0, ac, am, c, m, j):
    """``score_after(j)`` — the f64 score after the ``j``-th placement —
    in numpy, elementwise over broadcastable inputs.

    The ONE definition of the host-side score math: the bulk engine's
    order/waterline search and the trace engine's keys both call it, so
    their f64 values are bit-identical to each other (and to the scan's
    ``_normalized_headroom`` epilogue: same int64 headroom subtract, two
    guarded divides, left-to-right sum)."""
    j1 = np.asarray(j, dtype=np.int64) + 1
    num_c = (hc0 - j1 * c).astype(np.float64)
    num_m = (hm0 - j1 * m).astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        sc = np.where(ac > 0, num_c / ac.astype(np.float64), 0.0)
        sm = np.where(am > 0, num_m / am.astype(np.float64), 0.0)
    return sc + sm


@partial(jax.jit, static_argnames=("n_replicas", "policy", "max_per_node"))
def place_replicas(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req,
    mem_req,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
):
    """Greedily place ``n_replicas`` identical pods, one per scan step.

    Feasibility mirrors the strict fit kernel exactly: integer headroom
    ``alloc - used >= request`` per resource, one free pod slot, healthy,
    and (optionally) an external constraint ``node_mask``.  Returns
    ``(assignments[n_replicas], per_node_counts[N])`` where an assignment
    of ``-1`` means that replica found no feasible node (all later
    replicas of a full cluster are ``-1`` too — the state stops changing).

    ``max_per_node`` caps how many of THESE replicas one node may take
    (self-anti-affinity / topology spread).

    ``n_replicas``, ``policy`` and ``max_per_node`` are static: one
    compile per combination, then every (snapshot, request) reuses it.
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    if n_replicas < 0:
        raise ValueError("n_replicas must be >= 0")
    alloc_cpu = jnp.asarray(alloc_cpu, jnp.int64)
    alloc_mem = jnp.asarray(alloc_mem, jnp.int64)
    c = jnp.asarray(cpu_req, jnp.int64)
    m = jnp.asarray(mem_req, jnp.int64)
    eligible = jnp.asarray(healthy, jnp.bool_)
    if node_mask is not None:
        eligible = eligible & jnp.asarray(node_mask, jnp.bool_)

    hc0 = alloc_cpu - jnp.asarray(used_cpu, jnp.int64)
    hm0 = alloc_mem - jnp.asarray(used_mem, jnp.int64)
    slots0 = jnp.maximum(
        jnp.asarray(alloc_pods, jnp.int64) - jnp.asarray(pods_count, jnp.int64),
        0,
    )
    n = hc0.shape[0]
    idx_arange = jnp.arange(n)

    # Incremental-score scan: each step changes ONE node's state, so the
    # [N] score vector is carried (pre-masked: infeasible lanes hold +inf)
    # and only the placed lane is recomputed, with scalar math.  The
    # original formulation recomputed two [N]-wide f64 divides per step —
    # on TPU f64 is software-emulated, so at R=1k replicas those divides
    # dominated the whole engine (BENCH r03: 51 ms / 1k placements).
    # Bit-exactness is by construction: untouched lanes keep the fl()
    # value a full recompute would reproduce (their state is unchanged),
    # and the placed lane's scalar ops are the same sequence (int64
    # subtract, two f64 divides, left-to-right sum) the vector form runs.
    def scalar_score(i, hc_i, hm_i):
        """Policy-signed after-placement score of one node —
        ``_normalized_headroom`` applied to the single updated lane (it is
        shape-polymorphic, so vector seed and scalar rescore share one
        definition and cannot drift apart)."""
        if policy == "first-fit":
            return i.astype(jnp.float64)
        after = _normalized_headroom(
            hc_i - c, hm_i - m, alloc_cpu[i], alloc_mem[i]
        )
        return after if policy == "best-fit" else -after

    feasible0 = (hc0 >= c) & (hm0 >= m) & (slots0 >= 1) & eligible
    if max_per_node is not None and max_per_node <= 0:
        # Static degenerate cap: no node may take even one replica.
        feasible0 = jnp.zeros_like(feasible0)
    if policy == "first-fit":
        score0 = idx_arange.astype(jnp.float64)
    else:
        after0 = _normalized_headroom(hc0 - c, hm0 - m, alloc_cpu, alloc_mem)
        score0 = after0 if policy == "best-fit" else -after0
    masked0 = jnp.where(feasible0, score0, jnp.inf)

    def body(state, _):
        hc, hm, slots, mine, masked = state
        idx = jnp.argmin(masked)
        ok = jnp.isfinite(masked[idx])
        dec_c = jnp.where(ok, c, jnp.int64(0))
        dec_m = jnp.where(ok, m, jnp.int64(0))
        one = jnp.where(ok, jnp.int64(1), jnp.int64(0))
        hc = hc.at[idx].add(-dec_c)
        hm = hm.at[idx].add(-dec_m)
        slots = slots.at[idx].add(-one)
        mine = mine.at[idx].add(one)
        # Scalar re-feasibility + re-score of the single updated lane.
        hc_i, hm_i = hc[idx], hm[idx]
        feas_i = (
            (hc_i >= c) & (hm_i >= m) & (slots[idx] >= 1) & eligible[idx]
        )
        if max_per_node is not None:
            feas_i = feas_i & (mine[idx] < max_per_node)
        new_val = jnp.where(feas_i, scalar_score(idx, hc_i, hm_i), jnp.inf)
        masked = masked.at[idx].set(jnp.where(ok, new_val, masked[idx]))
        assignment = jnp.where(ok, idx.astype(jnp.int64), jnp.int64(-1))
        return (hc, hm, slots, mine, masked), assignment

    mine0 = jnp.zeros(n, dtype=jnp.int64)
    _, assignments = jax.lax.scan(
        body, (hc0, hm0, slots0, mine0, masked0), None, length=n_replicas
    )
    counts = jnp.sum(
        (assignments[:, None] == idx_arange[None, :]), axis=0, dtype=jnp.int64
    )
    return assignments, counts


def place_replicas_bulk(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req: int,
    mem_req: int,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[np.ndarray, int]:
    """Closed-form placement plan for R identical replicas — no scan.

    Returns ``(counts[N], placed)``: exactly the per-node replica counts
    the :func:`place_replicas` R-step greedy scan produces, computed with
    O(N) vector math instead of R sequential argmin steps (the round-1
    scalability gap: 1k replicas on 10k nodes was 1k dependent scan steps).

    Why a closed form exists — for IDENTICAL pods each policy's greedy
    trajectory collapses:

    * ``first-fit`` fills nodes to capacity in index order (placing on a
      node never makes it preferable to skip);
    * ``best-fit`` picks the feasible node with minimum after-placement
      headroom; placing there only LOWERS its score, so the filling
      node's trajectory stays strictly below every other node's untouched
      initial score and can never cross one — it stays the argmin until
      exhausted → fill-to-capacity in ascending initial-score order
      (ties: lowest index, like the scan's ``argmin``).  This holds in
      f64 too: each score is ``fl(fl(a) + fl(b))`` of monotone terms, and
      ``fl`` is monotone, so rounding can flatten a step into a plateau
      but never invert the order; a plateau tied with an equal-initial-
      score node still resolves to the lowest index on both sides.
      Counts therefore match the scan in ALL cases;
    * ``spread`` picks the maximum; placing there lowers the node's score,
      so the greedy walk is a k-way head merge of per-node monotone
      non-increasing score sequences — i.e. the global top-R elements of
      the multiset ``{score_i(j) : j < cap_i}`` (water-filling).  The
      R-th value is found by bisection on the float64 bit lattice with
      EXACT per-node binary-search counting (the same f64 scores the scan
      compares — see ``count_ge``), and boundary ties at the waterline
      are distributed in the scan's order (lowest index first, each
      node's plateau exhausted before the next), so spread counts match
      the scan in ALL cases.

    Exactness is pinned by ``tests/test_placement.py::TestBulkParity`` —
    randomized snapshots plus adversarial tie grids (equal allocatables
    and aligned integer headrooms force exact f64 score collisions), all
    policies, R swept through every boundary.

    The per-replica assignment ORDER (which the scan also returns) is
    policy-defined given the counts: index order for first-fit, score
    order for best-fit, round-robin-by-score for spread; callers who need
    the order at small R keep using :func:`place_replicas`.
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    if int(n_replicas) < 0:
        raise ValueError("n_replicas must be >= 0")
    ac = np.asarray(alloc_cpu, dtype=np.int64)
    am = np.asarray(alloc_mem, dtype=np.int64)
    c, m = int(cpu_req), int(mem_req)
    if c <= 0 or m <= 0:
        raise ValueError("cpu_req and mem_req must be > 0")
    hc0 = ac - np.asarray(used_cpu, dtype=np.int64)
    hm0 = am - np.asarray(used_mem, dtype=np.int64)
    slots = np.maximum(
        np.asarray(alloc_pods, dtype=np.int64)
        - np.asarray(pods_count, dtype=np.int64),
        0,
    )
    eligible = np.asarray(healthy, dtype=bool)
    if node_mask is not None:
        eligible = eligible & np.asarray(node_mask, dtype=bool)

    # Per-node capacity for THESE replicas (the scan's feasibility checks,
    # integrated over its whole trajectory).
    caps = np.minimum(
        np.where(hc0 >= c, hc0 // c, 0), np.where(hm0 >= m, hm0 // m, 0)
    )
    caps = np.minimum(caps, slots)
    if max_per_node is not None:
        caps = np.minimum(caps, int(max_per_node))
    caps = np.where(eligible, np.maximum(caps, 0), 0)

    total = int(caps.sum())
    r = int(n_replicas)
    if r <= 0:
        return np.zeros_like(caps), 0
    if r >= total:
        return caps.copy(), total

    def fill_in_order(order: np.ndarray) -> np.ndarray:
        k = caps[order]
        before = np.concatenate(([0], np.cumsum(k)[:-1]))
        got = np.clip(r - before, 0, k)
        counts = np.zeros_like(caps)
        counts[order] = got
        return counts

    if policy == "first-fit":
        return fill_in_order(np.arange(caps.shape[0])), r

    def score_after(j):
        """Score after the ``j``-th placement on each node — bit-identical
        to the scan step's ``_normalized_headroom(hc - c, hm - m, ...)``
        when the node has already taken ``j`` replicas.  ``j`` may be a
        scalar or an ``[N]`` array.  Shared with the trace engine via
        :func:`_np_score_after`."""
        return _np_score_after(hc0, hm0, ac, am, c, m, j)

    if policy == "best-fit":
        s0 = score_after(0)
        # Ascending initial score, node index breaking ties (argmin rule).
        order = np.lexsort((np.arange(caps.shape[0]), s0))
        order = order[caps[order] > 0]
        return fill_in_order(order), r

    # --- spread: top-R of the union of per-node decreasing sequences.
    feas = caps > 0
    if not feas.any():
        return np.zeros_like(caps), 0

    def count_ge(theta: float) -> tuple[np.ndarray, int]:
        """Per-node count of sequence elements with score >= theta — EXACT.

        Each node's score sequence is monotone non-increasing in ``j``
        (exact-math strictly decreasing; f64 rounding can only flatten
        steps into plateaus, never invert them, because ``fl`` and the
        two-term sum are monotone), so the count is the first ``j`` with
        ``score < theta``.  Found by a vectorized per-node binary search
        that evaluates the SAME f64 scores the scan compares — no
        float-algebra estimate, no correction window, no error bound to
        argue about.  O(N log max_cap).
        """
        lo = np.zeros_like(caps)
        hi = caps.copy()  # count lives in [0, caps]
        while True:
            active = lo < hi
            if not active.any():
                break
            mid = (lo + hi) // 2
            ge = score_after(mid) >= theta
            lo = np.where(active & ge, mid + 1, lo)
            hi = np.where(active & ~ge, mid, hi)
        cnt = np.where(feas, lo, 0)
        return cnt, int(cnt.sum())

    # Bisect theta on the ordered-int64 view of f64 (monotone encoding):
    # after ~64 halvings lo/hi are adjacent floats and lo is exactly the
    # R-th largest score in the multiset.
    def f2i(x: float) -> int:
        bits = np.float64(x).view(np.int64)
        return int(bits if bits >= 0 else (-(1 << 63)) - bits - 1)

    def i2f(i: int) -> float:
        bits = i if i >= 0 else (-(1 << 63)) - i - 1
        return float(np.int64(bits).view(np.float64))

    smax = float(score_after(0)[feas].max())
    smin = float(score_after(np.maximum(caps - 1, 0))[feas].min())
    lo_i, hi_i = f2i(smin), f2i(smax) + 1
    # invariant: count_ge(i2f(lo_i)) >= r, count_ge(i2f(hi_i)) < r
    while hi_i - lo_i > 1:
        mid = (lo_i + hi_i) // 2
        if count_ge(i2f(mid))[1] >= r:
            lo_i = mid
        else:
            hi_i = mid
    theta = i2f(lo_i)
    base, n_ge = count_ge(theta)
    strict, n_gt = count_ge(i2f(lo_i + 1))
    # Elements strictly above theta all place.  The ``r - n_gt`` remaining
    # go to elements EQUAL to theta in the scan's order: argmin breaks the
    # cross-node tie by lowest index, and after a node takes one
    # theta-element its next element is <= theta — if it EQUALS theta
    # (an f64 plateau) argmin stays on that same lowest index.  So the
    # scan exhausts each node's theta-plateau fully before moving to the
    # next node, in index order — exactly a cumsum fill over the per-node
    # plateau lengths ``base - strict``.
    at = base - strict  # elements == theta per node (plateaus can be > 1)
    before = np.concatenate(([0], np.cumsum(at)[:-1]))
    take = np.clip(r - n_gt - before, 0, at)
    return strict + take, r


def place_replicas_trace(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req: int,
    mem_req: int,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Closed-form per-replica assignment SEQUENCE — the scan's full trace
    without the scan.

    Returns ``(assignments[n_replicas], counts[N], placed)`` where
    ``assignments`` is element-for-element what :func:`place_replicas`
    emits (``-1`` once nothing fits).  :func:`place_replicas_bulk` proves
    the per-node counts collapse to closed form for identical replicas;
    the placement ORDER collapses too:

    * ``first-fit`` / ``best-fit``: the greedy argmin stays on the filling
      node until exhausted (the bulk engine's trajectory argument), so the
      trace is each fill-order node's index repeated ``counts`` times;
    * ``spread``: the greedy walk is a k-way head merge of per-node
      non-increasing key sequences (``key(i, t) = score_after(t)`` for the
      ``t+1``-th placement on node ``i``), so the trace is the placed
      multiset sorted by (key desc, node index asc, t asc) — ties resolve
      to the lowest index with that node's plateau exhausted first,
      exactly the scan's ``argmin`` rule.

    O(R log R) host math; exactness is pinned against the scan by
    ``tests/test_placement.py`` (all policies, tie grids, boundary R).
    Use this (or :func:`place_replicas_bulk` when only counts matter)
    for identical replicas; the ``lax.scan`` engine remains for on-device
    composition into jitted pipelines.
    """
    counts, placed = place_replicas_bulk(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
        healthy, cpu_req, mem_req, n_replicas=n_replicas, policy=policy,
        node_mask=node_mask, max_per_node=max_per_node,
    )
    ac = np.asarray(alloc_cpu, dtype=np.int64)
    am = np.asarray(alloc_mem, dtype=np.int64)
    hc0 = ac - np.asarray(used_cpu, dtype=np.int64)
    hm0 = am - np.asarray(used_mem, dtype=np.int64)
    c, m = int(cpu_req), int(mem_req)
    score0 = (
        _np_score_after(hc0, hm0, ac, am, c, m, 0)
        if policy == "best-fit"
        else None
    )
    assignments = _assemble_trace(
        counts, placed, n_replicas, policy, score0,
        lambda i_arr, t_arr: _np_score_after(
            hc0[i_arr], hm0[i_arr], ac[i_arr], am[i_arr], c, m, t_arr
        ),
    )
    return assignments, counts, placed


def place_replicas_python(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req: int,
    mem_req: int,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[list[int], list[int]]:
    """Sequential ground truth for :func:`place_replicas` (same tie rules:
    numpy argmin picks the lowest index among equal scores, as the kernel's
    ``jnp.argmin`` does)."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r}")
    n = len(alloc_cpu)
    hc = [int(a) - int(u) for a, u in zip(alloc_cpu, used_cpu)]
    hm = [int(a) - int(u) for a, u in zip(alloc_mem, used_mem)]
    slots = [max(int(a) - int(p), 0) for a, p in zip(alloc_pods, pods_count)]
    eligible = [
        bool(healthy[i]) and (node_mask is None or bool(node_mask[i]))
        for i in range(n)
    ]
    assignments: list[int] = []
    counts = [0] * n
    for _ in range(n_replicas):
        best, best_score = -1, None
        for i in range(n):
            if not (
                eligible[i]
                and hc[i] >= cpu_req
                and hm[i] >= mem_req
                and slots[i] >= 1
                and (max_per_node is None or counts[i] < max_per_node)
            ):
                continue
            if policy == "first-fit":
                score = float(i)
            else:
                after = 0.0
                if alloc_cpu[i] > 0:
                    after += (hc[i] - cpu_req) / float(alloc_cpu[i])
                if alloc_mem[i] > 0:
                    after += (hm[i] - mem_req) / float(alloc_mem[i])
                score = after if policy == "best-fit" else -after
            if best_score is None or score < best_score:
                best, best_score = i, score
        if best < 0:
            assignments.append(-1)
            continue
        hc[best] -= cpu_req
        hm[best] -= mem_req
        slots[best] -= 1
        counts[best] += 1
        assignments.append(best)
    return assignments, counts


# --- Placement under a topology spread constraint.
#
# The PodTopologySpread DoNotSchedule predicate, checked the way
# kube-scheduler checks it: at EVERY placement, the candidate zone's
# count after placing may exceed the global minimum by at most maxSkew.
# The minimum moves as zones fill, so feasibility changes globally each
# step — like place_pods, the scan re-derives it fully (the
# incremental-score carry of place_replicas cannot apply).  For
# identical replicas this greedy provably lands exactly the closed form
# sum(min(c_z, min_z c_z + maxSkew)) the capacity method reports
# (tested): at termination the minimum-count zone must be
# resource-capped (a skew block at the minimum needs maxSkew < 1), so
# the terminal counts are min(c_z, min_z c_z + maxSkew) per zone.


@partial(
    jax.jit,
    static_argnames=(
        "n_replicas", "policy", "max_skew", "n_zones", "max_per_node",
    ),
)
def place_replicas_spread(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req,
    mem_req,
    zone_of,
    *,
    n_replicas: int,
    n_zones: int,
    policy: str = "first-fit",
    max_skew: int = 1,
    node_mask=None,
    max_per_node: int | None = None,
):
    """Greedy placement with the per-step maxSkew gate.

    ``zone_of`` is ``[N]`` int: the node's topology-domain index in
    ``[0, n_zones)``, or ``-1`` for nodes outside every domain (missing
    the key, or domain-ineligible) — those are infeasible, the
    DoNotSchedule rule.  ``max_per_node`` composes the hostname-level
    spread cap on top of the zone constraint (two simultaneous
    topology constraints, as real pod specs carry).  Returns
    ``(assignments[R], per_node[N], per_zone[n_zones])``.
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    if n_replicas < 0:
        raise ValueError("n_replicas must be >= 0")
    if n_zones < 1:
        raise ValueError("n_zones must be >= 1 (no domains = nothing places)")
    if max_skew < 1:
        raise ValueError("max_skew must be >= 1")
    alloc_cpu = jnp.asarray(alloc_cpu, jnp.int64)
    alloc_mem = jnp.asarray(alloc_mem, jnp.int64)
    c = jnp.asarray(cpu_req, jnp.int64)
    m = jnp.asarray(mem_req, jnp.int64)
    zone_of = jnp.asarray(zone_of, jnp.int64)
    eligible = jnp.asarray(healthy, jnp.bool_) & (zone_of >= 0)
    if node_mask is not None:
        eligible = eligible & jnp.asarray(node_mask, jnp.bool_)

    hc0 = alloc_cpu - jnp.asarray(used_cpu, jnp.int64)
    hm0 = alloc_mem - jnp.asarray(used_mem, jnp.int64)
    slots0 = jnp.maximum(
        jnp.asarray(alloc_pods, jnp.int64) - jnp.asarray(pods_count, jnp.int64),
        0,
    )
    n = hc0.shape[0]
    idx_f64 = jnp.arange(n).astype(jnp.float64)
    zone_gather = jnp.where(zone_of >= 0, zone_of, 0)  # safe index

    def body(state, _):
        hc, hm, slots, counts, mine = state
        zone_ok = (
            counts[zone_gather] + 1 - jnp.min(counts)
        ) <= jnp.int64(max_skew)
        feasible = (
            (hc >= c) & (hm >= m) & (slots >= 1) & eligible & zone_ok
        )
        if max_per_node is not None:
            feasible = feasible & (mine < max_per_node)
        if policy == "first-fit":
            score = idx_f64
        else:
            after = _normalized_headroom(hc - c, hm - m, alloc_cpu, alloc_mem)
            score = after if policy == "best-fit" else -after
        masked = jnp.where(feasible, score, jnp.inf)
        idx = jnp.argmin(masked)
        ok = jnp.isfinite(masked[idx])
        one = jnp.where(ok, jnp.int64(1), jnp.int64(0))
        hc = hc.at[idx].add(-jnp.where(ok, c, jnp.int64(0)))
        hm = hm.at[idx].add(-jnp.where(ok, m, jnp.int64(0)))
        slots = slots.at[idx].add(-one)
        counts = counts.at[zone_gather[idx]].add(one)
        mine = mine.at[idx].add(one)
        assignment = jnp.where(ok, idx.astype(jnp.int64), jnp.int64(-1))
        return (hc, hm, slots, counts, mine), assignment

    counts0 = jnp.zeros(n_zones, dtype=jnp.int64)
    mine0 = jnp.zeros(n, dtype=jnp.int64)
    # The final `mine` carry IS the per-node count (it increments at the
    # chosen node on every successful step) — no R×N re-derivation.
    (_, _, _, per_zone, per_node), assignments = jax.lax.scan(
        body, (hc0, hm0, slots0, counts0, mine0), None, length=n_replicas
    )
    return assignments, per_node, per_zone


# --- Heterogeneous-pod placement (drain / rehoming simulation).
#
# place_replicas places R IDENTICAL replicas; a drain simulation must
# rehome a node's EXISTING pods, each with its own requests.  The scan
# body therefore re-derives feasibility and scores for every node at
# every step (the per-step request changes, so the incremental-score
# trick above does not apply — nothing is reusable between steps), and
# pods place in the caller's order (callers sort; CapacityModel.drain
# uses size-descending, the classic first-fit-decreasing heuristic).
# The general engine is R-resource (the zero-request "does not consume"
# convention of place_replicas_multi, which per-pod zero entries need
# anyway: a requestless pod consumes only a slot); place_pods is the
# (cpu, mem) row-stacking wrapper.  The pod axis pads to power-of-two
# buckets with an in-scan validity lane, so a serving path draining
# differently-populated nodes compiles once per (policy, R, bucket)
# instead of once per pod count.


def _pod_bucket(p: int) -> int:
    """Smallest power of two >= p (min 8) — the scan-length pad target."""
    b = 8
    while b < p:
        b *= 2
    return b


@partial(jax.jit, static_argnames=("policy",))
def _place_pods_scan(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_rp,
    valid,
    *,
    policy: str,
    node_mask=None,
):
    """The padded heterogeneous scan: ``reqs_rp`` is ``[R, B]`` (one
    request column per step), ``valid[B]`` False for pad steps (they can
    never place, so the carried state is untouched).  Returns
    ``assignments[B]``."""
    alloc_rn = jnp.asarray(alloc_rn, jnp.int64)
    reqs_rp = jnp.asarray(reqs_rp, jnp.int64)
    n = alloc_rn.shape[1]
    n_res = alloc_rn.shape[0]
    eligible = jnp.asarray(healthy, jnp.bool_)
    if node_mask is not None:
        eligible = eligible & jnp.asarray(node_mask, jnp.bool_)

    h0 = alloc_rn - jnp.asarray(used_rn, jnp.int64)  # [R, N]
    slots0 = jnp.maximum(
        jnp.asarray(alloc_pods, jnp.int64)
        - jnp.asarray(pods_count, jnp.int64),
        0,
    )
    idx_f64 = jnp.arange(n).astype(jnp.float64)

    def body(state, xs):
        h, slots = state
        req_r, ok_step = xs  # [R], scalar bool
        active = req_r > 0
        sub = jnp.where(active, req_r, jnp.int64(0))  # [R]
        feasible = (
            jnp.all(~active[:, None] | (h >= req_r[:, None]), axis=0)
            & (slots >= 1)
            & eligible
            & ok_step
        )
        if policy == "first-fit":
            score = idx_f64
        else:
            acc = jnp.zeros(n, dtype=jnp.float64)
            for r in range(n_res):  # static unroll: row order = caller order
                acc = acc + jnp.where(
                    alloc_rn[r] > 0,
                    (h[r] - sub[r]).astype(jnp.float64)
                    / alloc_rn[r].astype(jnp.float64),
                    0.0,
                )
            score = acc if policy == "best-fit" else -acc
        masked = jnp.where(feasible, score, jnp.inf)
        idx = jnp.argmin(masked)
        ok = jnp.isfinite(masked[idx])
        h = h.at[:, idx].add(-jnp.where(ok, sub, jnp.int64(0)))
        slots = slots.at[idx].add(-jnp.where(ok, jnp.int64(1), jnp.int64(0)))
        assignment = jnp.where(ok, idx.astype(jnp.int64), jnp.int64(-1))
        return (h, slots), assignment

    _, assignments = jax.lax.scan(
        body, (h0, slots0), (reqs_rp.T, jnp.asarray(valid, jnp.bool_))
    )
    return assignments


def place_pods_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_rp,
    *,
    policy: str = "first-fit",
    node_mask=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Greedily place P pods with PER-POD request vectors, one step each.

    ``reqs_rp`` is ``[R, P]`` int64 — pod ``p`` places at step ``p`` with
    request column ``reqs_rp[:, p]`` (zero entries do not consume,
    :func:`place_replicas_multi`'s convention).  Same policy family and
    argmin tie rule as the identical-replica engines; ``-1`` for a pod
    no node can take — later pods still try (a small pod may fit where a
    big one did not, so a ``-1`` is not absorbing).  Returns
    ``(assignments[P], per_node_counts[N])`` numpy int64.
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    reqs_rp = np.asarray(reqs_rp, dtype=np.int64)
    if reqs_rp.ndim != 2:
        raise ValueError(f"reqs_rp must be [R, P], got shape {reqs_rp.shape}")
    n = np.asarray(alloc_pods).shape[0]
    p = reqs_rp.shape[1]
    if p == 0:
        return (
            np.zeros(0, dtype=np.int64),
            np.zeros(n, dtype=np.int64),
        )
    b = _pod_bucket(p)
    padded = np.zeros((reqs_rp.shape[0], b), dtype=np.int64)
    padded[:, :p] = reqs_rp
    assignments = np.asarray(
        _place_pods_scan(
            alloc_rn,
            used_rn,
            alloc_pods,
            pods_count,
            healthy,
            padded,
            np.arange(b) < p,
            policy=policy,
            node_mask=node_mask,
        )
    )[:p]
    counts = np.bincount(
        assignments[assignments >= 0], minlength=n
    ).astype(np.int64)
    return assignments, counts


def place_pods(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    *,
    policy: str = "first-fit",
    node_mask=None,
) -> tuple[np.ndarray, np.ndarray]:
    """2-resource :func:`place_pods_multi`: rows stack as (cpu, mem)."""
    return place_pods_multi(
        np.stack([np.asarray(alloc_cpu), np.asarray(alloc_mem)]),
        np.stack([np.asarray(used_cpu), np.asarray(used_mem)]),
        alloc_pods,
        pods_count,
        healthy,
        np.stack(
            [
                np.asarray(cpu_reqs, dtype=np.int64),
                np.asarray(mem_reqs, dtype=np.int64),
            ]
        ),
        policy=policy,
        node_mask=node_mask,
    )


def place_pods_multi_python(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_rp,
    *,
    policy: str = "first-fit",
    node_mask=None,
) -> tuple[list[int], list[int]]:
    """Sequential ground truth for :func:`place_pods_multi` (same tie
    rules and zero-request convention)."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r}")
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    reqs_rp = np.asarray(reqs_rp, dtype=np.int64)
    n_res, n = alloc_rn.shape
    h = [
        [int(alloc_rn[r, i]) - int(used_rn[r][i]) for i in range(n)]
        for r in range(n_res)
    ]
    slots = [max(int(a) - int(p), 0) for a, p in zip(alloc_pods, pods_count)]
    eligible = [
        bool(healthy[i]) and (node_mask is None or bool(node_mask[i]))
        for i in range(n)
    ]
    assignments: list[int] = []
    counts = [0] * n
    for p in range(reqs_rp.shape[1]):
        req = [int(reqs_rp[r, p]) for r in range(n_res)]
        best, best_score = -1, None
        for i in range(n):
            if not (
                eligible[i]
                and slots[i] >= 1
                and all(
                    req[r] <= 0 or h[r][i] >= req[r] for r in range(n_res)
                )
            ):
                continue
            if policy == "first-fit":
                score = float(i)
            else:
                after = 0.0
                for r in range(n_res):
                    if alloc_rn[r, i] > 0:
                        sub = req[r] if req[r] > 0 else 0
                        after += (h[r][i] - sub) / float(alloc_rn[r, i])
                score = after if policy == "best-fit" else -after
            if best_score is None or score < best_score:
                best, best_score = i, score
        if best < 0:
            assignments.append(-1)
            continue
        for r in range(n_res):
            if req[r] > 0:
                h[r][best] -= req[r]
        slots[best] -= 1
        counts[best] += 1
        assignments.append(best)
    return assignments, counts


def place_pods_python(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    *,
    policy: str = "first-fit",
    node_mask=None,
) -> tuple[list[int], list[int]]:
    """2-resource :func:`place_pods_multi_python`."""
    return place_pods_multi_python(
        np.stack([np.asarray(alloc_cpu), np.asarray(alloc_mem)]),
        np.stack([np.asarray(used_cpu), np.asarray(used_mem)]),
        alloc_pods,
        pods_count,
        healthy,
        np.stack(
            [
                np.asarray(cpu_reqs, dtype=np.int64),
                np.asarray(mem_reqs, dtype=np.int64),
            ]
        ),
        policy=policy,
        node_mask=node_mask,
    )


# --- R-resource generalization (placement with GPUs / ephemeral-storage).
#
# Same engines, R resource rows instead of the fixed (cpu, mem) pair.  A
# zero request row means "does not consume" (excluded from feasibility and
# headroom updates), matching the R-dim fit kernel's convention.  All three
# implementations accumulate the normalized-headroom score LEFT-TO-RIGHT
# over rows in the caller's order, so their f64 values are bit-identical
# and the bulk closed form's tie arguments carry over unchanged: each
# per-row term is monotone non-increasing in the per-node placement count,
# fl() and the left-fold sum are monotone, so plateaus can appear but the
# order never inverts (the same argument place_replicas_bulk documents for
# the 2-row case).


@partial(jax.jit, static_argnames=("n_replicas", "policy", "max_per_node"))
def place_replicas_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_r,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
):
    """R-resource greedy placement scan — see :func:`place_replicas`.

    ``alloc_rn``/``used_rn`` are ``[R, N]`` int64, ``reqs_r`` the ``[R]``
    per-replica request vector (zero rows do not consume).
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    if n_replicas < 0:
        raise ValueError("n_replicas must be >= 0")
    alloc_rn = jnp.asarray(alloc_rn, jnp.int64)
    reqs = jnp.asarray(reqs_r, jnp.int64)
    n = alloc_rn.shape[1]
    n_res = alloc_rn.shape[0]
    active = reqs > 0  # [R]
    eligible = jnp.asarray(healthy, jnp.bool_)
    if node_mask is not None:
        eligible = eligible & jnp.asarray(node_mask, jnp.bool_)

    h0 = alloc_rn - jnp.asarray(used_rn, jnp.int64)  # [R, N]
    slots0 = jnp.maximum(
        jnp.asarray(alloc_pods, jnp.int64)
        - jnp.asarray(pods_count, jnp.int64),
        0,
    )
    idx_arange = jnp.arange(n)
    sub = jnp.where(active, reqs, 0)[:, None]  # [R, 1] headroom delta

    def score_of(h):
        acc = jnp.zeros(n, dtype=jnp.float64)
        for r in range(n_res):  # static unroll: row order = caller order
            term = jnp.where(
                alloc_rn[r] > 0,
                (h[r] - sub[r, 0]).astype(jnp.float64)
                / alloc_rn[r].astype(jnp.float64),
                0.0,
            )
            acc = acc + term
        return acc

    # Incremental-score scan, as in :func:`place_replicas`: the [N]
    # pre-masked score vector rides in the carry and only the placed
    # lane is recomputed (scalar left-fold over the R rows, same order
    # as the vector form — R wide f64 divides per step become R scalar
    # ones).  Bit-exact vs the full recompute for the same reasons.
    def scalar_score(i, h_col):
        if policy == "first-fit":
            return i.astype(jnp.float64)
        acc = jnp.float64(0.0)
        for r in range(n_res):  # static unroll: row order = caller order
            acc = acc + jnp.where(
                alloc_rn[r, i] > 0,
                (h_col[r] - sub[r, 0]).astype(jnp.float64)
                / alloc_rn[r, i].astype(jnp.float64),
                0.0,
            )
        return acc if policy == "best-fit" else -acc

    feasible0 = (
        jnp.all(~active[:, None] | (h0 >= reqs[:, None]), axis=0)
        & (slots0 >= 1)
        & eligible
    )
    if max_per_node is not None and max_per_node <= 0:
        # Static degenerate cap: no node may take even one replica.
        feasible0 = jnp.zeros_like(feasible0)
    if policy == "first-fit":
        score0 = idx_arange.astype(jnp.float64)
    else:
        after0 = score_of(h0)
        score0 = after0 if policy == "best-fit" else -after0
    masked0 = jnp.where(feasible0, score0, jnp.inf)

    def body(state, _):
        h, slots, mine, masked = state
        idx = jnp.argmin(masked)
        ok = jnp.isfinite(masked[idx])
        dec = jnp.where(ok, sub[:, 0], jnp.int64(0))  # [R]
        one = jnp.where(ok, jnp.int64(1), jnp.int64(0))
        h = h.at[:, idx].add(-dec)
        slots = slots.at[idx].add(-one)
        mine = mine.at[idx].add(one)
        h_col = h[:, idx]  # [R]
        feas_i = (
            jnp.all(~active | (h_col >= reqs))
            & (slots[idx] >= 1)
            & eligible[idx]
        )
        if max_per_node is not None:
            feas_i = feas_i & (mine[idx] < max_per_node)
        new_val = jnp.where(feas_i, scalar_score(idx, h_col), jnp.inf)
        masked = masked.at[idx].set(jnp.where(ok, new_val, masked[idx]))
        assignment = jnp.where(ok, idx.astype(jnp.int64), jnp.int64(-1))
        return (h, slots, mine, masked), assignment

    mine0 = jnp.zeros(n, dtype=jnp.int64)
    _, assignments = jax.lax.scan(
        body, (h0, slots0, mine0, masked0), None, length=n_replicas
    )
    counts = jnp.sum(
        (assignments[:, None] == idx_arange[None, :]), axis=0, dtype=jnp.int64
    )
    return assignments, counts


def place_replicas_bulk_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_r,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[np.ndarray, int]:
    """Closed-form R-resource plan — see :func:`place_replicas_bulk`.

    The 2-row proofs generalize verbatim: per-node capacity is the min
    over ACTIVE rows of ``headroom // request`` (then slots/cap/mask), and
    the score-after-j sequence is a left-fold of R monotone f64 terms —
    monotone, plateau-capable, never order-inverting — so fill-in-order
    (best-fit) and waterline-with-plateau-ties (spread) stay exact vs the
    scan.  At least one request must be positive (an all-zero request
    consumes only pod slots; use the 2-resource bulk engine's slot path
    or the scan for that degenerate case).
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    if int(n_replicas) < 0:
        raise ValueError("n_replicas must be >= 0")
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    used_rn = np.asarray(used_rn, dtype=np.int64)
    reqs = np.asarray(reqs_r, dtype=np.int64)
    if (reqs < 0).any():
        raise ValueError("requests must be >= 0")
    if not (reqs > 0).any():
        raise ValueError("bulk multi placement needs a positive request")
    h0 = alloc_rn - used_rn  # [R, N]
    slots = np.maximum(
        np.asarray(alloc_pods, dtype=np.int64)
        - np.asarray(pods_count, dtype=np.int64),
        0,
    )
    eligible = np.asarray(healthy, dtype=bool)
    if node_mask is not None:
        eligible = eligible & np.asarray(node_mask, dtype=bool)

    caps = slots.copy()
    for r in range(alloc_rn.shape[0]):
        if reqs[r] > 0:
            row_cap = np.where(h0[r] >= reqs[r], h0[r] // reqs[r], 0)
            caps = np.minimum(caps, row_cap)
    if max_per_node is not None:
        caps = np.minimum(caps, int(max_per_node))
    caps = np.where(eligible, np.maximum(caps, 0), 0)

    total = int(caps.sum())
    r_want = int(n_replicas)
    if r_want <= 0:
        return np.zeros_like(caps), 0
    if r_want >= total:
        return caps.copy(), total

    def fill_in_order(order: np.ndarray) -> np.ndarray:
        k = caps[order]
        before = np.concatenate(([0], np.cumsum(k)[:-1]))
        got = np.clip(r_want - before, 0, k)
        counts = np.zeros_like(caps)
        counts[order] = got
        return counts

    if policy == "first-fit":
        return fill_in_order(np.arange(caps.shape[0])), r_want

    _all_nodes = np.arange(alloc_rn.shape[1])

    def score_after(j):
        # Shared with the trace engine via _np_score_after_multi.
        return _np_score_after_multi(h0, alloc_rn, reqs, _all_nodes, j)

    if policy == "best-fit":
        s0 = score_after(0)
        order = np.lexsort((np.arange(caps.shape[0]), s0))
        order = order[caps[order] > 0]
        return fill_in_order(order), r_want

    # spread: identical waterline machinery to the 2-row engine, over the
    # generalized score_after.
    feas = caps > 0
    if not feas.any():
        return np.zeros_like(caps), 0

    def count_ge(theta: float) -> tuple[np.ndarray, int]:
        lo = np.zeros_like(caps)
        hi = caps.copy()
        while True:
            active_b = lo < hi
            if not active_b.any():
                break
            mid = (lo + hi) // 2
            ge = score_after(mid) >= theta
            lo = np.where(active_b & ge, mid + 1, lo)
            hi = np.where(active_b & ~ge, mid, hi)
        cnt = np.where(feas, lo, 0)
        return cnt, int(cnt.sum())

    def f2i(x: float) -> int:
        bits = np.float64(x).view(np.int64)
        return int(bits if bits >= 0 else (-(1 << 63)) - bits - 1)

    def i2f(i: int) -> float:
        bits = i if i >= 0 else (-(1 << 63)) - i - 1
        return float(np.int64(bits).view(np.float64))

    smax = float(score_after(0)[feas].max())
    smin = float(score_after(np.maximum(caps - 1, 0))[feas].min())
    lo_i, hi_i = f2i(smin), f2i(smax) + 1
    while hi_i - lo_i > 1:
        mid = (lo_i + hi_i) // 2
        if count_ge(i2f(mid))[1] >= r_want:
            lo_i = mid
        else:
            hi_i = mid
    theta = i2f(lo_i)
    base, _n_ge = count_ge(theta)
    strict, n_gt = count_ge(i2f(lo_i + 1))
    at = base - strict
    before = np.concatenate(([0], np.cumsum(at)[:-1]))
    take = np.clip(r_want - n_gt - before, 0, at)
    return strict + take, r_want


def place_replicas_trace_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_r,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[np.ndarray, np.ndarray, int]:
    """R-resource closed-form trace — see :func:`place_replicas_trace`.

    The 2-resource order arguments generalize verbatim because the score
    is a left-fold of R monotone non-increasing f64 terms (the same
    argument :func:`place_replicas_bulk_multi` makes for counts):
    first/best-fit fill nodes to capacity in (initial score, index)
    order, and spread is the multiset of ``score_after(t)`` keys sorted
    by (key desc, index asc, t asc).  Exactness pinned against the scan
    by ``tests/test_placement.py``.
    """
    counts, placed = place_replicas_bulk_multi(
        alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_r,
        n_replicas=n_replicas, policy=policy,
        node_mask=node_mask, max_per_node=max_per_node,
    )
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    used_rn = np.asarray(used_rn, dtype=np.int64)
    reqs = np.asarray(reqs_r, dtype=np.int64)
    h0 = alloc_rn - used_rn
    score0 = (
        _np_score_after_multi(
            h0, alloc_rn, reqs, np.arange(counts.shape[0]), 0
        )
        if policy == "best-fit"
        else None
    )
    assignments = _assemble_trace(
        counts, placed, n_replicas, policy, score0,
        lambda i_arr, t_arr: _np_score_after_multi(
            h0, alloc_rn, reqs, i_arr, t_arr
        ),
    )
    return assignments, counts, placed


def place_replicas_multi_python(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_r,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[list[int], list[int]]:
    """Sequential ground truth for :func:`place_replicas_multi`."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r}")
    alloc_rn = [list(map(int, row)) for row in np.asarray(alloc_rn)]
    used_rn = [list(map(int, row)) for row in np.asarray(used_rn)]
    reqs = [int(x) for x in np.asarray(reqs_r)]
    n = len(alloc_rn[0])
    h = [
        [alloc_rn[r][i] - used_rn[r][i] for i in range(n)]
        for r in range(len(reqs))
    ]
    slots = [max(int(a) - int(p), 0) for a, p in zip(alloc_pods, pods_count)]
    eligible = [
        bool(healthy[i]) and (node_mask is None or bool(node_mask[i]))
        for i in range(n)
    ]
    assignments: list[int] = []
    counts = [0] * n
    for _ in range(n_replicas):
        best, best_score = -1, None
        for i in range(n):
            if not (
                eligible[i]
                and slots[i] >= 1
                and all(
                    reqs[r] == 0 or h[r][i] >= reqs[r]
                    for r in range(len(reqs))
                )
                and (max_per_node is None or counts[i] < max_per_node)
            ):
                continue
            if policy == "first-fit":
                score = float(i)
            else:
                after = 0.0
                for r in range(len(reqs)):
                    if alloc_rn[r][i] > 0:
                        sub = reqs[r] if reqs[r] > 0 else 0
                        after += (h[r][i] - sub) / float(alloc_rn[r][i])
                score = after if policy == "best-fit" else -after
            if best_score is None or score < best_score:
                best, best_score = i, score
        if best < 0:
            assignments.append(-1)
            continue
        for r in range(len(reqs)):
            if reqs[r] > 0:
                h[r][best] -= reqs[r]
        slots[best] -= 1
        counts[best] += 1
        assignments.append(best)
    return assignments, counts
