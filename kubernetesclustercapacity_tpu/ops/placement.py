"""Replica placement simulator — a sequential scheduler as a ``lax.scan``.

The reference (and this framework's fit kernels) answer *how many* replicas
fit by treating nodes independently (``ClusterCapacity.go:105-140``); a real
scheduler answers *where each replica lands*, and every placement changes
the feasibility of the next.  That sequential dependence is exactly what
``lax.scan`` expresses on TPU: the loop body is a branchless
score→argmin→subtract step over dense ``[N]`` arrays, compiled once per
(policy, replica-count) pair — no data-dependent Python control flow.

Policies (the classic bin-packing family):

* ``first-fit``  — lowest-index feasible node (kube-scheduler's default
  behavior is closer to scored spreading, but first-fit is the canonical
  baseline);
* ``best-fit``   — the feasible node left with the LEAST normalized
  headroom after placement (packs tightly, frees whole nodes);
* ``spread``     — the feasible node left with the MOST normalized
  headroom (worst-fit; balances load like the scheduler's
  ``LeastAllocated`` scoring).

Invariant (tested): for identical replicas every work-conserving greedy
policy places exactly ``min(R, sum(strict per-node fits))`` — placement
*order* differs, capacity does not.  This pins the simulator to the
bit-exactness chain anchored at the fit kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["place_replicas", "place_replicas_python", "POLICIES"]

POLICIES = ("first-fit", "best-fit", "spread")


def _normalized_headroom(hc, hm, alloc_cpu, alloc_mem):
    """Score in [0, 2]: how empty a node would remain (f64 for ordering
    only — never feeds back into the integer feasibility state)."""
    safe = lambda num, den: jnp.where(  # noqa: E731 - local two-liner
        den > 0, num.astype(jnp.float64) / den.astype(jnp.float64), 0.0
    )
    return safe(hc, alloc_cpu) + safe(hm, alloc_mem)


@partial(jax.jit, static_argnames=("n_replicas", "policy", "max_per_node"))
def place_replicas(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req,
    mem_req,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
):
    """Greedily place ``n_replicas`` identical pods, one per scan step.

    Feasibility mirrors the strict fit kernel exactly: integer headroom
    ``alloc - used >= request`` per resource, one free pod slot, healthy,
    and (optionally) an external constraint ``node_mask``.  Returns
    ``(assignments[n_replicas], per_node_counts[N])`` where an assignment
    of ``-1`` means that replica found no feasible node (all later
    replicas of a full cluster are ``-1`` too — the state stops changing).

    ``max_per_node`` caps how many of THESE replicas one node may take
    (self-anti-affinity / topology spread).

    ``n_replicas``, ``policy`` and ``max_per_node`` are static: one
    compile per combination, then every (snapshot, request) reuses it.
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (want one of {POLICIES})")
    alloc_cpu = jnp.asarray(alloc_cpu, jnp.int64)
    alloc_mem = jnp.asarray(alloc_mem, jnp.int64)
    c = jnp.asarray(cpu_req, jnp.int64)
    m = jnp.asarray(mem_req, jnp.int64)
    eligible = jnp.asarray(healthy, jnp.bool_)
    if node_mask is not None:
        eligible = eligible & jnp.asarray(node_mask, jnp.bool_)

    hc0 = alloc_cpu - jnp.asarray(used_cpu, jnp.int64)
    hm0 = alloc_mem - jnp.asarray(used_mem, jnp.int64)
    slots0 = jnp.maximum(
        jnp.asarray(alloc_pods, jnp.int64) - jnp.asarray(pods_count, jnp.int64),
        0,
    )
    n = hc0.shape[0]
    idx_arange = jnp.arange(n)

    def body(state, _):
        hc, hm, slots, mine = state
        feasible = (hc >= c) & (hm >= m) & (slots >= 1) & eligible
        if max_per_node is not None:
            feasible = feasible & (mine < max_per_node)
        if policy == "first-fit":
            score = idx_arange.astype(jnp.float64)
        else:
            after = _normalized_headroom(hc - c, hm - m, alloc_cpu, alloc_mem)
            score = after if policy == "best-fit" else -after
        score = jnp.where(feasible, score, jnp.inf)
        idx = jnp.argmin(score)
        ok = feasible[idx]
        one_hot = (idx_arange == idx) & ok
        hc = hc - jnp.where(one_hot, c, 0)
        hm = hm - jnp.where(one_hot, m, 0)
        one = jnp.where(one_hot, jnp.int64(1), jnp.int64(0))
        slots = slots - one
        mine = mine + one
        assignment = jnp.where(ok, idx.astype(jnp.int64), jnp.int64(-1))
        return (hc, hm, slots, mine), assignment

    mine0 = jnp.zeros(n, dtype=jnp.int64)
    _, assignments = jax.lax.scan(
        body, (hc0, hm0, slots0, mine0), None, length=n_replicas
    )
    counts = jnp.sum(
        (assignments[:, None] == idx_arange[None, :]), axis=0, dtype=jnp.int64
    )
    return assignments, counts


def place_replicas_python(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_req: int,
    mem_req: int,
    *,
    n_replicas: int,
    policy: str = "first-fit",
    node_mask=None,
    max_per_node: int | None = None,
) -> tuple[list[int], list[int]]:
    """Sequential ground truth for :func:`place_replicas` (same tie rules:
    numpy argmin picks the lowest index among equal scores, as the kernel's
    ``jnp.argmin`` does)."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r}")
    n = len(alloc_cpu)
    hc = [int(a) - int(u) for a, u in zip(alloc_cpu, used_cpu)]
    hm = [int(a) - int(u) for a, u in zip(alloc_mem, used_mem)]
    slots = [max(int(a) - int(p), 0) for a, p in zip(alloc_pods, pods_count)]
    eligible = [
        bool(healthy[i]) and (node_mask is None or bool(node_mask[i]))
        for i in range(n)
    ]
    assignments: list[int] = []
    counts = [0] * n
    for _ in range(n_replicas):
        best, best_score = -1, None
        for i in range(n):
            if not (
                eligible[i]
                and hc[i] >= cpu_req
                and hm[i] >= mem_req
                and slots[i] >= 1
                and (max_per_node is None or counts[i] < max_per_node)
            ):
                continue
            if policy == "first-fit":
                score = float(i)
            else:
                after = 0.0
                if alloc_cpu[i] > 0:
                    after += (hc[i] - cpu_req) / float(alloc_cpu[i])
                if alloc_mem[i] > 0:
                    after += (hm[i] - mem_req) / float(alloc_mem[i])
                score = after if policy == "best-fit" else -after
            if best_score is None or score < best_score:
                best, best_score = i, score
        if best < 0:
            assignments.append(-1)
            continue
        hc[best] -= cpu_req
        hm[best] -= mem_req
        slots[best] -= 1
        counts[best] += 1
        assignments.append(best)
    return assignments, counts
