"""Pallas TPU fast path for multi-resource sweeps (BASELINE config 4).

Generalizes the fused 2-resource kernel (:mod:`.pallas_fit`) to R resource
rows — the reference's 2-way min at ``ClusterCapacity.go:133`` extended to
``min`` over R rows the way :func:`.fit.fit_per_node_multi` defines it.
Same architecture: each grid step loads a node tile of every per-resource
alloc/used slab into VMEM, evaluates a ``(scenario-tile × node-tile)``
block per resource on the VPU, R-way-min's in-register, applies the mode
epilogue + lane mask, and accumulates partial sums — the ``[S, N]`` fit
matrix never exists in HBM (the int64 XLA path materializes ``[R, N]``
per scenario, which is exactly what made config 4 40× off the headline).

Eligibility generalizes the KiB-rescale proof per row: each resource row
gets the smallest power-of-1024 scale that keeps alloc/used/requests
int32-range while dividing all of them exactly — the rescale is then a
bijection on the row's domain, so the int32 quotient equals the int64
one.  Divisibility is monotone down the scale ladder (failing 1024 means
failing 1024²), so the search is a short ascending walk.  Zero requests
mean "does not consume this resource" (row excluded from the min via an
int32-max fit, matching the exact kernel's int64-max sentinel — both are
``>=`` every real fit, and the epilogue bounds the all-inactive case).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetesclustercapacity_tpu.ops.fit import sweep_grid_multi
from kubernetesclustercapacity_tpu.ops.pallas_fit import (
    LANES,
    NODE_TILE_ROWS,
    SCENARIO_TILE,
    _epilogue,
    _rcp_div,
    pad_node_array,
    pad_scenario_array,
    padded_node_shape,
    padded_scenario_shape,
    scenario_reciprocals,
)

__all__ = [
    "multi_row_scales",
    "fast_multi_eligible",
    "rcp_multi_eligible",
    "sweep_pallas_multi",
    "sweep_multi_auto",
]

_I32_MAX = np.iinfo(np.int32).max
_SCALES = (1, 1024, 1024**2, 1024**3)


def _positive_reqs(reqs_col: np.ndarray) -> np.ndarray:
    reqs_col = np.asarray(reqs_col)
    return reqs_col[reqs_col > 0]


def multi_row_scales(alloc_rn, used_rn, reqs_sr) -> list[int] | None:
    """Per-row rescale factors proving int32 exactness, or None.

    For each resource row r: the smallest ``s ∈ {1, 1024, 1024², 1024³}``
    such that ``alloc[r]``, ``used[r]`` and every POSITIVE request in
    ``reqs_sr[:, r]`` are all non-negative multiples of ``s`` with
    quotients in int32 range.  Divisibility by a larger power of 1024
    implies divisibility by the smaller ones, so the first divisibility
    failure ends the row's search.
    """
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    used_rn = np.asarray(used_rn, dtype=np.int64)
    reqs_sr = np.asarray(reqs_sr, dtype=np.int64)
    if reqs_sr.ndim != 2 or alloc_rn.shape[0] != reqs_sr.shape[1]:
        return None
    if reqs_sr.size and reqs_sr.min() < 0:
        # The exact kernel divides negative requests as-is; the fused
        # kernel's "active = req > 0" test would silently exclude them.
        return None
    scales: list[int] = []
    for r in range(alloc_rn.shape[0]):
        row_arrays = (alloc_rn[r], used_rn[r], _positive_reqs(reqs_sr[:, r]))
        if any(a.size and a.min() < 0 for a in row_arrays):
            return None
        chosen = None
        for s in _SCALES:
            if s > 1 and any(
                a.size and (a % s).any() for a in row_arrays
            ):
                break  # no larger scale can divide either
            if all(
                (not a.size) or (a // s).max() <= _I32_MAX
                for a in row_arrays
            ):
                chosen = s
                break
        if chosen is None:
            return None
        scales.append(chosen)
    return scales


def fast_multi_eligible(
    alloc_rn, used_rn, alloc_pods, pods_count, reqs_sr
) -> tuple[list[int] | None, bool]:
    """``(row_scales, ok)`` — ok iff the fused int32 R-dim kernel is exact.

    Beyond the per-row rescale (:func:`multi_row_scales`): pod columns in
    int32 range, and the int32 accumulator sum bound.  The per-node fit
    after the epilogue is ``<=`` the fit of ANY active row, and which rows
    a scenario activates is per-scenario — so the conservative per-node
    bound takes the MAX over rows of ``alloc[r] // min_positive_req[r]``
    (rows with no positive request anywhere in the grid can never bind and
    are skipped), joined with the pod-cap values ``alloc_pods`` /
    ``pods_count`` that the epilogue can emit.
    """
    scales = multi_row_scales(alloc_rn, used_rn, reqs_sr)
    if scales is None:
        return None, False
    alloc_pods = np.asarray(alloc_pods, dtype=np.int64)
    pods_count = np.asarray(pods_count, dtype=np.int64)
    for a in (alloc_pods, pods_count):
        if a.size and (a.min() < 0 or a.max() > _I32_MAX):
            return scales, False
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    reqs_sr = np.asarray(reqs_sr, dtype=np.int64)
    bound = np.maximum(alloc_pods, pods_count)
    for r in range(alloc_rn.shape[0]):
        pos = _positive_reqs(reqs_sr[:, r])
        if pos.size:
            bound = np.maximum(bound, alloc_rn[r] // int(pos.min()))
    return scales, int(bound.sum()) <= _I32_MAX


def rcp_multi_eligible(alloc_rn, used_rn, reqs_sr, scales) -> bool:
    """Per-row reciprocal-division exactness, on the SCALED values.

    Same two bounds as the 2-resource proof
    (:func:`.pallas_fit.rcp_division_eligible`): quotient ``<= 2^20`` and
    divisor ``<= 2^29``, per row, with dividends clamped to
    ``[0, max(alloc)]``.  Zero requests never divide (the kernel
    substitutes divisor 1 and wheres the row out), so only positive
    requests bound the row.
    """
    qmax = np.int64(1) << 20
    dmax = np.int64(1) << 29
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    reqs_sr = np.asarray(reqs_sr, dtype=np.int64)
    for r, s in enumerate(scales):
        alloc = alloc_rn[r] // s
        pos = _positive_reqs(reqs_sr[:, r]) // s
        if not pos.size:
            continue
        if pos.max() > dmax:
            return False
        if alloc.size and alloc.max() // pos.min() > qmax:
            return False
    return True


def _make_multi_kernel(n_res: int, use_rcp: bool, strict: bool,
                       use_mask: bool):
    def kernel(*refs):
        node = refs[: 2 * n_res]  # alloc_0, used_0, alloc_1, used_1, ...
        i = 2 * n_res
        ap, pc = refs[i], refs[i + 1]
        i += 2
        mk = None
        if use_mask:
            mk = refs[i]
            i += 1
        reqs = refs[i : i + n_res]
        i += n_res
        rcps = None
        if use_rcp:
            rcps = refs[i : i + n_res]
            i += n_res
        out = refs[i]
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            out[...] = jnp.zeros_like(out)

        # (BS, 1) per-resource request columns; divisor-safe + active mask
        # computed once per tile (scenario-only values).
        zero = jnp.int32(0)
        one = jnp.int32(1)
        big = jnp.int32(_I32_MAX)
        req_cols = [rq[...] for rq in reqs]
        act_cols = [rq > zero for rq in req_cols]
        safe_cols = [jnp.maximum(rq, one) for rq in req_cols]
        rcp_cols = [rc[...] for rc in rcps] if use_rcp else None

        acc = jnp.zeros_like(out)
        for r in range(NODE_TILE_ROWS):
            row = slice(r, r + 1)
            fit = None
            for k in range(n_res):
                a = node[2 * k][row]
                u = node[2 * k + 1][row]
                if use_rcp:
                    q = _rcp_div(
                        jnp.maximum(a - u, zero), safe_cols[k], rcp_cols[k]
                    )
                else:
                    q = (a - u) // safe_cols[k]
                fit_k = jnp.where(
                    act_cols[k], jnp.where(a <= u, zero, q), big
                )
                fit = fit_k if fit is None else jnp.minimum(fit, fit_k)
            mk_row = mk[row] if use_mask else None
            acc += _epilogue(fit, ap[row], pc[row], mk_row, strict)
        out[...] += acc

    return kernel


@partial(jax.jit, static_argnames=("use_rcp", "strict", "interpret"))
def _sweep_pallas_multi_padded(
    node_ops, ap, pc, req_ops, rcp_ops, mk=None,
    *, use_rcp=False, strict=True, interpret=False,
):
    """Inner jitted R-dim pallas sweep on padded int32 arrays.

    ``node_ops``: tuple of 2R ``(N/128, 128)`` arrays (alloc/used pairs in
    resource order, each pre-scaled by its row scale); ``req_ops`` /
    ``rcp_ops``: tuples of R ``(S, 1)`` request / reciprocal columns
    (``rcp_ops=()`` without rcp); returns int64 ``totals[S]``.
    """
    n_res = len(node_ops) // 2
    n_rows = ap.shape[0]
    s = req_ops[0].shape[0]
    grid = (s // SCENARIO_TILE, n_rows // NODE_TILE_ROWS)

    node_spec = pl.BlockSpec(
        (NODE_TILE_ROWS, LANES), lambda i, j: (j, 0),
        memory_space=pltpu.VMEM,
    )
    scen_spec = pl.BlockSpec(
        (SCENARIO_TILE, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    out_spec = pl.BlockSpec(
        (SCENARIO_TILE, LANES), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )

    use_mask = mk is not None
    operands = (*node_ops, ap, pc)
    in_specs = [node_spec] * (len(node_ops) + 2)
    if use_mask:
        operands += (mk,)
        in_specs += [node_spec]
    operands += tuple(req_ops)
    in_specs += [scen_spec] * len(req_ops)
    if use_rcp:
        operands += tuple(rcp_ops)
        in_specs += [scen_spec] * len(rcp_ops)

    with jax.enable_x64(False):  # same Mosaic x64 constraint as pallas_fit
        partial_sums = pl.pallas_call(
            _make_multi_kernel(n_res, use_rcp, strict, use_mask),
            out_shape=jax.ShapeDtypeStruct((s, LANES), jnp.int32),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            interpret=interpret,
        )(*operands)
    return jnp.sum(partial_sums.astype(jnp.int64), axis=1)


def pad_multi_operands(
    alloc_rn, used_rn, alloc_pods, pods_count, reqs_sr, scales,
    node_mask=None,
):
    """Host-side packing: scaled int32 kernel layout for the R-dim sweep.

    Returns ``(node_ops, ap, pc, req_ops, mk)`` — see
    :func:`_sweep_pallas_multi_padded`.  Row scales divide exactly (the
    eligibility contract), so ``//`` here is the bijective rescale.
    """
    alloc_rn = np.asarray(alloc_rn, dtype=np.int64)
    used_rn = np.asarray(used_rn, dtype=np.int64)
    reqs_sr = np.asarray(reqs_sr, dtype=np.int64)
    n = alloc_rn.shape[1]
    s = reqs_sr.shape[0]
    n_pad = padded_node_shape(n)
    s_pad = padded_scenario_shape(s)
    node_ops = []
    req_ops = []
    for r, scale in enumerate(scales):
        node_ops.append(pad_node_array(alloc_rn[r] // scale, n_pad))
        node_ops.append(pad_node_array(used_rn[r] // scale, n_pad))
        req_ops.append(pad_scenario_array(reqs_sr[:, r] // scale, s_pad))
    ap = pad_node_array(alloc_pods, n_pad)
    pc = pad_node_array(pods_count, n_pad)
    mk = None
    if node_mask is not None:
        mk = pad_node_array(np.asarray(node_mask).astype(np.int64), n_pad)
    return tuple(node_ops), ap, pc, tuple(req_ops), mk


def sweep_pallas_multi(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    reqs_sr,
    replicas,
    scales,
    *,
    mode: str = "strict",
    node_mask=None,
    use_rcp: bool | None = None,
    interpret: bool = False,
):
    """Fused R-dim Pallas sweep.  Caller must have checked eligibility.

    ``scales`` is :func:`multi_row_scales`' output for these inputs; for
    strict mode callers fold ``healthy`` into ``node_mask``.  Returns
    ``(totals[S], schedulable[S])`` numpy arrays.
    """
    if mode not in ("reference", "strict"):
        raise ValueError(f"unknown mode {mode!r}")
    if use_rcp is None:
        use_rcp = rcp_multi_eligible(alloc_rn, used_rn, reqs_sr, scales)
    s = np.asarray(reqs_sr).shape[0]
    node_ops, ap, pc, req_ops, mk = pad_multi_operands(
        alloc_rn, used_rn, alloc_pods, pods_count, reqs_sr, scales,
        node_mask=node_mask,
    )
    rcp_ops = (
        tuple(scenario_reciprocals(np.maximum(rq, 1)) for rq in req_ops)
        if use_rcp
        else ()
    )
    totals = _sweep_pallas_multi_padded(
        node_ops, ap, pc, req_ops, rcp_ops, mk,
        use_rcp=use_rcp, strict=(mode == "strict"), interpret=interpret,
    )
    totals = np.asarray(totals)[:s]
    schedulable = totals >= np.asarray(replicas, dtype=np.int64)
    return totals, schedulable


def sweep_multi_auto(
    alloc_rn,
    used_rn,
    alloc_pods,
    pods_count,
    healthy,
    reqs_sr,
    replicas,
    *,
    mode: str = "strict",
    node_masks=None,
    max_per_node=None,
    interpret: bool | None = None,
    force_exact: bool = False,
):
    """R-dim sweep on the fastest provably-exact kernel.

    Mirrors :func:`.pallas_fit.sweep_auto` for the multi-resource surface:
    eligible sweeps with a shared (or absent) node mask and no per-node
    cap take the fused kernel; per-scenario ``[S, N]`` masks,
    ``max_per_node``, or eligibility failure fall back to
    :func:`.fit.sweep_grid_multi`.  Returns ``(totals, schedulable,
    kernel_name)``.
    """
    import time as _time

    from kubernetesclustercapacity_tpu.telemetry import (
        compilewatch as _compilewatch,
    )
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        enabled as _telemetry_enabled,
    )

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    shared_mask = None
    fused_ok = max_per_node is None and not force_exact
    if node_masks is not None:
        nm = np.asarray(node_masks)
        if nm.ndim == 1:
            shared_mask = nm.astype(bool)
        else:
            fused_ok = False
    if fused_ok:
        scales, ok = fast_multi_eligible(
            alloc_rn, used_rn, alloc_pods, pods_count, reqs_sr
        )
        if ok:
            if mode == "strict":
                healthy_arr = np.asarray(healthy, dtype=bool)
                kernel_mask = (
                    healthy_arr
                    if shared_mask is None
                    else healthy_arr & shared_mask
                )
            else:
                kernel_mask = shared_mask
            use_rcp = rcp_multi_eligible(alloc_rn, used_rn, reqs_sr, scales)
            t0 = _time.perf_counter()
            totals, sched = sweep_pallas_multi(
                alloc_rn, used_rn, alloc_pods, pods_count, reqs_sr,
                replicas, scales, mode=mode, node_mask=kernel_mask,
                use_rcp=use_rcp, interpret=interpret,
            )
            name = (
                "pallas_multi_i32_rcp_fused"
                if use_rcp
                else "pallas_multi_i32_fused"
            )
            if _telemetry_enabled():
                # Host-side after sweep_pallas_multi's numpy
                # materialization (the device sync for this dispatch).
                _compilewatch.observe_dispatch(
                    name, _time.perf_counter() - t0
                )
            return totals, sched, name
    t0 = _time.perf_counter()
    totals, sched = sweep_grid_multi(
        alloc_rn, used_rn, alloc_pods, pods_count, healthy, reqs_sr,
        replicas, mode=mode, node_masks=node_masks,
        max_per_node=max_per_node,
    )
    totals, sched = np.asarray(totals), np.asarray(sched)
    if _telemetry_enabled():
        _compilewatch.observe_dispatch(
            "xla_int64_multi", _time.perf_counter() - t0
        )
    return totals, sched, "xla_int64_multi"
