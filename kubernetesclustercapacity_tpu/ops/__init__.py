"""Compute kernels (L1): the vectorized capacity-fit ops."""
