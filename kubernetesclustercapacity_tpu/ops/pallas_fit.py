"""Pallas TPU fast path: fused int32 fit + reduction for eligible sweeps.

Why this exists: the exact kernel (:mod:`.fit`) is int64 because memory is
tracked in bytes (node memory ≈ 2^34), and TPUs emulate int64 with 32-bit
pairs — every subtract/compare/divide costs multiple VPU ops.  But kubelets
report memory in ``Ki`` and realistic pod requests are MiB-granular, so on
real snapshots every memory quantity is a multiple of 1024.  Under that
precondition (checked, never assumed) the whole fit is exact in int32:

    (alloc − used) // req  ==  ((alloc/1024) − (used/1024)) // (req/1024)

when all three are multiples of 1024 — the rescale is a bijection on the
eligible domain, so the fast path is bit-exact, not approximate.

The Pallas kernel fuses the whole sweep: each grid step loads a
``(node-tile)`` slab of the six snapshot arrays into VMEM, evaluates a
``(scenario-tile × node-tile)`` block of fits on the VPU, reduces over the
node axis in-register, and accumulates ``(scenario-tile, 128)`` partial sums
— the ``[S, N]`` fit matrix never exists in HBM.  Layout: node arrays are
reshaped to ``(N/128, 128)`` lanes; scenario requests ride as ``(S, 1)``
columns; the final 128-lane reduction happens outside the kernel (an ``[S,
128] → [S]`` sum, negligible).

Eligibility (:func:`fast_sweep_eligible`) requires every value non-negative,
int32-range after rescale, and KiB-quantized memory.  Ineligible inputs fall
back to the exact int64 path; :func:`sweep_auto` picks automatically.
"""

from __future__ import annotations

import threading as _threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetesclustercapacity_tpu import devcache as _devcache
from kubernetesclustercapacity_tpu.ops.fit import (
    sweep_grid_bucketed,
    sweep_grouped_bucketed,
)
from kubernetesclustercapacity_tpu.snapshot import grouped_for_dispatch
from kubernetesclustercapacity_tpu.resilience import (
    CircuitBreaker as _CircuitBreaker,
)
from kubernetesclustercapacity_tpu.telemetry import (
    compilewatch as _compilewatch,
)
from kubernetesclustercapacity_tpu.telemetry import phases as _phases
from kubernetesclustercapacity_tpu.telemetry.metrics import (
    SUB_MS_LATENCY_BUCKETS_S as _SUB_MS_BUCKETS,
)
from kubernetesclustercapacity_tpu.telemetry.metrics import (
    enabled as _telemetry_enabled,
)

__all__ = [
    "fast_sweep_eligible",
    "rcp_division_eligible",
    "sweep_pallas",
    "sweep_auto",
    "sweep_snapshot_auto",
    "sweep_explain_snapshot_auto",
    "fast_path_error",
    "fast_path_breaker_snapshot",
    "last_dispatch_fast_path",
    "reset_fast_path",
]

# Most recent in-dispatch fast-path failure (compile/legalization), or
# None.  sweep_auto degrades to the exact kernel when the fused kernel
# raises AND trips a circuit breaker: a Mosaic failure is deterministic
# per (kernel, chip), and JAX does not cache failed compiles, so
# re-attempting on every request would bolt seconds of failing compile
# onto each ~1 ms sweep.  Read via fast_path_error() — a `from ...
# import` of the bare global would snapshot None forever.
last_fast_path_error: str | None = None

# Fused-path health metrics on the process-default registry, built
# lazily (first dispatch) so merely importing this module registers
# nothing.  All calls are host-side, OUTSIDE jitted code — the registry
# never appears inside a kernel — and every call site checks
# _telemetry_enabled() first, so KCCAP_TELEMETRY=0 leaves the hot sweep
# path with zero registry calls.
_MET: dict | None = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY

        _MET = {
            "hits": REGISTRY.counter(
                "kccap_fused_path_hits_total",
                "Sweeps served by the fused Pallas kernel.",
            ),
            "misses": REGISTRY.counter(
                "kccap_fused_path_misses_total",
                "Sweeps that fell back to the exact int64 kernel, "
                "by reason.",
                ("reason",),
            ),
            "failures": REGISTRY.counter(
                "kccap_fused_path_failures_total",
                "In-dispatch fused-kernel failures (compile/legalization "
                "or runtime), by disposition.",
                ("disposition",),
            ),
            "latency": REGISTRY.histogram(
                "kccap_sweep_kernel_seconds",
                "Sweep kernel latency by kernel (host-timed around the "
                "dispatch; the numpy materialization is the "
                "block_until_ready sync point).",
                ("kernel",),
                # Sub-ms ladder: the fused path's ~0.7 ms p50 needs
                # finer bins than the default 0.5 ms floor resolves.
                buckets=_SUB_MS_BUCKETS,
            ),
            "transitions": REGISTRY.counter(
                "kccap_breaker_transitions_total",
                "Circuit-breaker state transitions, by breaker and "
                "destination state.",
                ("breaker", "to"),
            ),
        }
    return _MET


def _breaker_transition(old: str, new: str) -> None:
    if _telemetry_enabled():
        _metrics()["transitions"].labels(
            breaker="pallas_fused_sweep", to=new
        ).inc()


# The real breaker (closed/open/half-open, resilience.CircuitBreaker)
# replacing the old ad-hoc `_fast_path_broken` bool.  threshold=1: ONE
# non-transient failure is already proof (the inputs were proven
# eligible, so the failure is compiler-deterministic for this (kernel,
# chip)).  recovery_timeout_s=None: a failed compile does not heal with
# time — the breaker stays open until reset_fast_path() re-arms it.
_breaker = _CircuitBreaker(
    name="pallas_fused_sweep",
    failure_threshold=1,
    recovery_timeout_s=None,
    on_state_change=_breaker_transition,
)

# Per-dispatch-thread record of the LAST sweep_auto call on this thread:
# did it attempt the fused path, and did that attempt fail?  The service
# reads this to attach fast_path_error to exactly the responses whose
# request attempted the fused kernel — never a stale error from an
# earlier request (ADVICE.md, server.py:705).
_dispatch_tls = _threading.local()


def fast_path_error() -> str | None:
    """The most recent fused-path failure (breaker-tripping or not)."""
    return last_fast_path_error


def fast_path_breaker_snapshot() -> dict:
    """Breaker state + lifetime counters (service info op / doctor)."""
    return _breaker.snapshot()


def last_dispatch_fast_path() -> tuple[bool, str | None]:
    """``(attempted, error)`` for the calling thread's most recent
    :func:`sweep_auto` dispatch — ``attempted`` is True iff the fused
    kernel actually ran (or tried to) for that request, and ``error``
    is THAT attempt's failure, never a stale one."""
    return (
        getattr(_dispatch_tls, "attempted", False),
        getattr(_dispatch_tls, "error", None),
    )


# Transient-failure markers: device/runtime conditions that are data- or
# moment-dependent (OOM, tunnel drops, deadlines).  Anything NOT
# transient trips the breaker: the dispatch's inputs are already proven
# eligible, so an unexplained in-dispatch failure is near-certainly a
# deterministic compile/legalization problem for this (kernel, chip) —
# defaulting the unknown case to "trip" avoids re-paying a failing
# multi-second compile on every request, at worst costing fast-path
# speed until reset_fast_path().
_TRANSIENT_MARKERS = (
    "resource_exhausted",
    "out of memory",
    "unavailable",
    "deadline",
    "cancelled",
    "connection",
    "socket",
    "interrupt",
)


def _is_transient_failure(e: Exception) -> bool:
    if isinstance(e, RecursionError):
        # The observed i64→i32 lowering non-termination — deterministic.
        return False
    text = f"{type(e).__name__}: {e}".lower()
    return any(m in text for m in _TRANSIENT_MARKERS)


def reset_fast_path() -> None:
    """Re-arm the fused path after a breaker trip (tests / operators)."""
    global last_fast_path_error
    last_fast_path_error = None
    _breaker.reset()
    _dispatch_tls.attempted = False
    _dispatch_tls.error = None

LANES = 128
# Node tile: 16 sublanes x 128 lanes = 2048 nodes per step; scenario tile 256.
NODE_TILE_ROWS = 16
SCENARIO_TILE = 256

_I32_MAX = np.iinfo(np.int32).max


def fast_sweep_eligible(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    cpu_reqs,
    mem_reqs,
    *,
    counts=None,
) -> bool:
    """True iff the int32 KiB-rescaled kernel is bit-exact for these inputs.

    Three conditions, all checked — never assumed:

    1. every value non-negative and int32-range (memory after /1024), with
       memory KiB-quantized (the rescale bijection);
    2. every request strictly positive (the fast kernel divides without the
       exact kernel's divisor clamp; zero requests are invalid upstream but
       must not become undefined behavior here);
    3. the worst-case per-scenario TOTAL fits in int32: per node the fit is
       bounded by ``max(alloc_cpu // min_cpu_req, alloc_pods, pods_count)``
       (resource bound, the Q1 cap value, and its negative magnitude), and
       the kernel accumulates totals in int32 lanes — so the sum of those
       bounds must stay under 2^31.

    ``counts`` (grouped dispatch) weights condition 3: the rows are node
    GROUPS and each contributes ``count_g`` times, so the int32
    accumulator bound is ``Σ count_g · bound_g``; the counts themselves
    must also be non-negative int32 (they multiply inside the kernel).
    """
    for a in (alloc_cpu, used_cpu, cpu_reqs, alloc_pods, pods_count):
        a = np.asarray(a)
        if a.size and (a.min() < 0 or a.max() > _I32_MAX):
            return False
    if counts is not None:
        c = np.asarray(counts)
        if c.size and (c.min() < 0 or c.max() > _I32_MAX):
            return False
    for a in (alloc_mem, used_mem, mem_reqs):
        a = np.asarray(a)
        if a.size == 0:
            continue
        if a.min() < 0 or (a % 1024).any() or (a // 1024).max() > _I32_MAX:
            return False
    cpu_reqs = np.asarray(cpu_reqs)
    mem_reqs = np.asarray(mem_reqs)
    if cpu_reqs.size == 0 or mem_reqs.size == 0:
        return True
    if cpu_reqs.min() < 1 or mem_reqs.min() < 1024:
        return False
    per_node_bound = np.maximum(
        np.asarray(alloc_cpu, dtype=np.int64) // int(cpu_reqs.min()),
        np.maximum(
            np.asarray(alloc_pods, dtype=np.int64),
            np.asarray(pods_count, dtype=np.int64),
        ),
    )
    if counts is not None:
        per_node_bound = per_node_bound * np.asarray(counts, dtype=np.int64)
    return int(per_node_bound.sum()) <= _I32_MAX


def rcp_division_eligible(
    alloc_cpu,
    alloc_mem,
    used_cpu,
    used_mem,
    cpu_reqs,
    mem_reqs,
) -> bool:
    """True iff f32-reciprocal division is provably exact for these inputs.

    The rcp kernel replaces each emulated int32 ``//`` (~6x slower on the
    VPU) with ``floor(float32(a) * float32(1/d))`` plus ONE integer fixup
    round.  That is bit-exact when the initial estimate lands within ±1 of
    the true quotient, which holds under (callers must already have passed
    :func:`fast_sweep_eligible`, so values are non-negative int32 and
    memory is KiB-quantized; KiB units are used below):

    1. quotient bound: ``max(dividend)/min(divisor) <= 2**20``.  Relative
       f32 error stacks to at most ``5*2^-24 < 2^-21.6`` (one conversion
       each for a and d, one IEEE divide for 1/d, one multiply), so the
       absolute error is ``<= 2^20 * 2^-21.6 < 0.5`` — after ``floor`` the
       estimate is in ``{q-1, q, q+1}``, and one fixup round is EXACT for
       that whole set: est = q-1 gives ``rem = (a - q*d) + d ∈ [d, 2d)``
       (the ``>= d`` branch adds 1), est = q+1 gives ``rem ∈ [-d, 0)``
       (the ``< 0`` branch subtracts 1), est = q gives ``rem ∈ [0, d)``
       (both branches off).  The single round therefore relies on the
       reciprocal being correctly rounded — :func:`scenario_reciprocals`
       is the one sanctioned producer.
    2. divisor bound ``<= 2**29``: keeps the fixup intermediate
       ``a - q*d`` in ``(-d, 2d)`` ⊂ int32 range.

    Dividends are ``alloc - used`` clamped at 0 (negative headrooms are
    where'd out of the result), so ``max(alloc)`` bounds them.
    """
    qmax = np.int64(1) << 20
    dmax = np.int64(1) << 29
    for alloc, reqs, scale in (
        (alloc_cpu, cpu_reqs, 1),
        (alloc_mem, mem_reqs, 1024),
    ):
        alloc = np.asarray(alloc, dtype=np.int64) // scale
        reqs = np.asarray(reqs, dtype=np.int64) // scale
        if alloc.size == 0 or reqs.size == 0:
            continue
        if reqs.min() < 1 or reqs.max() > dmax:
            return False
        if alloc.max() // reqs.min() > qmax:
            return False
    return True


def _rcp_div(a, d, r):
    """Exact ``a // d`` for the :func:`rcp_division_eligible` domain.

    ``a`` int32 ``>= 0``, ``d`` int32 ``> 0``, ``r`` = f32 ``1/d`` computed
    by a correctly-rounded IEEE divide (:func:`scenario_reciprocals`).
    One fixup round — exact for the proof's ±1 estimate set; see
    :func:`rcp_division_eligible`.  (A second round was carried through
    round 3 as margin; it is a proven no-op and cost ~10% of the kernel.)
    """
    q = jnp.floor(a.astype(jnp.float32) * r).astype(jnp.int32)
    rem = a - q * d
    return q + (rem >= d).astype(jnp.int32) - (rem < 0).astype(jnp.int32)


def _epilogue(fit, ap, pc, mk, strict: bool):
    """The mode epilogue + constraint mask, on ``(BS, LANES)`` fit blocks.

    Reference mode is the Q1 conditional overwrite (``ClusterCapacity.go:
    134-136`` — may go negative; int32 handles that fine).  Strict mode is
    the corrected 3-way min: clamp to remaining pod slots and to zero (the
    healthy filter rides in ``mk`` — in the eligible domain zeroing a lane
    via the mask is exactly the exact kernel's ``where(healthy, fit, 0)``).
    ``mk`` is a ``(1, LANES)`` int32 0/1 row or ``None``; multiplying is
    cheaper than a select on the VPU and exact for 0/1 masks.
    """
    if strict:
        zero = jnp.int32(0)
        slots = jnp.maximum(ap - pc, zero)
        fit = jnp.maximum(jnp.minimum(fit, slots), zero)
    else:
        fit = jnp.where(fit >= ap, (ap - pc) + jnp.zeros_like(fit), fit)
    if mk is not None:
        fit = fit * mk
    return fit


def _fit_row(ac, am, ap, uc, um, pc, mk, cr, mr, strict):
    """Fit of one node sublane row against all scenarios.

    ``ac..pc`` (and ``mk`` when present) are ``(1, LANES)`` node rows,
    ``cr``/``mr`` are ``(BS, 1)`` scenario requests; returns ``(BS, LANES)``
    fits.  In the eligible domain (non-negative int32) Go's uint64/int64
    semantics and int32 semantics coincide, including the conditional
    pod-cap overwrite.

    Everything here is a 2-D ``(scenario, lane)`` op with standard
    rank-2×rank-2 broadcasting — Mosaic's native vector layout.  (The first
    formulation materialized a 3-D ``(BS, ROWS, LANES)`` block; composing
    broadcast `//` and 2-D-condition `where` on that shape failed Mosaic
    legalization on real TPU, and the 3-D intermediate is layout-hostile
    anyway.)  Literal zeros are explicit int32: under jax_enable_x64 a bare
    ``0`` is a weak i64 scalar, and Mosaic's i64→i32 conversion lowering
    does not terminate (observed as RecursionError at compile time).
    """
    zero = jnp.int32(0)
    cpu_fit = jnp.where(ac <= uc, zero, (ac - uc) // cr)
    mem_fit = jnp.where(am <= um, zero, (am - um) // mr)
    fit = jnp.minimum(cpu_fit, mem_fit)
    return _epilogue(fit, ap, pc, mk, strict)


def _fit_row_rcp(ac, am, ap, uc, um, pc, mk, cr, mr, crr, mrr, strict):
    """:func:`_fit_row`'s fit via fused reciprocal division — one floor and
    ONE combined fixup for the two-resource min (rcp-eligible domain only).

    Dividends clamp at 0: negative headroom gives estimate 0 whose fixup
    cannot fire upward (``r = 0 - 0 < cr``), so the explicit
    ``ac <= uc`` select the exact kernel needs is redundant here — the
    clamp IS the zero-fit branch, and it keeps dividends inside the
    exactness proof's ``[0, max(alloc)]`` domain.

    Why fusing min into the floor stays exact (on top of
    :func:`rcp_division_eligible`'s per-divide proof):

    * each float estimate is within 0.5 of its REAL quotient
      (``|est_c − hc/cr| < 0.5``, the proof's error-stack bound), so
      ``|min(est_c, est_m) − min(hc/cr, hm/mr)| < 0.5`` (min is
      1-Lipschitz in each argument);
    * ``floor(min(x, y)) == min(floor(x), floor(y))`` for reals, so
      ``f = floor(min est) ∈ {M−1, M, M+1}`` where
      ``M = min(hc//cr, hm//mr)`` is the true fit;
    * one combined fixup resolves all three: with
      ``r1 = hc − f·cr, r2 = hm − f·mr``, feasibility of ``f+1`` is
      ``r1 ≥ cr ∧ r2 ≥ mr`` (fires exactly when ``f = M−1``), and
      infeasibility of ``f`` is ``r1 < 0 ∨ r2 < 0`` (fires exactly when
      ``f = M+1``); both intermediates stay in int32 because ``f`` is at
      most one above its own resource's quotient, so ``r1 ∈ (−2·cr, hc]``
      (divisors ≤ 2^29, dividends ≤ int32 max — the same wraparound
      argument as the per-divide fixup).

    Versus two independent ``_rcp_div`` calls + min + two selects this
    drops ~8 of ~25 per-cell VPU ops — the second floor/convert chain,
    the second fixup's compares, and both zero-selects.
    """
    zero = jnp.int32(0)
    hc = jnp.maximum(ac - uc, zero)
    hm = jnp.maximum(am - um, zero)
    est = jnp.minimum(
        hc.astype(jnp.float32) * crr, hm.astype(jnp.float32) * mrr
    )
    f = jnp.floor(est).astype(jnp.int32)
    r1 = hc - f * cr
    r2 = hm - f * mr
    up = ((r1 >= cr) & (r2 >= mr)).astype(jnp.int32)
    down = ((r1 < 0) | (r2 < 0)).astype(jnp.int32)
    fit = f + up - down
    return _epilogue(fit, ap, pc, mk, strict)


def _make_sweep_kernel(
    use_rcp: bool, strict: bool, use_mask: bool, use_counts: bool = False
):
    def kernel(*refs):
        ac, am, ap, uc, um, pc = refs[:6]
        i = 6
        mk = None
        if use_mask:
            mk = refs[i]
            i += 1
        ct = None
        if use_counts:
            ct = refs[i]
            i += 1
        cr, mr = refs[i], refs[i + 1]
        i += 2
        if use_rcp:
            crr, mrr = refs[i], refs[i + 1]
            i += 2
        out = refs[i]
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            out[...] = jnp.zeros_like(out)

        cr = cr[...]  # (BS, 1)
        mr = mr[...]
        if use_rcp:
            crr = crr[...]
            mrr = mrr[...]
        # Unrolled loop over the tile's sublane rows: each step is a fused
        # (BS, LANES) 2-D block of VPU ops — no 3-D intermediate ever exists.
        # dtype stays i32 throughout (x64 promotion would break Mosaic).
        acc = jnp.zeros_like(out)
        for r in range(NODE_TILE_ROWS):
            row = slice(r, r + 1)
            mk_row = mk[row] if use_mask else None
            if use_rcp:
                fit = _fit_row_rcp(
                    ac[row], am[row], ap[row], uc[row], um[row], pc[row],
                    mk_row, cr, mr, crr, mrr, strict,
                )
            else:
                fit = _fit_row(
                    ac[row], am[row], ap[row], uc[row], um[row], pc[row],
                    mk_row, cr, mr, strict,
                )
            if use_counts:
                # Grouped form: each lane is a node-shape GROUP standing
                # for count identical rows — weight before accumulating
                # (eligibility bounds Σ count·|fit| inside int32, and
                # zero-count padded lanes vanish here).
                fit = fit * ct[row]
            acc += fit
        out[...] += acc

    return kernel


@partial(jax.jit, static_argnames=("strict", "interpret"))
def _sweep_pallas_padded(
    ac, am, ap, uc, um, pc, cr, mr, mk=None, ct=None,
    *, strict=False, interpret=False,
):
    """Inner jitted pallas sweep on padded arrays (int32 ``//`` kernel).

    ``ac..pc``: ``(N/128, 128)`` int32 node arrays; ``cr``/``mr``: ``(S, 1)``
    int32 requests; ``mk``: optional ``(N/128, 128)`` int32 0/1 constraint
    mask (for strict mode this carries healthy∧constraints); ``ct``:
    optional ``(N/128, 128)`` int32 group counts (grouped form — each
    lane's fit is weighted before the reduction); returns int64
    ``totals[S]``.
    """
    return _pallas_dispatch(
        ac, am, ap, uc, um, pc, mk, ct, cr, mr, None, None,
        use_rcp=False, strict=strict, interpret=interpret,
    )


@partial(jax.jit, static_argnames=("strict", "interpret"))
def _sweep_pallas_padded_rcp(
    ac, am, ap, uc, um, pc, cr, mr, crr, mrr, mk=None, ct=None,
    *, strict=False, interpret=False,
):
    """Reciprocal-division variant: ``crr``/``mrr`` are f32 ``(S, 1)``
    reciprocals of ``cr``/``mr`` staged through
    :func:`scenario_reciprocals` — the one sanctioned producer (correctly
    rounded; the single-fixup proof depends on it).  Only valid on
    :func:`rcp_division_eligible` inputs."""
    return _pallas_dispatch(
        ac, am, ap, uc, um, pc, mk, ct, cr, mr, crr, mrr,
        use_rcp=True, strict=strict, interpret=interpret,
    )


def _pallas_dispatch(
    ac, am, ap, uc, um, pc, mk, ct, cr, mr, crr, mrr,
    *, use_rcp, strict, interpret,
):
    n_rows = ac.shape[0]
    s = cr.shape[0]
    grid = (s // SCENARIO_TILE, n_rows // NODE_TILE_ROWS)

    node_spec = pl.BlockSpec(
        (NODE_TILE_ROWS, LANES),
        lambda i, j: (j, 0),
        memory_space=pltpu.VMEM,
    )
    scen_spec = pl.BlockSpec(
        (SCENARIO_TILE, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    out_spec = pl.BlockSpec(
        (SCENARIO_TILE, LANES), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )

    use_mask = mk is not None
    use_counts = ct is not None
    operands = (ac, am, ap, uc, um, pc)
    in_specs = [node_spec] * 6
    if use_mask:
        operands += (mk,)
        in_specs += [node_spec]
    if use_counts:
        operands += (ct,)
        in_specs += [node_spec]
    operands += (cr, mr)
    in_specs += [scen_spec] * 2
    if use_rcp:
        operands += (crr, mrr)
        in_specs += [scen_spec] * 2

    # The kernel must trace with x64 OFF: the framework enables x64 globally
    # (exact int64 path), but under x64 pallas ref-slice/program_id index
    # arithmetic traces as i64, which Mosaic cannot legalize on real TPU
    # (interpret mode on CPU masks this).  All kernel values are i32 either
    # way; only the trace-time index/promotion semantics change.
    with jax.enable_x64(False):
        partial_sums = pl.pallas_call(
            _make_sweep_kernel(use_rcp, strict, use_mask, use_counts),
            out_shape=jax.ShapeDtypeStruct((s, LANES), jnp.int32),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            interpret=interpret,
        )(*operands)
    return jnp.sum(partial_sums.astype(jnp.int64), axis=1)


def _pad_to(x: np.ndarray, size: int, fill=0) -> np.ndarray:
    pad = size - x.shape[0]
    return np.pad(x, (0, pad), constant_values=fill) if pad else x


def padded_node_shape(n: int) -> int:
    """Nodes padded up to a whole number of (NODE_TILE_ROWS × LANES) tiles."""
    node_block = NODE_TILE_ROWS * LANES
    return -(-max(n, 1) // node_block) * node_block


def padded_scenario_shape(s: int) -> int:
    """Scenarios padded up to a whole number of SCENARIO_TILE blocks."""
    return -(-max(s, 1) // SCENARIO_TILE) * SCENARIO_TILE


def pad_node_array(a, n_pad: int, *, kib: bool = False) -> np.ndarray:
    """``[N]`` int64 → ``(n_pad/LANES, LANES)`` int32 kernel layout.

    Zero rows are fit-neutral: ``0 >= alloc_pods 0`` rewrites to ``0 − 0``.
    """
    a = np.asarray(a, dtype=np.int64)
    if kib:
        a = a // 1024
    return _pad_to(a.astype(np.int32), n_pad).reshape(n_pad // LANES, LANES)


def pad_scenario_array(a, s_pad: int, *, kib: bool = False) -> np.ndarray:
    """``[S]`` int64 → ``(s_pad, 1)`` int32 request column.

    Pads with ``1``-probes (valid divisors) whose outputs are dropped.
    """
    a = np.asarray(a, dtype=np.int64)
    if kib:
        a = a // 1024
    return _pad_to(a.astype(np.int32), s_pad, fill=1).reshape(s_pad, 1)


def scenario_reciprocals(padded_requests: np.ndarray) -> np.ndarray:
    """The rcp kernel's proof-bearing reciprocal: f64 divide halved to f32.

    This exact computation (correctly rounded, <= 1/2 ulp) is what the
    reciprocal-division exactness proof assumes; every caller of the rcp
    kernel must stage divisor reciprocals through here.
    """
    return (1.0 / np.asarray(padded_requests).astype(np.float64)).astype(
        np.float32
    )


def sweep_pallas(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
    counts=None,
    interpret: bool = False,
    use_rcp: bool | None = None,
    staged_nodes=None,
):
    """Fused Pallas sweep. Caller must check eligibility.

    ``mode`` selects the epilogue: ``"reference"`` is the Q1 conditional
    pod-cap overwrite; ``"strict"`` the corrected 3-way min (callers fold
    ``healthy`` into ``node_mask`` — the exact kernel's
    ``where(healthy, fit, 0)`` is the same lane-zeroing).  ``node_mask``
    (``[N]`` bool/int 0-1, optional) zeroes constraint-infeasible nodes
    after the epilogue, matching :func:`..fit.fit_per_node`'s ordering.

    Padding: nodes pad with zero rows (fit 0 in both modes — reference
    rewrites ``0 >= alloc_pods 0`` to ``0 − 0``, strict clamps to zero
    slots); a present mask pads with 0 (masked out).  Scenarios pad with
    ``(1, 1)`` probes whose outputs are dropped.  ``use_rcp`` selects the
    reciprocal-division kernel (~6x faster divides); ``None`` auto-enables
    it when :func:`rcp_division_eligible` proves it exact.
    ``staged_nodes`` (optional) is the devcache's already-padded,
    device-resident 6-tuple of node operands in kernel layout (what
    :meth:`..devcache.DeviceCache.pallas_arrays` returns for this exact
    snapshot) — the per-request pad + host→device upload is skipped; the
    positional node arrays are still consulted for ``n``.  ``counts``
    (``[N]`` int, optional — the grouped form) weights each row's fit by
    its node-shape multiplicity inside the kernel; pass eligibility the
    same counts (the int32 accumulator bound becomes count-weighted).
    Padded count lanes fill 0, so they vanish from the reduction.
    Returns ``(totals[S], schedulable[S])`` numpy arrays.
    """
    if mode not in ("reference", "strict"):
        raise ValueError(f"unknown mode {mode!r}")
    if use_rcp is None:
        use_rcp = rcp_division_eligible(
            alloc_cpu, alloc_mem, used_cpu, used_mem, cpu_reqs, mem_reqs
        )
    n = np.asarray(alloc_cpu).shape[0]
    s = np.asarray(cpu_reqs).shape[0]
    n_pad = padded_node_shape(n)
    s_pad = padded_scenario_shape(s)

    if staged_nodes is not None:
        node_args = tuple(staged_nodes)
    else:
        node_args = (
            pad_node_array(alloc_cpu, n_pad),
            pad_node_array(alloc_mem, n_pad, kib=True),
            pad_node_array(alloc_pods, n_pad),
            pad_node_array(used_cpu, n_pad),
            pad_node_array(used_mem, n_pad, kib=True),
            pad_node_array(pods_count, n_pad),
        )
    args = node_args + (
        pad_scenario_array(cpu_reqs, s_pad),
        pad_scenario_array(mem_reqs, s_pad, kib=True),
    )
    mk = None
    if node_mask is not None:
        mk = pad_node_array(
            np.asarray(node_mask).astype(np.int64), n_pad
        )
    ct = None
    if counts is not None:
        ct = pad_node_array(np.asarray(counts, dtype=np.int64), n_pad)
    strict = mode == "strict"
    import time as _time

    clk = _phases.current()
    t0 = _time.perf_counter() if clk else 0.0
    with clk.live("device_exec"):
        if use_rcp:
            recips = tuple(scenario_reciprocals(args[i]) for i in (6, 7))
            totals = _sweep_pallas_padded_rcp(
                *args, *recips, mk, ct, strict=strict, interpret=interpret
            )
        else:
            totals = _sweep_pallas_padded(
                *args, mk, ct, strict=strict, interpret=interpret
            )
    if clk:
        # Launch vs device→host sync, timed apart (same split as the
        # exact wrapper): the jitted call dispatches asynchronously and
        # np.asarray is the block_until_ready point.  sweep_auto moves
        # both into the compile phase when compilewatch classifies this
        # dispatch as a first call.
        t_launch = _time.perf_counter()
        clk.record("device_exec", t_launch - t0)
        with clk.live("fetch"):
            totals = np.asarray(totals)[:s]
        clk.record("fetch", _time.perf_counter() - t_launch)
    else:
        totals = np.asarray(totals)[:s]
    schedulable = totals >= np.asarray(replicas, dtype=np.int64)
    return totals, schedulable


def sweep_auto(
    alloc_cpu,
    alloc_mem,
    alloc_pods,
    used_cpu,
    used_mem,
    pods_count,
    healthy,
    cpu_reqs,
    mem_reqs,
    replicas,
    *,
    mode: str = "reference",
    node_mask=None,
    interpret: bool | None = None,
    force_exact: bool = False,
    sync: bool = True,
    _snapshot=None,
):
    """Fast path when eligible, exact int64 path otherwise — always bit-exact.

    Both modes take the fused path when eligible: reference with the Q1
    epilogue, strict (the :class:`..models.capacity.CapacityModel` default,
    where every surface also carries the implicit taint mask) with the
    clamped epilogue and ``healthy`` folded into the kernel's lane mask.
    The ONE dispatcher: every auto-kernel surface
    (:func:`sweep_snapshot_auto`, and through it the CLI and service)
    funnels here, so eligibility/padding fixes land everywhere at once.
    Returns numpy ``(totals[S], schedulable[S], kernel_name)`` with
    ``kernel_name`` one of ``pallas_i32_rcp_fused``, ``pallas_i32_fused``,
    ``xla_int64``.  ``interpret=None`` auto-selects Pallas interpret mode
    off-TPU (the real chip may register under a plugin platform name, so
    detect the one backend that NEEDS interpret mode).

    ``_snapshot`` (private; :func:`sweep_snapshot_auto` threads it) names
    the ClusterSnapshot the positional node arrays came from, unlocking
    the device-resident cache: the fused path reuses its staged int32
    node tiles and the exact fallback its bucket-padded int64 arrays —
    identical numbers, minus the per-request upload.

    ``sync=False`` threads the async-dispatch contract down to the exact
    bucketed path (:func:`..fit.sweep_grid_bucketed`): when that path can
    return without blocking it yields device ``jax.Array`` futures instead
    of numpy, letting the caller overlap the fetch with its next batch
    window (``fetch_overlap``).  The Pallas fused path materializes numpy
    internally (its np.asarray IS the sync point), so async applies only
    to the XLA fallback — callers must branch on the returned array type
    either way.  Values are bit-identical regardless.
    """
    import time as _time

    global last_fast_path_error
    _dispatch_tls.attempted = False
    _dispatch_tls.error = None
    tel = _metrics() if _telemetry_enabled() else None
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if mode == "strict":
        # Strict zeroes unhealthy nodes inside the exact kernel; the fused
        # kernel expresses that as the same lane mask the constraint mask
        # uses, so fold them (reference mode ignores healthy: its phantom
        # nodes are handled at packing).
        healthy_arr = np.asarray(healthy, dtype=bool)
        kernel_mask = (
            healthy_arr
            if node_mask is None
            else healthy_arr & np.asarray(node_mask, dtype=bool)
        )
    else:
        kernel_mask = node_mask
    # Decomposed (rather than one short-circuit conditional) so the
    # telemetry miss counter can say WHY a sweep fell back — the
    # breaker-vs-ineligible distinction is exactly what an operator
    # needs when fused-path throughput drops.
    fallback_reason = None
    if force_exact:
        fallback_reason = "forced_exact"
    elif not fast_sweep_eligible(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
        pods_count, cpu_reqs, mem_reqs,
    ):
        fallback_reason = "ineligible"
    elif not _breaker.allow():
        # The breaker check comes LAST: an open breaker for an eligible
        # request is what counts as "degraded" (an ineligible request
        # was never going to take the fused path anyway).
        fallback_reason = "breaker_open"
    if fallback_reason is None:
        _dispatch_tls.attempted = True
        use_rcp = rcp_division_eligible(
            alloc_cpu, alloc_mem, used_cpu, used_mem, cpu_reqs, mem_reqs
        )
        staged = None
        if _snapshot is not None and _devcache.enabled():
            # Device-resident staged tiles for this snapshot (warm after
            # the first sweep of a generation); a cache failure must
            # degrade to the per-request pad path, never the request.
            try:
                staged = _devcache.CACHE.pallas_arrays(_snapshot)
            except Exception:  # noqa: BLE001 - cache is an optimization
                staged = None
        t0 = _time.perf_counter()
        try:
            totals, sched = sweep_pallas(
                alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem,
                pods_count, cpu_reqs, mem_reqs, replicas, mode=mode,
                node_mask=kernel_mask, interpret=interpret, use_rcp=use_rcp,
                staged_nodes=staged,
            )
        except Exception as e:  # noqa: BLE001 - availability over speed
            # The value-domain eligibility proof cannot anticipate a
            # Mosaic/compiler failure on the real chip (round 4 recorded
            # two legalization failures that only reproduce there).  A
            # fast path that will not COMPILE must degrade to the exact
            # kernel, not take down the serve path — and must not re-pay
            # the failing compile per request: trip the breaker, keep the
            # error observable (fast_path_error()), re-arm only via
            # reset_fast_path().  Recognizably-transient runtime errors
            # (device OOM, tunnel hiccup) degrade THIS request only, so
            # one oversized sweep cannot disable the fast path
            # process-wide; everything else — compile/legalization
            # failures included — trips the breaker (see
            # _is_transient_failure for why unknown defaults to trip).
            last_fast_path_error = f"{type(e).__name__}: {e}"
            _dispatch_tls.error = last_fast_path_error
            transient = _is_transient_failure(e)
            if not transient:
                _breaker.record_failure(last_fast_path_error)
            if tel is not None:
                tel["failures"].labels(
                    disposition="transient" if transient else "breaker_trip"
                ).inc()
            fallback_reason = "kernel_error"
        else:
            # A fused success clears any prior transient failure: the
            # service must not report a stale fast_path_error alongside
            # a healthy fast-path kernel.  (A tripped breaker never
            # reaches here, so ITS error stays visible.)
            last_fast_path_error = None
            _breaker.record_success()
            name = "pallas_i32_rcp_fused" if use_rcp else "pallas_i32_fused"
            if tel is not None:
                # sweep_pallas materialized numpy totals, so perf_counter
                # here has already waited for the device (np.asarray IS
                # the block_until_ready sync for this dispatch).
                dt = _time.perf_counter() - t0
                tel["latency"].labels(kernel=name).observe(dt)
                tel["hits"].inc()
                kind = _compilewatch.observe_dispatch(name, dt)
                if kind == "compile":
                    # The phase clock recorded this dispatch as
                    # device_exec + fetch before compilewatch could
                    # classify it; a first call is trace + Mosaic
                    # compile — reattribute so cold starts decompose as
                    # compile, not as a runtime spike.
                    clk = _phases.current()
                    clk.move("device_exec", "compile")
                    clk.move("fetch", "compile")
            return totals, sched, name
    if tel is not None:
        tel["misses"].labels(reason=fallback_reason).inc()
        t0 = _time.perf_counter()
    totals, sched = sweep_grid_bucketed(
        alloc_cpu, alloc_mem, alloc_pods, used_cpu, used_mem, pods_count,
        healthy, cpu_reqs, mem_reqs, replicas, mode=mode,
        node_mask=node_mask, snapshot=_snapshot, sync=sync,
    )
    if tel is not None and isinstance(totals, np.ndarray):
        # np.asarray blocked on the device result above — same sync
        # policy as the fused branch.  An async dispatch (sync=False,
        # jax.Array result) never blocked, so host-timing it here would
        # record launch latency as kernel latency — skip the coarse
        # label; the bucketed label inside sweep_grid_bucketed already
        # carried the compile classification for this shape.
        dt = _time.perf_counter() - t0
        tel["latency"].labels(kernel="xla_int64").observe(dt)
        _compilewatch.observe_dispatch("xla_int64", dt)
    return totals, sched, "xla_int64"


def _sweep_auto_grouped(
    grouped,
    grid,
    *,
    mode: str = "reference",
    node_mask=None,
    interpret: bool | None = None,
    force_exact: bool = False,
):
    """:func:`sweep_auto`'s node-shape-compressed twin: the same
    eligible→fused / ineligible→exact ladder over ``G`` group rows with
    count weighting (ROADMAP item 1).

    ``node_mask`` folds into the per-group effective counts (a masked
    node's fit is zero in every mode, so removing it from its group's
    multiplicity is the identical sum); strict mode's ``healthy`` rides
    as the kernel lane mask exactly like the ungrouped fused path.
    Shares the fused-path circuit breaker, counters and thread-local
    attempt attribution with :func:`sweep_auto`.  Returns numpy
    ``(totals[S], schedulable[S], kernel_name)`` with the grouped kernel
    names ``pallas_i32{_rcp,}_fused_grouped`` / ``xla_int64_grouped``.
    """
    import time as _time

    global last_fast_path_error
    _dispatch_tls.attempted = False
    _dispatch_tls.error = None
    tel = _metrics() if _telemetry_enabled() else None
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    counts = grouped.effective_counts(node_mask)
    kernel_mask = (
        np.asarray(grouped.healthy, dtype=bool) if mode == "strict" else None
    )
    cpu_reqs = grid.cpu_request_milli
    mem_reqs = grid.mem_request_bytes
    fallback_reason = None
    if force_exact:
        fallback_reason = "forced_exact"
    elif not fast_sweep_eligible(
        grouped.alloc_cpu_milli, grouped.alloc_mem_bytes,
        grouped.alloc_pods, grouped.used_cpu_req_milli,
        grouped.used_mem_req_bytes, grouped.pods_count,
        cpu_reqs, mem_reqs, counts=counts,
    ):
        fallback_reason = "ineligible"
    elif not _breaker.allow():
        fallback_reason = "breaker_open"
    if fallback_reason is None:
        _dispatch_tls.attempted = True
        use_rcp = rcp_division_eligible(
            grouped.alloc_cpu_milli, grouped.alloc_mem_bytes,
            grouped.used_cpu_req_milli, grouped.used_mem_req_bytes,
            cpu_reqs, mem_reqs,
        )
        staged = None
        if _devcache.enabled():
            try:
                staged = _devcache.CACHE.grouped_pallas_arrays(grouped)
            except Exception:  # noqa: BLE001 - cache is an optimization
                staged = None
        t0 = _time.perf_counter()
        try:
            totals, sched = sweep_pallas(
                grouped.alloc_cpu_milli, grouped.alloc_mem_bytes,
                grouped.alloc_pods, grouped.used_cpu_req_milli,
                grouped.used_mem_req_bytes, grouped.pods_count,
                cpu_reqs, mem_reqs, grid.replicas, mode=mode,
                node_mask=kernel_mask, counts=counts,
                interpret=interpret, use_rcp=use_rcp, staged_nodes=staged,
            )
        except Exception as e:  # noqa: BLE001 - availability over speed
            # Same disposition policy as sweep_auto: transient failures
            # degrade this request only, anything else trips the shared
            # breaker (see sweep_auto's rationale).
            last_fast_path_error = f"{type(e).__name__}: {e}"
            _dispatch_tls.error = last_fast_path_error
            transient = _is_transient_failure(e)
            if not transient:
                _breaker.record_failure(last_fast_path_error)
            if tel is not None:
                tel["failures"].labels(
                    disposition="transient" if transient else "breaker_trip"
                ).inc()
            fallback_reason = "kernel_error"
        else:
            last_fast_path_error = None
            _breaker.record_success()
            name = (
                "pallas_i32_rcp_fused_grouped"
                if use_rcp
                else "pallas_i32_fused_grouped"
            )
            if tel is not None:
                dt = _time.perf_counter() - t0
                tel["latency"].labels(kernel=name).observe(dt)
                tel["hits"].inc()
                kind = _compilewatch.observe_dispatch(name, dt)
                if kind == "compile":
                    clk = _phases.current()
                    clk.move("device_exec", "compile")
                    clk.move("fetch", "compile")
            return totals, sched, name
    if tel is not None:
        tel["misses"].labels(reason=fallback_reason).inc()
        t0 = _time.perf_counter()
    totals, sched = sweep_grouped_bucketed(
        grouped, cpu_reqs, mem_reqs, grid.replicas,
        mode=mode, node_mask=node_mask,
    )
    if tel is not None:
        dt = _time.perf_counter() - t0
        tel["latency"].labels(kernel="xla_int64_grouped").observe(dt)
        _compilewatch.observe_dispatch("xla_int64_grouped", dt)
    return totals, sched, "xla_int64_grouped"


def sweep_snapshot_auto(
    snapshot,
    grid,
    *,
    mode: str = "reference",
    kernel: str = "auto",
    interpret: bool | None = None,
    node_mask=None,
    sync: bool = True,
):
    """Production sweep entry: fastest kernel that is provably bit-exact.

    The dispatch the CLI ``-grid`` path and the service ``sweep`` op use
    (the reference evaluates its one scenario with the sequential loop at
    ``ClusterCapacity.go:105-140``; a sweep is that loop over S what-if
    specs).  Eligible sweeps take the fused Pallas int32 path — the same
    kernel the headline bench times — in BOTH modes, masked or not: strict
    (the production default, always implicitly masked by hard taints) runs
    the fused clamped epilogue with healthy∧mask as the kernel's lane
    mask.  Everything else falls back to the exact int64 XLA kernel.

    ``node_mask`` (``[N]`` bool, optional) zeroes constraint-infeasible
    nodes — e.g. the implicit hard-taint mask every strict surface shares
    (:func:`..masks.implicit_taint_mask`).

    ``kernel="exact"`` forces the int64 path (operator escape hatch);
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU.
    Returns ``(totals[S], schedulable[S], kernel_name)`` with numpy arrays
    and the kernel actually used.

    ``sync=False`` opts into async dispatch where supported (the exact
    XLA devcache path on an ungrouped snapshot with a warm compile
    cache): totals/schedulable come back as ``jax.Array`` futures and
    the caller blocks only when it serializes — the folded-sweep
    server path's ``fetch_overlap``.  Grouped and Pallas routes stay
    synchronous (their reductions materialize internally); callers
    branch on the returned type.  Bit-identical values either way.
    """
    if kernel not in ("auto", "exact"):
        raise ValueError(f"unknown kernel {kernel!r}")
    if mode not in ("reference", "strict"):
        raise ValueError(f"unknown mode {mode!r}")
    grid.validate()
    grouped = grouped_for_dispatch(snapshot)
    if grouped is not None:
        # Degenerate fleet: dispatch over node-shape groups with count
        # weighting (bit-exact; KCCAP_GROUPING=0 restores this exact
        # ungrouped path).  kernel="exact" forces the exact grouped
        # kernel, same contract as the ungrouped escape hatch.
        return _sweep_auto_grouped(
            grouped,
            grid,
            mode=mode,
            node_mask=node_mask,
            interpret=interpret,
            force_exact=(kernel == "exact"),
        )
    return sweep_auto(
        snapshot.alloc_cpu_milli,
        snapshot.alloc_mem_bytes,
        snapshot.alloc_pods,
        snapshot.used_cpu_req_milli,
        snapshot.used_mem_req_bytes,
        snapshot.pods_count,
        snapshot.healthy,
        grid.cpu_request_milli,
        grid.mem_request_bytes,
        grid.replicas,
        mode=mode,
        node_mask=node_mask,
        interpret=interpret,
        force_exact=(kernel == "exact"),
        sync=sync,
        _snapshot=snapshot,
    )

def sweep_explain_snapshot_auto(
    snapshot,
    grid,
    *,
    mode: str = "reference",
    node_mask=None,
):
    """Auto entry for the fused sweep+explain super-kernel.

    Mirrors :func:`sweep_snapshot_auto`'s signature so the service's
    folded dispatcher can route a mixed sweep/explain batch through one
    call — but there is deliberately NO Pallas route here: the explain
    attribution carries the full int64 per-resource quotients
    (``cpu_fit``/``mem_fit``/``slots``), which the i32 lane kernel
    cannot represent, so every fused sweep+explain dispatch is the
    exact XLA program (:func:`..fit.sweep_explain_grid`) and the
    kernel label says so honestly.  Delegates to
    :func:`...explain.sweep_explain_snapshot`, which owns the devcache
    staging, grouped expansion and compilewatch labeling.  Returns
    ``(ExplainResult, kernel_name)``.
    """
    from kubernetesclustercapacity_tpu.explain import sweep_explain_snapshot

    return sweep_explain_snapshot(
        snapshot, grid, mode=mode, node_mask=node_mask
    )
