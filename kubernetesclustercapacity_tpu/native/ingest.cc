// Native pod-walk for the columnar snapshot packers (CPython extension).
//
// SURVEY.md §2.1 names the snapshot packer (C3) + quantity codecs (C6/C7)
// as the natural native component; the codecs live in capacity.cc and this
// file supplies the packer's hot loop: the ~100k-pod dict walk that
// collects, per container, an interned "quad" code (the tuple of its
// quantity strings) plus its grouping index.  Everything numeric stays in
// Python/numpy (the LUT parse + scatter-adds are already vectorized);
// everything the interpreter made slow (per-dict method dispatch on ~10
// lookups x ~300k containers) runs here at C speed with the SAME dict
// operations, so insertion orders, defaults, and grouping are identical
// to the pure-Python walks in snapshot.py (the tests pin this).
//
// Semantics stay single-sourced: phase sets come in from the caller
// (oracle._EXCLUDED_PHASES / snapshot._STRICT_TERMINATED), and any object
// that is not JSON-shaped (non-dict pod/resources, non-list containers,
// non-str nodeName...) makes the walk return None so the caller reruns
// the pure-Python loop and raises exactly what it always raised.
//
// Reference walk mirrors snapshot._pack_reference's loop
// (ClusterCapacity.go:232-299 semantics: field-selector by phase, usage
// grouped by raw nodeName string including the phantom "" group); strict
// walk mirrors snapshot._pack_strict's loop (assigned & non-terminated
// pods, containers + initContainers collected separately).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <new>
#include <vector>

namespace {

// Pre-built key strings (PyDict_GetItemString allocates a fresh unicode
// per call; these are made once at module init).
PyObject *s_phase, *s_nodeName, *s_containers, *s_initContainers;
PyObject *s_resources, *s_requests, *s_limits, *s_cpu, *s_memory, *s_zero,
    *s_empty;

// Fallback signal: the structure wasn't JSON-shaped; caller must rerun
// the pure-Python walk (which raises its usual exceptions on such input).
struct Fallback {};
// Real error: a Python exception is set and must propagate.
struct Raised {};

// RAII strong reference: several dict/set operations below can execute
// arbitrary Python (__hash__/__eq__ of hostile keys colliding with ours),
// which may mutate the containers we borrowed from — every object we keep
// using across such a call is pinned for the duration.
struct Ref {
  PyObject* o;
  explicit Ref(PyObject* obj) : o(obj) { Py_XINCREF(o); }
  ~Ref() { Py_XDECREF(o); }
  Ref(const Ref&) = delete;
  Ref& operator=(const Ref&) = delete;
};

PyObject* dict_get(PyObject* dict, PyObject* key) {
  // dict.get(key) -> borrowed ref or nullptr (absent).
  PyObject* v = PyDict_GetItemWithError(dict, key);
  if (v == nullptr && PyErr_Occurred()) throw Raised{};
  return v;
}

// pod.get(key, {}).get(...) chains: returns borrowed dict or nullptr for
// "empty"; anything present-but-not-a-dict falls back (the Python walk
// then raises AttributeError/TypeError exactly as it always did).
PyObject* get_dict_or_empty(PyObject* owner, PyObject* key) {
  PyObject* v = dict_get(owner, key);
  if (v == nullptr) return nullptr;
  if (!PyDict_CheckExact(v)) throw Fallback{};
  return v;
}

Py_ssize_t intern_code(PyObject* interned, PyObject* quad) {
  // interned.setdefault(quad, len(interned)) with quad consumed.
  PyObject* def = PyLong_FromSsize_t(PyDict_Size(interned));
  if (def == nullptr) { Py_DECREF(quad); throw Raised{}; }
  PyObject* got = PyDict_SetDefault(interned, quad, def);  // borrowed
  Py_DECREF(def);
  Py_DECREF(quad);
  if (got == nullptr) throw Raised{};
  Py_ssize_t code = PyLong_AsSsize_t(got);
  if (code == -1 && PyErr_Occurred()) throw Raised{};
  return code;
}

PyObject* vec_to_bytes(const std::vector<int64_t>& v) {
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(v.data()),
      static_cast<Py_ssize_t>(v.size() * sizeof(int64_t)));
}

// Shared container-quad collection.  cpu slots take ``cpu_default``
// (the "0" string in reference mode, None in strict mode) when ABSENT;
// an explicit null stays None, exactly like dict.get's default rules.
// ``extended`` (strict only) appends req.get(name) per extended resource.
PyObject* build_quad(PyObject* container, PyObject* cpu_default,
                     PyObject* extended /* tuple or nullptr */) {
  if (!PyDict_CheckExact(container)) throw Fallback{};
  // Every fetched dict is pinned BEFORE the next hostile-capable lookup:
  // a colliding key's __eq__ during the s_requests lookup must not be
  // able to free res (via del container['resources']), nor the s_limits
  // lookup free req — each object's only other strong ref is the parent
  // dict slot such a callback can clear.
  PyObject* res = dict_get(container, s_resources);
  Ref pin_res(res);
  PyObject* req = nullptr;
  PyObject* lim = nullptr;
  if (res != nullptr) {
    if (!PyDict_CheckExact(res)) throw Fallback{};
    req = get_dict_or_empty(res, s_requests);
  }
  Ref pin_req(req);
  if (res != nullptr) {
    lim = get_dict_or_empty(res, s_limits);
  }
  Ref pin_lim(lim);
  Py_ssize_t n_ext = extended ? PyTuple_GET_SIZE(extended) : 0;
  PyObject* quad = PyTuple_New(4 + n_ext);
  if (quad == nullptr) throw Raised{};
  try {
    PyObject* v;
    v = req ? dict_get(req, s_cpu) : nullptr;
    if (v == nullptr) v = cpu_default;
    Py_INCREF(v); PyTuple_SET_ITEM(quad, 0, v);
    v = lim ? dict_get(lim, s_cpu) : nullptr;
    if (v == nullptr) v = cpu_default;
    Py_INCREF(v); PyTuple_SET_ITEM(quad, 1, v);
    v = req ? dict_get(req, s_memory) : nullptr;
    if (v == nullptr) v = Py_None;
    Py_INCREF(v); PyTuple_SET_ITEM(quad, 2, v);
    v = lim ? dict_get(lim, s_memory) : nullptr;
    if (v == nullptr) v = Py_None;
    Py_INCREF(v); PyTuple_SET_ITEM(quad, 3, v);
    for (Py_ssize_t e = 0; e < n_ext; ++e) {
      v = req ? dict_get(req, PyTuple_GET_ITEM(extended, e)) : nullptr;
      if (v == nullptr) v = Py_None;
      Py_INCREF(v); PyTuple_SET_ITEM(quad, 4 + e, v);
    }
  } catch (...) {
    Py_DECREF(quad);  // unfilled slots are NULL — safe to deallocate
    throw;
  }
  return quad;
}

// walk_reference(pods: list, excluded_phases: set-like)
//   -> (name_gid: dict, interned: dict, pod_gids, c_gids, c_codes) | None
PyObject* walk_reference(PyObject*, PyObject* args) {
  PyObject *pods, *excluded;
  if (!PyArg_ParseTuple(args, "OO", &pods, &excluded)) return nullptr;
  if (!PyList_CheckExact(pods)) Py_RETURN_NONE;

  PyObject* interned = PyDict_New();
  PyObject* name_gid = PyDict_New();
  if (interned == nullptr || name_gid == nullptr) {
    Py_XDECREF(interned); Py_XDECREF(name_gid);
    return nullptr;
  }
  std::vector<int64_t> pod_gids, c_gids, c_codes;

  try {
    // List sizes re-read per iteration and items pinned while hostile
    // __hash__/__eq__ callbacks could run: a callback that mutates the
    // fixture mid-walk gets odd-but-memory-safe behavior, never UAF.
    for (Py_ssize_t p = 0; p < PyList_GET_SIZE(pods); ++p) {
      Ref pod(PyList_GET_ITEM(pods, p));
      if (!PyDict_CheckExact(pod.o)) throw Fallback{};
      Ref phase(dict_get(pod.o, s_phase));
      int ex = PySet_Contains(excluded, phase.o ? phase.o : Py_None);
      if (ex < 0) throw Raised{};
      if (ex) continue;  // does not survive the field selector

      Ref node_name(dict_get(pod.o, s_nodeName));
      PyObject* def = PyLong_FromSsize_t(PyDict_Size(name_gid));
      if (def == nullptr) throw Raised{};
      PyObject* got = PyDict_SetDefault(
          name_gid, node_name.o ? node_name.o : s_empty, def);
      Py_DECREF(def);
      if (got == nullptr) throw Raised{};
      Py_ssize_t gid = PyLong_AsSsize_t(got);
      if (gid == -1 && PyErr_Occurred()) throw Raised{};
      pod_gids.push_back(gid);

      Ref containers(dict_get(pod.o, s_containers));
      if (containers.o == nullptr) continue;
      if (!PyList_CheckExact(containers.o)) throw Fallback{};
      for (Py_ssize_t ci = 0; ci < PyList_GET_SIZE(containers.o); ++ci) {
        Ref container(PyList_GET_ITEM(containers.o, ci));
        PyObject* quad = build_quad(container.o, s_zero, nullptr);
        c_gids.push_back(gid);
        c_codes.push_back(intern_code(interned, quad));
      }
    }
  } catch (Fallback&) {
    Py_DECREF(interned); Py_DECREF(name_gid);
    Py_RETURN_NONE;
  } catch (Raised&) {
    Py_DECREF(interned); Py_DECREF(name_gid);
    return nullptr;
  } catch (const std::bad_alloc&) {
    Py_DECREF(interned); Py_DECREF(name_gid);
    PyErr_NoMemory();
    return nullptr;
  }

  PyObject* out = Py_BuildValue(
      "(NNNNN)", name_gid, interned, vec_to_bytes(pod_gids),
      vec_to_bytes(c_gids), vec_to_bytes(c_codes));
  if (out == nullptr) return nullptr;  // N stole what it could; give up
  return out;
}

// walk_strict(pods: list, index: dict[str, int], terminated: set-like,
//             extended: tuple[str, ...])
//   -> (interned, pod_nodes, c_pod, c_codes, i_pod, i_codes) | None
PyObject* walk_strict(PyObject*, PyObject* args) {
  PyObject *pods, *index, *terminated, *extended;
  if (!PyArg_ParseTuple(args, "OOOO", &pods, &index, &terminated, &extended))
    return nullptr;
  if (!PyList_CheckExact(pods) || !PyDict_CheckExact(index) ||
      !PyTuple_CheckExact(extended))
    Py_RETURN_NONE;

  PyObject* interned = PyDict_New();
  if (interned == nullptr) return nullptr;
  std::vector<int64_t> pod_nodes, c_pod, c_codes, i_pod, i_codes;

  try {
    // Same pinning/re-read discipline as walk_reference — see there.
    for (Py_ssize_t p = 0; p < PyList_GET_SIZE(pods); ++p) {
      Ref pod(PyList_GET_ITEM(pods, p));
      if (!PyDict_CheckExact(pod.o)) throw Fallback{};
      Ref node_name(dict_get(pod.o, s_nodeName));
      if (node_name.o == nullptr) continue;  // .get("nodeName", "") falsy
      if (!PyUnicode_CheckExact(node_name.o)) throw Fallback{};
      if (PyUnicode_GetLength(node_name.o) == 0) continue;
      PyObject* row = dict_get(index, node_name.o);
      if (row == nullptr) continue;  // not a known node
      Py_ssize_t row_i = PyLong_AsSsize_t(row);
      if (row_i == -1 && PyErr_Occurred()) throw Raised{};

      Ref phase(dict_get(pod.o, s_phase));
      int term = PySet_Contains(terminated, phase.o ? phase.o : Py_None);
      if (term < 0) throw Raised{};
      if (term) continue;

      int64_t pid = static_cast<int64_t>(pod_nodes.size());
      pod_nodes.push_back(row_i);

      struct Kind { PyObject* key; std::vector<int64_t>* pods_v;
                    std::vector<int64_t>* codes_v; };
      const Kind kinds[2] = {{s_containers, &c_pod, &c_codes},
                             {s_initContainers, &i_pod, &i_codes}};
      for (const Kind& k : kinds) {
        Ref seq(dict_get(pod.o, k.key));
        if (seq.o == nullptr) continue;
        if (!PyList_CheckExact(seq.o)) throw Fallback{};
        for (Py_ssize_t ci = 0; ci < PyList_GET_SIZE(seq.o); ++ci) {
          Ref container(PyList_GET_ITEM(seq.o, ci));
          PyObject* quad = build_quad(container.o, Py_None, extended);
          k.pods_v->push_back(pid);
          k.codes_v->push_back(intern_code(interned, quad));
        }
      }
    }
  } catch (Fallback&) {
    Py_DECREF(interned);
    Py_RETURN_NONE;
  } catch (Raised&) {
    Py_DECREF(interned);
    return nullptr;
  } catch (const std::bad_alloc&) {
    Py_DECREF(interned);
    PyErr_NoMemory();
    return nullptr;
  }

  return Py_BuildValue(
      "(NNNNNN)", interned, vec_to_bytes(pod_nodes), vec_to_bytes(c_pod),
      vec_to_bytes(c_codes), vec_to_bytes(i_pod), vec_to_bytes(i_codes));
}

PyMethodDef methods[] = {
    {"walk_reference", walk_reference, METH_VARARGS,
     "Reference-semantics columnar pod walk; None => caller falls back."},
    {"walk_strict", walk_strict, METH_VARARGS,
     "Strict-semantics columnar pod walk; None => caller falls back."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "_kccap_ingest",
                      "Native columnar pod walk for the snapshot packers.",
                      -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__kccap_ingest(void) {
  s_phase = PyUnicode_InternFromString("phase");
  s_nodeName = PyUnicode_InternFromString("nodeName");
  s_containers = PyUnicode_InternFromString("containers");
  s_initContainers = PyUnicode_InternFromString("initContainers");
  s_resources = PyUnicode_InternFromString("resources");
  s_requests = PyUnicode_InternFromString("requests");
  s_limits = PyUnicode_InternFromString("limits");
  s_cpu = PyUnicode_InternFromString("cpu");
  s_memory = PyUnicode_InternFromString("memory");
  s_zero = PyUnicode_InternFromString("0");
  s_empty = PyUnicode_InternFromString("");
  if (!s_phase || !s_nodeName || !s_containers || !s_initContainers ||
      !s_resources || !s_requests || !s_limits || !s_cpu || !s_memory ||
      !s_zero || !s_empty)
    return nullptr;
  return PyModule_Create(&module);
}
