// Native CPU backend: quantity codecs + the sequential capacity-fit kernel.
//
// This is the framework's compiled "CPU reference path" — the same role the
// reference's Go binary plays (a compiled sequential implementation of the
// per-node loop at src/KubeAPI/ClusterCapacity.go:105-140), exposed through a
// C ABI for ctypes.  Semantics notes:
//
//  * Go-style arithmetic: uint64 compare/divide for CPU, two's-complement
//    wrap-around int64 subtraction for memory (computed via unsigned casts —
//    signed overflow is UB in C++), truncating division (C++ native).
//  * kcc_cpu_to_milli mirrors convertCPUToMilis (ClusterCapacity.go:301-319):
//    Go Atoi acceptance (sign + ASCII digits, int64 range), failure -> 0,
//    uint64 wrap on the x1000.
//  * kcc_to_bytes mirrors bytefmt.ToBytes (bytes.go:75-105): trim + upper,
//    split at first (ASCII) letter, all-base-2 suffix table with the GI/TI
//    gap, value <= 0 or no suffix -> error, int64 truncation with the
//    amd64 out-of-range convention (INT64_MIN), underscore digit
//    separators accepted between digits (Go 1.13+/Python float()).
//    The whitespace trim is Go's exact TrimSpace set (Unicode
//    White_Space, UTF-8 aware — go_space_len below), matching the Python
//    codec's _GO_SPACE_CHARS.  Divergences (documented, same as the
//    Python codec): inf/nan/hex spellings are rejected; only ASCII
//    letters split the suffix.
//  * kcc_fit_arrays / kcc_sweep: mode 0 = reference (conditional pod-cap
//    overwrite, may go negative), mode 1 = strict (3-way min, clamp at 0,
//    healthy mask).  A zero divisor reached behind a positive headroom
//    returns an error code exactly where the reference would panic.
//
// The sweep is parallelized over scenarios with std::thread — the native
// analog of the TPU kernel's vmap axis — so CPU-vs-TPU comparisons are fair.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <thread>
#include <vector>

extern "C" {

static const uint64_t KIB = 1024ull;
static const uint64_t MIB = KIB * 1024;
static const uint64_t GIB = MIB * 1024;
static const uint64_t TIB = GIB * 1024;

// Go strconv.Atoi acceptance: optional sign, 1+ ASCII digits, int64 range.
// Returns 1 on success.
static int go_atoi(const char* s, size_t len, int64_t* out) {
  if (len == 0) return 0;
  size_t i = 0;
  int neg = 0;
  if (s[0] == '+' || s[0] == '-') {
    neg = s[0] == '-';
    i = 1;
  }
  if (i == len) return 0;
  uint64_t acc = 0;
  const uint64_t limit = neg ? 0x8000000000000000ull : 0x7fffffffffffffffull;
  for (; i < len; i++) {
    if (s[i] < '0' || s[i] > '9') return 0;
    uint64_t d = (uint64_t)(s[i] - '0');
    if (acc > (limit - d) / 10) return 0;  // overflow -> range error
    acc = acc * 10 + d;
  }
  // Negate in unsigned space: acc may be 2^63 (INT64_MIN's magnitude) and
  // signed negation of INT64_MIN would be UB.
  *out = neg ? (int64_t)(0ull - acc) : (int64_t)acc;
  return 1;
}

// convertCPUToMilis semantics; returns the uint64 bit pattern.  Length is
// explicit so embedded NUL bytes parse exactly like the Python codec
// (which would reject the full string) instead of silently truncating.
uint64_t kcc_cpu_to_milli_n(const char* cpu, int64_t len_in) {
  size_t len = (size_t)len_in;
  int has_m = len > 0 && cpu[len - 1] == 'm';
  if (has_m) len--;
  int64_t v;
  if (!go_atoi(cpu, len, &v)) return 0;
  uint64_t u = (uint64_t)v;
  if (!has_m) u *= 1000ull;  // wraps mod 2^64 like Go
  return u;
}


// bytefmt.ToBytes semantics; returns 0 and stores into *out on success,
// -1 on the reference's invalid-byte-quantity error.
// Byte length of one Go-White_Space rune at s[i..e) in UTF-8, else 0 —
// the exact set Go's strings.TrimSpace trims (unicode.IsSpace ==
// White_Space: ASCII \t\n\v\f\r space, U+0085, U+00A0, U+1680,
// U+2000-200A, U+2028, U+2029, U+202F, U+205F, U+3000).  C isspace()
// was wrong in both directions: it misses every non-ASCII space and the
// multi-byte checks below can never false-match mid-rune (space runes
// start with 0xC2/0xE1/0xE2/0xE3, never a continuation byte).
static size_t go_space_len(const std::string& s, size_t i, size_t e) {
  unsigned char c0 = (unsigned char)s[i];
  if (c0 == 0x09 || c0 == 0x0a || c0 == 0x0b || c0 == 0x0c ||
      c0 == 0x0d || c0 == 0x20)
    return 1;
  if (i + 1 < e && c0 == 0xC2) {
    unsigned char c1 = (unsigned char)s[i + 1];
    if (c1 == 0x85 || c1 == 0xA0) return 2;  // U+0085, U+00A0
  }
  if (i + 2 < e) {
    unsigned char c1 = (unsigned char)s[i + 1];
    unsigned char c2 = (unsigned char)s[i + 2];
    if (c0 == 0xE1 && c1 == 0x9A && c2 == 0x80) return 3;  // U+1680
    if (c0 == 0xE2 && c1 == 0x80 &&
        ((c2 >= 0x80 && c2 <= 0x8A) ||  // U+2000-200A
         c2 == 0xA8 || c2 == 0xA9 ||    // U+2028, U+2029
         c2 == 0xAF))                   // U+202F
      return 3;
    if (c0 == 0xE2 && c1 == 0x81 && c2 == 0x9F) return 3;  // U+205F
    if (c0 == 0xE3 && c1 == 0x80 && c2 == 0x80) return 3;  // U+3000
  }
  return 0;
}

int kcc_to_bytes_n(const char* s_in, int64_t len_in, int64_t* out) {
  std::string s(s_in, (size_t)len_in);
  // Go strings.TrimSpace (White_Space runes, UTF-8 aware) + ToUpper.
  size_t b = 0, e = s.size();
  for (size_t l; b < e && (l = go_space_len(s, b, e)) > 0;) b += l;
  for (bool more = true; more && e > b;) {
    more = false;
    for (size_t l = 1; l <= 3 && l <= e - b; l++) {
      if (go_space_len(s, e - l, e) == l) {
        e -= l;
        more = true;
        break;
      }
    }
  }
  s = s.substr(b, e - b);
  for (auto& c : s) c = (char)toupper((unsigned char)c);

  size_t li = std::string::npos;
  for (size_t i = 0; i < s.size(); i++) {
    if (isalpha((unsigned char)s[i])) {
      li = i;
      break;
    }
  }
  if (li == std::string::npos) return -1;

  std::string num = s.substr(0, li), suffix = s.substr(li);
  if (num.empty()) return -1;
  // Underscore digit separators: both Go ParseFloat and Python float()
  // accept them, but only BETWEEN digits ("1_5" ok, "_1"/"1_"/"1_.5"
  // rejected).  Validate, then strip for strtod (which knows nothing of
  // them).  Everything else strtod might creatively accept (whitespace,
  // inf/nan/hex — the suffix split already took the first letter) is
  // rejected by the char filter.
  std::string cleaned;
  cleaned.reserve(num.size());
  for (size_t i = 0; i < num.size(); i++) {
    char c = num[i];
    if (c == '_') {
      if (i == 0 || i + 1 >= num.size() ||
          !isdigit((unsigned char)num[i - 1]) ||
          !isdigit((unsigned char)num[i + 1]))
        return -1;
      continue;  // valid separator: drop it
    }
    if (!(isdigit((unsigned char)c) || c == '.' || c == '+' || c == '-'))
      return -1;
    cleaned.push_back(c);
  }
  // Locale-independent parse: the embedding process may have called
  // setlocale (GUI toolkits do), and strtod honors LC_NUMERIC's decimal
  // point — Go/Python semantics never do.
  static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  char* endp = nullptr;
  double v = c_loc != (locale_t)0
                 ? strtod_l(cleaned.c_str(), &endp, c_loc)
                 : strtod(cleaned.c_str(), &endp);
  if (endp != cleaned.c_str() + cleaned.size()) return -1;
  // Overflow-to-infinity is Go's ErrRange -> the reference's error path.
  if (!std::isfinite(v)) return -1;
  if (!(v > 0)) return -1;  // <= 0 (or NaN) -> error (bytes.go:87-89)

  uint64_t mult;
  if (suffix == "T" || suffix == "TB" || suffix == "TIB") mult = TIB;
  else if (suffix == "G" || suffix == "GB" || suffix == "GIB") mult = GIB;
  else if (suffix == "M" || suffix == "MB" || suffix == "MIB" || suffix == "MI") mult = MIB;
  else if (suffix == "K" || suffix == "KB" || suffix == "KIB" || suffix == "KI") mult = KIB;
  else if (suffix == "B") mult = 1;
  else return -1;

  double scaled = v * (double)mult;
  // Go int64(float64) out of range: amd64/arm64 produce INT64_MIN.
  if (!(scaled < 9.223372036854775807e18) || scaled < -9.223372036854775808e18)
    *out = INT64_MIN;
  else
    *out = (int64_t)scaled;
  return 0;
}


// One node's fit, Go semantics.  Returns 0 ok, -1 divide-by-zero "panic".
static int fit_one(int64_t alloc_cpu, int64_t alloc_mem, int64_t alloc_pods,
                   int64_t used_cpu, int64_t used_mem, int64_t pods_count,
                   uint8_t healthy, int64_t cpu_req, int64_t mem_req,
                   int mode, int64_t* out) {
  uint64_t ac = (uint64_t)alloc_cpu, uc = (uint64_t)used_cpu;
  uint64_t cr = (uint64_t)cpu_req;
  int64_t cpu_fit;
  if (ac <= uc) {
    cpu_fit = 0;
  } else {
    if (cr == 0) return -1;  // ClusterCapacity.go:123 panic
    cpu_fit = (int64_t)((ac - uc) / cr);
  }
  int64_t mem_fit;
  if (alloc_mem <= used_mem) {
    mem_fit = 0;
  } else {
    if (mem_req == 0) return -1;  // :129 panic
    // Wrap-around subtraction via unsigned cast; C++ '/' truncates like
    // Go.  INT64_MIN / -1 is UB in C++ (SIGFPE on x86-64) but defined in
    // Go (wraps to INT64_MIN); negate through unsigned space instead.
    int64_t head = (int64_t)((uint64_t)alloc_mem - (uint64_t)used_mem);
    mem_fit = mem_req == -1 ? (int64_t)(0ull - (uint64_t)head)
                            : head / mem_req;
  }
  int64_t fit = cpu_fit <= mem_fit ? cpu_fit : mem_fit;  // findMin :159-164
  // Subtractions wrap through unsigned space: Go wraps, C++ signed
  // overflow is UB.
  if (mode == 0) {  // reference: conditional overwrite (:134-136)
    if (fit >= alloc_pods)
      fit = (int64_t)((uint64_t)alloc_pods - (uint64_t)pods_count);
  } else {  // strict: 3-way min, clamp, health mask
    int64_t slots = (int64_t)((uint64_t)alloc_pods - (uint64_t)pods_count);
    if (slots < 0) slots = 0;
    if (fit > slots) fit = slots;
    if (fit < 0) fit = 0;
    if (!healthy) fit = 0;
  }
  *out = fit;
  return 0;
}

// Sequential per-node fits for one scenario.  healthy may be NULL (all 1).
int kcc_fit_arrays(int64_t n, const int64_t* alloc_cpu,
                   const int64_t* alloc_mem, const int64_t* alloc_pods,
                   const int64_t* used_cpu, const int64_t* used_mem,
                   const int64_t* pods_count, const uint8_t* healthy,
                   int64_t cpu_req, int64_t mem_req, int mode,
                   int64_t* fits_out) {
  for (int64_t i = 0; i < n; i++) {
    if (fit_one(alloc_cpu[i], alloc_mem[i], alloc_pods[i], used_cpu[i],
                used_mem[i], pods_count[i], healthy ? healthy[i] : 1,
                cpu_req, mem_req, mode, &fits_out[i]) != 0)
      return -1;
  }
  return 0;
}

// Multi-threaded scenario sweep: totals[s] = sum_n fit(n, s).
// Returns 0 ok, -1 if any scenario hit a zero divisor.
int kcc_sweep(int64_t n, int64_t s, const int64_t* alloc_cpu,
              const int64_t* alloc_mem, const int64_t* alloc_pods,
              const int64_t* used_cpu, const int64_t* used_mem,
              const int64_t* pods_count, const uint8_t* healthy,
              const int64_t* cpu_reqs, const int64_t* mem_reqs, int mode,
              int n_threads, int64_t* totals_out) {
  if (n_threads <= 0) n_threads = (int)std::thread::hardware_concurrency();
  if (n_threads <= 0) n_threads = 1;
  if ((int64_t)n_threads > s) n_threads = (int)(s > 0 ? s : 1);

  std::vector<int> errs((size_t)n_threads, 0);
  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; t++) {
    threads.emplace_back([&, t]() {
      for (int64_t j = t; j < s; j += n_threads) {
        int64_t total = 0, fit = 0;
        for (int64_t i = 0; i < n; i++) {
          if (fit_one(alloc_cpu[i], alloc_mem[i], alloc_pods[i], used_cpu[i],
                      used_mem[i], pods_count[i], healthy ? healthy[i] : 1,
                      cpu_reqs[j], mem_reqs[j], mode, &fit) != 0) {
            errs[(size_t)t] = 1;
            return;
          }
          // Running sum wraps like Go's int accumulator, not UB.
          total = (int64_t)((uint64_t)total + (uint64_t)fit);
        }
        totals_out[j] = total;
      }
    });
  }
  for (auto& th : threads) th.join();
  for (int e : errs)
    if (e) return -1;
  return 0;
}

}  // extern "C"
