"""Shared on-demand ``g++`` build machinery for the native components.

Both native loaders (the ctypes capacity library and the ingest CPython
extension) build their shared object the same way: into ``_build/`` next
to the source, keyed on source mtime, via a temp file + atomic rename so
concurrent processes never dlopen a half-written object.  One
implementation here so compiler-flag or caching fixes land in both.
"""

from __future__ import annotations

import os
import subprocess
import tempfile

__all__ = ["build_so"]


def build_so(
    src: str,
    out_name: str,
    *,
    compile_args: tuple[str, ...] = (),
    link_args: tuple[str, ...] = (),
) -> str:
    """Build ``src`` into ``_build/<out_name>`` iff missing/stale.

    Returns the shared-object path; raises :class:`RuntimeError` carrying
    the compiler's stderr on failure.
    """
    # Everything filesystem-touching sits inside the try: a read-only
    # checkout (PermissionError from makedirs/mkstemp) must surface as
    # the same RuntimeError the loaders turn into their "unavailable"
    # signal, not crash callers whose contract is silent fallback.
    tmp = None
    try:
        build_dir = os.path.join(
            os.path.dirname(os.path.abspath(src)), "_build"
        )
        os.makedirs(build_dir, exist_ok=True)
        so_path = os.path.join(build_dir, out_name)
        if (
            os.path.exists(so_path)
            and os.path.getmtime(so_path) >= os.path.getmtime(src)
        ):
            return so_path
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=build_dir)
        os.close(fd)
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            *compile_args, "-o", tmp, src, *link_args,
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, so_path)
    except (OSError, subprocess.CalledProcessError) as e:
        if tmp is not None and os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise RuntimeError(getattr(e, "stderr", "") or str(e)) from e
    return so_path
